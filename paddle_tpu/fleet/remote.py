"""Cross-process serving fleet: out-of-process replicas over the
framed transport.

The PR-10 fleet is in-process — every replica shares one Python
process and one GIL, and ``PredictorServer.kill()`` merely *simulates*
death. This module lifts the replica boundary onto the same
length-prefixed framed protocol the async-PS path speaks
(:mod:`paddle_tpu.parallel.async_ps` — one ASCII header line, binary
bodies of a length named in the header, trace tokens riding the
header): each replica is a separate OS process
(:mod:`paddle_tpu.fleet.replica_main`) running its own
``PredictorServer``, and the router talks to a :class:`RemoteReplica`
proxy that duck-types the ``PredictorServer`` surface
``FleetRouter`` routes over — so SIGKILL, TCP partitions, and
slow links are *real*, not injected.

Wire verbs (client → replica)::

    SUBMIT <meta_len> <payload_len> <deadline|-> trace=<span>  + body
    HEALTH | REPORT | METRICS | JOURNAL <since_seq>
    RELOAD <len> | KILL <len> | SHUTDOWN <len>                 + json

Replies: ``OK <id>`` (submit accepted), ``OK <len>`` + json (control),
``ERR <errname> <len>`` + json detail (typed errors reconstructed
client-side), and the per-request lifecycle pushed on the submit
connection — ``DISPATCHED <id>`` (written when a worker picks the
request up, BEFORE execution), then ``DONE <id> <meta_len>
<payload_len>`` + outputs or ``FAIL <id> <errname> <len>`` + detail.

**The at-most-once contract over a real wire** (the serving mirror of
``PSClient.push``): a SUBMIT is sent at most once — connection
*establishment* may retry, but once the header left the socket the
request is never resent. When the link dies before the outcome
arrives, the client classifies:

- process **provably dead** (owned child exited / fresh connect
  refused) and ``DISPATCHED`` never seen → :class:`~paddle_tpu.
  serving.ServerClosed` — the request provably never began executing
  (SIGKILL delivers bytes written before death, and the replica
  writes ``DISPATCHED`` before execution), so the router reroutes it
  transparently;
- ``DISPATCHED`` seen → :class:`~paddle_tpu.serving.ReplicaDied`
  exactly once, never retried;
- **cannot prove death** (partition / half-open connection: probes
  time out, the peer may still be executing) → :class:`~paddle_tpu.
  serving.ReplicaDied` — reply lost after send, surfaced exactly
  once, never resent.

Health probes are bounded by construction (socket timeout + capped
retries with exponential backoff via :class:`~paddle_tpu.parallel.
async_ps.FramedClient`), cache a *down* verdict for ``down_cooldown``
seconds (a partitioned replica must not stall every subsequent route
for a full probe timeout), and measure probe latency: a replica that
answers but slower than ``slow_after`` is marked ``slow`` — the
router demotes it below other ready replicas instead of treating
alive as healthy.

Trace tokens ride the SUBMIT header (`` trace=<span>``, same optional
trailing-token scheme as the PS wire): the span is minted at the
front door and adopted by the replica's ``PredictorServer.submit``,
so one trace id correlates both processes' journals end to end; the
``JOURNAL`` verb ships the replica's retained ring back over the same
link (``RunJournal.ingest``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..parallel.async_ps import (FramedClient, ReplyLost, child_python_env,
                                 read_exact, read_line)
from ..serving import (CircuitOpen, DeadlineExceeded, ReloadFailed,
                       ReplicaDied, ServerClosed, ServerOverloaded,
                       ServingError, WorkerHung)
from ..io import InvalidRequest


def _log():
    import logging
    return logging.getLogger("paddle_tpu.fleet.remote")


# -- tree packing (feeds + outputs) -------------------------------------------


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registered extension dtypes (bfloat16, fp8)
        return np.dtype(getattr(ml_dtypes, name))


def _wire_spec(spec):
    """Normalize a ``WireSpec`` | its field dict (the JSON shape that
    rides a SUBMIT meta item) into a ``WireSpec``."""
    from ..data.wire import WireSpec

    if isinstance(spec, WireSpec):
        return spec
    if isinstance(spec, dict):
        return WireSpec(**spec)
    raise TypeError(f"feed wire spec: expected WireSpec or dict, "
                    f"got {type(spec).__name__}")


def pack_tree(obj, wire: Optional[Dict[str, Any]] = None) \
        -> Tuple[bytes, bytes]:
    """Encode a feed dict / output tree of arrays as ``(meta_json,
    payload)``: the meta names each leaf's place, shape, and dtype; the
    payload is the leaves' contiguous bytes concatenated in meta
    order. Supported shapes: dict of arrays, single array, list/tuple
    of arrays (scalars ride as 0-d arrays).

    ``wire`` (dict-shaped feeds only) maps field names to
    :class:`~paddle_tpu.data.wire.WireSpec`s: those fields cross the
    link in the narrower wire dtype (the 53 MB/s lesson applied to
    serving SUBMITs), with the spec embedded in the meta item so the
    replica's :func:`unpack_tree` decodes back to the logical value —
    the wire schema itself is unchanged (same two bodies)."""
    chunks: List[bytes] = []

    def leaf(v, spec=None) -> Dict[str, Any]:
        a = np.ascontiguousarray(np.asarray(v))
        extra: Dict[str, Any] = {}
        if spec is not None and spec.kind != "passthrough":
            a = np.ascontiguousarray(spec.encode(a))
            extra["wire"] = {
                "kind": spec.kind, "wire_dtype": spec.wire_dtype,
                "decode_dtype": spec.decode_dtype,
                "scale": spec.scale, "zero_point": spec.zero_point}
        b = a.tobytes()
        chunks.append(b)
        return {"shape": list(a.shape), "dtype": a.dtype.name,
                "nbytes": len(b), **extra}

    if isinstance(obj, dict):
        specs = {k: _wire_spec(s) for k, s in (wire or {}).items()}
        meta: Dict[str, Any] = {
            "kind": "dict",
            "items": [{"name": str(k), **leaf(obj[k], specs.get(str(k)))}
                      for k in sorted(obj, key=str)]}
    elif isinstance(obj, (list, tuple)):
        meta = {"kind": "list" if isinstance(obj, list) else "tuple",
                "items": [leaf(v) for v in obj]}
    else:
        meta = {"kind": "single", "items": [leaf(obj)]}
    return json.dumps(meta).encode(), b"".join(chunks)


def unpack_tree(meta_bytes: bytes, payload: bytes,
                counters: Optional[Dict[str, int]] = None):
    """Inverse of :func:`pack_tree`: wire-encoded items (a ``"wire"``
    spec in the meta) are decoded back to their logical dtype.
    ``counters`` (optional dict) accumulates ``wire_bytes`` (what
    actually crossed the link) and ``logical_bytes`` (what a
    passthrough transfer of the same values would have cost) — the
    replica's serving report reads them."""
    meta = json.loads(meta_bytes)
    leaves = []
    off = 0
    wire_bytes = logical_bytes = 0
    for item in meta["items"]:
        n = int(item["nbytes"])
        a = np.frombuffer(payload[off:off + n],
                          dtype=_np_dtype(item["dtype"]))
        a = a.reshape(item["shape"]).copy()
        w = item.get("wire")
        if w is not None:
            a = np.asarray(_wire_spec(w).decode(a))
        wire_bytes += n
        logical_bytes += int(a.nbytes)
        leaves.append(a)
        off += n
    if counters is not None:
        counters["wire_bytes"] = counters.get("wire_bytes", 0) + wire_bytes
        counters["logical_bytes"] = (counters.get("logical_bytes", 0)
                                     + logical_bytes)
    if meta["kind"] == "dict":
        return {item["name"]: leaf
                for item, leaf in zip(meta["items"], leaves)}
    if meta["kind"] == "list":
        return leaves
    if meta["kind"] == "tuple":
        return tuple(leaves)
    return leaves[0]


# -- typed errors over the wire -----------------------------------------------

_ERROR_ATTRS = ("field", "reason", "queue_depth", "capacity", "retry_after",
                "dirname", "path")


def error_payload(e: BaseException) -> Tuple[str, Dict[str, Any]]:
    """``(errname, detail)`` for the ``ERR``/``FAIL`` frames: the class
    name plus the constructor attributes the client needs to rebuild
    the typed error."""
    detail: Dict[str, Any] = {"message": str(e)}
    for k in _ERROR_ATTRS:
        v = getattr(e, k, None)
        if v is not None:
            detail[k] = v
    return type(e).__name__, detail


def build_remote_error(name: str, detail: Dict[str, Any]) -> BaseException:
    """Rebuild a replica-side typed error from its wire payload —
    the client raises EXACTLY the class the in-process fleet would
    have, so ``FleetPending``'s reroute/at-most-once dispatch on
    exception type is wire-transparent."""
    from .. import resilience

    msg = str(detail.get("message", ""))
    if name == "InvalidRequest":
        return InvalidRequest(detail.get("field", "?"),
                              detail.get("reason", msg))
    if name == "ServerOverloaded":
        return ServerOverloaded(int(detail.get("queue_depth", 0)),
                                int(detail.get("capacity", 0)))
    if name == "CircuitOpen":
        return CircuitOpen(float(detail.get("retry_after", 0.0)))
    if name == "ReloadFailed":
        return ReloadFailed(detail.get("dirname", "?"),
                            detail.get("reason", msg))
    if name == "CheckpointCorrupt":
        return resilience.CheckpointCorrupt(detail.get("path", "?"),
                                            detail.get("reason", msg))
    cls = {"DeadlineExceeded": DeadlineExceeded, "WorkerHung": WorkerHung,
           "ServerClosed": ServerClosed, "ReplicaDied": ReplicaDied,
           "ServingError": ServingError}.get(name)
    if cls is not None:
        return cls(msg)
    return ServingError(f"{name}: {msg}")


class _ControlClient(FramedClient):
    """Control-plane client (HEALTH/REPORT/METRICS/JOURNAL and the
    one-shot RELOAD/KILL/SHUTDOWN connections): the framed reconnect-
    with-backoff machinery of :class:`FramedClient` with the replica's
    ``ERR <name> <len>`` + json-detail error frames raised typed."""

    peer_name = "fleet replica"

    def _on_err_reply(self, resp: str):
        _, name, blen = resp.split()
        body = self._read_exact(int(blen))
        raise build_remote_error(name, json.loads(body or b"{}"))

    def call(self, line: str, payload: bytes = b"",
             idempotent: bool = True, timeout: Optional[float] = None):
        """One ``OK <len>`` + json round trip."""
        _, body = self._request(line, payload, idempotent=idempotent,
                                body_len=lambda r: int(r.split()[1]),
                                timeout=timeout)
        return json.loads(body) if body else None


# -- artifact distribution ----------------------------------------------------

ARTIFACT_CHUNK = 1 << 18   # 256 KiB ARTIFACT chunk frames


def parse_hostport(addr) -> Tuple[str, int]:
    """``"host:port"`` or ``(host, port)`` → ``(host, port)``."""
    if isinstance(addr, str):
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"bad host address {addr!r} (want host:port)")
        return (host, int(port))
    host, port = addr
    return (str(host), int(port))


def ship_artifact(addr: Tuple[str, int], dirname: str,
                  timeout: float = 600.0,
                  chunk_bytes: int = ARTIFACT_CHUNK) -> str:
    """Stream a committed ``save_inference_model`` dir to the artifact
    store behind ``addr`` (a replica or a host agent — both speak the
    same door) and return the RECEIVER-side committed path.

    Protocol: ``FETCH`` negotiates (the manifest's file/CRC table under
    a content-addressed token — an already-committed token is a
    zero-byte no-op, and the reply's have-map resumes a torn transfer
    where it stopped), ``ARTIFACT`` frames carry per-chunk-CRC'd file
    bytes with no reply (pipelined), and a final ``FETCH commit``
    CRC-validates every staged file against the manifest before the
    receiver's atomic rename — a connection lost mid-stream leaves only
    a resumable staging dir, never a half-written artifact. Raises
    ``ConnectionError`` (connection-shaped, so the router's reload
    rollback machinery engages) when the receiver stays unreachable."""
    import zlib

    from ..io import artifact_fingerprint
    from ..resilience import MANIFEST_NAME, _crc32_file

    path = os.path.abspath(dirname)
    man, token = artifact_fingerprint(path)
    mf_crc, mf_size = _crc32_file(os.path.join(path, MANIFEST_NAME))
    # the manifest file ships verbatim like any other member, so the
    # committed copy is byte-identical to the source dir
    expected = {name: {"crc32": int(spec["crc32"]),
                       "size": int(spec["size"])}
                for name, spec in man["files"].items()}
    expected[MANIFEST_NAME] = {"crc32": mf_crc, "size": mf_size}
    negotiate = json.dumps({"token": token, "files": expected,
                            "commit": False}).encode()
    commit = json.dumps({"token": token, "commit": True}).encode()
    last_err: Optional[BaseException] = None
    for _attempt in range(3):
        cli = _ControlClient(tuple(addr), timeout=timeout, retries=2,
                             retry_backoff=0.05, connect=False)
        try:
            st = cli.call(f"FETCH {token} {len(negotiate)}", negotiate,
                          timeout=timeout)
            if st.get("complete"):
                return st["path"]
            have = dict(st.get("have") or {})
            sock = cli._sock
            for fname in sorted(expected):
                start = int(have.get(fname, 0))
                if start >= expected[fname]["size"]:
                    continue
                with open(os.path.join(path, fname), "rb") as f:
                    f.seek(start)
                    off = start
                    while True:
                        data = f.read(chunk_bytes)
                        if not data:
                            break
                        crc = zlib.crc32(data) & 0xFFFFFFFF
                        hdr = (f"ARTIFACT {token} {fname} {off} "
                               f"{len(data)} {crc:08x}\n").encode()
                        sock.sendall(hdr + data)
                        off += len(data)
            st = cli.call(f"FETCH {token} {len(commit)}", commit,
                          timeout=timeout)
            if st.get("complete"):
                return st["path"]
            # receiver rejected some staged files (corrupted in
            # flight): the next lap renegotiates and re-ships exactly
            # the files its have-map no longer covers
            last_err = ConnectionError(
                f"artifact {token} commit rejected by {addr}: "
                f"bad={st.get('bad')}")
        except (OSError, ConnectionError) as e:
            last_err = e
        finally:
            try:
                cli.close()
            except Exception:
                pass
    raise ConnectionError(
        f"could not ship artifact {dirname!r} to {addr}: {last_err}")


class ArtifactStore:
    """Receiver half of the FETCH/ARTIFACT pair: a per-host artifact
    cache keyed by content-addressed token. Chunks land in a
    ``<token>.staging`` sibling (resumable — the negotiate reply's
    have-map is just the staged sizes); commit CRC-validates every file
    against the negotiated table and renames the staging dir into place
    atomically, so the cache either holds a fully-validated artifact at
    ``<root>/<token>`` or nothing there at all. A bad chunk never
    errors the stream (ARTIFACT frames have no reply, the sender is
    pipelining): the staged file is dropped and the commit reply's
    ``bad`` list makes the sender re-ship it."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self._expected: Dict[str, Dict[str, Any]] = {}

    def _paths(self, token: str) -> Tuple[str, str]:
        if not token or "/" in token or "\\" in token or ".." in token:
            raise ValueError(f"bad artifact token {token!r}")
        return (os.path.join(self.root, token),
                os.path.join(self.root, token + ".staging"))

    @staticmethod
    def _safe_name(fname: str) -> bool:
        return bool(fname) and "/" not in fname and "\\" not in fname \
            and ".." not in fname and not fname.startswith(".")

    def handle_fetch(self, token: str, body: bytes) -> Dict[str, Any]:
        """One FETCH round trip: negotiate (``commit: false``) or
        commit (``commit: true``)."""
        req = json.loads(body or b"{}")
        final, staging = self._paths(token)
        with self._lock:
            if req.get("commit"):
                return self._commit_locked(token, final, staging)
            files = {name: spec
                     for name, spec in dict(req.get("files") or {}).items()
                     if self._safe_name(name)}
            return self._begin_locked(token, final, staging, files)

    def _begin_locked(self, token, final, staging, files):
        if os.path.isdir(final):
            return {"complete": True, "path": final}
        self._expected[token] = files
        os.makedirs(staging, exist_ok=True)
        have = {}
        for name in os.listdir(staging):
            p = os.path.join(staging, name)
            if os.path.isfile(p):
                have[name] = os.path.getsize(p)
        return {"complete": False, "have": have, "path": final}

    def handle_chunk(self, token: str, fname: str, off: int,
                     crc: int, data: bytes) -> None:
        """One ARTIFACT frame: append iff the chunk CRC matches and it
        lands exactly at the staged tail; anything else poisons the
        staged file (dropped, re-shipped after commit reports it)."""
        import zlib

        _, staging = self._paths(token)
        if not self._safe_name(fname):
            return
        with self._lock:
            if not os.path.isdir(staging):
                return    # no negotiation for this token: drop
            p = os.path.join(staging, fname)
            size = os.path.getsize(p) if os.path.exists(p) else 0
            if (zlib.crc32(data) & 0xFFFFFFFF) != crc or off != size:
                if os.path.exists(p):
                    os.unlink(p)
                return
            with open(p, "ab") as f:
                f.write(data)

    def _commit_locked(self, token, final, staging):
        from .. import resilience

        if os.path.isdir(final):
            return {"complete": True, "path": final}
        expected = self._expected.get(token)
        if expected is None or not os.path.isdir(staging):
            return {"complete": False, "bad": ["<no staging session>"],
                    "have": {}}
        bad = []
        for name, spec in expected.items():
            p = os.path.join(staging, name)
            try:
                crc, size = resilience._crc32_file(p)
            except OSError:
                bad.append(name)
                continue
            if size != int(spec["size"]) or crc != int(spec["crc32"]):
                os.unlink(p)
                bad.append(name)
        if bad:
            have = {}
            for name in expected:
                p = os.path.join(staging, name)
                if name not in bad and os.path.exists(p):
                    have[name] = os.path.getsize(p)
            return {"complete": False, "bad": sorted(bad), "have": have}
        for name in expected:
            with open(os.path.join(staging, name), "rb") as f:
                os.fsync(f.fileno())
        os.rename(staging, final)
        try:
            dfd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        self._expected.pop(token, None)
        return {"complete": True, "path": final}


# -- the replica process ------------------------------------------------------


class ReplicaProcess:
    """Spawn-and-own one out-of-process replica: a child Python running
    :mod:`paddle_tpu.fleet.replica_main` over a ``save_inference_model``
    artifact (config shipped as a JSON file; the golden feed as an
    npz next to it). ``wait_ready()`` blocks until the child prints
    ``PORT <n>`` — i.e. its ``PredictorServer`` is warmed and the
    listener is up — so several processes can be launched first and
    awaited together (they AOT-compile concurrently)."""

    def __init__(self, dirname: str, server_kw: Optional[Dict] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 artifact_root: Optional[str] = None,
                 bind: Optional[str] = None):
        self.dirname = dirname
        self._cfg_dir = tempfile.mkdtemp(prefix="pdtpu_replica_")
        cfg = self._build_config(dirname, dict(server_kw or {}), host, port)
        if artifact_root:
            cfg["artifact_root"] = artifact_root
        if bind:
            cfg["bind"] = bind
        cfg_path = os.path.join(self._cfg_dir, "replica.json")
        with open(cfg_path, "w", encoding="utf-8") as f:
            json.dump(cfg, f)
        # PDTPU_TELEMETRY_ADDR is deliberately KEPT (each replica
        # process ships to the collector on its own) but the ORIGIN
        # override is not — it names ONE process, and inheriting it
        # would collapse the whole fleet onto a single origin
        env = child_python_env(pop=("PDTPU_TELEMETRY_ORIGIN",))
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.fleet.replica_main",
             cfg_path],
            stdout=subprocess.PIPE, text=True, env=env)
        self.addr: Optional[Tuple[str, int]] = None
        self._host = host

    def _build_config(self, dirname: str, kw: Dict, host: str,
                      port: int) -> Dict[str, Any]:
        bp = kw.pop("batch_policy", None)
        if bp is not None and dataclasses.is_dataclass(bp):
            bp = dataclasses.asdict(bp)
        breaker = kw.pop("breaker", None)
        if breaker is not None and dataclasses.is_dataclass(breaker):
            breaker = dataclasses.asdict(breaker)
        golden = kw.pop("golden_feed", None)
        golden_path = None
        if golden is not None:
            golden_path = os.path.join(self._cfg_dir, "golden.npz")
            np.savez(golden_path, **{k: np.asarray(v)
                                     for k, v in golden.items()})
        # anything left must be JSON-serializable (workers, queue_size,
        # deadlines, watchdog, warmup, reject_nonfinite, ...): a
        # non-serializable kwarg fails HERE, loudly, not in the child
        return {"dirname": dirname, "host": host, "port": int(port),
                "server_kw": kw, "batch_policy": bp, "breaker": breaker,
                "golden_feed": golden_path}

    @property
    def pid(self) -> int:
        return self._proc.pid

    def wait_ready(self, timeout: float = 300.0) -> Tuple[str, int]:
        """Block until the child printed ``PORT <n>``; returns the
        replica's address. Raises if the child exits first, and
        honors ``timeout`` even when the child hangs without printing
        anything (the pipe is select()ed, never blocking-read past
        the deadline)."""
        import select

        if self.addr is not None:
            return self.addr
        deadline = time.monotonic() + timeout
        line = ""
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            ready, _, _ = select.select([self._proc.stdout], [], [],
                                        min(remaining, 1.0))
            if not ready:
                continue
            line = self._proc.stdout.readline()
            if not line:
                rc = self._proc.poll()
                raise RuntimeError(
                    f"replica process exited (rc={rc}) before reporting "
                    "its port — see its stderr above")
            line = line.strip()
            if line.startswith("PORT "):
                self.addr = (self._host, int(line.split()[1]))
                return self.addr
        raise TimeoutError(
            f"replica process did not report a port within {timeout}s "
            f"(last line: {line!r})")

    def poll(self) -> Optional[int]:
        return self._proc.poll()

    def wait(self, timeout: Optional[float] = None) -> int:
        return self._proc.wait(timeout)

    def kill(self) -> None:
        """SIGKILL, no cleanup — the real process-death injector."""
        if self._proc.poll() is None:
            self._proc.kill()

    def stop(self) -> None:
        self.kill()
        try:
            self._proc.wait(timeout=5.0)
        except Exception:
            pass

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


# -- the client-side proxy ----------------------------------------------------


class RemotePending:
    """Client half of one in-flight remote request: owns the SUBMIT
    connection and reads the pushed lifecycle (``DISPATCHED`` →
    ``DONE``/``FAIL``). Duck-types :class:`~paddle_tpu.serving.
    PendingResult` for :class:`~paddle_tpu.fleet.FleetPending`. A lost
    connection is classified per the module contract: never-dispatched
    on a provably dead process → ``ServerClosed`` (the router
    reroutes), anything else → ``ReplicaDied`` exactly once."""

    def __init__(self, replica: "RemoteReplica", sock: socket.socket,
                 rid: str, span: str):
        self._replica = replica
        self._sock: Optional[socket.socket] = sock
        self.rid = rid
        self._span = span
        self._lock = threading.Lock()
        # monotonic bool (False -> True once, under _lock): _classify's
        # lock-free read can only be STALE-False, which classifies a
        # lost connection conservatively (ReplicaDied, never resent)
        self.dispatched = False   # lint: allow(thread:unguarded-access)
        self._done = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self._submitted = time.monotonic()
        self._completed: Optional[float] = None
        self._last_activity = time.monotonic()
        # receive buffer: a poll timeout mid-line must PRESERVE the
        # bytes already read — discarding them would desync the framed
        # stream (the next pump would parse a half header)
        self._rbuf = bytearray()

    @property
    def span(self) -> Optional[str]:
        return self._span

    @property
    def latency(self) -> Optional[float]:
        return (None if self._completed is None
                else self._completed - self._submitted)

    def done(self) -> bool:
        if not self._done.is_set():
            self._pump(0.0)
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        bound = None if timeout is None else time.monotonic() + timeout
        while not self._done.is_set():
            if bound is not None:
                remaining = bound - time.monotonic()
                if remaining <= 0:
                    raise DeadlineExceeded(
                        f"no remote result within {timeout:.2f}s (request "
                        f"{self.rid} still queued or executing on "
                        f"{self._replica.addr})")
                self._pump(min(0.25, remaining))
            else:
                self._pump(0.25)
        if self._error is not None:
            raise self._error
        return self._value

    def _recv_line(self) -> str:
        """One header line from the buffered stream; a socket timeout
        propagates with the partial bytes KEPT in the buffer."""
        while True:
            i = self._rbuf.find(b"\n")
            if i >= 0:
                line = self._rbuf[:i].decode()
                del self._rbuf[:i + 1]
                return line
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionError("replica closed connection")
            self._rbuf += chunk

    def _recv_exact(self, n: int) -> bytes:
        """``n`` framed body bytes, buffer first."""
        while len(self._rbuf) < n:
            chunk = self._sock.recv(max(4096, n - len(self._rbuf)))
            if not chunk:
                raise ConnectionError("replica closed connection")
            self._rbuf += chunk
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    def _pump(self, timeout: float) -> None:
        """Read lifecycle messages off the submit connection for up to
        ``timeout`` seconds (0 = one non-blocking peek)."""
        with self._lock:
            if self._done.is_set() or self._sock is None:
                return
            try:
                self._sock.settimeout(max(timeout, 1e-3))
                line = self._recv_line()
            except socket.timeout:
                self._check_stall()
                return
            except (OSError, ConnectionError) as e:
                self._classify(e)
                return
            self._last_activity = time.monotonic()
            try:
                parts = line.split()
                if parts[0] == "DISPATCHED":
                    self.dispatched = True
                    return
                # DONE/FAIL carry a framed body: a short pump timeout
                # must not tear mid-body — the body follows the header
                # immediately, so a generous bound is safe
                self._sock.settimeout(30.0)
                if parts[0] == "DONE":
                    meta = self._recv_exact(int(parts[2]))
                    payload = self._recv_exact(int(parts[3]))
                    self._complete(value=unpack_tree(meta, payload))
                elif parts[0] == "FAIL":
                    body = self._recv_exact(int(parts[3]))
                    self._complete(error=build_remote_error(
                        parts[2], json.loads(body or b"{}")))
                else:
                    self._complete(error=ServingError(
                        f"replica protocol error: unexpected {line!r}"))
            except (OSError, ConnectionError) as e:
                self._classify(e)
            except (ValueError, IndexError, KeyError,
                    UnicodeDecodeError) as e:
                # a corrupt/unparseable frame is a typed outcome, not
                # an exception leaking out of result() with the socket
                # stuck mid-frame
                self._complete(error=ServingError(
                    f"replica protocol error parsing {line!r}: "
                    f"{type(e).__name__}: {e}"))

    def _check_stall(self) -> None:
        """The lifecycle socket has been silent past the stall bound
        (``submit_timeout`` since the last byte): a partitioned link
        looks exactly like a slow dispatch from here — no error ever
        arrives, the sends all succeeded into kernel buffers. Resolve
        the ambiguity with a bounded health probe of the replica: a
        probe that answers (and is live) means the request is
        genuinely slow/queued — reset the clock and keep waiting; an
        unreachable or stopped replica means this connection is as
        good as dead — classify at-most-once (the half-open case the
        drill pins: surfaced once, never resent, never left hanging
        until the caller's deadline)."""
        if time.monotonic() - self._last_activity <= \
                self._replica.submit_timeout:
            return
        try:
            h = self._replica.health()
        except Exception as e:
            self._classify(ConnectionError(
                f"no lifecycle bytes for "
                f"{time.monotonic() - self._last_activity:.1f}s and the "
                f"replica is unreachable ({e})"))
            return
        if not h.get("live"):
            self._classify(ConnectionError(
                f"replica no longer live ({h.get('state')}) with this "
                "request outstanding"))
            return
        self._last_activity = time.monotonic()

    def _classify(self, cause: Exception) -> None:
        """Connection lost before the outcome arrived — the wire
        re-proof of the in-process kill() contract (see module
        docstring)."""
        if self._replica._provably_dead() and not self.dispatched:
            err: BaseException = ServerClosed(
                f"replica process at {self._replica.addr} died with this "
                f"request accepted but never dispatched ({cause}); safe "
                "to resubmit")
        else:
            err = ReplicaDied(
                f"connection to replica at {self._replica.addr} lost "
                f"{'after' if self.dispatched else 'with'} this request "
                f"{'dispatched' if self.dispatched else 'in an unknown state'}"
                f" ({cause}); at-most-once — surfaced once, never resent")
        self._complete(error=err)

    def _complete(self, value=None,
                  error: Optional[BaseException] = None) -> None:
        self._value = value
        self._error = error
        self._completed = time.monotonic()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._done.set()


class RemoteReplica:
    """Client-side proxy over one out-of-process replica, duck-typing
    the ``PredictorServer`` surface :class:`~paddle_tpu.fleet.
    FleetRouter` supervises: ``submit``/``health``/``kill``/``reload``/
    ``close``/``report``/``telemetry_families``/``repin_compiles``/
    ``generation``. Control verbs ride one persistent framed
    connection (bounded timeout + capped exponential-backoff retries);
    each SUBMIT gets its own connection that carries that request's
    pushed lifecycle; RELOAD/KILL/SHUTDOWN use one-shot connections so
    a long reload never blocks a health probe.

    Probe discipline: ``probe_timeout`` bounds one HEALTH round trip,
    a failed probe caches a *down* verdict for ``down_cooldown``
    seconds (routing stays responsive during a partition), a
    successful one is cached for ``health_ttl`` (the per-submit
    routing scan costs at most one round trip per TTL), and a probe
    slower than ``slow_after`` marks the replica ``slow`` for the
    router's probe-latency demotion."""

    # every probe is bounded at the socket (timeout + capped retries +
    # down-verdict cache): the router reads this and probes INLINE
    # instead of paying a bounding thread per health check
    probe_bounded = True

    def __init__(self, addr: Tuple[str, int],
                 proc: Optional[ReplicaProcess] = None,
                 name: Optional[str] = None,
                 num_workers: int = 2,
                 probe_timeout: float = 1.0,
                 probe_retries: int = 2,
                 probe_backoff: float = 0.05,
                 down_cooldown: float = 1.0,
                 health_ttl: float = 0.05,
                 slow_after: Optional[float] = None,
                 submit_timeout: float = 30.0,
                 connect_timeout: float = 1.0,
                 reload_timeout: float = 600.0,
                 agent: Optional["AgentClient"] = None,
                 pid: Optional[int] = None,
                 ship_artifacts: bool = False,
                 feed_wire: Optional[Dict[str, Any]] = None):
        self.addr = tuple(addr)
        self.proc = proc
        self.name = name
        # cross-host adoption: `agent` is the per-host launcher that
        # owns the replica process (the waitpid oracle a proxied link
        # can't be), `pid` its pid THERE, `ship_artifacts` makes
        # reload() stream the dir over FETCH/ARTIFACT first (the
        # replica's filesystem has never seen the router's paths), and
        # `feed_wire` ({field: WireSpec}) narrows SUBMIT payloads
        self.agent = agent
        self.pid = pid if pid is not None else \
            (proc.pid if proc is not None else None)
        self.ship_artifacts = bool(ship_artifacts)
        self.feed_wire = ({k: _wire_spec(s) for k, s in feed_wire.items()}
                          if feed_wire else None)
        self.num_workers = int(num_workers)
        self.probe_timeout = probe_timeout
        self.down_cooldown = down_cooldown
        self.health_ttl = health_ttl
        self.slow_after = slow_after
        self.submit_timeout = submit_timeout
        self.connect_timeout = connect_timeout
        self.reload_timeout = reload_timeout
        self._ctl = _ControlClient(self.addr, timeout=probe_timeout,
                                   retries=max(1, int(probe_retries)),
                                   retry_backoff=probe_backoff,
                                   connect=False)
        self._ctl_lock = threading.Lock()
        self._health_lock = threading.Lock()
        self._health_cache: Optional[Dict[str, Any]] = None
        self._health_time = 0.0
        self._down_until = 0.0
        self._down_error = ""
        self._killed = False

    @property
    def journal(self):
        # resolved per use, not cached at construction: the process
        # journal can be swapped (tests, re-rooted sinks) after a
        # long-lived proxy was built
        from ..telemetry import get_journal
        return get_journal()

    # -- liveness ------------------------------------------------------------

    def _provably_dead(self) -> bool:
        """True only when the replica PROCESS is known dead — an owned
        child that exited, a host agent reporting its pid reaped, or a
        fresh probe refused/EOF'd. A timeout (a partition, a half-open
        link) proves nothing and returns False.

        Across a PROXIED link (testing/faults.LinkProxy, or any real
        LB) "connect succeeded" means nothing — the proxy always
        accepts — and "connect refused" never happens. Two proofs
        replace waitpid there: (a) the replica's host agent IS a
        waitpid oracle for children it spawned; (b) probe-EOF — a
        fresh connection that accepts a probe and then closes cleanly
        before a single reply byte is a proxy whose backend connect
        was refused (LinkProxy and real proxies both do this), i.e.
        nothing is listening where the process was. A partitioned
        link times out instead of EOF'ing, so it still proves
        nothing."""
        if self._killed:
            return True
        if self.proc is not None:
            try:
                self.proc.wait(timeout=0.25)
                return True
            except Exception:
                return False
        if self.agent is not None and self.pid is not None:
            try:
                procs = {int(p.get("pid", -1)): p
                         for p in self.agent.ps().get("procs", [])}
                p = procs.get(int(self.pid))
                # untracked => the agent reaped it; tracked+exited =>
                # dead; tracked+alive => provably NOT dead
                return p is None or not p.get("alive", False)
            except Exception:
                pass   # agent unreachable (whole-host kill): probe below
        try:
            s = socket.create_connection(self.addr,
                                         timeout=self.probe_timeout)
        except ConnectionRefusedError:
            return True
        except OSError:
            return False
        try:
            s.settimeout(self.probe_timeout)
            probe = b"HEALTH\n"
            s.sendall(probe)
            first = s.recv(1)
            return not first   # orderly EOF before any reply byte
        except OSError:
            return False       # timeout/reset: cannot prove death
        finally:
            try:
                s.close()
            except OSError:
                pass

    # -- health --------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """One bounded wire probe (cached per the probe discipline).
        Raises ``ConnectionError`` when the replica is unreachable —
        the router maps that to *unavailable* and keeps routing."""
        if self._killed:
            return {"live": False, "ready": False, "state": "stopped",
                    "queue_depth": 0, "workers_busy": 0, "workers": 0}
        now = time.monotonic()
        with self._health_lock:
            if now < self._down_until:
                raise ConnectionError(
                    f"replica at {self.addr} marked down for another "
                    f"{self._down_until - now:.2f}s ({self._down_error})")
            if self._health_cache is not None and \
                    now - self._health_time < self.health_ttl:
                return dict(self._health_cache)
        t0 = time.monotonic()
        try:
            with self._ctl_lock:
                h = self._ctl.call("HEALTH", timeout=self.probe_timeout)
        except (ReplyLost, ConnectionError, OSError) as e:
            with self._health_lock:
                self._down_until = time.monotonic() + self.down_cooldown
                self._down_error = f"{type(e).__name__}: {e}"[:200]
                self._health_cache = None
            raise ConnectionError(
                f"health probe to {self.addr} failed: {e}") from e
        lat = time.monotonic() - t0
        h["probe_latency_s"] = round(lat, 6)
        h["slow"] = bool(self.slow_after is not None and
                         lat > self.slow_after)
        with self._health_lock:
            self._health_cache = dict(h)
            self._health_time = time.monotonic()
            self._down_until = 0.0
        return h

    @property
    def generation(self) -> int:
        return int(self.health().get("generation", 0))

    # -- request path --------------------------------------------------------

    def submit(self, feed: Dict[str, Any],
               deadline: Optional[float] = None) -> RemotePending:
        """Ship one request over the wire. The span is minted HERE (the
        front door) and rides the header's trace token, so the replica
        journals the same trace id. Never resends: a reply lost after
        the header left the socket is classified at-most-once."""
        span = self.journal.new_span()
        meta, payload = pack_tree(feed, wire=self.feed_wire)
        dl = "-" if deadline is None else repr(float(deadline))
        # retry: at-most-once
        header = (f"SUBMIT {len(meta)} {len(payload)} {dl} "
                  f"trace={span}").encode() + b"\n"
        budget = self.connect_timeout
        if deadline is not None:
            budget = max(1e-3, min(budget, deadline))
        try:
            sock = socket.create_connection(self.addr, timeout=budget)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError as e:
            raise ServerClosed(
                f"replica at {self.addr} unreachable at submit "
                f"({e}); nothing was sent") from e
        self.journal.emit("fleet.remote_submit", span=span,
                          replica=self.name or f"{self.addr[0]}:"
                                               f"{self.addr[1]}",
                          deadline_s=deadline)
        sent = False
        try:
            sock.settimeout(self.submit_timeout if deadline is None
                            else min(self.submit_timeout, deadline + 1.0))
            sock.sendall(header + meta + payload)
            sent = True
            resp = read_line(sock)
            parts = resp.split()
            if parts[0] == "ERR":
                body = read_exact(sock, int(parts[2]))
                sock.close()
                raise build_remote_error(parts[1],
                                         json.loads(body or b"{}"))
            return RemotePending(self, sock, parts[1], span)
        except (OSError, ConnectionError) as e:
            try:
                sock.close()
            except OSError:
                pass
            if not sent:
                raise ServerClosed(
                    f"could not send to replica at {self.addr} ({e}); "
                    "nothing was sent") from e
            if self._provably_dead():
                # the process died with the submit un-acked: whatever
                # it did died unobserved with it — safe to reroute
                raise ServerClosed(
                    f"replica process at {self.addr} died before "
                    f"acknowledging the submit ({e}); safe to "
                    "resubmit") from e
            raise ReplicaDied(
                f"submit reply from {self.addr} lost after send ({e}); "
                "at-most-once — the request may be executing, surfaced "
                "once, never resent") from e

    def run(self, feed: Dict[str, Any], timeout: Optional[float] = None):
        return self.submit(feed, deadline=timeout).result(timeout)

    # -- control plane -------------------------------------------------------

    def _one_shot(self, line: str, payload: bytes,
                  timeout: float, idempotent: bool = False):
        """A control call on its OWN connection (RELOAD may run for
        minutes; health probes on the persistent connection must not
        queue behind it)."""
        cli = _ControlClient(self.addr, timeout=timeout, retries=2,
                             retry_backoff=0.05, connect=False)
        try:
            return cli.call(line, payload, idempotent=idempotent,
                            timeout=timeout)
        finally:
            cli.close()

    def reload(self, dirname: str, block: bool = True):
        """Hot reload the replica's served artifact (``dirname`` must
        be reachable from the replica process — same host or shared
        filesystem). Typed failures (``ReloadFailed``,
        ``CheckpointCorrupt``) re-raise exactly; a reply lost after
        send raises :class:`~paddle_tpu.parallel.async_ps.ReplyLost`
        (a ``ConnectionError``) — the replica MAY have swapped, which
        the router's rollback treats as swapped-unknown.

        With ``ship_artifacts`` the dir is streamed over
        FETCH/ARTIFACT first (content-addressed: an artifact the
        replica's host already holds is a zero-byte negotiation) and
        RELOAD points at the replica-side committed copy; a mid-ship
        partition raises connection-shaped errors, which the router's
        canary/rollback machinery converts to a typed ``ReloadFailed``
        — and the receiver's atomic commit means there is never a
        half-written artifact dir to roll back."""
        try:
            if self.ship_artifacts:
                dirname = ship_artifact(self.addr, dirname,
                                        timeout=self.reload_timeout)
            body = json.dumps({"dirname": dirname}).encode()
            return self._one_shot(f"RELOAD {len(body)}", body,
                                  timeout=self.reload_timeout)
        finally:
            # success bumped the generation; a lost reply left it
            # UNKNOWN; a failed artifact ship means the link itself is
            # suspect — in every case the cached health snapshot is
            # stale (and a router rollback's next probe must be real,
            # else a long health_ttl keeps routing to a replica whose
            # wire just proved unreachable)
            with self._health_lock:
                self._health_cache = None

    def kill(self, reason: str = "replica killed") -> None:
        """Terminate the replica process (the remote analog of
        ``PredictorServer.kill``): best-effort KILL verb (the replica
        fails in-flight work with the typed at-most-once outcomes and
        exits), then SIGKILL of the owned child. Idempotent."""
        if self._killed:
            return
        self._killed = True
        body = json.dumps({"reason": reason}).encode()
        try:
            self._one_shot(f"KILL {len(body)}", body, timeout=2.0)
        except Exception:
            pass
        if self.proc is not None:
            self.proc.stop()
        if self.agent is not None and self.pid is not None:
            try:
                self.agent.stop(self.pid)
            except Exception:
                pass

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Graceful shutdown: the replica drains (or fails queued work
        typed) and exits; the owned child is reaped, SIGKILL as the
        backstop."""
        if self._killed:
            return
        body = json.dumps({"drain": bool(drain),
                           "timeout": timeout}).encode()
        try:
            self._one_shot(f"SHUTDOWN {len(body)}", body,
                           timeout=(timeout or 30.0) + 15.0)
        except Exception:
            pass
        self._killed = True
        if self.proc is not None:
            try:
                self.proc.wait(timeout=10.0)
            except Exception:
                self.proc.stop()
        if self.agent is not None and self.pid is not None:
            try:
                self.agent.stop(self.pid)
            except Exception:
                pass
        self._ctl.close()

    # -- observability -------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        with self._ctl_lock:
            return self._ctl.call("REPORT", timeout=self.probe_timeout * 5)

    def telemetry_families(self):
        """The replica's full registry export, shipped as a snapshot
        over the control link and rebuilt as families — what the
        router's ``merge_exports`` aggregation consumes, exactly as it
        would an in-process replica's."""
        from ..telemetry.registry import families_from_snapshot

        with self._ctl_lock:
            snap = self._ctl.call("METRICS", timeout=self.probe_timeout * 5)
        return families_from_snapshot(snap or {})

    def journal_events(self, since_seq: int = 0) -> List[Dict[str, Any]]:
        """The replica's retained journal ring (events with ``seq`` >
        ``since_seq``) — the pull half of off-host span shipping; feed
        it to ``RunJournal.ingest`` (``FleetRouter.ship_journals`` does
        both ends)."""
        with self._ctl_lock:
            out = self._ctl.call(f"JOURNAL {int(since_seq)}",
                                 timeout=self.probe_timeout * 5)
        return list((out or {}).get("events", []))

    def repin_compiles(self) -> None:
        """No-op: the AOT compile counter is per-process, and a fleet
        sibling's load happens in a DIFFERENT process — nothing to
        re-pin here (the in-process hazard this guards against cannot
        occur across a process boundary)."""

    def __repr__(self) -> str:
        return (f"RemoteReplica({self.addr[0]}:{self.addr[1]}, "
                f"pid={self.pid if self.pid is not None else '?'})")


# -- the per-host agent, client side ------------------------------------------


def encode_server_kw(kw: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe ``server_kw`` for the SPAWN body: dataclass policies
    become dicts and the golden feed rides as base64 npz bytes — the
    agent's host has no shared filesystem to read an npz path from."""
    import base64
    import io as _io

    kw = dict(kw)
    for key in ("batch_policy", "breaker"):
        v = kw.get(key)
        if v is not None and dataclasses.is_dataclass(v):
            kw[key] = dataclasses.asdict(v)
    golden = kw.pop("golden_feed", None)
    if golden is not None:
        buf = _io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in golden.items()})
        kw["golden_feed_npz"] = base64.b64encode(buf.getvalue()).decode()
    return kw


class AgentClient:
    """Client for one per-host fleet agent (``python -m
    paddle_tpu.fleet.agent``): SPAWN/STOP/PS over the framed wire plus
    the same FETCH/ARTIFACT artifact door every replica has — ship an
    artifact to a host once, spawn any number of replicas over it.
    ``ps()`` doubles as the death oracle :meth:`RemoteReplica.
    _provably_dead` consults for agent-managed replicas."""

    def __init__(self, addr, timeout: float = 30.0):
        self.addr = parse_hostport(addr)
        self._timeout = timeout
        self._cli = _ControlClient(self.addr, timeout=timeout, retries=3,
                                   retry_backoff=0.05, connect=False)
        self._lock = threading.Lock()

    def ship(self, dirname: str, timeout: Optional[float] = None) -> str:
        """Push an artifact into the agent's host cache; returns the
        host-side committed path (a no-op when the token is cached)."""
        return ship_artifact(self.addr, dirname,
                             timeout=timeout or max(self._timeout, 600.0))

    def spawn(self, dirname: str, server_kw: Optional[Dict] = None,
              name: Optional[str] = None,
              timeout: float = 600.0) -> Dict[str, Any]:
        """Launch one replica process over a HOST-side artifact dir
        (usually a :meth:`ship` result); blocks until its listener is
        up. At-most-once: a spawn is never blindly resent — a lost
        reply surfaces (the orphan, if any, is visible in ``ps()``)."""
        body = json.dumps({"dirname": dirname, "name": name,
                           "server_kw": encode_server_kw(
                               dict(server_kw or {}))}).encode()
        with self._lock:
            return self._cli.call(f"SPAWN {len(body)}", body,
                                  idempotent=False, timeout=timeout)

    def stop(self, pid: int) -> Dict[str, Any]:
        body = json.dumps({"pid": int(pid)}).encode()
        with self._lock:
            return self._cli.call(f"STOP {len(body)}", body,
                                  timeout=self._timeout)

    def ps(self) -> Dict[str, Any]:
        with self._lock:
            return self._cli.call("PS", timeout=self._timeout)

    def close(self) -> None:
        try:
            self._cli.close()
        except Exception:
            pass

    def __repr__(self) -> str:
        return f"AgentClient({self.addr[0]}:{self.addr[1]})"


def adopt_replica(agent: AgentClient, dirname: str, name: str,
                  remote_kw: Optional[Dict[str, Any]] = None,
                  link=None, **server_kw) -> RemoteReplica:
    """Ship ``dirname`` into ``agent``'s host cache (content-addressed
    no-op when already there), SPAWN a replica over the host-side
    copy, and wrap it in a :class:`RemoteReplica` that uses the agent
    as its death oracle and ships artifacts on reload. ``link``
    optionally maps the replica's advertised addr (tests route every
    cross-"host" connection through a ``LinkProxy``)."""
    path = agent.ship(dirname)
    info = agent.spawn(path, server_kw=server_kw, name=name)
    addr = (str(info["addr"][0]), int(info["addr"][1]))
    if link is not None:
        addr = tuple(link(addr))
    kw = dict(remote_kw or {})
    kw.setdefault("name", name)
    return RemoteReplica(addr, proc=None, agent=agent,
                         pid=int(info["pid"]), ship_artifacts=True,
                         num_workers=int(server_kw.get("workers", 2)),
                         **kw)


def spawn_host_fleet(dirname: str, hosts, replicas: int = 2,
                     remote_kw: Optional[Dict[str, Any]] = None,
                     link=None, **server_kw):
    """Adopt ``replicas`` agent-managed replicas round-robin across
    ``hosts`` (each a ``host:port`` fleet agent). Returns ``(agents,
    {name: RemoteReplica})`` — the router keeps the agents for
    ``replace()`` respawns after a host dies."""
    agents = [a if isinstance(a, AgentClient) else AgentClient(a)
              for a in hosts]
    out: Dict[str, RemoteReplica] = {}
    try:
        for i in range(int(replicas)):
            out[f"r{i}"] = adopt_replica(
                agents[i % len(agents)], dirname, f"r{i}",
                remote_kw=remote_kw, link=link, **server_kw)
    except BaseException:
        for rep in out.values():
            try:
                rep.kill()
            except Exception:
                pass
        for a in agents:
            a.close()
        raise
    return agents, out


# -- spawning -----------------------------------------------------------------


def spawn_replica(dirname: str, remote_kw: Optional[Dict[str, Any]] = None,
                  **server_kw) -> RemoteReplica:
    """Launch ONE out-of-process replica over ``dirname`` and return
    its ready proxy. ``server_kw`` is the ``PredictorServer`` config
    (workers, queue_size, batch_policy, golden_feed, ...) shipped to
    the child; ``remote_kw`` tunes the client proxy (probe_timeout,
    slow_after, submit_timeout, ...)."""
    proc = ReplicaProcess(dirname, server_kw=server_kw)
    proc.wait_ready()
    return RemoteReplica(proc.addr, proc=proc,
                         num_workers=int(server_kw.get("workers", 2)),
                         **(remote_kw or {}))


def spawn_fleet(dirname: str, replicas: int = 2,
                remote_kw: Optional[Dict[str, Any]] = None,
                **server_kw) -> Dict[str, RemoteReplica]:
    """Launch N replica processes CONCURRENTLY (each pays its own
    artifact load + per-bucket AOT compile; starting them all before
    awaiting any overlaps that) and return ``{name: RemoteReplica}``
    for ``FleetRouter`` adoption."""
    procs = [ReplicaProcess(dirname, server_kw=server_kw)
             for _ in range(int(replicas))]
    out: Dict[str, RemoteReplica] = {}
    try:
        for i, proc in enumerate(procs):
            proc.wait_ready()
            out[f"r{i}"] = RemoteReplica(
                proc.addr, proc=proc, name=f"r{i}",
                num_workers=int(server_kw.get("workers", 2)),
                **(remote_kw or {}))
    except BaseException:
        for proc in procs:
            proc.stop()
        raise
    return out


__all__ = [
    "AgentClient", "ArtifactStore", "RemotePending", "RemoteReplica",
    "ReplicaProcess", "adopt_replica", "build_remote_error",
    "encode_server_kw", "error_payload", "pack_tree", "parse_hostport",
    "ship_artifact", "spawn_fleet", "spawn_host_fleet", "spawn_replica",
    "unpack_tree",
]
