"""DataFeeder + device prefetch.

Analog of python/paddle/fluid/data_feeder.py (DataFeeder.feed:167 —
converts a list of per-sample tuples into batched dense arrays) and of
the py_reader/double_buffer device pipeline (operators/reader/
buffered_reader.cc, layers/io.py:478): ``DeviceFeeder`` runs the host
reader in a background thread and keeps N batches in flight on device so
host→HBM transfer overlaps with compute.
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core.dtypes import convert_dtype


class DataFeeder:
    """Convert reader samples (tuples) into a named feed dict of batched
    numpy arrays (DataFeeder.feed analog, data_feeder.py:167)."""

    def __init__(self, feed_list: Sequence[str], dtypes: Optional[Sequence[Any]] = None):
        self.feed_list = list(feed_list)
        self.dtypes = list(dtypes) if dtypes is not None else [None] * len(self.feed_list)

    def feed(self, samples: Sequence[Tuple]) -> Dict[str, np.ndarray]:
        cols = list(zip(*samples))
        if len(cols) != len(self.feed_list):
            raise ValueError(
                f"sample arity {len(cols)} != feed_list arity {len(self.feed_list)}")
        out = {}
        for name, dt, col in zip(self.feed_list, self.dtypes, cols):
            arr = np.stack([np.asarray(v) for v in col])
            if dt is not None:
                arr = arr.astype(np.dtype(convert_dtype(dt).name))
            out[name] = arr
        return out


class DeviceFeeder:
    """Double-buffered host→device prefetch (py_reader + double_buffer
    analog). Wraps an iterator of feed dicts; `__iter__` yields dicts of
    on-device arrays while the next batches transfer in the background."""

    def __init__(self, batches: Callable[[], Iterator[Dict[str, np.ndarray]]],
                 put_fn: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, jax.Array]]] = None,
                 capacity: int = 2):
        self.batches = batches
        self.put_fn = put_fn or (lambda d: jax.device_put(d))
        self.capacity = capacity

    def __iter__(self):
        q: _queue.Queue = _queue.Queue(maxsize=self.capacity)
        END = object()
        err: List[BaseException] = []

        def fill():
            try:
                for b in self.batches():
                    q.put(self.put_fn(b))
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                q.put(END)

        threading.Thread(target=fill, daemon=True).start()
        while True:
            item = q.get()
            if item is END:
                if err:
                    raise err[0]
                return
            yield item
