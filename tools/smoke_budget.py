"""Smoke-tier budget checker (VERDICT r4 #9): the tier must stay under
its wall-clock budget and no single smoke test may exceed the per-test
cap — otherwise it silently drifts back past the 10-minute goal the
way rounds 3→4 showed.

    python -m pytest tests/ -m "not slow" -q     # writes the record
    python tools/smoke_budget.py                 # checks it

Reads tests/.last_run_durations.json (written by the conftest
pytest_terminal_summary hook on any ≥100-test run) and exits non-zero
when the budget is violated, printing the offenders to demote with
@pytest.mark.slow.

Both budgets are on SUMMED per-test call seconds — the serial cost of
the tier, which is what drifts as tests accumulate and equals wall
time on the 1-core build host (parallel CI runners finish sooner but
the serial cost is still the thing to keep bounded). A record from a
partial tier run (aborted, or a file subset) is refused via the
MIN_TESTS floor rather than silently passing the wrong data.
"""

from __future__ import annotations

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECORD = os.path.join(ROOT, "tests", ".last_run_durations.json")

PER_TEST_CAP_S = 20.0
TIER_BUDGET_S = 900.0  # summed call seconds (~wall on the 1-core host)
MIN_TESTS = 600        # the tier is ~680 tests; fewer = partial record


def main():
    if not os.path.exists(RECORD):
        print(f"no record at {RECORD} — run the smoke tier first "
              "(python -m pytest tests/ -m 'not slow' -q)")
        return 2
    rec = json.load(open(RECORD))
    if "not slow" not in rec.get("markexpr", ""):
        print(f"last recorded run used markexpr={rec.get('markexpr')!r}, "
              "not the smoke tier — re-run with -m 'not slow'")
        return 2
    if rec.get("num_tests", 0) < MIN_TESTS:
        print(f"record holds only {rec.get('num_tests')} tests "
              f"(< {MIN_TESTS}) — a partial/aborted run; re-run the full "
              "tier (python -m pytest tests/ -m 'not slow' -q)")
        return 2
    over = {k: v for k, v in rec["durations"].items() if v > PER_TEST_CAP_S}
    total = rec["total_s"]
    print(f"smoke tier: {rec['num_tests']} tests, {total:.0f}s summed call "
          f"time (budget {TIER_BUDGET_S:.0f}s), "
          f"{len(over)} over the {PER_TEST_CAP_S:.0f}s per-test cap")
    rc = 0
    for k, v in sorted(over.items(), key=lambda kv: -kv[1]):
        print(f"  DEMOTE to @pytest.mark.slow: {v:7.1f}s  {k}")
        rc = 1
    if total > TIER_BUDGET_S:
        print(f"  TIER OVER BUDGET by {total - TIER_BUDGET_S:.0f}s — demote "
              "the slowest tests above or split compile-heavy cases")
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
