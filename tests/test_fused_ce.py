"""Chunked (logits-free) softmax CE (ops/fused_ce.py): value+grad
equivalence vs the dense path, with and without label smoothing, plus
the transformer integration flag."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.fused_ce import chunked_softmax_cross_entropy


@pytest.mark.parametrize("eps", [0.0, 0.1])
@pytest.mark.parametrize("with_bias", [True, False])
def test_fused_ce_matches_dense(eps, with_bias):
    rng = np.random.RandomState(0)
    n, d, v = 12, 16, 50           # v=50 with chunk=16 -> ragged last chunk
    h = jnp.asarray(rng.randn(n, d).astype(np.float32))
    w = jnp.asarray(rng.randn(d, v).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(v).astype(np.float32) * 0.1) if with_bias else None
    lab = jnp.asarray(rng.randint(0, v, n))

    def dense(h, w, b):
        logits = h @ w + (b if b is not None else 0.0)
        logp = jax.nn.log_softmax(logits, -1)
        nll = -jnp.take_along_axis(logp, lab[:, None], 1)[:, 0]
        return ((1 - eps) * nll - eps * jnp.mean(logp, -1)).sum()

    def fused(h, w, b):
        return chunked_softmax_cross_entropy(h, w, b, lab, eps, 16).sum()

    argnums = (0, 1, 2) if with_bias else (0, 1)
    v1, g1 = jax.value_and_grad(dense, argnums)(h, w, b)
    v2, g2 = jax.value_and_grad(fused, argnums)(h, w, b)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    for a, bb in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb), rtol=2e-4, atol=1e-5)


def test_fused_ce_bf16_inputs_close_to_f32():
    rng = np.random.RandomState(1)
    n, d, v = 8, 16, 32
    h = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, v).astype(np.float32) * 0.1
    lab = jnp.asarray(rng.randint(0, v, n))
    f32 = chunked_softmax_cross_entropy(jnp.asarray(h), jnp.asarray(w), None, lab, 0.0, 16)
    bf = chunked_softmax_cross_entropy(jnp.asarray(h, jnp.bfloat16),
                                       jnp.asarray(w, jnp.bfloat16), None, lab, 0.0, 16)
    assert bf.dtype == jnp.float32  # loss always reduces in f32
    np.testing.assert_allclose(np.asarray(f32), np.asarray(bf), rtol=0.05, atol=0.05)


@pytest.mark.slow
def test_transformer_fused_ce_equals_dense():
    rng = np.random.RandomState(0)
    from paddle_tpu.models import transformer
    feed = {"src_ids": rng.randint(3, 64, (2, 8)).astype(np.int64),
            "trg_ids": rng.randint(3, 64, (2, 8)).astype(np.int64),
            "labels": rng.randint(0, 64, (2, 8)).astype(np.int64)}
    losses, grads = {}, {}
    for fused in (False, True):
        cfg = transformer.base_config(
            src_vocab=64, trg_vocab=64, d_model=16, d_inner=32, num_heads=2,
            num_encoder_layers=1, num_decoder_layers=1, dropout=0.0,
            fused_ce=fused, ce_chunk=16)
        prog = pt.build(transformer.make_model(cfg))
        params, state = prog.init(jax.random.PRNGKey(0), **feed)

        def loss_fn(p):
            out, _ = prog.apply(p, state, **feed)
            return out["loss"]

        losses[fused], grads[fused] = jax.value_and_grad(loss_fn)(params)
    np.testing.assert_allclose(float(losses[False]), float(losses[True]), rtol=1e-5)
    for k in grads[False]:
        np.testing.assert_allclose(np.asarray(grads[False][k]),
                                   np.asarray(grads[True][k]), rtol=5e-4, atol=1e-6)
