"""Model zoo mirroring the reference's book/benchmark configs
(BASELINE.json: MNIST MLP, ResNet-50, Transformer-base, DeepFM,
BERT-base; plus VGG/AlexNet/GoogLeNet/LSTM from benchmark/fluid/models/
and the recommender_system / label_semantic_roles book chapters)."""

from . import bert, convnets, deepfm, fit_a_line, lstm, mnist, recommender, resnet, seq2seq, srl, transformer, vgg, word2vec

__all__ = ["bert", "convnets", "deepfm", "fit_a_line", "lstm", "mnist", "recommender",
           "resnet", "seq2seq", "srl", "transformer", "vgg", "word2vec"]
