"""Runnable multi-process PIPELINE-PARALLEL trainer: transformer
stages split ACROSS processes — the multi-host pipeline shape (stage
boundary activations hop the DCN-analog link each microbatch).

    python dist_pp_runner.py <proc_id> <nprocs> <port> <steps> \
        [dropout] [samemesh]

Each process owns 2 virtual devices; the mesh is {"dp": 2,
"pp": nprocs} with the pp axis laid across processes, so every
stage-to-stage transfer crosses the process boundary while dp rides
inside each process. With nprocs=1 the same script (single device, no
mesh) is the reference. With nprocs=1 and samemesh=1 it instead builds
the SAME {"pp": 2, "dp": 2} mesh on 4 local devices — the reference
for dropout runs, where per-step parity requires identical mesh
positions (the pipeline folds rng per (layer, microbatch, data-shard),
so only an identical global mesh draws identical masks). Prints
`LOSS <step> <value>` per step.
"""

import os
import sys

pid, nprocs, port, steps = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                            int(sys.argv[4]))
dropout = float(sys.argv[5]) if len(sys.argv) > 5 else 0.0
samemesh = len(sys.argv) > 6 and sys.argv[6] == "1"
local_devices = 2 if nprocs > 1 else (4 if samemesh else 1)
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append(f"--xla_force_host_platform_device_count={local_devices}")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax

jax.config.update("jax_platforms", "cpu")

if nprocs > 1:
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nprocs, process_id=pid)

import numpy as np

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.models import transformer
from paddle_tpu.parallel import DistStrategy, transformer_tp_rules

VOCAB, SEQ = 64, 12


def batch(step, bs=8):
    rng = np.random.RandomState(700 + step)
    src = rng.randint(3, VOCAB, (bs, SEQ)).astype(np.int32)
    trg = np.roll(src, 1, axis=1)
    trg[:, 0] = 1
    labels = np.concatenate([trg[:, 1:], np.full((bs, 1), 2)],
                            axis=1).astype(np.int32)
    return {"src_ids": src, "trg_ids": trg, "labels": labels}


def main():
    cfg = transformer.base_config(src_vocab=VOCAB, trg_vocab=VOCAB,
                                  d_model=32, d_inner=64, num_heads=4,
                                  num_encoder_layers=4, num_decoder_layers=4,
                                  dropout=dropout, stacked=True)
    prog = pt.build(transformer.make_model(cfg))
    if nprocs > 1 or samemesh:
        # pp OUTERMOST so its axis spans processes; dp lives inside each
        # process (mesh axes are laid out major-to-minor over devices)
        mesh = pt.make_mesh({"pp": 2 if samemesh else nprocs,
                             "dp": 2 if samemesh else local_devices})
        trainer = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss",
                             mesh=mesh,
                             sharding_rules=transformer_tp_rules(),
                             strategy=DistStrategy(pp_microbatches=2))
    else:
        trainer = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss")
    trainer.startup(rng=jax.random.PRNGKey(3), sample_feed=batch(0))
    for s in range(steps):
        out = trainer.step(batch(s), rng=jax.random.PRNGKey(300 + s))
        print(f"LOSS {s} {float(out['loss']):.6f}", flush=True)


if __name__ == "__main__":
    main()
