"""Place / device abstraction.

TPU-native analog of the reference's Place variant (platform/place.h:
CPUPlace/CUDAPlace/CUDAPinnedPlace) and DeviceContextPool
(platform/device_context.h:264). In JAX, devices are first-class and
streams/handles are managed by the runtime, so a Place reduces to a
device handle (or a set of them, for SPMD execution over a mesh — see
paddle_tpu.parallel.mesh for the multi-device story that replaces the
reference's ParallelExecutor places list).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax


@dataclasses.dataclass(frozen=True)
class Place:
    """Device identity. platform/place.h analog."""

    platform: str  # 'tpu' | 'cpu' | 'gpu'
    device_id: int = 0

    def device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _platform_of(d) == self.platform]
        if not devs:
            # Fall back to the default backend (e.g. tests forcing cpu).
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def __repr__(self) -> str:  # mirrors e.g. "CUDAPlace(0)"
        return f"{self.platform.upper()}Place({self.device_id})"


def _platform_of(d: jax.Device) -> str:
    p = d.platform
    # The axon transport exposes TPUs under an experimental platform name.
    if "tpu" in str(getattr(d, "device_kind", "")).lower():
        return "tpu"
    return p


def CPUPlace(device_id: int = 0) -> Place:
    return Place("cpu", device_id)


def TPUPlace(device_id: int = 0) -> Place:
    return Place("tpu", device_id)


def CUDAPlace(device_id: int = 0) -> Place:  # API parity; resolves to gpu
    return Place("gpu", device_id)


def default_place() -> Place:
    """Best available place: TPU > GPU > CPU (InitDevices analog)."""
    d = jax.devices()[0]
    return Place(_platform_of(d), 0)


def available_places(platform: Optional[str] = None) -> List[Place]:
    out = []
    for i, d in enumerate(jax.devices()):
        p = _platform_of(d)
        if platform is None or p == platform:
            out.append(Place(p, i))
    return out


def device_count() -> int:
    return jax.device_count()
