"""CLI: lint a zoo model's program before it ever compiles.

    python -m paddle_tpu.analysis --model mnist
    python -m paddle_tpu.analysis --model moe_transformer --amp bfloat16 \
        --mesh fsdp=8 --rules fsdp --fail-on warning --format json

Exit status: 0 when the report is clean at ``--fail-on`` (default
``warning``), 1 otherwise — CI-greppable like any linter.
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_mesh(spec: str):
    from ..parallel import make_mesh
    axes = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        axes[k.strip()] = int(v)
    return make_mesh(axes)


def _parse_rules(name: str):
    from ..parallel import fsdp, replicated, transformer_tp_rules
    table = {"replicated": replicated, "fsdp": fsdp,
             "tp": transformer_tp_rules}
    if name not in table:
        raise SystemExit(f"--rules must be one of {sorted(table)}")
    return table[name]()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="static jaxpr-level lint of a model-zoo program")
    ap.add_argument("--model", required=True,
                    help="zoo model: mnist | transformer | moe_transformer | gpt")
    ap.add_argument("--variant", default="",
                    help="model variant (mnist: mlp|conv)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--mesh", default="",
                    help='mesh axes, e.g. "dp=4,tp=2" (needs that many devices)')
    ap.add_argument("--rules", default="",
                    help="sharding preset: replicated | fsdp | tp")
    ap.add_argument("--amp", default="",
                    help="lint under this compute dtype (e.g. bfloat16)")
    ap.add_argument("--loss-name", default="loss")
    ap.add_argument("--select", default="",
                    help="comma-list restricting rule families, e.g. "
                         '"pipeline,collective" (default: all)')
    ap.add_argument("--pp-microbatches", type=int, default=0,
                    help="lint this pipeline schedule shape "
                         "(pipeline:* family) against --batch / --mesh")
    ap.add_argument("--pp-interleave", type=int, default=1)
    ap.add_argument("--fail-on", default="warning",
                    choices=("info", "warning", "error"),
                    help="exit 1 when findings at/above this severity exist")
    ap.add_argument("--level", default="info",
                    choices=("info", "warning", "error"),
                    help="minimum severity to print")
    ap.add_argument("--format", default="text", choices=("text", "json"))
    args = ap.parse_args(argv)

    from . import check
    from .zoo import build_model

    program, feed = build_model(args.model, args.variant, args.batch, args.seq)
    mesh = _parse_mesh(args.mesh) if args.mesh else None
    rules = _parse_rules(args.rules) if args.rules else None
    strategy = None
    if args.pp_microbatches:
        from ..parallel import DistStrategy
        strategy = DistStrategy(pp_microbatches=args.pp_microbatches,
                                pp_interleave=args.pp_interleave)
    select = {s.strip() for s in args.select.split(",") if s.strip()} or None
    report = check(program, feed, mesh=mesh, rules=rules, strategy=strategy,
                   amp=args.amp or None, loss_name=args.loss_name,
                   select=select)
    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=1, default=str))
    else:
        print(report.render(args.level))
    return 0 if report.ok(args.fail_on) else 1


if __name__ == "__main__":
    sys.exit(main())
