"""Optimizer tests vs hand-computed references — the
test_sgd_op/test_adam_op/... family analog."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import clip as pclip
from paddle_tpu import lr_scheduler as lrs
from paddle_tpu import optimizer as opt
from paddle_tpu import regularizer as reg
from paddle_tpu.framework import ParamInfo


def _one_param(val=None):
    p = {"w": jnp.asarray(val if val is not None else np.array([1.0, -2.0, 3.0], np.float32))}
    g = {"w": jnp.asarray(np.array([0.1, 0.2, -0.3], np.float32))}
    return p, g


def test_sgd():
    p, g = _one_param()
    o = opt.SGD(0.1)
    s = o.init(p)
    p2, s2 = o.update(g, s, p)
    np.testing.assert_allclose(np.asarray(p2["w"]), [1 - 0.01, -2 - 0.02, 3 + 0.03], rtol=1e-6)
    assert int(s2["step"]) == 1


def test_momentum_matches_reference_formula():
    p, g = _one_param()
    o = opt.Momentum(0.1, momentum=0.9)
    s = o.init(p)
    p1, s1 = o.update(g, s, p)
    p2, s2 = o.update(g, s1, p1)
    # velocity_1 = g; velocity_2 = 0.9 g + g
    v2 = 0.9 * np.asarray(g["w"]) + np.asarray(g["w"])
    want = np.asarray(p1["w"]) - 0.1 * v2
    np.testing.assert_allclose(np.asarray(p2["w"]), want, rtol=1e-6)


def test_momentum_nesterov():
    p, g = _one_param()
    o = opt.Momentum(0.1, momentum=0.9, use_nesterov=True)
    s = o.init(p)
    p1, _ = o.update(g, s, p)
    gw = np.asarray(g["w"])
    want = np.asarray(p["w"]) - 0.1 * (gw + 0.9 * gw)
    np.testing.assert_allclose(np.asarray(p1["w"]), want, rtol=1e-6)


def test_adagrad():
    p, g = _one_param()
    o = opt.Adagrad(0.5, epsilon=1e-6)
    s = o.init(p)
    p1, _ = o.update(g, s, p)
    gw = np.asarray(g["w"])
    want = np.asarray(p["w"]) - 0.5 * gw / (np.sqrt(gw * gw) + 1e-6)
    np.testing.assert_allclose(np.asarray(p1["w"]), want, rtol=1e-5)


def test_adam_bias_correction_first_step():
    p, g = _one_param()
    o = opt.Adam(0.001, beta1=0.9, beta2=0.999, epsilon=1e-8)
    s = o.init(p)
    p1, s1 = o.update(g, s, p)
    gw = np.asarray(g["w"])
    m1 = 0.1 * gw
    m2 = 0.001 * gw * gw
    lr_t = 0.001 * np.sqrt(1 - 0.999) / (1 - 0.9)
    want = np.asarray(p["w"]) - lr_t * m1 / (np.sqrt(m2) + 1e-8)
    np.testing.assert_allclose(np.asarray(p1["w"]), want, rtol=1e-5)


def test_adam_converges_quadratic():
    # Optimize f(w) = ||w - t||^2 — convergence sanity for the suite.
    target = jnp.asarray([1.0, -0.5, 2.0])
    params = {"w": jnp.zeros(3)}
    # LAMB's trust ratio keeps |step| ∝ |param|, so it needs LR decay to
    # settle — give it the schedule it's designed for.
    lamb_lr = lrs.polynomial_decay(0.1, 300, end_learning_rate=1e-4)
    for Opt, lr, kw in [(opt.Adam, 0.1, {}), (opt.RMSProp, 0.05, {}),
                        (opt.Adadelta, 5.0, {}), (opt.Adamax, 0.2, {}),
                        (opt.Lamb, lamb_lr, {"lamb_weight_decay": 0.0})]:
        o = Opt(lr, **kw)
        s = o.init(params)
        p = dict(params)
        for _ in range(300):
            grads = {"w": 2 * (p["w"] - target)}
            p, s = o.update(grads, s, p)
        np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(target), atol=0.05,
                                   err_msg=f"{Opt.__name__} failed to converge")


def test_rmsprop_centered_and_ftrl_run():
    p, g = _one_param()
    for o in [opt.RMSProp(0.01, centered=True, momentum=0.9),
              opt.Ftrl(0.1, l1=0.01, l2=0.01),
              opt.DecayedAdagrad(0.01), opt.LarsMomentum(0.01)]:
        s = o.init(p)
        p1, s1 = o.update(g, s, p)
        assert np.all(np.isfinite(np.asarray(p1["w"])))


def test_l2_regularization_applied():
    p, g = _one_param()
    o = opt.SGD(1.0, regularization=reg.L2Decay(0.1))
    s = o.init(p)
    p1, _ = o.update(g, s, p)
    gw = np.asarray(g["w"]) + 0.1 * np.asarray(p["w"])
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p["w"]) - gw, rtol=1e-6)


def test_param_attr_regularizer_overrides_global():
    p, g = _one_param()
    info = {"w": ParamInfo(shape=(3,), dtype=jnp.float32, regularizer=reg.L2Decay(0.5))}
    o = opt.SGD(1.0, regularization=reg.L2Decay(0.1))
    s = o.init(p)
    p1, _ = o.update(g, s, p, info)
    gw = np.asarray(g["w"]) + 0.5 * np.asarray(p["w"])
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p["w"]) - gw, rtol=1e-6)


def test_grad_clip_by_global_norm():
    p = {"a": jnp.asarray([3.0]), "b": jnp.asarray([4.0])}
    g = {"a": jnp.asarray([3.0]), "b": jnp.asarray([4.0])}  # global norm 5
    o = opt.SGD(1.0, grad_clip=pclip.GradientClipByGlobalNorm(1.0))
    s = o.init(p)
    p1, _ = o.update(g, s, p)
    # grads scaled by 1/5
    np.testing.assert_allclose(np.asarray(p1["a"]), [3.0 - 0.6], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(p1["b"]), [4.0 - 0.8], rtol=1e-5)


def test_grad_clip_by_value():
    p, g = _one_param()
    o = opt.SGD(1.0, grad_clip=pclip.GradientClipByValue(0.15))
    s = o.init(p)
    p1, _ = o.update(g, s, p)
    want = np.asarray(p["w"]) - np.clip(np.asarray(g["w"]), -0.15, 0.15)
    np.testing.assert_allclose(np.asarray(p1["w"]), want, rtol=1e-6)


def test_non_trainable_param_frozen():
    p, g = _one_param()
    info = {"w": ParamInfo(shape=(3,), dtype=jnp.float32, trainable=False)}
    o = opt.SGD(0.1)
    s = o.init(p)
    p1, _ = o.update(g, s, p, info)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p["w"]))


def test_per_param_lr_multiplier():
    p, g = _one_param()
    info = {"w": ParamInfo(shape=(3,), dtype=jnp.float32, learning_rate=0.5)}
    o = opt.SGD(0.2)
    s = o.init(p)
    p1, _ = o.update(g, s, p, info)
    np.testing.assert_allclose(np.asarray(p1["w"]),
                               np.asarray(p["w"]) - 0.1 * np.asarray(g["w"]), rtol=1e-6)


def test_lr_schedule_in_optimizer():
    sched = lrs.piecewise_decay([2], [0.1, 0.01])
    p, g = _one_param()
    o = opt.SGD(sched)
    s = o.init(p)
    assert float(o.learning_rate(s["step"])) == pytest.approx(0.1)
    for _ in range(3):
        p, s = o.update(g, s, p)
    assert float(o.learning_rate(s["step"])) == pytest.approx(0.01)


def test_lr_schedules_shapes():
    for sched in [
        lrs.noam_decay(512, 4000), lrs.exponential_decay(0.1, 100, 0.9),
        lrs.natural_exp_decay(0.1, 100, 0.9), lrs.inverse_time_decay(0.1, 100, 0.9),
        lrs.polynomial_decay(0.1, 100), lrs.cosine_decay(0.1, 10, 10),
        lrs.linear_lr_warmup(0.1, 10, 0.0, 0.1),
    ]:
        v0 = float(sched(jnp.asarray(0)))
        v100 = float(sched(jnp.asarray(100)))
        assert np.isfinite(v0) and np.isfinite(v100)


def test_warmup_then_decay():
    sched = lrs.linear_lr_warmup(lrs.exponential_decay(0.1, 10, 0.5, staircase=True),
                                 warmup_steps=5, start_lr=0.0, end_lr=0.1)
    assert float(sched(jnp.asarray(0))) == pytest.approx(0.0)
    assert float(sched(jnp.asarray(4))) == pytest.approx(0.08, abs=1e-6)
    assert float(sched(jnp.asarray(20))) == pytest.approx(0.1 * 0.25)


def test_model_average():
    ma = opt.ModelAverage()
    params = {"w": jnp.asarray([0.0])}
    st = ma.init(params)
    for v in [1.0, 2.0, 3.0]:
        st = ma.accumulate(st, {"w": jnp.asarray([v])})
    avg = ma.average_params(st, params)
    np.testing.assert_allclose(np.asarray(avg["w"]), [2.0], rtol=1e-6)


def test_ema():
    ema = opt.ExponentialMovingAverage(decay=0.5)
    params = {"w": jnp.asarray([0.0])}
    st = ema.init(params)
    st = ema.accumulate(st, {"w": jnp.asarray([2.0])})
    np.testing.assert_allclose(np.asarray(st["w"]), [1.0], rtol=1e-6)


def test_bf16_optimizer_state_trains_close_to_f32():
    """state_dtype=bfloat16 halves Adam-moment storage; update math
    stays f32, so training tracks the f32-state run closely and the
    stored accums really are bf16."""
    import paddle_tpu as pt
    from paddle_tpu import layers as L
    from paddle_tpu.parallel import DistStrategy

    def net(x, label):
        h = L.fc(x, 32, act="relu", name="h")
        loss = L.mean(L.softmax_with_cross_entropy(L.fc(h, 4, name="o"), label))
        return {"loss": loss}

    rng = np.random.RandomState(0)
    one = {"x": rng.randn(16, 8).astype(np.float32),
           "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}
    feeds = [one] * 30  # fixed batch: overfit trajectory comparison

    def train(strategy):
        tr = pt.Trainer(pt.build(net), opt.Adam(5e-3), loss_name="loss",
                        strategy=strategy)
        tr.startup(sample_feed=feeds[0])
        return tr, [float(tr.step(f)["loss"]) for f in feeds]

    _, ref = train(None)
    tr16, got = train(DistStrategy(opt_state_dtype="bfloat16"))
    # moments stored bf16
    accs = tr16.scope.opt_state["accums"]["h/w"]
    assert all(v.dtype == jnp.bfloat16 for v in accs.values()
               if jnp.issubdtype(v.dtype, jnp.floating)), accs
    # training still converges on the same trajectory (bf16 moment
    # rounding perturbs, it must not derail)
    assert got[-1] < got[0] * 0.7
    np.testing.assert_allclose(got[-1], ref[-1], rtol=0.3, atol=0.1)


def test_bf16_optimizer_state_checkpoint_round_trip(tmp_path):
    """bf16 accums survive save_trainer/load_trainer (the npz exotic-
    dtype encoding) with dtype and values intact."""
    import paddle_tpu as pt
    from paddle_tpu import io as pio, layers as L
    from paddle_tpu.parallel import DistStrategy

    def net(x):
        return {"loss": L.mean(L.fc(x, 4, name="w1"))}

    feed = {"x": np.random.RandomState(0).randn(4, 6).astype(np.float32)}
    tr = pt.Trainer(pt.build(net), opt.Adam(1e-3), loss_name="loss",
                    strategy=DistStrategy(opt_state_dtype="bfloat16"))
    tr.startup(sample_feed=feed)
    tr.step(feed)
    d = str(tmp_path / "ck")
    pio.save_trainer(d, tr)

    tr2 = pt.Trainer(pt.build(net), opt.Adam(1e-3), loss_name="loss",
                     strategy=DistStrategy(opt_state_dtype="bfloat16"))
    tr2.startup(sample_feed=feed)
    pio.load_trainer(d, tr2)
    for k, acc in tr.scope.opt_state["accums"].items():
        for name, v in acc.items():
            got = tr2.scope.opt_state["accums"][k][name]
            assert got.dtype == v.dtype, (k, name, got.dtype, v.dtype)
            np.testing.assert_array_equal(np.asarray(got, np.float32),
                                          np.asarray(v, np.float32))
