"""MoE expert parallelism: EP shard_map path vs dense path on the
8-device CPU mesh (multi-place in-process fixture pattern, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.parallel.moe import moe


def _build(mesh, b=8, s=4, d=16, E=8, ff=32, top_k=2, cf=8.0):
    def fn(x):
        out, aux = moe(x, num_experts=E, d_ff=ff, top_k=top_k,
                       capacity_factor=cf, mesh=mesh)
        return {"out": out, "aux": aux}
    return pt.build(fn)


def _input(b=8, s=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(b, s, d).astype(np.float32)


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_ep_matches_dense():
    x = _input()
    dense = _build(None)
    params, _ = dense.init(jax.random.PRNGKey(0), x)

    mesh = pt.make_mesh({"ep": 8})
    ep = _build(mesh)
    out_d, _ = dense.apply(params, {}, x)
    out_e, _ = ep.apply(params, {}, x)
    # ample capacity → no drops → EP and dense agree exactly (the combine
    # is order-independent within an expert)
    np.testing.assert_allclose(np.asarray(out_e["out"]), np.asarray(out_d["out"]),
                               atol=1e-5, rtol=1e-5)
    # aux is per-token-group (GShard semantics): the EP value is the mean of
    # per-device group losses, not the global-batch loss — same scale though
    assert np.isfinite(float(out_e["aux"])) and float(out_e["aux"]) >= 1.0 - 1e-5


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_ep_with_dp_axis():
    x = _input(b=8)
    dense = _build(None)
    params, _ = dense.init(jax.random.PRNGKey(0), x)

    mesh = pt.make_mesh({"dp": 2, "ep": 4})
    ep = _build(mesh)
    out_d, _ = dense.apply(params, {}, x)
    out_e, _ = ep.apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(out_e["out"]), np.asarray(out_d["out"]),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_ep_gradients_match_dense():
    x = _input()
    dense = _build(None)
    params, _ = dense.init(jax.random.PRNGKey(0), x)
    mesh = pt.make_mesh({"ep": 8})
    ep = _build(mesh)

    # loss over out only: the aux term is group-local by design so its
    # router grads differ between groupings
    def loss(prog):
        def f(p):
            out, _ = prog.apply(p, {}, x)
            return jnp.sum(out["out"] ** 2)
        return f

    gd = jax.grad(loss(dense))(params)
    ge = jax.grad(loss(ep))(params)
    for k in gd:
        np.testing.assert_allclose(np.asarray(ge[k]), np.asarray(gd[k]),
                                   atol=1e-4, rtol=1e-4, err_msg=k)


def test_capacity_drops_tokens():
    # capacity_factor → tiny capacity: some tokens dropped, out stays finite,
    # dropped tokens produce zero output rows
    x = _input(b=4, s=4)
    prog = _build(None, b=4, cf=0.25, top_k=1)
    params, _ = prog.init(jax.random.PRNGKey(0), x)
    out, _ = prog.apply(params, {}, x)
    assert np.all(np.isfinite(np.asarray(out["out"])))


def test_aux_loss_balanced_uniform():
    # uniform router (zero weights) → perfectly balanced → aux ≈ 1.0
    x = _input()
    prog = _build(None)
    params, _ = prog.init(jax.random.PRNGKey(0), x)
    params = dict(params)
    for k in params:
        if k.endswith("router_w"):
            params[k] = jnp.zeros_like(params[k])
    out, _ = prog.apply(params, {}, x)
    np.testing.assert_allclose(float(out["aux"]), 1.0, atol=1e-5)
