"""Sharding rules: name-pattern → PartitionSpec.

This is the BuildStrategy/DistributeTranspiler analog collapsed into
data (SURVEY §7): where the reference *rewrote programs* to place
parameters (slice_variable distribute_transpiler.py:81, multi-device
SSA replication multi_devices_graph_pass.cc), we annotate. A
:class:`ShardingRules` maps parameter-name regexes to PartitionSpecs;
XLA's SPMD partitioner inserts the collectives (psum for grads —
AllReduceOpHandle analog; all-gathers for fsdp params — the
param-slicing/broadcast analog).
"""

from __future__ import annotations

import re
import warnings
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

SpecLike = Union[P, Tuple, None]


class ShardingRules:
    """Ordered (regex → PartitionSpec) table for parameters, plus the
    batch-axis spec for inputs.

    Example (transformer TP+FSDP)::

        rules = ShardingRules([
            (r".*/attn_qkv/w", P("fsdp", "tp")),
            (r".*/attn_out/w", P("tp", "fsdp")),
            (r".*/ffn_in/w",  P("fsdp", "tp")),
            (r".*/ffn_out/w", P("tp", "fsdp")),
            (r".*embedding.*/w", P("tp", None)),
        ], default=P())
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, SpecLike]]] = None,
                 default: SpecLike = None,
                 batch_axes: Optional[Sequence[str]] = None,
                 seq_axis: Optional[str] = None):
        self.rules = [(re.compile(pat), _as_spec(spec)) for pat, spec in (rules or [])]
        self.default = _as_spec(default)
        self.batch_axes = tuple(batch_axes) if batch_axes is not None else None
        # opt-in: shard feeds' dim 1 (sequence) over this axis — the
        # input-side of sequence parallelism ([b, s] ids land sharded)
        self.seq_axis = seq_axis

    # ------------------------------------------------------------------
    def adapted_to(self, mesh: Mesh) -> "ShardingRules":
        """Return a copy with axes absent from ``mesh`` removed from
        every spec — the intentional way to run a preset rule table
        (which names the full dp/fsdp/tp/pp/sp/ep axis vocabulary) on a
        smaller mesh. Unlike the ``_validate`` fallback, dropping a
        *canonical* axis here is silent: the caller is declaring the
        mesh, so shedding preset vocabulary is the requested adaptation.
        Dropping a NON-canonical axis still warns — that's a typo in a
        hand-written rule, not preset adaptation. ``Trainer`` and
        ``parallel.api`` apply this automatically; results are memoized
        per mesh axis-set, so per-step callers (put_batch) pay nothing.
        """
        names = tuple(mesh.axis_names)
        if getattr(self, "_adapted_for", None) == names:
            return self
        cache = self.__dict__.setdefault("_adapted_cache", {})
        if names in cache:
            return cache[names]
        nameset = set(names)

        def adapt(spec: P) -> P:
            out = []
            for entry in spec:
                keep, dropped = _filter_axes(entry, nameset)
                for a in dropped:
                    if a not in CANONICAL_AXES:
                        _warn_drop(("adapt-typo", a),
                                   f"adapted_to: rule axis {a!r} is neither in the "
                                   f"mesh {names} nor a canonical axis name "
                                   f"{sorted(CANONICAL_AXES)} — likely a typo; "
                                   f"that dim will be replicated")
                out.append(keep)
            return P(*out)

        adapted = ShardingRules.__new__(type(self))
        adapted.__dict__.update(self.__dict__)
        adapted.rules = [(pat, adapt(spec)) for pat, spec in self.rules]
        adapted.default = adapt(self.default)
        if self.batch_axes is not None:
            adapted.batch_axes = tuple(a for a in self.batch_axes if a in nameset)
        if self.seq_axis is not None and self.seq_axis not in nameset:
            adapted.seq_axis = None
        adapted.__dict__["_adapted_for"] = names
        adapted.__dict__["_adapted_cache"] = {}
        cache[names] = adapted
        return adapted

    # ------------------------------------------------------------------
    def spec_for(self, name: str, shape: Tuple[int, ...], mesh: Mesh) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                return _validate(spec, shape, mesh, name)
        return _validate(self.default, shape, mesh, name)

    def batch_spec(self, mesh: Mesh, ndim: int,
                   shape: Optional[Tuple[int, ...]] = None) -> P:
        axes = self.batch_axes if self.batch_axes is not None else mesh_lib.data_axis_names(mesh)
        axes = tuple(a for a in axes if a in mesh.axis_names and mesh.shape[a] > 1)
        # seq sharding (dim 1) only applies to feeds that look like
        # sequences: without the shape we can't tell, and a [b, 1] label
        # or [b, c, h, w] image must not be sharded on 'sp'
        seq = None
        if (self.seq_axis in mesh.axis_names
                and mesh.shape.get(self.seq_axis, 1) > 1
                and shape is not None and len(shape) >= 2
                and shape[1] > 1 and shape[1] % mesh.shape[self.seq_axis] == 0):
            seq = self.seq_axis
        if not axes and seq is None:
            return P()
        lead = axes if len(axes) > 1 else (axes[0] if axes else None)
        rest = [seq] + [None] * (ndim - 2) if ndim >= 2 else []
        return P(lead, *rest)

    def shard_params(self, mesh: Mesh, params: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        out = {}
        for k, v in params.items():
            ns = NamedSharding(mesh, self.spec_for(k, v.shape, mesh))
            out[k] = jax.device_put(v, ns)
        return out


CANONICAL_AXES = frozenset((mesh_lib.DP, mesh_lib.FSDP, mesh_lib.TP,
                            mesh_lib.SP, mesh_lib.PP, mesh_lib.EP))


def _as_spec(spec: SpecLike) -> P:
    if spec is None:
        return P()
    if isinstance(spec, P):
        return spec
    return P(*spec)


def _filter_axes(entry, nameset):
    """Split one PartitionSpec entry into (kept-entry, dropped-axes) by
    mesh membership — the single normalization shared by ``adapted_to``
    and ``_validate`` (entry → axis tuple → keep-in-mesh → collapse back
    to scalar/tuple/None)."""
    if entry is None:
        return None, ()
    axes = entry if isinstance(entry, tuple) else (entry,)
    keep = tuple(a for a in axes if a in nameset)
    dropped = tuple(a for a in axes if a not in nameset)
    return (keep if len(keep) > 1 else (keep[0] if keep else None)), dropped


class ShardingRuleWarning(UserWarning):
    """A sharding rule degraded (axis dropped / dim not divisible) —
    the multi_devices_check_pass analog: silently-replicated params are
    the reference's classic mis-sharding failure mode."""


# warnings-module registry for warn_explicit: dedup is once per unique
# message (≈ once per rule key — every key renders a distinct message),
# honoring the ambient warning filters ("always" re-enables, "error"
# raises) and resettable with reset_drop_warnings(), unlike the old
# module-global set that could never re-warn.
_DROP_REGISTRY: dict = {}

# rule-key kind → lint code for the report-collector path
_DROP_CODES = {
    "missing": "sharding:unknown-axis",
    "adapt-typo": "sharding:unknown-axis",
    "divide": "sharding:indivisible",
    "rank": "sharding:rank-mismatch",
}


def reset_drop_warnings():
    """Re-arm the once-per-key drop warnings (test helper)."""
    _DROP_REGISTRY.clear()


def _warn_drop(key, msg):
    """Surface one rule-degradation diagnostic: routed into the active
    :class:`~paddle_tpu.analysis.LintReport` when a lint run has one
    installed (analysis.report.collect_into), else warned once per key
    via the warnings module."""
    from ..analysis import report as _lint

    rep = _lint.active_report()
    if rep is not None:
        rep.add(_DROP_CODES.get(key[0], "sharding:dropped-axis"), "warning",
                msg, where=str(key[1]) if len(key) > 1 else "")
        return
    warnings.warn_explicit(msg, ShardingRuleWarning, __file__, 0,
                           module=__name__, registry=_DROP_REGISTRY)


def _validate(spec: P, shape: Tuple[int, ...], mesh: Mesh, name: str) -> P:
    """Drop axes that don't divide the dim or aren't in the mesh —
    permissive like GSPMD so preset rule tables degrade gracefully on
    smaller meshes, but each drop warns once (size-1 mesh axes excepted:
    dropping those is a no-op)."""
    nameset = set(mesh.axis_names)
    out = []
    for i, entry in enumerate(spec):
        if entry is None:
            out.append(None)
            continue
        kept, dropped = _filter_axes(entry, nameset)
        for a in dropped:
            # once per (axis, mesh shape): presets legitimately run on
            # smaller meshes, so per-param warnings would flood — the
            # message carries no param name so registry dedup matches
            # the key granularity
            _warn_drop(("missing", a, tuple(mesh.shape.items())),
                       f"sharding rule names axis {a!r} which is not in the "
                       f"mesh {dict(mesh.shape)}; replicating that dim "
                       f"(warned once per axis and mesh shape)")
        keep = [] if kept is None else list(kept if isinstance(kept, tuple) else (kept,))
        size = 1
        for a in keep:
            size *= mesh.shape[a]
        if i >= len(shape):
            if keep and size > 1:
                _warn_drop(("rank", name, i),
                           f"sharding rule for {name!r} has more entries than the "
                           f"param rank {len(shape)}; extra axes {keep} dropped")
            out.append(None)
        elif not keep:
            out.append(None)
        elif shape[i] % size != 0:
            if size > 1:
                _warn_drop(("divide", name, i),
                           f"sharding rule for {name!r}: dim {i} of shape {shape} "
                           f"is not divisible by mesh axes {keep} (size {size}); "
                           f"replicating that dim")
            out.append(None)
        else:
            out.append(kept)
    out = out[:len(shape)]
    return P(*out)


# Preset rule tables ---------------------------------------------------------

def replicated() -> ShardingRules:
    """Pure DP: params replicated, grads psum'd — kAllReduce mode."""
    return ShardingRules([], default=P())


def fsdp(min_size_to_shard: int = 1024) -> ShardingRules:
    """Shard every parameter's largest dim over 'fsdp' — the kReduce /
    pserver param-slicing analog (build_strategy.h:34, ZeRO-3-ish).
    Rule resolution happens per-shape in spec_for via _LargestDim."""
    return _FsdpRules(min_size_to_shard)


class _FsdpRules(ShardingRules):
    def __init__(self, min_size_to_shard: int):
        super().__init__([], default=P())
        self.min_size = min_size_to_shard

    def spec_for(self, name, shape, mesh):
        if mesh_lib.FSDP not in mesh.axis_names or not shape:
            return P()
        n = mesh.shape[mesh_lib.FSDP]
        size = 1
        for s in shape:
            size *= s
        if size < self.min_size:
            return P()
        # shard the largest divisible dim
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
        for i in order:
            if shape[i] % n == 0:
                spec = [None] * len(shape)
                spec[i] = mesh_lib.FSDP
                return P(*spec)
        return P()


def transformer_tp_rules(extra: Sequence[Tuple[str, SpecLike]] = ()) -> ShardingRules:
    """Megatron-style TP rules for the built-in transformer/BERT models
    (gap-fill capability per SURVEY §2.2: TP absent in reference).

    The ``_stack/`` rules cover stacked-block parameters
    (layers.stacked): leading layer dim over ``pp``, Megatron dims over
    ``tp`` — matching the specs pipeline_apply uses inside its
    shard_map, so jit-level and pipeline-level shardings agree."""
    rules = [
        (r".*_stack/(qkv|xkv)/w$", P("pp", None, None, "tp")),
        (r".*_stack/(qkv|xkv)/b$", P("pp", None, "tp")),
        (r".*_stack/(out|xout)/w$", P("pp", "tp", None)),
        (r".*_stack/(ffn_in|xq)/w$", P("pp", None, "tp")),
        (r".*_stack/(ffn_in|xq)/b$", P("pp", "tp")),
        (r".*_stack/ffn_out/w$", P("pp", "tp", None)),
        (r".*_stack/", P("pp")),
    ] + [
        # fused projections are [d_in, 3|2, d_model] / [3|2, d_model]
        # (layers/attention.py fuse_qkv): tp on the LAST axis so the
        # per-sub-projection split needs no GSPMD resharding
        (r".*(qkv_proj|kv_proj)/w$", P("fsdp", None, "tp")),
        (r".*(qkv_proj|kv_proj)/b$", P(None, "tp")),
        (r".*(q_proj|k_proj|v_proj)/w$", P("fsdp", "tp")),
        (r".*(q_proj|k_proj|v_proj)/b$", P("tp")),
        (r".*out_proj/w$", P("tp", "fsdp")),
        (r".*ffn_in/w$", P("fsdp", "tp")),
        (r".*ffn_in/b$", P("tp")),
        (r".*ffn_out/w$", P("tp", "fsdp")),
        (r".*embedding.*/w$", P("tp", None)),
        (r".*/w$", P(None, "fsdp")),
    ] + list(extra)
    return ShardingRules(rules, default=P())
