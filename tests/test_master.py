"""C++ master task-queue service: lease/finish/fail lifecycle, lease
timeout requeue, retry-then-discard, snapshot/recover across restart,
reader integration (go/master capability parity, SURVEY §5)."""

import os
import time

import numpy as np
import pytest

from paddle_tpu.data.master import MasterClient, MasterServer, task_reader


def test_lease_finish_lifecycle():
    with MasterServer() as srv:
        c = MasterClient(srv.addr)
        ids = c.set_tasks([f"shard-{i}" for i in range(5)])
        assert len(ids) == 5
        seen = []
        while True:
            t = c.get_task(wait=False)
            if t is None:
                break
            tid, payload = t
            seen.append(payload.decode())
            c.finish_task(tid)
        assert sorted(seen) == [f"shard-{i}" for i in range(5)]
        st = c.status()
        assert st["done"] == 5 and st["todo"] == 0 and st["leased"] == 0
        c.close()


def test_fail_requeues_then_discards():
    with MasterServer(failure_max=2) as srv:
        c = MasterClient(srv.addr)
        c.set_tasks(["only"])
        tid, _ = c.get_task()
        c.fail_task(tid)                       # failure 1 → requeued
        tid2, _ = c.get_task()
        assert tid2 == tid
        c.fail_task(tid2)                      # failure 2 == failure_max → discarded
        assert c.get_task(wait=False) is None
        assert c.status()["discarded"] == 1
        c.close()


def test_lease_timeout_requeues():
    with MasterServer(failure_max=5, lease_timeout_ms=400) as srv:
        a = MasterClient(srv.addr)
        a.set_tasks(["t"])
        tid, _ = a.get_task()
        # a "crashes" (never finishes); b eventually gets the requeued task
        b = MasterClient(srv.addr)
        deadline = time.time() + 5
        got = None
        while time.time() < deadline:
            got = b.get_task(wait=False)
            if got is not None:
                break
            time.sleep(0.1)
        assert got is not None and got[0] == tid
        a.close(); b.close()


def test_snapshot_recover(tmp_path):
    snap = str(tmp_path / "master.snap")
    srv = MasterServer(snapshot_path=snap, failure_max=3)
    c = MasterClient(srv.addr)
    c.set_tasks(["a", "b", "c"])
    tid, _ = c.get_task()
    c.finish_task(tid)
    tid2, _ = c.get_task()                     # leased, never finished
    c.close()
    srv.stop()                                 # hard kill

    srv2 = MasterServer(snapshot_path=snap)    # recover from snapshot
    c2 = MasterClient(srv2.addr)
    st = c2.status()
    # done survives; the un-finished lease is requeued (leases don't
    # survive restart), so todo = 2
    assert st["done"] == 1 and st["todo"] == 2 and st["total"] == 3
    remaining = set()
    while True:
        t = c2.get_task(wait=False)
        if t is None:
            break
        remaining.add(t[1].decode())
        c2.finish_task(t[0])
    assert len(remaining) == 2
    c2.close(); srv2.stop()


def test_corrupt_snapshot_starts_fresh(tmp_path):
    """All-or-nothing recovery (mirrors the pserver): a truncated
    snapshot is discarded whole — the master boots empty rather than
    resuming with a silently partial task set."""
    snap = str(tmp_path / "master.snap")
    srv = MasterServer(snapshot_path=snap)
    c = MasterClient(srv.addr)
    c.set_tasks(["x" * 200, "y" * 200, "z" * 200])
    c.close()
    srv.stop()
    data = open(snap, "rb").read()
    open(snap, "wb").write(data[:len(data) - 120])  # truncate mid-payload

    srv2 = MasterServer(snapshot_path=snap)
    c2 = MasterClient(srv2.addr)
    assert c2.status()["total"] == 0  # fresh, not half-recovered
    c2.close(); srv2.stop()


def test_reset_pass():
    with MasterServer() as srv:
        c = MasterClient(srv.addr)
        c.set_tasks(["x", "y"])
        while True:
            t = c.get_task(wait=False)
            if t is None:
                break
            c.finish_task(t[0])
        assert c.get_task(wait=False) is None
        assert c.reset_pass() == 1             # new pass requeues everything
        assert c.status()["todo"] == 2
        c.close()


def test_task_reader_integration(tmp_path):
    # shards on disk; one shard is corrupt → failed over and discarded
    paths = []
    for i in range(3):
        p = tmp_path / f"shard{i}.npy"
        np.save(p, np.arange(4) + 10 * i)
        paths.append(str(p))

    def make_reader(path):
        def r():
            for v in np.load(path):
                yield int(v)
        return r

    with MasterServer(failure_max=1) as srv:
        c = MasterClient(srv.addr)
        c.set_tasks(paths + [str(tmp_path / "missing.npy")])
        got = sorted(task_reader(c, make_reader)())
        assert got == sorted(list(range(4)) + list(range(10, 14)) + list(range(20, 24)))
        assert c.status()["discarded"] == 1
        c.close()
