"""Test-support utilities shipped with the framework (deterministic
fault injection for resilience testing). Production code never imports
this package; it imports :mod:`paddle_tpu.resilience`'s crash-point
registry lazily instead."""

from . import faults  # noqa: F401
