"""Elementwise / activation ops.

Analog of python/paddle/fluid/layers/ops.py — there these are
auto-generated wrappers over C++ activation OpKernels
(layer_function_generator.py); here they are jax.numpy compositions that
XLA fuses into neighboring matmuls (the fusion the reference needed
hand-written passes and xbyak JIT kernels for — operators/math/jit_kernel.h).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


def logsigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


def exp(x, name=None):
    return jnp.exp(x)


def tanh(x, name=None):
    return jnp.tanh(x)


def tanh_shrink(x, name=None):
    return x - jnp.tanh(x)


def softshrink(x, alpha=0.5, name=None):
    return jnp.where(x > alpha, x - alpha, jnp.where(x < -alpha, x + alpha, 0.0))


def sqrt(x, name=None):
    return jnp.sqrt(x)


def rsqrt(x, name=None):
    return jax.lax.rsqrt(x)


def abs(x, name=None):
    return jnp.abs(x)


def ceil(x, name=None):
    return jnp.ceil(x)


def floor(x, name=None):
    return jnp.floor(x)


def cos(x, name=None):
    return jnp.cos(x)


def sin(x, name=None):
    return jnp.sin(x)


def round(x, name=None):
    return jnp.round(x)


def reciprocal(x, name=None):
    return 1.0 / x


def square(x, name=None):
    return jnp.square(x)


def log(x, name=None):
    return jnp.log(x)


def relu(x, name=None):
    return jax.nn.relu(x)


def relu6(x, threshold=6.0, name=None):
    return jnp.clip(x, 0.0, threshold)


def leaky_relu(x, alpha=0.02, name=None):
    return jnp.where(x >= 0, x, alpha * x)


def elu(x, alpha=1.0, name=None):
    return jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))


def selu(x, name=None):
    return jax.nn.selu(x)


def gelu(x, approximate=True, name=None):
    return jax.nn.gelu(x, approximate=approximate)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return jnp.clip(x, t_min, t_max)


def soft_relu(x, threshold=40.0, name=None):
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


def softplus(x, name=None):
    return jax.nn.softplus(x)


def softsign(x, name=None):
    return x / (1.0 + jnp.abs(x))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return x * jnp.clip(x + offset, 0.0, threshold) / scale


def swish(x, beta=1.0, name=None):
    return x * jax.nn.sigmoid(beta * x)


def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


def thresholded_relu(x, threshold=1.0, name=None):
    return jnp.where(x > threshold, x, 0.0)


def pow(x, factor=1.0, name=None):
    return jnp.power(x, factor)


def erf(x, name=None):
    return jax.lax.erf(x)


def maxout(x, groups, axis=1, name=None):
    """maxout_op.cc analog: out[:, k] = max over the ``groups``
    consecutive channels k*groups..(k+1)*groups; C_out = C/groups."""
    shape = list(x.shape)
    c = shape[axis]
    if c % groups != 0:
        raise ValueError(f"channels {c} not divisible by groups {groups}")
    new_shape = shape[:axis] + [c // groups, groups] + shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


# Registry of activation names usable as `act=` on fc/conv2d/... —
# mirrors LayerHelper.append_activation.
ACTIVATIONS = {
    None: lambda x: x,
    "relu": relu,
    "relu6": relu6,
    "leaky_relu": leaky_relu,
    "sigmoid": sigmoid,
    "tanh": tanh,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "softplus": softplus,
    "softsign": softsign,
    "stanh": stanh,
    "hard_sigmoid": hard_sigmoid,
    "swish": swish,
    "mish": mish,
    "exp": exp,
    "square": square,
    "sqrt": sqrt,
    "abs": abs,
    "brelu": brelu,
    "soft_relu": soft_relu,
}


def apply_activation(x, act):
    if callable(act):
        return act(x)
    if act not in ACTIVATIONS:
        raise ValueError(f"Unknown activation {act!r}; known: {sorted(k for k in ACTIVATIONS if k)}")
    return ACTIVATIONS[act](x)
