"""Analytic model-FLOP accounting for MFU reporting.

MFU = model FLOPs (the math the model *defines* — excluding remat
recompute and XLA bookkeeping) / step time / chip peak FLOP/s. This is
the honest utilization denominator BASELINE.json asks for ("CUDA-parity
… ≥70% scaling"), replacing throughput-vs-2018-Xeon ratios.

Conventions (PaLM appendix-B style, Megatron matmul accounting):
- dense matmul train FLOPs = 6 · (matmul params) · tokens
  (forward 2N, backward 4N);
- attention adds fwd 4·s·d per token per layer (QK^T + AV), ×3 for
  train = 12·L·s·d per token; *causal* attention is halved because the
  flash kernel computes only the lower triangle — counting the full
  square would inflate MFU;
- elementwise/norm/gather FLOPs are excluded (undercount, never
  overcount).

Reference analog: the fluid benchmark suite reported raw imgs/sec only
(benchmark/fluid/fluid_benchmark.py); FLOP/utilization accounting has
no reference counterpart and is TPU-first by design.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

# -- chip peak ---------------------------------------------------------------

# bf16 dense peak per *jax device*, by device_kind substring (first match
# wins — order matters: "v5p" before "v5", "v5 lite"/"v5e" before "v5").
# Sources: public TPU spec sheets (How to Scale Your Model, cloud docs).
_PEAK_BF16 = [
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v3", 61.5e12),   # one jax device = one core on v2/v3 (2 cores/chip)
    ("v2", 22.5e12),
]


def device_peak_flops(device=None, dtype: str = "bfloat16") -> Tuple[float, str]:
    """(peak FLOP/s, source) for one jax device. Falls back to a measured
    large-matmul rate when the device kind is unknown (e.g. CPU), so MFU
    stays meaningful everywhere the bench runs."""
    import jax

    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            if dtype in ("float32", "f32"):
                # MXU fp32 runs at 1/~8 of bf16 on recent TPUs; we only
                # report bf16-denominated MFU, so keep bf16 peak and let
                # f32 configs show the (real) utilization hit.
                pass
            return peak, f"table:{kind}"
    return measured_matmul_peak(device=device, dtype=dtype), "measured_matmul"


def measured_matmul_peak(device=None, dtype: str = "bfloat16", n: Optional[int] = None,
                         iters: int = 4) -> float:
    """Achieved FLOP/s of an n×n×n matmul chain — a practical peak proxy
    on platforms missing from the table."""
    import time

    import jax
    import jax.numpy as jnp

    device = device or jax.devices()[0]
    if n is None:  # keep the CPU fallback cheap; accelerators get a real tile
        n = 1024 if device.platform == "cpu" else 4096
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    a = jax.device_put(jnp.ones((n, n), dt), device)
    b = jax.device_put(jnp.ones((n, n), dt), device)

    @jax.jit
    def chain(a, b):
        for _ in range(4):
            a = jnp.matmul(a, b)
        return a

    chain(a, b).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = chain(a, b)
    out.block_until_ready()
    dtm = time.perf_counter() - t0
    return 2.0 * n ** 3 * 4 * iters / dtm


# -- transformer family ------------------------------------------------------


def _attn_train_flops(tokens: int, seq: int, d_model: int, layers: int,
                      causal: bool) -> float:
    f = 12.0 * layers * seq * d_model * tokens
    return f / 2 if causal else f


def transformer_train_flops(bs: int, seq: int, cfg) -> float:
    """Train-step FLOPs of the encoder-decoder transformer
    (models/transformer.py). Encoder: full self-attn. Decoder: causal
    self-attn (halved) + full cross-attn, whose q/kv/out projections add
    ~4·d² params per decoder layer on top of the self-attn 4·d². Vocab
    projection counted on decoder tokens only."""
    d, di = cfg.d_model, cfg.d_inner
    tokens = bs * seq
    enc_layer_params = 4 * d * d + 2 * d * di
    dec_layer_params = 8 * d * d + 2 * d * di  # + cross q/kv/out projections
    f = 6.0 * tokens * (enc_layer_params * cfg.num_encoder_layers +
                        dec_layer_params * cfg.num_decoder_layers)
    f += _attn_train_flops(tokens, seq, d, cfg.num_encoder_layers, causal=False)
    f += _attn_train_flops(tokens, seq, d, cfg.num_decoder_layers, causal=True)
    f += _attn_train_flops(tokens, seq, d, cfg.num_decoder_layers, causal=False)  # cross
    f += 6.0 * d * cfg.trg_vocab * tokens  # output projection
    return f


def gpt_train_flops(bs: int, seq: int, cfg) -> float:
    """Train-step FLOPs of the decoder-only LM (models/gpt.py): causal
    stack (attention halved) + LM head over every token."""
    d, di, L = cfg.d_model, cfg.d_inner, cfg.num_layers
    tokens = bs * seq
    f = 6.0 * (4 * d * d + 2 * d * di) * tokens * L
    f += _attn_train_flops(tokens, seq, d, L, causal=True)
    f += 6.0 * d * cfg.vocab_size * tokens  # lm head
    return f


def gpt_decode_flops(bs: int, prompt: int, new_tokens: int, cfg) -> float:
    """Forward-only FLOPs of prefill(prompt) + the incremental decode
    steps the generator actually runs: the first generated token comes
    from the prefill's own head eval (no stack step), so only
    new_tokens-1 incremental stack steps execute, with new_tokens head
    evals total (fwd only, no ×3; undercount-never-overcount)."""
    d, di, L = cfg.d_model, cfg.d_inner, cfg.num_layers
    params = (4 * d * d + 2 * d * di) * L
    inc = max(new_tokens - 1, 0)
    tokens = bs * (prompt + inc)
    f = 2.0 * params * tokens
    f += 2.0 * d * cfg.vocab_size * bs * new_tokens  # head: prefill + inc steps
    # prefill causal attention (halved, fwd-only) + per-step cache attention
    f += _attn_train_flops(bs * prompt, prompt, d, L, causal=True) / 3.0
    avg_ctx = prompt + inc / 2.0
    f += 4.0 * L * avg_ctx * d * bs * inc
    return f


def bert_train_flops(bs: int, seq: int, num_masked: int, cfg) -> float:
    """Train-step FLOPs of BERT pretraining (models/bert.py): encoder
    stack + MLM head (transform + vocab proj over masked positions) +
    pooler/NSP head."""
    d, di, L = cfg.d_model, cfg.d_inner, cfg.num_layers
    tokens = bs * seq
    f = 6.0 * (4 * d * d + 2 * d * di) * tokens * L
    f += _attn_train_flops(tokens, seq, d, L, causal=False)
    f += 6.0 * (d * d + d * cfg.vocab_size) * bs * num_masked  # MLM head
    f += 6.0 * (d * d + 2 * d) * bs  # pooler + NSP
    return f


# -- convnets ----------------------------------------------------------------


def _conv_flops(cin: int, cout: int, k: int, hout: int, wout: int) -> float:
    return 2.0 * k * k * cin * cout * hout * wout


def resnet_fwd_flops(depth: int = 50, image_size: int = 224,
                     class_num: int = 1000) -> float:
    """Per-image forward FLOPs of ResNet-50/101/152 (bottleneck blocks,
    models/resnet.py architecture). Validated ≈8.2 GFLOPs for
    50/224 (2 FLOPs per MAC)."""
    blocks = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}[depth]
    s = image_size
    f = _conv_flops(3, 64, 7, s // 2, s // 2)  # stem, stride 2
    s //= 4  # stem stride 2 + maxpool stride 2
    cin = 64
    for stage, n in enumerate(blocks):
        width = 64 * (2 ** stage)
        cout = width * 4
        stride = 1 if stage == 0 else 2
        for b in range(n):
            st = stride if b == 0 else 1
            so = s // st
            f += _conv_flops(cin, width, 1, s, s)  # 1×1 at input res (v1.5: stride on the 3×3)
            f += _conv_flops(width, width, 3, so, so)
            f += _conv_flops(width, cout, 1, so, so)
            if b == 0:
                f += _conv_flops(cin, cout, 1, so, so)  # projection shortcut
            cin, s = cout, so
    f += 2.0 * cin * class_num  # fc
    return f


def vgg_fwd_flops(depth: int = 16, image_size: int = 224,
                  class_num: int = 1000) -> float:
    """Per-image forward FLOPs of VGG-16/19. ≈31 GFLOPs for 16/224."""
    cfgs = {16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}[depth]
    chans = (64, 128, 256, 512, 512)
    s, cin, f = image_size, 3, 0.0
    for n, c in zip(cfgs, chans):
        for _ in range(n):
            f += _conv_flops(cin, c, 3, s, s)
            cin = c
        s //= 2
    flat = cin * s * s
    for dims in ((flat, 4096), (4096, 4096), (4096, class_num)):
        f += 2.0 * dims[0] * dims[1]
    return f


def alexnet_fwd_flops(image_size: int = 224, class_num: int = 1000) -> float:
    """Per-image forward FLOPs of AlexNet (models/convnets.make_alexnet).
    ≈1.4 GFLOPs at 224 (2 FLOPs per MAC; the classic ~720M-MAC figure)."""
    s = (image_size + 2 * 2 - 11) // 4 + 1          # conv1 k11 s4 p2
    f = _conv_flops(3, 64, 11, s, s)
    s = (s - 3) // 2 + 1                             # pool 3/2
    f += _conv_flops(64, 192, 5, s, s)
    s = (s - 3) // 2 + 1
    f += _conv_flops(192, 384, 3, s, s)
    f += _conv_flops(384, 256, 3, s, s)
    f += _conv_flops(256, 256, 3, s, s)
    s = (s - 3) // 2 + 1
    for dims in ((256 * s * s, 4096), (4096, 4096), (4096, class_num)):
        f += 2.0 * dims[0] * dims[1]
    return f


# GoogLeNet v1 inception parameter table (models/convnets.make_googlenet):
# (c1, c3r, c3, c5r, c5, proj) per block, grouped by spatial stage.
_GOOGLENET_STAGES = (
    ((64, 96, 128, 16, 32, 32), (128, 128, 192, 32, 96, 64)),
    ((192, 96, 208, 16, 48, 64), (160, 112, 224, 24, 64, 64),
     (128, 128, 256, 24, 64, 64), (112, 144, 288, 32, 64, 64),
     (256, 160, 320, 32, 128, 128)),
    ((256, 160, 320, 32, 128, 128), (384, 192, 384, 48, 128, 128)),
)


def googlenet_fwd_flops(image_size: int = 224, class_num: int = 1000) -> float:
    """Per-image forward FLOPs of GoogLeNet v1. ≈3 GFLOPs at 224."""
    s = image_size // 2                              # stem conv7 s2
    f = _conv_flops(3, 64, 7, s, s)
    s = (s + 2 - 3) // 2 + 1                         # pool 3/2 p1
    f += _conv_flops(64, 64, 1, s, s)
    f += _conv_flops(64, 192, 3, s, s)
    s = (s + 2 - 3) // 2 + 1
    cin = 192
    for stage in _GOOGLENET_STAGES:
        for (c1, c3r, c3, c5r, c5, proj) in stage:
            f += _conv_flops(cin, c1, 1, s, s)
            f += _conv_flops(cin, c3r, 1, s, s) + _conv_flops(c3r, c3, 3, s, s)
            f += _conv_flops(cin, c5r, 1, s, s) + _conv_flops(c5r, c5, 5, s, s)
            f += _conv_flops(cin, proj, 1, s, s)
            cin = c1 + c3 + c5 + proj
        s = (s + 2 - 3) // 2 + 1                     # inter-stage pool 3/2 p1
    f += 2.0 * cin * class_num
    return f


def se_resnext_fwd_flops(depth: int = 50, image_size: int = 224,
                         class_num: int = 1000, cardinality: int = 32,
                         reduction: int = 16) -> float:
    """Per-image forward FLOPs of SE-ResNeXt-50/101
    (models/convnets.make_se_resnext): grouped 3×3 divides that conv's
    FLOPs by cardinality-groups; SE adds two tiny FCs per block.
    ≈8.4 GFLOPs for 50/224."""
    stages = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3)}[depth]
    s = image_size // 2                       # stem conv7 s2
    f = _conv_flops(3, 64, 7, s, s)
    s = (s + 2 - 3) // 2 + 1                  # maxpool 3/2 p1
    cin = 64
    for stage, n in enumerate(stages):
        filters = 128 * (2 ** stage)
        cout = filters * 2
        for b in range(n):
            st = 2 if stage > 0 and b == 0 else 1
            so = s // st
            f += _conv_flops(cin, filters, 1, s, s)
            # grouped conv: in-channels per group × total out-channels
            f += _conv_flops(filters // cardinality, filters, 3, so, so)
            f += _conv_flops(filters, cout, 1, so, so)
            se_mid = max(cout // reduction, 4)
            f += 2.0 * (cout * se_mid + se_mid * cout)          # SE FCs
            if cin != cout or st != 1:
                f += _conv_flops(cin, cout, 1, so, so)          # projection
            cin, s = cout, so
    f += 2.0 * cin * class_num
    return f


def convnet_train_flops(fwd_flops_per_image: float, bs: int) -> float:
    """Train = fwd + bwd ≈ 3× fwd (bwd does ~2× fwd work)."""
    return 3.0 * fwd_flops_per_image * bs


# -- small models ------------------------------------------------------------


def mlp_train_flops(bs: int, dims: Sequence[int]) -> float:
    params = sum(a * b for a, b in zip(dims[:-1], dims[1:]))
    return 6.0 * params * bs


def lstm_train_flops(bs: int, seq: int, hidden: int, num_layers: int,
                     emb_dim: Optional[int] = None) -> float:
    """2 matmuls (input + recurrent) of 4 gates per step per layer."""
    emb_dim = emb_dim or hidden
    f = 0.0
    for layer in range(num_layers):
        xin = emb_dim if layer == 0 else hidden
        f += 6.0 * (4 * hidden * (xin + hidden)) * bs * seq
    return f


def seq2seq_train_flops(bs: int, src_len: int, trg_len: int, emb_dim: int,
                        hidden: int, trg_vocab: int) -> float:
    """GRU seq2seq with additive attention (models/seq2seq.py — the
    book machine-translation model; benchmark/fluid machine_translation
    analog). Counts the gate/attention/output matmuls at the train
    factor 6 (fwd + 2x bwd); embedding gathers, softmaxes, and
    elementwise attention math are excluded (undercounts, never
    inflates)."""
    f = 0.0
    # bi-GRU encoder: 2 directions x 3 gates x h x (emb + h) per token
    f += 2 * 6.0 * (3 * hidden * (emb_dim + hidden)) * bs * src_len
    # encoder attention projection [2h -> h] per source token
    f += 6.0 * (2 * hidden * hidden) * bs * src_len
    # decoder per target step: query proj [h->h], score dot [s x h],
    # context einsum [s x 2h], GRU x-proj [(emb+2h) -> 3h], h-proj
    f += 6.0 * (hidden * hidden) * bs * trg_len
    f += 6.0 * (src_len * hidden) * bs * trg_len
    f += 6.0 * (src_len * 2 * hidden) * bs * trg_len
    f += 6.0 * (3 * hidden * (emb_dim + 2 * hidden)) * bs * trg_len
    f += 6.0 * (3 * hidden * hidden) * bs * trg_len
    # output projection [h -> V]
    f += 6.0 * (hidden * trg_vocab) * bs * trg_len
    return f


def deepfm_train_flops(bs: int, num_fields: int, emb_size: int, num_dense: int,
                       hidden_dims: Sequence[int]) -> float:
    """MLP tower + linear heads; embedding gathers/FM interactions are
    bandwidth-bound and excluded (undercount)."""
    dims = [num_fields * emb_size + num_dense, *hidden_dims, 1]
    f = mlp_train_flops(bs, dims)
    f += 6.0 * num_dense * bs  # dense linear head
    return f
