"""HBM budget / rematerialization advisor.

Closes the loop the ROADMAP names: estimate the train step's per-device
HBM appetite (params + optimizer state + backward-held activations)
from the traced jaxpr, compare against the device budget, and emit a
``memory:remat-candidate`` finding suggesting ``DistStrategy.remat`` /
``remat_policy`` with the projected saving — BEFORE XLA aborts with an
allocation error that names nothing.

Estimation model (coarse on purpose — an advisor, not an allocator):

- **params / opt state**: actual scope leaf bytes, divided by each
  leaf's sharding factor (the product of mesh axis sizes its
  PartitionSpec names) so fsdp/tp shards count per-device; opt-state
  subtrees inherit their parameter's factor via the name-keyed walk
  contract (Optimizer base class).
- **activations**: the sum of intermediate value bytes in the traced
  train-path jaxpr — an upper bound (XLA reuses buffers), but the
  quantity remat actually attacks. Values produced INSIDE a
  ``remat``-wrapped region are recomputed in the backward pass rather
  than held, so the walk skips remat bodies and counts only their
  outputs: tracing with/without remat yields the projected saving.
  Batch-sharded under dp/fsdp, the sum divides by the data-shard
  product (per-device-correct, the ``compiled_memory_usage`` review
  fix).
- The advisor's suggestion is verified against XLA's own number:
  :func:`verify_remat` rebuilds the step under the suggested strategy
  and reports the ``temp_mb`` delta from ``memory_analysis()``
  (hardware-honest: XLA:CPU's buffer assignment ignores remat regions,
  so the CPU-runnable pin is on the estimate and the ``temp_mb`` pin
  runs where a real accelerator is present — same split as
  tests/test_remat_determinism.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

# primitives whose nested jaxprs are rematerialized in the backward
# pass: their intermediates are NOT held as residuals
_REMAT_PRIMS = frozenset({"remat2", "remat", "checkpoint"})

# suggest remat only when the projected saving is worth a recompute
# pass: below this fraction of the budget the advice would be noise
_MIN_SAVING_FRAC = 0.02


def device_hbm_bytes(device=None) -> Optional[int]:
    """The device's usable memory budget in bytes, when the backend
    exposes one (``memory_stats()``); None on backends that don't
    (CPU) — pass an explicit budget there."""
    import jax

    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    for key in ("bytes_limit", "bytes_reservable_limit"):
        if stats.get(key):
            return int(stats[key])
    return None


def _shard_factor(spec, mesh) -> int:
    """Product of mesh axis sizes a PartitionSpec actually shards
    over — the per-device divisor for that leaf."""
    if spec is None or mesh is None:
        return 1
    n = 1
    for entry in spec:
        axes = entry if isinstance(entry, tuple) else (
            (entry,) if entry is not None else ())
        for a in axes:
            if a in mesh.axis_names:
                n *= mesh.shape[a]
    return max(1, n)


def _data_shards(mesh) -> int:
    if mesh is None:
        return 1
    n = 1
    for a in ("dp", "fsdp"):
        if a in mesh.axis_names:
            n *= mesh.shape[a]
    return max(1, n)


def _leaf_bytes(v) -> int:
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
    except TypeError:
        return 0  # extended dtypes (PRNG keys): not an HBM concern here


def _scope_bytes_per_device(trainer) -> Dict[str, float]:
    """Per-device param + optimizer-state bytes from the live scope,
    spec-aware under sharding rules. Under ZeRO weight-update sharding
    the live leaves ARE the realized per-device placement (1/N shard
    rows), so both param and opt-state bytes come straight from each
    leaf's ``shard_shape`` — that is the N× optimizer-HBM dividend the
    strategy buys — while the logical figures come from the spec
    recorded at startup."""
    import jax

    tz = getattr(trainer, "_zero", None)
    if tz is not None:
        def _realized(tree):
            total = 0
            for v in jax.tree.leaves(tree or {}):
                shape = getattr(v, "shape", None)
                dtype = getattr(v, "dtype", None)
                if shape is None or dtype is None:
                    continue
                sh = getattr(v, "sharding", None)
                local = (sh.shard_shape(tuple(shape))
                         if sh is not None and shape else tuple(shape))
                try:
                    total += (int(np.prod(local or (1,)))
                              * np.dtype(dtype).itemsize)
                except TypeError:
                    continue
            return total

        def _logical(spec):
            return sum(int(np.prod(e["shape"] or [1]))
                       * np.dtype(e["dtype"]).itemsize
                       for e in spec.values())

        return {
            "param_bytes": int(_realized(trainer.scope.params)),
            "param_bytes_logical": int(_logical(tz.arrays["params.npz"])),
            "opt_state_bytes": int(_realized(trainer.scope.opt_state or {})),
            "opt_state_bytes_logical": int(
                _logical(tz.arrays.get("opt_state.npz") or {})),
            "zero_shards": int(tz.n),
        }

    mesh, rules = trainer.mesh, trainer.sharding_rules
    param_b = param_logical = 0
    for name, leaf in trainer.scope.params.items():
        b = _leaf_bytes(leaf)
        param_logical += b
        spec = (rules.spec_for(name, tuple(leaf.shape), mesh)
                if rules is not None and mesh is not None else None)
        param_b += b // _shard_factor(spec, mesh)
    # opt-state leaves follow their parameter's placement (name-keyed
    # subtree contract); approximate per-device bytes with the params'
    # aggregate sharding ratio — exact for the built-in optimizers,
    # whose slots mirror param shapes
    opt_logical = sum(_leaf_bytes(v)
                      for v in jax.tree.leaves(trainer.scope.opt_state or {}))
    ratio = (param_b / param_logical) if param_logical else 1.0
    return {
        "param_bytes": int(param_b),
        "param_bytes_logical": int(param_logical),
        "opt_state_bytes": int(opt_logical * ratio),
        "opt_state_bytes_logical": int(opt_logical),
    }


def _activation_sum_bytes(trainer, feed) -> int:
    """Intermediate-value byte sum of the traced train path, skipping
    remat-wrapped bodies (only their outputs persist to the backward
    pass). Uses the same walk machinery as the analysis lints."""
    import jax

    from ..analysis.check import _concrete_feed
    from ..analysis.walker import aval_bytes, eqn_subjaxprs

    fw = getattr(trainer, "feed_wire", None)
    if fw is not None:
        # a wire-typed sample feed (raw uint8 pixels) must trace at its
        # LOGICAL dtype, the way Trainer.startup initializes the model
        feed = fw.logical_feed(feed)
    cfeed = _concrete_feed(feed)
    # under ZeRO the scope holds (1/N, k) shard rows — the loss must
    # trace against the logical (combined) params
    params = (trainer._logical_params()
              if hasattr(trainer, "_logical_params")
              else trainer.scope.params)
    closed = jax.make_jaxpr(
        lambda p, s, r, f: trainer._loss_and_aux(p, s, r, f)[0])(
            params, trainer.scope.state,
            jax.random.PRNGKey(0), cfeed)

    total = [0]

    def visit(jx):
        for eqn in jx.eqns:
            for ov in eqn.outvars:
                total[0] += aval_bytes(getattr(ov, "aval", None))
            if eqn.primitive.name in _REMAT_PRIMS:
                continue  # recomputed, not held — outputs counted above
            for sub in eqn_subjaxprs(eqn):
                visit(sub)

    visit(closed.jaxpr)
    return total[0]


def _with_remat(trainer, policy=None):
    """Context: temporarily present the trainer's strategy with
    ``remat=True`` so a trace sees the checkpointed graph."""
    import contextlib

    from ..parallel.strategy import DistStrategy

    @contextlib.contextmanager
    def ctx():
        old = trainer.strategy
        base = old if old is not None else DistStrategy()
        trainer.strategy = dataclasses.replace(
            base, remat=True,
            remat_policy=policy if policy is not None else base.remat_policy)
        try:
            yield
        finally:
            trainer.strategy = old

    return ctx()


def memory_estimate(trainer, feed, policy=None,
                    project_remat: bool = True) -> Dict[str, Any]:
    """Per-device HBM estimate of the train step: scope bytes +
    activation sums with and without remat (the projected saving).
    ``project_remat=False`` skips the second (checkpointed) trace —
    for callers that only need the current-state number
    (``debugger.compiled_memory_usage``'s fallback), halving the trace
    cost; ``activation_bytes_remat`` then just mirrors the current
    trace."""
    scope = _scope_bytes_per_device(trainer)
    dshard = _data_shards(trainer.mesh)
    act = _activation_sum_bytes(trainer, feed) // dshard
    if project_remat:
        with _with_remat(trainer, policy):
            act_remat = _activation_sum_bytes(trainer, feed) // dshard
    else:
        act_remat = act
    remat_on = bool(getattr(trainer.strategy, "remat", False))
    total = (scope["param_bytes"] + scope["opt_state_bytes"]
             + (act_remat if remat_on else act))
    return {
        **scope,
        "activation_bytes": int(act),
        "activation_bytes_remat": int(act_remat),
        "data_shards": dshard,
        "remat_enabled": remat_on,
        "est_total_bytes": int(total),
        "est_total_mb": round(total / 1e6, 3),
    }


def advise(trainer, feed, hbm_budget_bytes: Optional[int] = None,
           report=None, safety: float = 0.9, policy: str = "dots"):
    """Compare the step's estimated per-device HBM appetite against
    the budget and append ``memory:*`` findings to ``report`` (a
    :class:`analysis.LintReport`; one is created when None):

    - ``memory:remat-candidate`` (warning) — over budget, remat off,
      and the projected activation saving is material: suggests
      ``DistStrategy(remat=True, remat_policy=...)`` with numbers;
    - ``memory:over-budget`` (warning) — over budget with remat
      already on (the advisor has no cheaper lever: points at
      microbatching / sharding);
    - ``memory:fits`` (info) — under budget, with the margin.

    With no budget (CPU and no explicit ``hbm_budget_bytes``) the
    family is inert and the report comes back unchanged."""
    from ..analysis.report import LintReport

    if report is None:
        report = LintReport(subject=f"memory({trainer.program.name})")
    budget = (hbm_budget_bytes if hbm_budget_bytes is not None
              else device_hbm_bytes(
                  trainer.mesh.devices.flat[0] if trainer.mesh is not None
                  else trainer.place.device()))
    if budget is None:
        return report
    # trace once without the remat projection first: the common
    # memory:fits outcome never needs the second (checkpointed) trace,
    # and advise() runs at every lint-enabled startup
    est = memory_estimate(trainer, feed, policy=policy, project_remat=False)
    usable = safety * budget
    if est["est_total_bytes"] > usable:
        est = memory_estimate(trainer, feed, policy=policy)
    saving = est["activation_bytes"] - est["activation_bytes_remat"]
    if est["est_total_bytes"] <= usable:
        report.add(
            "memory:fits", "info",
            f"estimated {est['est_total_mb']:.1f} MB/device (params "
            f"{est['param_bytes'] / 1e6:.1f} + opt "
            f"{est['opt_state_bytes'] / 1e6:.1f} + activations "
            f"{(est['activation_bytes_remat'] if est['remat_enabled'] else est['activation_bytes']) / 1e6:.1f}) "
            f"within {safety:.0%} of the {budget / 1e6:.0f} MB budget",
            where="hbm", **est, hbm_budget_bytes=int(budget))
    elif not est["remat_enabled"] and saving > _MIN_SAVING_FRAC * budget:
        report.add(
            "memory:remat-candidate", "warning",
            f"estimated {est['est_total_mb']:.1f} MB/device exceeds "
            f"{safety:.0%} of the {budget / 1e6:.0f} MB budget and "
            f"activations dominate ({est['activation_bytes'] / 1e6:.1f} MB "
            f"held for backward) — set DistStrategy(remat=True, "
            f"remat_policy={policy!r}) to trade recompute for "
            f"~{saving / 1e6:.1f} MB (projected from the checkpointed "
            f"trace; verify with debugger.compiled_memory_usage temp_mb)",
            where="hbm", **est, hbm_budget_bytes=int(budget),
            suggested_policy=policy,
            projected_saving_bytes=int(saving))
    else:
        report.add(
            "memory:over-budget", "warning",
            f"estimated {est['est_total_mb']:.1f} MB/device exceeds "
            f"{safety:.0%} of the {budget / 1e6:.0f} MB budget"
            + (" with remat already enabled"
               if est["remat_enabled"] else
               " and remat would not recover enough")
            + " — shrink the per-device batch (accum_steps), shard "
            "params/opt state (fsdp / reduce_strategy='sharded'), or "
            "store opt state in bf16 (opt_state_dtype)",
            where="hbm", **est, hbm_budget_bytes=int(budget))
    return report


def verify_remat(trainer, feed, policy: str = "dots") -> Dict[str, Any]:
    """Measure the advisor's suggestion against XLA's own numbers:
    builds a second Trainer over the same program/optimizer with
    ``remat=True`` and returns the ``temp_mb`` (``memory_analysis``)
    and estimated-activation deltas. The estimate shrinks on every
    backend; ``temp_mb`` shrinks where the buffer assigner honors remat
    regions (real accelerators — XLA:CPU ignores them)."""
    from .. import executor as _executor
    from ..debugger import compiled_memory_usage
    from ..parallel.strategy import DistStrategy

    base = (trainer.strategy if trainer.strategy is not None
            else DistStrategy())
    remat_strategy = dataclasses.replace(base, remat=True,
                                         remat_policy=policy)
    before = compiled_memory_usage(trainer, feed)
    est_before = memory_estimate(trainer, feed, policy=policy)
    tr2 = _executor.Trainer(
        trainer.program, trainer.optimizer, loss_name=trainer.loss_name,
        place=trainer.place, mesh=trainer.mesh,
        sharding_rules=trainer.sharding_rules_raw,
        strategy=remat_strategy,
        # same donation setting as the measured trainer: the buffer
        # assigner reuses donated inputs, so a donate mismatch would
        # conflate remat's temp_mb effect with donation's
        donate=getattr(trainer, "donate", True),
        fetch_list=trainer.fetch_list,
        feed_wire=getattr(trainer, "feed_wire", None))
    tr2.startup(sample_feed=feed)
    after = compiled_memory_usage(tr2, feed)
    est_after = memory_estimate(tr2, feed, policy=policy)
    return {
        "temp_mb_before": before.get("temp_mb"),
        "temp_mb_after": after.get("temp_mb"),
        "memory_source": (before.get("source"), after.get("source")),
        "est_activation_mb_before": est_before["activation_bytes"] / 1e6,
        "est_activation_mb_after": est_after["activation_bytes_remat"] / 1e6,
        "suggested_policy": policy,
    }
