"""Python-free native trainer (native/trainer.cc) — the C++ training
entry parity test (train/demo/demo_trainer.cc: drive the whole epoch
loop from C++, no Python in the process).

Hermetic assertions on this box (the TPU is behind an IFRT-proxy
tunnel, not a local PJRT endpoint — same constraint as
test_native_predictor.py):
  * save_train_artifact exports a carry-aligned one-step StableHLO
    whose REPLAY (jax.export deserialize, outputs fed back positionally
    as the next step's inputs — exactly the C++ buffer swap) matches
    in-process Trainer training step-for-step,
  * the binary builds against the vendored PJRT header,
  * --probe exits 0: full artifact load + carry/seed/feed layout
    validation + plugin handshake,
  * artifact tampering (a truncated weight) dies loudly.
"""

import json
import os
import subprocess

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import layers as L
from paddle_tpu import optimizer as opt

TF_INCLUDE = "/opt/venv/lib/python3.12/site-packages/tensorflow/include"
LIBTPU = "/opt/venv/lib/python3.12/site-packages/libtpu/libtpu.so"

# only the subprocess tests need the native toolchain; the export and
# replay tests are pure-Python and must run everywhere (they guard the
# carry-ordering / meta-binding contract)
needs_native = pytest.mark.skipif(
    not os.path.exists(os.path.join(TF_INCLUDE, "xla/pjrt/c/pjrt_c_api.h"))
    or not os.path.exists(LIBTPU),
    reason="PJRT C API header or libtpu plugin not present in this image")


def _build():
    from paddle_tpu.native import build_native
    return build_native("trainer.cc", "trainer",
                        extra_flags=("-I" + TF_INCLUDE,), libs=("-ldl",))


def _net(x, label):
    h = L.fc(x, 16, act="relu", name="h")
    logits = L.fc(h, 3, name="out")
    return {"loss": L.mean(L.softmax_with_cross_entropy(logits, label))}


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("native_train"))
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 6).astype(np.float32),
            "label": rng.randint(0, 3, (8, 1)).astype(np.int64)}
    prog = pt.build(_net)
    tr = pt.Trainer(prog, opt.Momentum(0.1, 0.9), loss_name="loss")
    tr.startup(sample_feed=feed)
    pio.save_train_artifact(d, tr, feed)
    return d, tr, feed


def test_artifact_layout(artifact):
    d, _, feed = artifact
    meta = json.load(open(os.path.join(d, "meta_train.json")))
    n = meta["num_carry"]
    srcs = [i["source"] for i in meta["inputs"]]
    # carry prefix, then the seed scalar, then feeds — the layout the
    # C++ driver swap-loop assumes
    assert all(s in ("params.npz", "opt.npz", "state.npz") for s in srcs[:n])
    assert srcs[n] == "seed" and meta["inputs"][n]["shape"] == []
    assert srcs[n + 1:] == ["feed"] * len(feed)
    for f in ("train_step.mlir", "params.npz", "opt.npz", "state.npz",
              "feed_x.npy", "feed_label.npy"):
        assert os.path.exists(os.path.join(d, f)), f


def test_exported_step_replay_matches_trainer(artifact):
    """Replay the serialized artifact with positional carry feedback —
    the exact C++ execution model (output i becomes input i, seed =
    step index) — and pin it against in-process Trainer training."""
    d, tr, feed = artifact
    exported = jax.export.deserialize(
        open(os.path.join(d, "train_step.jaxexp"), "rb").read())
    meta = json.load(open(os.path.join(d, "meta_train.json")))
    n_carry = meta["num_carry"]
    feed_names = meta["feed_names"]

    # initial carry straight from the npz artifact through the meta
    # binding (meta names are byte-identical to npz members — exactly
    # how the C++ driver stages buffers); tree STRUCTURE comes from the
    # live trainer, which is what was exported
    import jax.tree_util as jtu
    from paddle_tpu.io import _flat_leaves_in_tree_order
    host = jax.device_get((tr.scope.params, tr.scope.opt_state,
                           tr.scope.state))
    blobs = {n: dict(np.load(os.path.join(d, n), allow_pickle=False))
             for n in ("params.npz", "opt.npz", "state.npz")}
    leaves = [blobs[i["source"]][i["name"]] for i in meta["inputs"][:n_carry]]
    assert len(leaves) == len(jtu.tree_leaves(host))
    p, o, s = jtu.tree_unflatten(jtu.tree_structure(host), leaves)
    feeds = [np.load(os.path.join(d, f"feed_{k}.npy")) for k in feed_names]

    # in-process reference: 3 Trainer steps with the same per-step keys
    losses_ref = []
    for step in range(3):
        out = tr.step(feed, rng=jax.random.PRNGKey(np.uint32(step)))
        losses_ref.append(float(out["loss"]))

    losses = []
    for step in range(3):
        p, o, s, loss = exported.call(p, o, s, np.uint32(step), *feeds)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, losses_ref, rtol=1e-5, atol=1e-6)
    assert losses[-1] < losses[0]


@needs_native
def test_probe_python_free(artifact):
    d, _, _ = artifact
    binary = _build()
    env = {k: v for k, v in os.environ.items() if k != "LD_PRELOAD"}
    r = subprocess.run([binary, d, LIBTPU, "--probe"], capture_output=True,
                       text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "PROBE OK" in r.stdout
    assert "artifact ok" in r.stderr


@needs_native
def test_tampered_artifact_dies(artifact, tmp_path):
    d, _, _ = artifact
    binary = _build()
    import shutil
    bad = str(tmp_path / "bad")
    shutil.copytree(d, bad)
    blob = open(os.path.join(bad, "params.npz"), "rb").read()
    open(os.path.join(bad, "params.npz"), "wb").write(blob[:len(blob) // 2])
    r = subprocess.run([binary, bad, LIBTPU, "--probe"], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode != 0
    assert "trainer:" in r.stderr
