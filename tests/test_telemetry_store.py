"""Durable-telemetry acceptance suite: on-disk series store, collector
restart recovery, HA failover, and alert-rule hot-reload.

The contracts (all CPU; real sockets, explicit clocks where possible):

  * resilience segment primitives: CRC-framed records survive a torn
    tail and a flipped byte as SKIPPED records (never a crash), sealed
    segments commit an atomic CRC sidecar that `check_segment` holds
    them to;
  * SegmentStore rotates at the byte bound, enforces retention by time
    AND bytes (oldest-segment deletion, active never deleted), and
    serves deterministic downsampled range reads;
  * collector restart with a populated store reproduces pre-restart
    /metrics (every fleet series + ingest counters; the store's own
    per-life I/O meta-series are the documented exception), /alerts
    (firing state with its original clock — no re-fire, no resolve
    flap), /query range reads, the fleet journal, and the EVENTS
    dedupe high-water marks — bit-identically;
  * torn/bit-flipped segments are detected by CRC, skipped, and
    counted (`paddle_tpu_collector_segments_corrupt_total`) while
    ingestion keeps working;
  * shipper failover: the comma-separated PDTPU_TELEMETRY_ADDR shape,
    a dead primary rotating to the standby WITHIN one flush, zero
    shipped-event loss across the cutover (dedupe high-water marks on
    the promoted standby), and the failover recorded in
    `paddle_tpu_shipper_flushes_total{outcome="failover"}`;
  * standby promotion replays the shared segment log: a pre-kill
    firing alert is firing on the standby with its original `since`
    and ZERO alert transitions journaled for it;
  * alert rules hot-reload through `lint_rules` with reject-on-
    findings (engine untouched), a journaled `alert.rules_reloaded`,
    state carried for persisting rule names, `POST /rules` and SIGHUP
    drive the same path;
  * `GET /query` serves range reads over HTTP (store-backed and the
    in-memory fallback);
  * tools/series_dump.py holds the 0/2/3 exit contract;
  * the ingest hot path WITH persistence stays under 2% of a measured
    K=16 fused dispatch (the established telemetry overhead pin).
"""

import json
import os
import signal
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu import resilience
from paddle_tpu import telemetry
from paddle_tpu.telemetry import alerts
from paddle_tpu.telemetry import shipper as tshipper
from paddle_tpu.telemetry.collector import TelemetryCollector
from paddle_tpu.telemetry.journal import RunJournal
from paddle_tpu.telemetry.registry import (MetricsRegistry,
                                           render_families_prometheus)
from paddle_tpu.telemetry.store import SegmentStore, downsample
from paddle_tpu.testing import faults


@pytest.fixture()
def fresh(tmp_path):
    old = telemetry.set_journal(RunJournal())
    try:
        yield telemetry.get_journal()
    finally:
        tshipper.stop_shipping()
        j = telemetry.set_journal(old)
        if j is not None:
            j.close()


def _snap(name, value, labels=None, type_="gauge", help_="h"):
    return {name: {"type": type_, "help": help_,
                   "samples": [{"labels": dict(labels or {}),
                                "value": value}]}}


def _gauge_snap_record(origin, t, value, name="paddle_tpu_serving_queue_depth"):
    return {"k": "snap", "o": origin, "t": t,
            "f": _snap(name, value, labels={"inst": "0"})}


# ---------------------------------------------------------------------------
# resilience segment primitives
# ---------------------------------------------------------------------------


def test_frame_and_iter_records_roundtrip_and_corruption(tmp_path):
    p = str(tmp_path / "seg.log")
    payloads = [json.dumps({"i": i}).encode() for i in range(5)]
    with open(p, "wb") as f:
        for b in payloads:
            f.write(resilience.frame_record(b))
    got = list(resilience.iter_records(p))
    assert [ok for ok, _ in got] == [True] * 5
    assert [b for _, b in got] == payloads

    # a newline-carrying payload is rejected at frame time (framing is
    # line-based)
    with pytest.raises(ValueError):
        resilience.frame_record(b"a\nb")

    # torn tail (kill -9 mid-append): last record unreadable, earlier
    # ones intact, no exception
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) - 3)
    got = list(resilience.iter_records(p))
    assert [ok for ok, _ in got] == [True] * 4 + [False]
    assert "torn tail" in got[-1][1]

    # a flipped byte fails exactly its record's CRC
    p2 = str(tmp_path / "seg2.log")
    with open(p2, "wb") as f:
        for b in payloads:
            f.write(resilience.frame_record(b))
    faults.flip_byte(str(tmp_path), "seg2.log",
                     offset=os.path.getsize(p2) // 2)
    got = list(resilience.iter_records(p2))
    assert got.count((True, payloads[0])) == 1
    assert sum(1 for ok, _ in got if not ok) == 1


def test_seal_and_check_segment(tmp_path):
    p = str(tmp_path / "segment-00000001.log")
    with open(p, "wb") as f:
        f.write(resilience.frame_record(b'{"k":"x"}'))
    meta = resilience.seal_segment(p, meta={"records": 1})
    assert meta["records"] == 1 and meta["size"] == os.path.getsize(p)
    ok, reason = resilience.check_segment(p)
    assert ok, reason
    # sidecar-less file is a finding
    p2 = str(tmp_path / "segment-00000002.log")
    open(p2, "wb").close()
    ok, reason = resilience.check_segment(p2)
    assert not ok and "sidecar" in reason
    # bit flip after sealing is caught by the whole-file CRC
    faults.flip_byte(str(tmp_path), os.path.basename(p))
    ok, reason = resilience.check_segment(p)
    assert not ok and "checksum mismatch" in reason


# ---------------------------------------------------------------------------
# SegmentStore: rotation, retention, range reads
# ---------------------------------------------------------------------------


def test_downsample_last_sample_per_bucket():
    pts = [(100.0, 1.0), (101.0, 2.0), (104.9, 3.0), (105.0, 4.0),
           (109.0, 5.0)]
    assert downsample(pts, 100.0, 0.0) == pts
    assert downsample(pts, 100.0, 5.0) == [(100.0, 3.0), (105.0, 5.0)]
    assert downsample([], 0.0, 5.0) == []


def test_segment_store_rotation_retention_and_query(tmp_path):
    seg = SegmentStore(str(tmp_path / "s"), segment_max_bytes=256,
                       retention_s=3600.0, retention_bytes=1 << 30,
                       state_fn=lambda: {"marker": True})
    seg.open()
    for i in range(20):
        assert seg.append(_gauge_snap_record("r0", 1000.0 + i, i))
    names = [os.path.basename(p) for p in seg.segment_paths()]
    assert sum(1 for n in names if n.endswith(".log")) >= 3
    assert sum(1 for n in names if n.endswith(".open")) == 1
    # sealed segments carry atomic CRC sidecars and validate clean
    assert seg.validate() == []
    # every segment BEGINS with a state record (the recovery baseline)
    first = next(seg._iter_payloads([seg.segment_paths()[0]]))
    assert first["k"] == "state" and first["marker"] is True

    # raw + downsampled range reads, label matching
    q = seg.query("paddle_tpu_serving_queue_depth", {"origin": "r0"},
                  start=1000.0, end=1019.0)
    assert len(q["series"]) == 1
    assert [p[1] for p in q["series"][0]["points"]] == \
        [float(i) for i in range(20)]
    q = seg.query("paddle_tpu_serving_queue_depth", {}, start=1000.0,
                  end=1019.0, step=10.0)
    assert q["series"][0]["points"] == [[1000.0, 9.0], [1010.0, 19.0]]
    assert seg.query("paddle_tpu_serving_queue_depth",
                     {"origin": "nope"}, 0, 2000.0)["series"] == []

    # retention by BYTES: oldest sealed segments deleted, active kept
    seg.retention_bytes = 600
    deleted = seg.enforce_retention(now=2000.0)
    assert deleted and all(n.endswith(".log") for n in deleted)
    assert seg.total_bytes() <= 600 + seg.segment_max_bytes
    remaining = [os.path.basename(p) for p in seg.segment_paths()]
    assert any(n.endswith(".open") for n in remaining)
    # the deleted prefix is GONE from range reads (the trade is
    # explicit: segment-granularity forgetting)
    q = seg.query("paddle_tpu_serving_queue_depth", {}, 1000.0, 1019.0)
    pts = q["series"][0]["points"] if q["series"] else []
    assert len(pts) < 20

    # retention by TIME: everything sealed is older than 1s at t+1h
    seg.retention_s = 1.0
    seg.rotate()   # seal the active tail so it is eligible
    deleted = seg.enforce_retention(now=1019.0 + 3600.0)
    assert deleted
    assert all(os.path.basename(p).endswith(".open")
               for p in seg.segment_paths())
    seg.close()


def test_segment_store_recovers_from_leftover_open_segment(tmp_path):
    """A killed writer leaves an .open segment (optionally torn):
    recovery reads it record-by-record, and the next open() seals it."""
    root = str(tmp_path / "s")
    seg = SegmentStore(root)
    seg.open()
    for i in range(5):
        seg.append(_gauge_snap_record("r0", 100.0 + i, i))
    seg.close()   # flushed but NOT sealed: simulates kill -9
    active = [p for p in seg.segment_paths() if p.endswith(".open")]
    assert len(active) == 1
    with open(active[0], "r+b") as f:   # torn tail
        f.truncate(os.path.getsize(f.name) - 2)

    seg2 = SegmentStore(root)
    got = []
    seg2.recover(lambda k, doc: got.append(doc))
    assert [d["f"]["paddle_tpu_serving_queue_depth"]["samples"][0]["value"]
            for d in got if d["k"] == "snap"] == [0, 1, 2, 3]
    assert seg2.counters["corrupt_records"] == 1
    seg2.open()
    assert not any(p.endswith(".open") and "00000001" in p
                   for p in seg2.segment_paths())
    # the sealed leftover + the new active
    assert len(seg2.segment_paths()) == 2
    seg2.close()


# ---------------------------------------------------------------------------
# collector restart: bit-identical recovery
# ---------------------------------------------------------------------------


_STORE_SELF_SERIES = "paddle_tpu_collector_store_"


def _strip_store_self_series(text):
    """The store's own I/O meta-series (appends/bytes/seconds/segment
    gauge) describe THIS process's disk work and are per-life by
    design — the one documented exception to restart bit-identity."""
    return "\n".join(l for l in text.splitlines()
                     if _STORE_SELF_SERIES not in l) + "\n"


def test_collector_restart_reproduces_state_bit_identically(fresh, tmp_path):
    store_dir = str(tmp_path / "tstore")
    rules = [alerts.parse_rule(
        "hot", "paddle_tpu_serving_queue_depth > 5 for 0s",
        severity="warn")]
    kw = dict(eval_interval=3600, rules=rules, store_dir=store_dir,
              flight_root=str(tmp_path / "flight"))
    col = TelemetryCollector(**kw)
    cli = tshipper.ShipperClient(col.addr)
    now = time.time()
    for i, v in enumerate([2, 7, 9]):
        cli.ship_snapshot("r0", _snap("paddle_tpu_serving_queue_depth", v,
                                      labels={"inst": "0"}))
    cli.ship_snapshot("r1", _snap("paddle_tpu_serving_errors_total", 4,
                                  labels={"inst": "0"}, type_="counter"))
    cli.ship_events("r0", "run1", [
        {"run": "run1", "seq": i, "sseq": i, "t": now + i, "kind": "x.y",
         "span": "s1"} for i in range(1, 6)])
    trans = col.evaluate_once()
    assert [t["state"] for t in trans] == ["firing"]
    cli.close()

    fixed = time.time()
    fam1 = _strip_store_self_series(
        render_families_prometheus(col.families(now=fixed)))
    al1 = col.engine.snapshot(now=fixed)
    q1 = col.query("paddle_tpu_serving_queue_depth", {}, 0.0,
                   fixed + 10, 0.0)
    qd1 = col.query("paddle_tpu_serving_queue_depth", {}, 0.0,
                    fixed + 10, 0.5)
    tl1 = col.timeline("s1")
    j1 = col.journal.recent(kind="x.")
    assert len(j1) == 5
    col.close()

    col2 = TelemetryCollector(**kw)
    try:
        # /metrics (modulo the per-life store I/O meta-series),
        # /alerts incl. in-flight firing state, /query raw AND
        # downsampled, /timeline, and the journal: all bit-identical
        assert _strip_store_self_series(
            render_families_prometheus(col2.families(now=fixed))) == fam1
        assert col2.engine.snapshot(now=fixed) == al1
        assert col2.query("paddle_tpu_serving_queue_depth", {}, 0.0,
                          fixed + 10, 0.0) == q1
        assert col2.query("paddle_tpu_serving_queue_depth", {}, 0.0,
                          fixed + 10, 0.5) == qd1
        assert col2.timeline("s1") == tl1
        assert col2.journal.recent(kind="x.") == j1
        # no spurious transitions on the next tick: the firing
        # instance carried its clock, the condition still holds
        assert col2.evaluate_once() == []
        assert [e for e in col2.journal.recent(kind="alert.")] == []
        # dedupe high-water marks survived: a shipper retrying the
        # pre-restart batch is still deduped to zero
        cli2 = tshipper.ShipperClient(col2.addr)
        assert cli2.ship_events("r0", "run1", [
            {"run": "run1", "seq": i, "sseq": i, "t": now + i,
             "kind": "x.y", "span": "s1"} for i in range(1, 6)]) == 0
        # ...and fresh pushes keep working
        assert cli2.ship_events("r0", "run1", [
            {"run": "run1", "seq": 6, "sseq": 6, "t": now + 6,
             "kind": "x.z"}]) == 1
        cli2.close()
    finally:
        col2.close()


def test_collector_recovery_skips_corrupt_segments_counts_and_ingests(
        fresh, tmp_path):
    store_dir = str(tmp_path / "cstore")
    kw = dict(eval_interval=3600, rules=[], store_dir=store_dir)
    col = TelemetryCollector(**kw)
    cli = tshipper.ShipperClient(col.addr)
    for i in range(4):
        cli.ship_snapshot("r0", _snap("paddle_tpu_serving_queue_depth", i,
                                      labels={"inst": "0"}))
    cli.close()
    col._seg.rotate()
    col.close()

    # flip a byte mid-segment AND truncate the newest one: both are
    # detected by CRC, skipped, counted — never a crash
    segs = sorted(p for p in os.listdir(store_dir) if p.endswith(".log"))
    faults.flip_byte(store_dir, segs[0])
    faults.truncate_file(store_dir, segs[-1],
                         keep_bytes=os.path.getsize(
                             os.path.join(store_dir, segs[-1])) - 4)
    col2 = TelemetryCollector(**kw)
    try:
        corrupt = [f for f in col2.families(now=time.time())
                   if f.name == "paddle_tpu_collector_segments_corrupt_total"]
        assert corrupt and corrupt[0].samples[0][1] >= 2
        # the surviving records are there, and ingestion still works
        assert col2.store.origins().keys() == {"r0"}
        cli2 = tshipper.ShipperClient(col2.addr)
        assert cli2.ship_snapshot(
            "r1", _snap("paddle_tpu_serving_queue_depth", 1,
                        labels={"inst": "0"})) == 1
        cli2.close()
        assert set(col2.store.origins()) == {"r0", "r1"}
    finally:
        col2.close()


# ---------------------------------------------------------------------------
# /query endpoint
# ---------------------------------------------------------------------------


def test_query_endpoint_http_and_memory_fallback(fresh, tmp_path):
    for store_dir in (str(tmp_path / "qstore"), None):
        col = TelemetryCollector(eval_interval=3600, rules=[],
                                 store_dir=store_dir)
        cli = tshipper.ShipperClient(col.addr)
        for i in range(6):
            cli.ship_snapshot("r0", _snap("paddle_tpu_serving_queue_depth",
                                          i, labels={"inst": "0"}))
            cli.ship_snapshot("r1", _snap("paddle_tpu_serving_queue_depth",
                                          10 + i, labels={"inst": "0"}))
        cli.close()
        srv = col.serve_http()
        try:
            doc = json.loads(urllib.request.urlopen(
                srv.url + "/query?metric=paddle_tpu_serving_queue_depth"
                          "&labels=origin=r1").read())
            assert len(doc["series"]) == 1
            assert 'origin="r1"' in doc["series"][0]["key"]
            assert [p[1] for p in doc["series"][0]["points"]] == \
                [float(v) for v in range(10, 16)]
            doc = json.loads(urllib.request.urlopen(
                srv.url + "/query?metric=paddle_tpu_serving_queue_depth"
                          "&step=3600").read())
            assert {len(s["points"]) for s in doc["series"]} == {1}
            assert doc["step"] == 3600.0
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/query")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    srv.url + "/query?metric=m&from=notanumber")
            assert ei.value.code == 400
        finally:
            col.close()


# ---------------------------------------------------------------------------
# shipper failover + standby promotion (the HA pair)
# ---------------------------------------------------------------------------


def _crash_collector(col):
    """Stop a collector WITHOUT the clean-close path (no final state
    record, active segment left .open, sockets refused) — the
    in-process stand-in for kill -9; the drill does the real SIGKILL."""
    col._stop.set()
    try:
        col._ls.close()
    except OSError:
        pass
    col._eval_thread.join(timeout=5)
    col._seg.close()


def test_shipper_failover_zero_loss_and_standby_promotion(fresh, tmp_path):
    store_dir = str(tmp_path / "ha")
    rule = alerts.parse_rule(
        "hot", "paddle_tpu_serving_breaker_open > 0 for 0s",
        severity="page")
    primary = TelemetryCollector(eval_interval=3600, rules=[rule],
                                 store_dir=store_dir,
                                 flight_root=str(tmp_path / "flight"))
    standby = TelemetryCollector(eval_interval=3600, rules=[rule],
                                 store_dir=store_dir, standby=True,
                                 takeover_s=30.0)
    assert standby.is_standby
    # a standby without a store is a loud misconfiguration
    with pytest.raises(ValueError):
        TelemetryCollector(eval_interval=3600, standby=True)

    j = RunJournal()
    reg = MetricsRegistry()
    reg.gauge("paddle_tpu_serving_breaker_open", "h").set(1)
    # the env-var shape: comma-separated failover list
    addr_list = (f"{primary.host}:{primary.port},"
                 f"{standby.host}:{standby.port}")
    assert tshipper.parse_addrs(addr_list) == (primary.addr, standby.addr)
    sh = tshipper.Shipper(addr_list, origin="o1", journal=j, registry=reg,
                          flush_interval=3600, client_timeout=1.0)
    try:
        for i in range(6):
            j.emit("tick.n", i=i)
        sh.flush()
        trans = primary.evaluate_once()
        assert [t["state"] for t in trans] == ["firing"]
        fired_since = primary.engine.firing()[0]["since"]
        assert sh.counters()["failovers"] == 0

        # primary dies mid-stream (no clean close, heartbeat left
        # FRESH). The first failed-over push hits the split-brain
        # fence: the standby refuses to promote while the writer's
        # stamp is fresher than takeover_s — a transiently stalled
        # primary must not lose its log to an eager standby. The
        # shipper re-buffers; nothing is lost.
        _crash_collector(primary)
        for i in range(6, 12):
            j.emit("tick.n", i=i)
        sh.flush()   # fails on primary, rotates, REJECTED by the fence
        assert standby.is_standby
        c = sh.counters()
        assert c["failovers"] == 1 and c["flush_failures"] == 1

        # the writer's heartbeat goes silent past takeover_s: now the
        # failed-over push promotes. The tail the shipper never got
        # acked for is RESENT — the replayed high-water marks dedupe
        # the overlap.
        hb = primary._seg._heartbeat_path
        os.utime(hb, (time.time() - 60, time.time() - 60))
        sh.flush()

        c = sh.counters()
        assert c["flush_failures"] == 1   # the retried flush SUCCEEDED
        fams = {f.name: f for f in sh._families()}
        outcomes = {labels["outcome"]: v for labels, v in
                    fams["paddle_tpu_shipper_flushes_total"].samples}
        assert outcomes["failover"] >= 1 and outcomes["ok"] == 2

        # the standby auto-promoted on the failed-over push
        assert not standby.is_standby
        # zero shipped-event loss, exactly once, in order
        ticks = [e["i"] for e in standby.journal.recent(kind="tick.")
                 if e.get("origin") == "o1"]
        assert ticks == list(range(12))
        # the pre-kill firing alert is FIRING on the standby with its
        # original clock, and NO transition was journaled for it
        firing = standby.engine.firing()
        assert [a["rule"] for a in firing] == ["hot"]
        assert firing[0]["since"] == fired_since
        assert standby.journal.recent(kind="alert.") == []
        # the promoted standby keeps evaluating without a flap
        standby.evaluate_once()
        assert standby.journal.recent(kind="alert.") == []
        # and appends to the shared log: a THIRD collector recovering
        # from it sees the full merged history
        standby.evaluate_once()
    finally:
        sh.close(timeout=5)
        standby.close()
        primary.close()

    col3 = TelemetryCollector(eval_interval=3600, rules=[rule],
                              store_dir=store_dir)
    try:
        ticks = [e["i"] for e in col3.journal.recent(kind="tick.")
                 if e.get("origin") == "o1"]
        assert ticks == list(range(12))
        assert [a["rule"] for a in col3.engine.firing()] == ["hot"]
    finally:
        col3.close()


# ---------------------------------------------------------------------------
# alert-rule hot-reload
# ---------------------------------------------------------------------------


def test_reload_rules_lint_reject_and_state_carry(fresh, tmp_path):
    rules = [alerts.parse_rule(
        "hot", "paddle_tpu_serving_queue_depth > 5 for 0s"),
        alerts.parse_rule(
            "doomed", "paddle_tpu_serving_workers_busy > 0 for 0s")]
    col = TelemetryCollector(eval_interval=3600, rules=rules)
    cli = tshipper.ShipperClient(col.addr)
    try:
        cli.ship_snapshot("r0", _snap("paddle_tpu_serving_queue_depth", 9,
                                      labels={"inst": "0"}))
        cli.ship_snapshot("r0", _snap("paddle_tpu_serving_workers_busy", 2,
                                      labels={"inst": "0"}))
        trans = col.evaluate_once()
        assert sorted(t["rule"] for t in trans) == ["doomed", "hot"]

        # findings REJECT the reload: the running rules stay in force
        findings = col.reload_rules(specs=[
            {"name": "bad", "expr": "paddle_tpu_nope > 1 for 5s"}])
        assert findings and findings[0].startswith("alert:unknown-metric")
        assert {r.name for r in col.engine.rules} == {"hot", "doomed"}
        assert [e["kind"] for e in col.journal.recent(kind="alert.rules")] \
            == ["alert.rules_rejected"]

        # a clean pack swaps in: 'hot' keeps its FIRING instance (new
        # threshold applies next tick), 'doomed' resolves exactly once
        out = col.reload_rules(specs=[
            {"name": "hot",
             "expr": "paddle_tpu_serving_queue_depth > 100 for 0s"},
            {"name": "fresh",
             "expr": "rate(paddle_tpu_serving_errors_total[30s]) > 1 "
                     "for 30s"}])
        assert out == []
        kinds = [e["kind"] for e in col.journal.recent(kind="alert.")]
        assert kinds.count("alert.rules_reloaded") == 1
        assert kinds.count("alert.resolved") == 1   # doomed, on removal
        assert [a["rule"] for a in col.engine.firing()] == ["hot"]
        # next tick: the EDITED threshold takes effect -> hot resolves
        trans = col.evaluate_once()
        assert [(t["rule"], t["state"]) for t in trans] == \
            [("hot", "resolved")]
    finally:
        cli.close()
        col.close()


def test_post_rules_endpoint(fresh):
    col = TelemetryCollector(eval_interval=3600, rules=[])
    srv = col.serve_http()
    try:
        body = json.dumps([
            {"name": "shed",
             "expr": "rate(paddle_tpu_serving_rejected_total[30s]) > 1 "
                     "for 30s"}]).encode()
        req = urllib.request.Request(srv.url + "/rules", data=body,
                                     method="POST")
        doc = json.loads(urllib.request.urlopen(req).read())
        assert doc["accepted"] is True
        assert [r["name"] for r in doc["rules"]] == ["shed"]
        assert {r.name for r in col.engine.rules} == {"shed"}

        # findings: 422, engine untouched
        bad = json.dumps([{"name": "x", "expr": "nope("}]).encode()
        req = urllib.request.Request(srv.url + "/rules", data=bad,
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 422
        doc = json.loads(ei.value.read())
        assert doc["accepted"] is False and doc["findings"]
        assert {r.name for r in col.engine.rules} == {"shed"}

        # not-JSON body: 400, never a traceback
        req = urllib.request.Request(srv.url + "/rules", data=b"not json",
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400
    finally:
        col.close()


def test_sighup_reloads_rules_in_daemon(fresh, tmp_path):
    """The daemon contract: SIGHUP re-lints the --rules file and
    hot-swaps the pack; a file with findings is rejected and the
    running rules stay."""
    from paddle_tpu.telemetry.collector import CollectorProcess

    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps([
        {"name": "first",
         "expr": "paddle_tpu_serving_queue_depth > 5 for 5s"}]))
    with CollectorProcess(rules_path=str(rules)) as cp:
        def rule_names():
            # a transient RST from the child's threaded HTTP daemon is
            # a retry, not a verdict (cross-process poll)
            for _ in range(10):
                try:
                    doc = json.loads(urllib.request.urlopen(
                        cp.http_url + "/alerts", timeout=10).read())
                    return [r["name"] for r in doc["rules"]]
                except (ConnectionError, urllib.error.URLError) as e:
                    last = e
                    time.sleep(0.3)
            raise AssertionError(
                f"collector /alerts unreachable (child rc="
                f"{cp._proc.poll()}, last={last!r})")

        assert rule_names() == ["first"]
        rules.write_text(json.dumps([
            {"name": "second",
             "expr": "paddle_tpu_serving_breaker_open > 0 for 10s"}]))
        os.kill(cp.pid, signal.SIGHUP)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and rule_names() != ["second"]:
            time.sleep(0.2)
        assert rule_names() == ["second"]

        # a broken file is REJECTED on SIGHUP: rules unchanged
        rules.write_text(json.dumps([{"name": "broken", "expr": "x >"}]))
        os.kill(cp.pid, signal.SIGHUP)
        time.sleep(1.0)
        assert rule_names() == ["second"]


# ---------------------------------------------------------------------------
# tools/series_dump.py contract
# ---------------------------------------------------------------------------


def test_series_dump_tool_contract(fresh, tmp_path, capsys):
    import importlib
    tool = importlib.import_module("tools.series_dump")

    store_dir = str(tmp_path / "dstore")
    col = TelemetryCollector(eval_interval=3600, rules=[],
                             store_dir=store_dir)
    cli = tshipper.ShipperClient(col.addr)
    for i in range(5):
        cli.ship_snapshot("r0", _snap("paddle_tpu_serving_queue_depth", i,
                                      labels={"inst": "0"}))
    cli.close()
    col._seg.rotate()
    col.close()

    assert tool.main([store_dir, "--list"]) == 0
    out = capsys.readouterr().out
    assert 'paddle_tpu_serving_queue_depth{inst="0",origin="r0"}' in out

    assert tool.main([store_dir, "--metric",
                      "paddle_tpu_serving_queue_depth",
                      "--labels", "origin=r0"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert [p[1] for p in doc["series"][0]["points"]] == \
        [0.0, 1.0, 2.0, 3.0, 4.0]

    assert tool.main([store_dir, "--metric",
                      "paddle_tpu_serving_queue_depth",
                      "--format", "csv"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("key,t,value") and out.count("\n") == 6

    assert tool.main([store_dir, "--validate"]) == 0
    # findings: a flipped byte in a sealed segment -> exit 2, named
    segs = sorted(p for p in os.listdir(store_dir) if p.endswith(".log"))
    faults.flip_byte(store_dir, segs[0])
    assert tool.main([store_dir, "--validate"]) == 2
    out = capsys.readouterr().out
    assert "checksum mismatch" in out or "CRC" in out
    # nothing to dump -> 2; not a store dir -> 2
    assert tool.main([store_dir, "--metric", "paddle_tpu_nope"]) == 2
    assert tool.main([str(tmp_path / "empty"), "--list"]) == 2


# ---------------------------------------------------------------------------
# the overhead pin: ingest hot path WITH persistence
# ---------------------------------------------------------------------------


DIM, CLASSES, BS = 6, 4, 4


def _net(x, label):
    from paddle_tpu import layers as L
    h = L.fc(x, 16, name="fc1")
    logits = L.fc(h, CLASSES, name="fc2")
    return {"loss": L.mean(L.softmax_with_cross_entropy(logits, label))}


def test_persisted_ingest_under_2pct_of_k16_dispatch(fresh, tmp_path):
    """The established telemetry pin extended to persistence: one
    EVENTS-batch ingest (dedupe + journal + CRC-framed write-through
    append) must cost under 2% of a measured K=16 fused dispatch."""
    from paddle_tpu.data.feeder import stack_batches

    prog = pt.build(_net)
    feed = {"x": np.zeros((BS, DIM), np.float32),
            "label": np.zeros((BS, 1), np.int64)}
    k, n = 16, 6
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.randn(BS, DIM).astype(np.float32),
              "label": rng.randint(0, CLASSES, (BS, 1)).astype(np.int64)}
             for _ in range(4)]
    tr = pt.Trainer(prog, opt.SGD(0.1), loss_name="loss")
    tr.startup(sample_feed=feed)
    stacked = tr._put_feed(
        stack_batches([feeds[i % len(feeds)] for i in range(k)]),
        stacked=True)
    out = tr.run_steps(stacked, k=k)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = tr.run_steps(stacked, k=k)
    jax.block_until_ready(out)
    dispatch_s = (time.perf_counter() - t0) / n

    col = TelemetryCollector(eval_interval=3600, rules=[],
                             store_dir=str(tmp_path / "perf"))
    try:
        # realistic shape: the shipper flushes BATCHES (one journal
        # event per dispatch, many dispatches per 0.25s flush), so the
        # pin is per EVENT — dedupe + journal + CRC-framed append
        # amortized over a 16-event batch, vs one dispatch each
        reps, per_batch = 400, 16
        batches = [
            {"run": "r", "events": [
                {"run": "r", "seq": b * per_batch + i,
                 "sseq": b * per_batch + i, "t": 1.0,
                 "kind": "trainer.dispatch", "span": "s", "k": k}
                for i in range(1, per_batch + 1)]}
            for b in range(reps)]
        t0 = time.perf_counter()
        for body in batches:
            col._ingest_events("o-bench", body)
        per_event = (time.perf_counter() - t0) / (reps * per_batch)
        assert per_event < 0.02 * dispatch_s, (per_event, dispatch_s)
    finally:
        col.close()


# ---------------------------------------------------------------------------
# the HA drill end to end (real SIGKILL)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_drill_collector_failover_contract(fresh):
    import importlib
    import tempfile

    fleet_drill = importlib.import_module("tools.fleet_drill")
    with tempfile.TemporaryDirectory(prefix="fd_colfail_") as root:
        violations = fleet_drill.drill_collector_failover(root, 2, 45)
    assert violations == []
