"""Asynchronous parameter-server training — client + server manager for
the C++ pserver (native/pserver.cc).

Capability parity with the reference's async-SGD path
(listen_and_serv_op.cc:217 RunAsyncLoop; distribute_transpiler.py
sync_mode=False): trainers compute gradients locally and push them to a
parameter server WITHOUT barriers; the server applies the optimizer
update per gradient on arrival; trainers pull fresh params on their own
schedule. DC-ASGD (distribute_transpiler.py:1571) adjusts each pushed
gradient by second-order delay compensation
``g + lambda * g*g*(w - w_bak[trainer])`` with ``w_bak`` captured at
this trainer's last pull.

The TPU division of labor: the jitted part is ONLY the gradient
computation (value_and_grad of the program, compiled by XLA); the
optimizer state lives host-side on the server exactly where the
reference placed it (optimize blocks run on the pserver,
distribute_transpiler.py:592-837). Synchronous SPMD collectives remain
the first-class training path — this module exists for the async-SGD /
DC-ASGD capability rows, which trade gradient staleness for never
stalling on a straggler.

Typical use (one server process, N trainer processes)::

    srv = PServerProcess(lr=0.05, optimizer="sgd")      # once
    t = AsyncPSTrainer(prog, srv.addr, trainer_id=k)    # per trainer
    t.startup(sample_feed=batch)
    for batch in data:
        out = t.step(batch)                              # push-grad, no barrier

Sharded fleet with elastic membership (pass a server LIST — params
route by rendezvous hash via :class:`PSShardGroup`; ``resize`` rides a
split/merge mid-run with full optimizer state migrated)::

    t = AsyncPSTrainer(prog, [srv1.addr, srv2.addr])
    ...
    t.client.resize([srv1.addr, srv2.addr, srv3.addr])   # shard split
"""

from __future__ import annotations

import socket
import subprocess
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..core.config import get_flag
from ..core.errors import enforce

from ..native import build_native


def _build_server() -> str:
    return build_native("pserver.cc", "pserver_server")


class PServerProcess:
    """Spawn-and-own a pserver_server process (the listen_and_serv
    runtime analog; one per param shard group in a real deployment)."""

    def __init__(self, port: int = 0, lr: float = 0.01,
                 optimizer: str = "sgd", dc_asgd: bool = False,
                 dc_lambda: float = 1.0, snapshot_path: Optional[str] = None):
        enforce(optimizer in ("sgd", "adagrad"),
                f"pserver optimizer must be sgd|adagrad, got {optimizer}")
        binpath = _build_server()
        self._proc = subprocess.Popen(
            [binpath, str(port), repr(float(lr)), optimizer,
             "1" if dc_asgd else "0", repr(float(dc_lambda)),
             snapshot_path or "-"],
            stdout=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline().strip()
        if not line.startswith("PORT "):
            raise RuntimeError(f"pserver_server failed to start: {line!r}")
        self.port = int(line.split()[1])
        self.addr = ("127.0.0.1", self.port)

    def stop(self):
        if self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class ReplyLost(ConnectionError):
    """A NON-idempotent request was SENT but the connection died before
    the peer's reply arrived: the request may or may not have applied
    remotely. The client reconnects for subsequent requests but never
    RESENDS this one — at-most-once semantics (a resend could
    double-apply)."""


class PushUndelivered(ReplyLost):
    """A push was SENT but the connection died before the server's
    reply arrived: the update may or may not have applied server-side.
    The client reconnects for subsequent requests but never RESENDS the
    push — at-most-once semantics (a resend could double-apply the
    gradient; losing one is ordinary async-SGD staleness)."""


def child_python_env(pop: Sequence[str] = ()) -> Dict[str, str]:
    """Environment for spawning a python child that must import this
    package: the parent's env with ``sys.path`` folded into
    ``PYTHONPATH`` (the child resolves ``paddle_tpu`` exactly as the
    parent did), minus the ``pop``'d variables — a spawned collector
    must not inherit ``PDTPU_TELEMETRY_ADDR`` and ship to itself, and
    a spawned replica must not inherit ``PDTPU_TELEMETRY_ORIGIN`` or
    every process in the fleet collapses onto ONE collector origin
    (colliding series, absence alerts that can never fire). Shared by
    every framed-wire process spawner (fleet replicas, the telemetry
    collector daemon)."""
    import os
    import sys

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in sys.path if p] +
        [env[k] for k in ("PYTHONPATH",) if env.get(k)])
    for k in pop:
        env.pop(k, None)
    return env


def read_line(sock: socket.socket) -> str:
    """Read one ``\\n``-terminated ASCII header line off a framed-
    protocol socket (the pserver / fleet-replica wire discipline)."""
    buf = bytearray()
    while True:
        c = sock.recv(1)
        if not c:
            raise ConnectionError("peer closed connection")
        if c == b"\n":
            return buf.decode()
        buf += c


def read_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes (a framed body) or raise
    ``ConnectionError`` on EOF mid-frame."""
    out = bytearray()
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("peer closed connection")
        out += chunk
    return bytes(out)


class FramedClient:
    """Transport base for the length-prefixed framed protocols
    (``native/pserver.cc`` verbs, the fleet replica wire): one ASCII
    header line, an optional binary body of a length named in the
    header, and a reply of the same shape.

    **Reconnect-with-backoff** (the ``data.master.MasterClient``
    discipline): a dead connection or restarted peer is retried
    transparently with exponential backoff for IDEMPOTENT requests.
    Non-idempotent requests are sent at most once: connection
    establishment still retries, but a reply lost after a completed
    send raises :class:`ReplyLost` (subclasses override
    :meth:`_make_reply_lost` for a typed error — ``PSClient`` raises
    :class:`PushUndelivered`) instead of resending."""

    peer_name = "peer"

    def __init__(self, addr: Tuple[str, int],
                 timeout: float = 30.0, retries: int = 30,
                 retry_backoff: float = 0.05, retry_backoff_max: float = 2.0,
                 connect: bool = True):
        self.addr = tuple(addr)
        self.timeout = timeout
        self.retries = max(1, int(retries))
        self.retry_backoff = retry_backoff
        self.retry_backoff_max = retry_backoff_max
        self._sock: Optional[socket.socket] = None
        # resilience counters (surfaced by report(), not bare pokes):
        # connects counts every successful TCP establish (reconnects =
        # connects - 1), retry_attempts every request re-issued after a
        # transport failure, replies_lost the at-most-once requests
        # whose reply was lost (never resent)
        self.requests_sent = 0
        self.retry_attempts = 0
        self.connects = 0
        self.replies_lost = 0
        self.last_reply: Optional[str] = None
        if connect:
            self._connect()  # fail fast on misconfigured addr

    # -- transport ----------------------------------------------------------
    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self.connects += 1

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _readline(self) -> str:
        return read_line(self._sock)

    def _read_exact(self, n: int) -> bytes:
        return read_exact(self._sock, n)

    def _on_err_reply(self, resp: str):
        """An ``ERR ...`` header arrived — raise it typed. The base
        protocol carries no body after ERR; subclasses whose protocol
        frames an error detail body read it here BEFORE raising (the
        persistent connection must stay in sync)."""
        raise RuntimeError(f"{self.peer_name}: {resp}")

    def _make_reply_lost(self, cause: Exception) -> ReplyLost:
        return ReplyLost(
            f"reply lost after send ({cause}); NOT resending — the "
            "request may have applied remotely")

    def _request(self, line: str, payload: bytes = b"",
                 idempotent: bool = True, body_len=None,
                 timeout: Optional[float] = None):
        """One protocol round trip with reconnect/backoff. ``body_len``
        (resp → byte count) reads a framed payload INSIDE the retry
        scope, so a connection lost mid-body retries the whole request
        (idempotent case) instead of desyncing. ``timeout`` overrides
        the socket timeout for this round trip only (a RELOAD takes
        seconds, a health probe must fail in fractions of one).
        Returns ``resp`` or ``(resp, body)``."""
        delay = self.retry_backoff
        last_err: Optional[Exception] = None
        for attempt in range(self.retries):
            if attempt:
                self.retry_attempts += 1
            try:
                if self._sock is None:
                    self._connect()
            except OSError as e:
                last_err = e
                time.sleep(delay)
                delay = min(delay * 2, self.retry_backoff_max)
                continue
            sent = False
            try:
                if timeout is not None:
                    self._sock.settimeout(timeout)
                try:
                    self._sock.sendall(line.encode() + b"\n" + payload)
                    sent = True
                    self.requests_sent += 1
                    resp = self._readline()
                    self.last_reply = resp
                    if resp.startswith("ERR"):
                        self._on_err_reply(resp)
                    if body_len is None:
                        return resp
                    return resp, self._read_exact(body_len(resp))
                finally:
                    if timeout is not None and self._sock is not None:
                        self._sock.settimeout(self.timeout)
            except (OSError, ConnectionError) as e:
                self._drop_sock()
                last_err = e
                if sent and not idempotent:
                    self.replies_lost += 1
                    raise self._make_reply_lost(e) from e
                time.sleep(delay)
                delay = min(delay * 2, self.retry_backoff_max)
        raise ConnectionError(
            f"{self.peer_name} unreachable at {self.addr} after "
            f"{self.retries} attempts: {last_err}")

    def close(self):
        if self._sock is None:
            return
        try:
            self._sock.sendall(b"QUIT\n")
        except OSError:
            pass
        self._drop_sock()


class PSClient(FramedClient):
    """Socket client for the pserver protocol. Dense params are flat f32
    buffers keyed by name; sparse pushes update [rows, dim] params
    row-wise (the distributed-lookup-table update path).

    Transport semantics come from :class:`FramedClient`
    (reconnect-with-backoff for IDEMPOTENT requests —
    ``pull``/``init_param`` (first-writer-wins makes a resend a no-op)/
    ``status``/``save``). ``push``/``push_quantized``/``push_rows`` are
    NOT idempotent: the request is sent at most once; connection
    establishment still retries, but a reply lost after a completed send
    raises :class:`PushUndelivered` instead of resending (see
    :class:`AsyncPSTrainer.step`, which drops that step's gradient and
    keeps training)."""

    peer_name = "pserver"

    def __init__(self, addr: Tuple[str, int], trainer_id: int = 0,
                 timeout: float = 30.0, retries: int = 30,
                 retry_backoff: float = 0.05, retry_backoff_max: float = 2.0):
        self.trainer_id = int(trainer_id)
        self.pushes_sent = 0
        self.pulls = 0
        super().__init__(addr, timeout=timeout, retries=retries,
                         retry_backoff=retry_backoff,
                         retry_backoff_max=retry_backoff_max)

    @property
    def pushes_undelivered(self) -> int:
        """At-most-once pushes whose reply was lost (never resent) —
        the pserver-flavored name of ``replies_lost``."""
        return self.replies_lost

    def _make_reply_lost(self, cause: Exception) -> ReplyLost:
        return PushUndelivered(
            f"push reply lost after send ({cause}); NOT resending — "
            "the update may have applied server-side")

    # -- param API ----------------------------------------------------------
    @staticmethod
    def _check_name(name: str) -> str:
        # the server parses names with %255s: longer (or
        # whitespace-bearing) names would truncate, desyncing the framed
        # payload that follows — reject client-side
        enforce(0 < len(name) <= 255 and not any(c.isspace() for c in name),
                f"param name must be 1-255 chars with no whitespace, got "
                f"{name[:64]!r}... ({len(name)} chars)")
        return name

    def init_param(self, name: str, value: np.ndarray) -> bool:
        """Register a param (first writer wins). Returns True if this
        call created it."""
        data = np.ascontiguousarray(value, dtype=np.float32).tobytes()
        resp = self._request(f"INIT {self._check_name(name)} {len(data)}", data)
        return resp == "OK NEW"

    @staticmethod
    def _trace_suffix(span: Optional[str]) -> str:
        """Optional trace field in the framed header: `` trace=<id>``
        appended AFTER the fields a peer parses positionally. An OLD
        peer's ``sscanf`` stops at its last conversion and ignores
        trailing tokens — fully backward/forward compatible; a NEW
        pserver echoes the token in its reply so the round trip is
        attributable to the specific server (see ``last_reply``)."""
        if span is None:
            return ""
        enforce(not any(c.isspace() for c in span),
                f"trace span must not contain whitespace: {span!r}")
        return f" trace={span}"

    def pull(self, name: str, shape, dtype=np.float32,
             span: Optional[str] = None) -> np.ndarray:
        _, data = self._request(
            f"PULL {self.trainer_id} {self._check_name(name)}"
            f"{self._trace_suffix(span)}",
            body_len=lambda resp: int(resp.split()[1]))
        self.pulls += 1
        arr = np.frombuffer(data, dtype=np.float32)
        return arr.reshape(shape).astype(dtype, copy=False)

    def push(self, name: str, grad: np.ndarray,
             span: Optional[str] = None) -> int:
        data = np.ascontiguousarray(grad, dtype=np.float32).tobytes()
        resp = self._request(
            f"PUSH {self.trainer_id} {self._check_name(name)} {len(data)}"
            f"{self._trace_suffix(span)}",
            data, idempotent=False)
        self.pushes_sent += 1
        return int(resp.split()[1])

    def push_quantized(self, name: str, grad: np.ndarray,
                       span: Optional[str] = None) -> int:
        """Int8-quantized dense push (abs-max symmetric, one f32 scale):
        4× less wire than :meth:`push`, dequantized server-side before
        the identical update path — the quantized-collective technique
        (EQuARX lineage) applied to the trainer→pserver hop."""
        g = np.ascontiguousarray(grad, dtype=np.float32).reshape(-1)
        scale = float(max(np.max(np.abs(g)), 1e-30))
        q = np.clip(np.round(g / scale * 127.0), -127, 127).astype(np.int8)
        resp = self._request(
            f"PUSHQ {self.trainer_id} {self._check_name(name)} {q.size} "
            f"{scale!r}{self._trace_suffix(span)}", q.tobytes(),
            idempotent=False)
        self.pushes_sent += 1
        return int(resp.split()[1])

    def push_quantized_blocks(self, name: str, grad: np.ndarray,
                              span: Optional[str] = None, bits: int = 8,
                              block: int = 256) -> int:
        """Block-scaled quantized dense push (PUSHQB): one f32 abs-max
        scale per ``block`` elements instead of :meth:`push_quantized`'s
        single per-tensor scale — an outlier only flattens its own
        block — and optional int4 packing (two codes per byte) for
        ~8× less wire. Shares its codec with the in-graph quantized
        collective (``parallel.quantized_collectives``): zero blocks
        encode exactly to zeros, non-finite blocks poison only their
        own scale. Dequantized server-side before the identical update
        path. The body is scales then codes; n is the UNPADDED element
        count (the server derives the padded/packed lengths from
        n/bits/block, pinned by the wire-contract analyzer)."""
        from .quantized_collectives import encode_wire_blocks
        g = np.ascontiguousarray(grad, dtype=np.float32).reshape(-1)
        q, scales = encode_wire_blocks(g, bits=bits, block_size=block)
        resp = self._request(
            f"PUSHQB {self.trainer_id} {self._check_name(name)} {g.size} "
            f"{int(bits)} {int(block)}{self._trace_suffix(span)}",
            scales.tobytes() + q.tobytes(), idempotent=False)
        self.pushes_sent += 1
        return int(resp.split()[1])

    def push_rows(self, name: str, row_ids: np.ndarray,
                  row_grads: np.ndarray,
                  span: Optional[str] = None) -> int:
        """Sparse push: ``row_grads[k]`` updates row ``row_ids[k]`` of the
        [rows, dim] param — SelectedRows send + pserver row-optimize."""
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        vals = np.ascontiguousarray(row_grads, dtype=np.float32)
        enforce(vals.ndim == 2 and ids.shape == (vals.shape[0],),
                "push_rows wants ids [n] and grads [n, dim]")
        resp = self._request(
            f"PUSHROWS {self.trainer_id} {self._check_name(name)} "
            f"{vals.shape[0]} {vals.shape[1]}{self._trace_suffix(span)}",
            ids.tobytes() + vals.tobytes(), idempotent=False)
        self.pushes_sent += 1
        return int(resp.split()[1])

    def report(self) -> Dict[str, Any]:
        """Client-side resilience/traffic counters (the typed surface
        tests and bench read instead of poking private attributes):
        requests/pushes/pulls sent, reconnects (successful re-
        establishes after the first connect), retry attempts, and
        at-most-once pushes whose reply was lost."""
        return {
            "addr": f"{self.addr[0]}:{self.addr[1]}",
            "requests": self.requests_sent,
            "pushes": self.pushes_sent,
            "pulls": self.pulls,
            "reconnects": max(0, self.connects - 1),
            "retries": self.retry_attempts,
            "pushes_undelivered": self.pushes_undelivered,
        }

    def save(self) -> None:
        """Trigger an atomic server-side checkpoint of params + optimizer
        accumulators (shard-checkpoint capability; the server recovers it
        at startup when launched with the same snapshot_path)."""
        self._request("SAVE")

    def status(self) -> Dict[str, int]:
        resp = self._request("STATUS")
        return {k: int(v) for k, v in
                (kv.split("=") for kv in resp[3:].split())}

    # -- shard migration ----------------------------------------------------
    def export_param(self, name: str) -> Tuple[np.ndarray, np.ndarray, int]:
        """Pull a param's FULL server-side state for shard migration:
        ``(value, optimizer accum, version)`` as flat f32 arrays.
        Idempotent (a read), so it retries transparently like
        :meth:`pull`."""

        def _blen(resp):
            _, vlen, alen, _ = resp.split()
            return (int(vlen) + int(alen)) * 4

        resp, data = self._request(f"EXPORT {self._check_name(name)}",
                                   body_len=_blen)
        _, vlen, alen, version = resp.split()
        vlen, alen = int(vlen), int(alen)
        buf = np.frombuffer(data, dtype=np.float32)
        return buf[:vlen].copy(), buf[vlen:vlen + alen].copy(), int(version)

    def import_param(self, name: str, value: np.ndarray,
                     accum: np.ndarray, version: int = 0) -> None:
        """Install a param's full state on this server (absolute
        overwrite-or-create) — the receive half of a shard split/merge.
        Unlike :meth:`push` this IS idempotent (it sets absolute state,
        it does not apply a delta), so a reply lost after send retries
        transparently instead of raising :class:`PushUndelivered`."""
        v = np.ascontiguousarray(value, dtype=np.float32).reshape(-1)
        a = np.ascontiguousarray(accum, dtype=np.float32).reshape(-1)
        self._request(
            f"IMPORT {self._check_name(name)} {v.size} {a.size} "
            f"{int(version)}", v.tobytes() + a.tobytes())

    def delete_param(self, name: str) -> None:
        """Drop a param from this server (idempotent) — the cleanup
        half of shard migration on the OLD owner."""
        self._request(f"DELETE {self._check_name(name)}")


def _rendezvous_score(name: str, addr: Tuple[str, int]) -> Tuple[int, Tuple]:
    """Highest-random-weight (rendezvous) score of ``(name, server)``:
    deterministic across processes (crc32, no PYTHONHASHSEED
    dependence), and minimal-movement by construction — adding or
    removing a server only re-homes the params whose max moved, ~1/N of
    the set, never a full reshuffle. The addr tiebreak makes the
    ordering total."""
    import zlib as _zlib

    key = f"{name}@{addr[0]}:{addr[1]}".encode()
    return (_zlib.crc32(key) & 0xFFFFFFFF, (str(addr[0]), int(addr[1])))


class PSShardGroup:
    """Client-side shard router over N pservers — the membership-change
    half of elastic training for the async-PS path (the reference's
    slice_variable/pserver-shard analog, distribute_transpiler.py:81,
    made dynamic).

    Params are routed to servers by rendezvous hashing of the param
    name, so every trainer process computes the SAME owner table from
    the same address list with no coordination. The per-server
    transport is a plain :class:`PSClient`, so the reconnect semantics
    are preserved verbatim: pulls/exports retry transparently with
    backoff, pushes stay at-most-once (:class:`PushUndelivered` on a
    lost reply — counted by ``AsyncPSTrainer.step``, never resent).

    **Membership change** (:meth:`resize`): when the server set grows
    (shard split) or shrinks (shard merge), exactly the params whose
    rendezvous owner changed migrate — full state (value + optimizer
    accumulator + version) moves via ``EXPORT`` from the old owner and
    ``IMPORT`` (absolute overwrite, idempotent) onto the new one, and
    the routing table switches only after EVERY move landed. A crash
    mid-resize (see the ``ps_resize:*`` crash points) therefore leaves
    the OLD routing fully authoritative; re-running ``resize`` re-
    exports from the old owners (picking up any pushes that landed in
    between) and re-imports idempotently; after the switch the old
    owner's copy is DELETEd, so repeated resizes do not accumulate dead
    shards server-side. One coordinator performs the migrating
    ``resize``; other trainer processes adopt the new membership with
    :meth:`rebind` (route-only, no data movement). A trainer that has
    NOT rebound yet and pushes into a migrated shard fails loudly
    (``ERR unknown param`` — the old copy is gone), never silently
    updates an orphan: rebind promptly after the coordinator announces
    a resize. Per-trainer DC-ASGD staleness baks do not migrate (same
    contract as the server's own snapshot).

    Crash points (armed by ``testing.faults``):

    - ``ps_resize:exported`` — after one param's state left its old
      owner, before the import (fires per moved param)
    - ``ps_resize:imported`` — all moves imported, routing not yet
      switched
    """

    def __init__(self, addrs: Sequence[Tuple[str, int]], trainer_id: int = 0,
                 **client_kw):
        enforce(len(addrs) >= 1, "PSShardGroup needs at least one pserver")
        self.trainer_id = int(trainer_id)
        self._client_kw = dict(client_kw)
        self._clients: Dict[Tuple[str, int], PSClient] = {}
        self.addrs: List[Tuple[str, int]] = []
        self._names: set = set()
        # counters of transports CLOSED by resize()/rebind(): folded
        # into report() so the aggregate totals stay monotonic across
        # membership changes (a Prometheus counter must never reverse)
        self._retired_counts: Dict[str, int] = {}
        self._set_addrs(addrs)

    def _set_addrs(self, addrs) -> None:
        new = [(str(h), int(p)) for h, p in addrs]
        enforce(len(set(new)) == len(new),
                f"duplicate pserver addrs in {new}")
        self.addrs = new

    def _client(self, addr: Tuple[str, int]) -> PSClient:
        if addr not in self._clients:
            self._clients[addr] = PSClient(addr, trainer_id=self.trainer_id,
                                           **self._client_kw)
        return self._clients[addr]

    def owner(self, name: str) -> Tuple[str, int]:
        """The server currently responsible for ``name``."""
        return max(self.addrs, key=lambda a: _rendezvous_score(name, a))

    # -- PSClient surface, routed by owner ----------------------------------
    def init_param(self, name: str, value: np.ndarray) -> bool:
        self._names.add(name)
        return self._client(self.owner(name)).init_param(name, value)

    def pull(self, name: str, shape, dtype=np.float32,
             span: Optional[str] = None) -> np.ndarray:
        return self._client(self.owner(name)).pull(name, shape, dtype=dtype,
                                                   span=span)

    def push(self, name: str, grad: np.ndarray,
             span: Optional[str] = None) -> int:
        return self._client(self.owner(name)).push(name, grad, span=span)

    def push_quantized(self, name: str, grad: np.ndarray,
                       span: Optional[str] = None) -> int:
        return self._client(self.owner(name)).push_quantized(name, grad,
                                                             span=span)

    def push_quantized_blocks(self, name: str, grad: np.ndarray,
                              span: Optional[str] = None, bits: int = 8,
                              block: int = 256) -> int:
        return self._client(self.owner(name)).push_quantized_blocks(
            name, grad, span=span, bits=bits, block=block)

    def push_rows(self, name: str, row_ids, row_grads,
                  span: Optional[str] = None) -> int:
        return self._client(self.owner(name)).push_rows(name, row_ids,
                                                        row_grads, span=span)

    def save(self) -> None:
        for addr in self.addrs:
            self._client(addr).save()

    def status(self) -> Dict[str, int]:
        """Aggregate counters summed over the live membership."""
        out: Dict[str, int] = {}
        for addr in self.addrs:
            for k, v in self._client(addr).status().items():
                out[k] = out.get(k, 0) + v
        return out

    _AGG_KEYS = ("requests", "pushes", "pulls", "reconnects", "retries",
                 "pushes_undelivered")

    def _retire_client(self, client: PSClient) -> None:
        """Fold a departing transport's counters into the retired
        aggregate BEFORE closing it — totals must stay monotonic
        across resize()/rebind() (their traffic happened)."""
        rep = client.report()
        for k in self._AGG_KEYS:
            self._retired_counts[k] = self._retired_counts.get(k, 0) + rep[k]
        client.close()

    def report(self) -> Dict[str, Any]:
        """Client-side counters: aggregate totals over every transport
        this group has opened — servers that left the membership
        included (their traffic is folded into the totals at
        retirement, so the aggregate never goes backwards) — plus the
        per-server breakdown of the LIVE transports keyed by
        ``host:port``."""
        servers = {f"{a[0]}:{a[1]}": c.report()
                   for a, c in sorted(self._clients.items())}
        agg: Dict[str, Any] = {k: self._retired_counts.get(k, 0)
                               for k in self._AGG_KEYS}
        for rep in servers.values():
            for k in self._AGG_KEYS:
                agg[k] += rep[k]
        agg["servers"] = servers
        return agg

    def close(self) -> None:
        for c in self._clients.values():
            self._retire_client(c)
        self._clients.clear()

    # -- membership change --------------------------------------------------
    def shard_map(self) -> Dict[Tuple[str, int], List[str]]:
        """{server addr: sorted param names it owns} — the routing table
        the group would use right now."""
        out: Dict[Tuple[str, int], List[str]] = {a: [] for a in self.addrs}
        for name in sorted(self._names):
            out[self.owner(name)].append(name)
        return out

    def resize(self, new_addrs: Sequence[Tuple[str, int]]) -> List[str]:
        """Split/merge the shard set onto a new server membership.
        Returns the (sorted) param names that migrated. Routing switches
        atomically at the end — any failure (unreachable exporter, an
        injected crash) leaves the old membership fully authoritative
        and the call retryable."""
        from .. import resilience

        new = [(str(h), int(p)) for h, p in new_addrs]
        enforce(len(set(new)) == len(new) and new,
                f"resize: bad membership {new}")
        old_owner = {name: self.owner(name) for name in self._names}
        new_owner = {name: max(new, key=lambda a: _rendezvous_score(name, a))
                     for name in self._names}
        moves = sorted(n for n in self._names
                       if old_owner[n] != new_owner[n])
        for name in moves:
            value, accum, version = \
                self._client(old_owner[name]).export_param(name)
            resilience.crash_point("ps_resize:exported")
            self._client(new_owner[name]).import_param(name, value, accum,
                                                       version)
        resilience.crash_point("ps_resize:imported")
        self._set_addrs(new)
        # ONLY after routing switched: drop the migrated shards from
        # their old owners (idempotent DELETE). Before the switch the
        # old copy is the crash-retry safety net; after it, keeping it
        # would leak a full value+accum per move AND silently absorb
        # pushes from trainers that have not rebound — deleting makes
        # those fail loudly (ERR unknown param) instead. Best-effort:
        # an old owner that already left/died has nothing worth
        # cleaning, and a skipped delete only costs memory until that
        # server restarts fresh.
        for name in moves:
            addr = old_owner[name]
            if addr not in self.addrs:
                continue  # server left the membership with its copy
            try:
                self._client(addr).delete_param(name)
            except (ConnectionError, OSError) as e:
                _ps_log().warning("could not clean up migrated shard %s "
                                  "on %s (%s)", name, addr, e)
        # drop transports to servers that left the membership
        for addr in [a for a in self._clients if a not in self.addrs]:
            self._retire_client(self._clients.pop(addr))
        _ps_log().info("resharded %d param(s) onto %d server(s)",
                       len(moves), len(new))
        return moves

    def rebind(self, new_addrs: Sequence[Tuple[str, int]]) -> None:
        """Adopt a membership some OTHER process's :meth:`resize`
        already migrated: route-only, no data movement."""
        self._set_addrs(new_addrs)
        for addr in [a for a in self._clients if a not in self.addrs]:
            self._retire_client(self._clients.pop(addr))


def _ps_log():
    import logging

    return logging.getLogger("paddle_tpu.async_ps")


def _named_leaves(tree) -> Sequence[Tuple[str, Any]]:
    """Stable name per leaf from its pytree path (the send_recv var-name
    analog)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))
            for k in path)
        out.append((name.replace(" ", "_") or "root", leaf))
    return out


def _make_ps_client(addr, trainer_id: int):
    """``addr`` may be one ``(host, port)`` (a single pserver → plain
    :class:`PSClient`), a sequence of them (a shard set →
    :class:`PSShardGroup`), or an already-built client/group (shared by
    a membership coordinator)."""
    if isinstance(addr, (PSClient, PSShardGroup)):
        return addr
    seq = list(addr)
    if seq and isinstance(seq[0], (tuple, list)):
        return PSShardGroup(seq, trainer_id=trainer_id)
    return PSClient(tuple(seq), trainer_id=trainer_id)


def _register_ps_telemetry(trainer: "AsyncPSTrainer") -> int:
    """Register the async-PS trainer's scrape-time collector: the
    client transport counters (push/pull/reconnect/retry/undelivered)
    plus the trainer's ``pushes_lost`` and step gauge, all read from
    :meth:`AsyncPSTrainer.report`'s store at scrape time. Weakly bound
    to the trainer (the registry hands the live trainer back at
    scrape time)."""
    from ..telemetry import get_registry
    from ..telemetry.registry import counter_family, gauge_family

    def collect(tr):
        rep = tr.report()
        cli = rep["client"]
        labels = {"inst": tr.telemetry_inst}
        return [
            gauge_family("paddle_tpu_ps_trainer_step",
                         "Async-PS trainer global step",
                         [(labels, rep["global_step"])]),
            counter_family(
                "paddle_tpu_ps_pushes_lost_total",
                "At-most-once pushes dropped after a lost reply",
                [(labels, rep["pushes_lost"])]),
            counter_family("paddle_tpu_ps_pushes_total",
                           "Gradient pushes sent to pservers",
                           [(labels, cli["pushes"])]),
            counter_family("paddle_tpu_ps_pulls_total",
                           "Parameter pulls from pservers",
                           [(labels, cli["pulls"])]),
            counter_family("paddle_tpu_ps_reconnects_total",
                           "Transport re-establishes after the first "
                           "connect", [(labels, cli["reconnects"])]),
            counter_family("paddle_tpu_ps_retries_total",
                           "Requests re-issued after a transport failure",
                           [(labels, cli["retries"])]),
        ]

    return get_registry().add_collector(collect, owner=trainer)


class AsyncPSTrainer:
    """Barrier-free trainer: jitted local gradients, server-side updates.

    ``pull_interval`` controls staleness: 1 pulls fresh params before
    every step (matches plain SGD exactly when training alone); larger
    values trade staleness for fewer round-trips — the async knob the
    reference exposes through sync_mode=False.

    ``addr`` may be a single pserver ``(host, port)`` or a LIST of them:
    the latter shards params across the set via :class:`PSShardGroup`,
    and ``trainer.client.resize([...])`` rides a pserver membership
    change mid-run (shard split/merge with state preserved) without
    touching the step loop — pushes into a migrating shard keep their
    at-most-once semantics (`pushes_lost` counts, never resends).
    """

    def __init__(self, program, addr, loss_name: str = "loss",
                 trainer_id: int = 0, pull_interval: int = 1,
                 fetch_list: Optional[Sequence[str]] = None,
                 compress_grads: bool = False, strategy=None):
        import jax

        self.program = program
        self.loss_name = loss_name
        self.client = _make_ps_client(addr, trainer_id)
        self.pull_interval = max(1, int(pull_interval))
        self.compress_grads = bool(compress_grads)
        # DistStrategy.quantized_allreduce routes pushes through the
        # SAME block-scaled encoder the collective path uses (PUSHQB
        # verb): the one strategy knob covers both link crossings.
        # Legacy compress_grads=True keeps the per-tensor PUSHQ verb.
        qmode = ((getattr(strategy, "quantized_allreduce", "none")
                  if strategy is not None else "none") or "none")
        enforce(qmode in ("none", "int8", "int4"),
                f"DistStrategy.quantized_allreduce={qmode!r} "
                "(none|int8|int4)")
        self.quant_bits = (None if qmode == "none"
                           else (8 if qmode == "int8" else 4))
        self.quant_block = int(getattr(strategy, "quant_block_size", 256)
                               ) if strategy is not None else 256
        self.fetch_list = list(fetch_list) if fetch_list is not None else None
        self.params = None
        self.state = None
        self.global_step = 0
        self.pushes_lost = 0  # at-most-once pushes whose reply was lost
        # unified telemetry: a per-step span rides the wire protocol's
        # optional trace field (old pservers ignore it), and the
        # client/trainer counters publish into the process registry
        # through one scrape-time collector (see report())
        from ..telemetry import get_journal, get_registry
        self.journal = get_journal()
        self.telemetry_inst = get_registry().next_instance("ps_trainer")

        def grad_step(params, state, rng, feed):
            def loss_fn(p, st, r, f):
                out, new_state = program.apply(p, st, training=True, rng=r, **f)
                if isinstance(out, dict):
                    loss = out[loss_name]
                else:
                    loss, out = out, {loss_name: out}
                if self.fetch_list is not None:
                    out = {k: out[k] for k in set(self.fetch_list) | {loss_name}}
                return loss, (out, new_state)

            (_, (out, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, rng, feed)
            return grads, out, new_state

        self._grad_fn = jax.jit(grad_step)
        # registered last: a scrape must never see a half-built trainer
        self._telemetry_cid = _register_ps_telemetry(self)

    # ------------------------------------------------------------------
    def startup(self, rng=None, sample_feed: Optional[Dict[str, Any]] = None):
        import jax

        from ..executor import _abstractify

        if rng is None:
            rng = jax.random.PRNGKey(get_flag("seed"))
        feed = {k: _abstractify(v) for k, v in (sample_feed or {}).items()}
        params, self.state = self.program.init(rng, **feed)
        # first trainer's init wins server-side; then EVERY trainer pulls,
        # so all replicas start from the same point regardless of race
        for name, leaf in _named_leaves(params):
            self.client.init_param(name, np.asarray(leaf, dtype=np.float32))
        self.params = self._pull_into(params)
        return self.params

    def _pull_into(self, params, span: Optional[str] = None):
        import jax

        leaves = _named_leaves(params)
        pulled = [self.client.pull(n, np.shape(l),
                                   dtype=getattr(l, "dtype", np.float32),
                                   span=span)
                  for n, l in leaves]
        treedef = jax.tree_util.tree_structure(params)
        return jax.tree_util.tree_unflatten(treedef, pulled)

    # ------------------------------------------------------------------
    def step(self, feed: Dict[str, Any], rng=None) -> Dict[str, Any]:
        import jax

        enforce(self.params is not None, "call startup() before step()")
        if rng is None:
            rng = jax.random.fold_in(
                jax.random.PRNGKey(get_flag("seed") + 1), self.global_step)
        # one span per optimizer step: every pull/push of this step
        # carries it on the wire (optional trace field in the framed
        # header — a new pserver echoes it, an old one ignores it), so
        # a slow or lost exchange is attributable to THIS step on THIS
        # worker against a specific pserver
        span = self.journal.new_span()
        if self.global_step % self.pull_interval == 0:
            self.params = self._pull_into(self.params, span=span)
        grads, out, self.state = self._grad_fn(self.params, self.state, rng, feed)
        if self.quant_bits is not None:
            import functools
            send = functools.partial(self.client.push_quantized_blocks,
                                     bits=self.quant_bits,
                                     block=self.quant_block)
        elif self.compress_grads:
            send = self.client.push_quantized
        else:
            send = self.client.push
        for name, leaf in _named_leaves(jax.device_get(grads)):
            try:
                send(name, leaf, span=span)
            except PushUndelivered as e:
                # at-most-once: the grad is dropped, never resent (a
                # resend could double-apply) — one stale step, the
                # trade async-SGD already makes for stragglers
                self.pushes_lost += 1
                self.journal.emit(
                    "ps.push_lost", span=span, inst=self.telemetry_inst,
                    param=name, step=self.global_step,
                    server=self._owner_str(name))
                import logging
                logging.getLogger("paddle_tpu.async_ps").warning(
                    "dropped push of %s at step %d (%s); continuing",
                    name, self.global_step, e)
        self.journal.emit("ps.step", span=span, inst=self.telemetry_inst,
                          step=self.global_step)
        self.global_step += 1
        return out

    def _owner_str(self, name: str) -> Optional[str]:
        owner = getattr(self.client, "owner", None)
        if owner is None:
            a = getattr(self.client, "addr", None)
            return f"{a[0]}:{a[1]}" if a else None
        a = owner(name)
        return f"{a[0]}:{a[1]}"

    def report(self) -> Dict[str, Any]:
        """Trainer + transport resilience counters in one dict (the
        typed surface replacing bare-attribute pokes): ``pushes_lost``
        (at-most-once pushes this trainer dropped), ``global_step``,
        and the :meth:`PSClient.report`/:meth:`PSShardGroup.report`
        counters under ``client``."""
        return {
            "global_step": self.global_step,
            "pushes_lost": self.pushes_lost,
            "client": self.client.report(),
        }
