"""Asynchronous parameter-server training — client + server manager for
the C++ pserver (native/pserver.cc).

Capability parity with the reference's async-SGD path
(listen_and_serv_op.cc:217 RunAsyncLoop; distribute_transpiler.py
sync_mode=False): trainers compute gradients locally and push them to a
parameter server WITHOUT barriers; the server applies the optimizer
update per gradient on arrival; trainers pull fresh params on their own
schedule. DC-ASGD (distribute_transpiler.py:1571) adjusts each pushed
gradient by second-order delay compensation
``g + lambda * g*g*(w - w_bak[trainer])`` with ``w_bak`` captured at
this trainer's last pull.

The TPU division of labor: the jitted part is ONLY the gradient
computation (value_and_grad of the program, compiled by XLA); the
optimizer state lives host-side on the server exactly where the
reference placed it (optimize blocks run on the pserver,
distribute_transpiler.py:592-837). Synchronous SPMD collectives remain
the first-class training path — this module exists for the async-SGD /
DC-ASGD capability rows, which trade gradient staleness for never
stalling on a straggler.

Typical use (one server process, N trainer processes)::

    srv = PServerProcess(lr=0.05, optimizer="sgd")      # once
    t = AsyncPSTrainer(prog, srv.addr, trainer_id=k)    # per trainer
    t.startup(sample_feed=batch)
    for batch in data:
        out = t.step(batch)                              # push-grad, no barrier
"""

from __future__ import annotations

import socket
import subprocess
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..core.config import get_flag
from ..core.errors import enforce

from ..native import build_native


def _build_server() -> str:
    return build_native("pserver.cc", "pserver_server")


class PServerProcess:
    """Spawn-and-own a pserver_server process (the listen_and_serv
    runtime analog; one per param shard group in a real deployment)."""

    def __init__(self, port: int = 0, lr: float = 0.01,
                 optimizer: str = "sgd", dc_asgd: bool = False,
                 dc_lambda: float = 1.0, snapshot_path: Optional[str] = None):
        enforce(optimizer in ("sgd", "adagrad"),
                f"pserver optimizer must be sgd|adagrad, got {optimizer}")
        binpath = _build_server()
        self._proc = subprocess.Popen(
            [binpath, str(port), repr(float(lr)), optimizer,
             "1" if dc_asgd else "0", repr(float(dc_lambda)),
             snapshot_path or "-"],
            stdout=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline().strip()
        if not line.startswith("PORT "):
            raise RuntimeError(f"pserver_server failed to start: {line!r}")
        self.port = int(line.split()[1])
        self.addr = ("127.0.0.1", self.port)

    def stop(self):
        if self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class PushUndelivered(ConnectionError):
    """A push was SENT but the connection died before the server's
    reply arrived: the update may or may not have applied server-side.
    The client reconnects for subsequent requests but never RESENDS the
    push — at-most-once semantics (a resend could double-apply the
    gradient; losing one is ordinary async-SGD staleness)."""


class PSClient:
    """Socket client for the pserver protocol. Dense params are flat f32
    buffers keyed by name; sparse pushes update [rows, dim] params
    row-wise (the distributed-lookup-table update path).

    **Reconnect-with-backoff** (the ``data.master.MasterClient``
    discipline): a dead connection or restarted pserver is retried
    transparently with exponential backoff for IDEMPOTENT requests —
    ``pull``/``init_param`` (first-writer-wins makes a resend a no-op)/
    ``status``/``save``. ``push``/``push_quantized``/``push_rows`` are
    NOT idempotent: the request is sent at most once; connection
    establishment still retries, but a reply lost after a completed send
    raises :class:`PushUndelivered` instead of resending (see
    :class:`AsyncPSTrainer.step`, which drops that step's gradient and
    keeps training)."""

    def __init__(self, addr: Tuple[str, int], trainer_id: int = 0,
                 timeout: float = 30.0, retries: int = 30,
                 retry_backoff: float = 0.05, retry_backoff_max: float = 2.0):
        self.addr = tuple(addr)
        self.trainer_id = int(trainer_id)
        self.timeout = timeout
        self.retries = max(1, int(retries))
        self.retry_backoff = retry_backoff
        self.retry_backoff_max = retry_backoff_max
        self._sock: Optional[socket.socket] = None
        self._connect()  # fail fast on misconfigured addr

    # -- transport ----------------------------------------------------------
    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _readline(self) -> str:
        buf = bytearray()
        while True:
            c = self._sock.recv(1)
            if not c:
                raise ConnectionError("pserver closed connection")
            if c == b"\n":
                return buf.decode()
            buf += c

    def _read_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("pserver closed connection")
            out += chunk
        return bytes(out)

    def _request(self, line: str, payload: bytes = b"",
                 idempotent: bool = True, body_len=None):
        """One protocol round trip with reconnect/backoff. ``body_len``
        (resp → byte count) reads a framed payload INSIDE the retry
        scope, so a connection lost mid-body retries the whole request
        (idempotent case) instead of desyncing. Returns ``resp`` or
        ``(resp, body)``."""
        delay = self.retry_backoff
        last_err: Optional[Exception] = None
        for _ in range(self.retries):
            try:
                if self._sock is None:
                    self._connect()
            except OSError as e:
                last_err = e
                time.sleep(delay)
                delay = min(delay * 2, self.retry_backoff_max)
                continue
            sent = False
            try:
                self._sock.sendall(line.encode() + b"\n" + payload)
                sent = True
                resp = self._readline()
                if resp.startswith("ERR"):
                    raise RuntimeError(f"pserver: {resp}")
                if body_len is None:
                    return resp
                return resp, self._read_exact(body_len(resp))
            except (OSError, ConnectionError) as e:
                self._drop_sock()
                last_err = e
                if sent and not idempotent:
                    raise PushUndelivered(
                        f"push reply lost after send ({e}); NOT resending — "
                        "the update may have applied server-side") from e
                time.sleep(delay)
                delay = min(delay * 2, self.retry_backoff_max)
        raise ConnectionError(
            f"pserver unreachable at {self.addr} after {self.retries} "
            f"attempts: {last_err}")

    def close(self):
        if self._sock is None:
            return
        try:
            self._sock.sendall(b"QUIT\n")
        except OSError:
            pass
        self._drop_sock()

    # -- param API ----------------------------------------------------------
    @staticmethod
    def _check_name(name: str) -> str:
        # the server parses names with %255s: longer (or
        # whitespace-bearing) names would truncate, desyncing the framed
        # payload that follows — reject client-side
        enforce(0 < len(name) <= 255 and not any(c.isspace() for c in name),
                f"param name must be 1-255 chars with no whitespace, got "
                f"{name[:64]!r}... ({len(name)} chars)")
        return name

    def init_param(self, name: str, value: np.ndarray) -> bool:
        """Register a param (first writer wins). Returns True if this
        call created it."""
        data = np.ascontiguousarray(value, dtype=np.float32).tobytes()
        resp = self._request(f"INIT {self._check_name(name)} {len(data)}", data)
        return resp == "OK NEW"

    def pull(self, name: str, shape, dtype=np.float32) -> np.ndarray:
        _, data = self._request(
            f"PULL {self.trainer_id} {self._check_name(name)}",
            body_len=lambda resp: int(resp.split()[1]))
        arr = np.frombuffer(data, dtype=np.float32)
        return arr.reshape(shape).astype(dtype, copy=False)

    def push(self, name: str, grad: np.ndarray) -> int:
        data = np.ascontiguousarray(grad, dtype=np.float32).tobytes()
        resp = self._request(
            f"PUSH {self.trainer_id} {self._check_name(name)} {len(data)}",
            data, idempotent=False)
        return int(resp.split()[1])

    def push_quantized(self, name: str, grad: np.ndarray) -> int:
        """Int8-quantized dense push (abs-max symmetric, one f32 scale):
        4× less wire than :meth:`push`, dequantized server-side before
        the identical update path — the quantized-collective technique
        (EQuARX lineage) applied to the trainer→pserver hop."""
        g = np.ascontiguousarray(grad, dtype=np.float32).reshape(-1)
        scale = float(max(np.max(np.abs(g)), 1e-30))
        q = np.clip(np.round(g / scale * 127.0), -127, 127).astype(np.int8)
        resp = self._request(
            f"PUSHQ {self.trainer_id} {self._check_name(name)} {q.size} "
            f"{scale!r}", q.tobytes(), idempotent=False)
        return int(resp.split()[1])

    def push_rows(self, name: str, row_ids: np.ndarray,
                  row_grads: np.ndarray) -> int:
        """Sparse push: ``row_grads[k]`` updates row ``row_ids[k]`` of the
        [rows, dim] param — SelectedRows send + pserver row-optimize."""
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        vals = np.ascontiguousarray(row_grads, dtype=np.float32)
        enforce(vals.ndim == 2 and ids.shape == (vals.shape[0],),
                "push_rows wants ids [n] and grads [n, dim]")
        resp = self._request(
            f"PUSHROWS {self.trainer_id} {self._check_name(name)} "
            f"{vals.shape[0]} {vals.shape[1]}",
            ids.tobytes() + vals.tobytes(), idempotent=False)
        return int(resp.split()[1])

    def save(self) -> None:
        """Trigger an atomic server-side checkpoint of params + optimizer
        accumulators (shard-checkpoint capability; the server recovers it
        at startup when launched with the same snapshot_path)."""
        self._request("SAVE")

    def status(self) -> Dict[str, int]:
        resp = self._request("STATUS")
        return {k: int(v) for k, v in
                (kv.split("=") for kv in resp[3:].split())}


def _named_leaves(tree) -> Sequence[Tuple[str, Any]]:
    """Stable name per leaf from its pytree path (the send_recv var-name
    analog)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))
            for k in path)
        out.append((name.replace(" ", "_") or "root", leaf))
    return out


class AsyncPSTrainer:
    """Barrier-free trainer: jitted local gradients, server-side updates.

    ``pull_interval`` controls staleness: 1 pulls fresh params before
    every step (matches plain SGD exactly when training alone); larger
    values trade staleness for fewer round-trips — the async knob the
    reference exposes through sync_mode=False.
    """

    def __init__(self, program, addr: Tuple[str, int], loss_name: str = "loss",
                 trainer_id: int = 0, pull_interval: int = 1,
                 fetch_list: Optional[Sequence[str]] = None,
                 compress_grads: bool = False):
        import jax

        self.program = program
        self.loss_name = loss_name
        self.client = PSClient(addr, trainer_id=trainer_id)
        self.pull_interval = max(1, int(pull_interval))
        self.compress_grads = bool(compress_grads)
        self.fetch_list = list(fetch_list) if fetch_list is not None else None
        self.params = None
        self.state = None
        self.global_step = 0
        self.pushes_lost = 0  # at-most-once pushes whose reply was lost

        def grad_step(params, state, rng, feed):
            def loss_fn(p, st, r, f):
                out, new_state = program.apply(p, st, training=True, rng=r, **f)
                if isinstance(out, dict):
                    loss = out[loss_name]
                else:
                    loss, out = out, {loss_name: out}
                if self.fetch_list is not None:
                    out = {k: out[k] for k in set(self.fetch_list) | {loss_name}}
                return loss, (out, new_state)

            (_, (out, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, rng, feed)
            return grads, out, new_state

        self._grad_fn = jax.jit(grad_step)

    # ------------------------------------------------------------------
    def startup(self, rng=None, sample_feed: Optional[Dict[str, Any]] = None):
        import jax

        from ..executor import _abstractify

        if rng is None:
            rng = jax.random.PRNGKey(get_flag("seed"))
        feed = {k: _abstractify(v) for k, v in (sample_feed or {}).items()}
        params, self.state = self.program.init(rng, **feed)
        # first trainer's init wins server-side; then EVERY trainer pulls,
        # so all replicas start from the same point regardless of race
        for name, leaf in _named_leaves(params):
            self.client.init_param(name, np.asarray(leaf, dtype=np.float32))
        self.params = self._pull_into(params)
        return self.params

    def _pull_into(self, params):
        import jax

        leaves = _named_leaves(params)
        pulled = [self.client.pull(n, np.shape(l),
                                   dtype=getattr(l, "dtype", np.float32))
                  for n, l in leaves]
        treedef = jax.tree_util.tree_structure(params)
        return jax.tree_util.tree_unflatten(treedef, pulled)

    # ------------------------------------------------------------------
    def step(self, feed: Dict[str, Any], rng=None) -> Dict[str, Any]:
        import jax

        enforce(self.params is not None, "call startup() before step()")
        if rng is None:
            rng = jax.random.fold_in(
                jax.random.PRNGKey(get_flag("seed") + 1), self.global_step)
        if self.global_step % self.pull_interval == 0:
            self.params = self._pull_into(self.params)
        grads, out, self.state = self._grad_fn(self.params, self.state, rng, feed)
        send = (self.client.push_quantized if self.compress_grads
                else self.client.push)
        for name, leaf in _named_leaves(jax.device_get(grads)):
            try:
                send(name, leaf)
            except PushUndelivered as e:
                # at-most-once: the grad is dropped, never resent (a
                # resend could double-apply) — one stale step, the
                # trade async-SGD already makes for stragglers
                self.pushes_lost += 1
                import logging
                logging.getLogger("paddle_tpu.async_ps").warning(
                    "dropped push of %s at step %d (%s); continuing",
                    name, self.global_step, e)
        self.global_step += 1
        return out
