"""Lock-discipline static analyzer over the framework's own source.

The runtime half of the checker (``analysis.runtime``): where the jaxpr
walker proves properties of the *compiled program*, this module proves
properties of the host runtime that dispatches it — the serving
workers, feeder threads, shipper loops and fleet routers whose bug
classes (unguarded shared-state reads, callbacks fired under a lock,
threads registered before ``.start()``) recur in every review pass.

It is a pure-``ast`` pass over Python source; nothing is imported or
executed. Per class it infers the *guarded-field set* — attributes
whose every non-``__init__`` write happens under ``with self._lock:``
— augments it with the explicit ``# guarded-by: <lock>`` annotation
convention, then checks four rules:

- ``thread:unguarded-access`` — a guarded field read/written without
  its lock in a method reachable from a thread entry point
  (``Thread(target=self.m)``, a registered callback reference) or in a
  method that itself takes locks;
- ``thread:callback-under-lock`` — a user/subscriber callback invoked
  while any lock is held (the breaker ``on_trip`` / alert-rule
  subscriber bug class);
- ``thread:lock-order`` — the package-wide lock-acquisition graph has
  a cycle (emitted by the aggregator in :mod:`.runtime`; this module
  contributes the per-file edges);
- ``thread:join-unstarted`` — a ``Thread`` published into a shared
  ``self.*`` container before ``.start()``, or joined without ever
  being started.

Suppression is by source annotation, not config: ``# lint:
allow(<rule>)`` on the offending line, its ``def`` line, or its
``class`` line; ``# guarded-by: <lock>`` both declares intent and
overrides inference for that field.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

from .report import LintReport

# attribute factories whose result is "a lock" for `with` tracking
_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore")

# names that smell like a user-supplied callback when called under a lock
_CALLBACK_NAME_RE = re.compile(
    r"(^on_|_callback$|_callbacks$|_cb$|_cbs$|_hook$|_hooks$|"
    r"^callbacks?$|_listeners?$|_subscribers?$|_waiters$)")

_GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_]\w*)")
_ALLOW_RE = re.compile(r"lint:\s*allow\(([^)]*)\)")

# container methods that mutate the receiver (a write to the field for
# guarded-set inference; `self._buf.append(x)` is a write to `_buf`)
_MUTATORS = {"append", "extend", "add", "remove", "discard", "pop",
             "popleft", "popitem", "appendleft", "clear", "update",
             "setdefault", "insert", "sort"}


def _comment_maps(src: str) -> Tuple[Dict[int, str], Dict[int, Set[str]]]:
    """Scan comments → ({lineno: lock-name} for ``guarded-by:``,
    {lineno: {rules}} for ``lint: allow(...)``)."""
    guarded: Dict[int, str] = {}
    allows: Dict[int, Set[str]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            m = _GUARDED_BY_RE.search(tok.string)
            if m:
                guarded[line] = m.group(1)
            m = _ALLOW_RE.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                allows.setdefault(line, set()).update(rules)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return guarded, allows


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` → ``"X"``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lock_factory(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    return name in _LOCK_FACTORIES


def _is_thread_ctor(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    return name in ("Thread", "Timer")


@dataclasses.dataclass
class Access:
    field: str
    kind: str                 # "read" | "write" (reassign) | "mutate"
    lineno: int
    held: Tuple[str, ...]     # lock attrs held at the site (innermost last)


@dataclasses.dataclass
class CallbackCall:
    desc: str                 # what was called, for the message
    lineno: int
    held: Tuple[str, ...]


@dataclasses.dataclass
class SelfCall:
    callee: str
    lineno: int
    held: Tuple[str, ...]


@dataclasses.dataclass
class MethodInfo:
    name: str
    lineno: int
    accesses: List[Access] = dataclasses.field(default_factory=list)
    self_calls: List[SelfCall] = dataclasses.field(default_factory=list)
    callback_calls: List[CallbackCall] = dataclasses.field(
        default_factory=list)
    escapes: Set[str] = dataclasses.field(default_factory=set)
    # locks acquired while no other class lock is held (for the one-level
    # cross-method lock-order expansion)
    toplevel_locks: Set[str] = dataclasses.field(default_factory=set)
    join_findings: List[Tuple[str, int, str]] = dataclasses.field(
        default_factory=list)     # (message, lineno, thread var)


class _MethodScanner(ast.NodeVisitor):
    """One method body: tracks the held-lock stack through ``with``
    blocks and records accesses / self-calls / callback calls / thread
    lifecycle events."""

    def __init__(self, cls: "_ClassInfo", method: str, lineno: int):
        self.cls = cls
        self.info = MethodInfo(name=method, lineno=lineno)
        self.held: List[str] = []
        # locals derived from shared self-state (loop vars over
        # self._subs, `fn = self._waiters.pop(k)` ...): calling one of
        # these under a lock is the callback-under-lock shape
        self.derived: Set[str] = set()
        # ctor-param callables stored on self are tracked class-wide
        # locals bound to a Thread(...) ctor in this function
        self.threads: Dict[str, dict] = {}

    # -- helpers -----------------------------------------------------------

    def _access(self, field: str, kind: str, lineno: int) -> None:
        if field in self.cls.locks or field in self.cls.methods:
            return
        self.info.accesses.append(Access(field, kind, lineno,
                                         tuple(self.held)))

    def _rooted_in_self(self, node: ast.AST) -> bool:
        """Does this expression read shared ``self.*`` state (possibly
        through a subscript / ``.get()`` / ``.pop()``)?"""
        while True:
            if _self_attr(node) is not None:
                return True
            if isinstance(node, ast.Subscript):
                node = node.value
            elif isinstance(node, ast.Attribute):
                node = node.value
            elif isinstance(node, ast.Call):
                node = node.func
            else:
                return False

    def _visit_target(self, node: ast.AST) -> None:
        """Assignment target: classify writes."""
        field = _self_attr(node)
        if field is not None:
            self._access(field, "write", node.lineno)
            return
        if isinstance(node, ast.Subscript):
            base = _self_attr(node.value)
            if base is not None:
                # self._rules[k] = v mutates the container; the
                # REFERENCE stays stable (distinct from a reassignment)
                self._access(base, "mutate", node.lineno)
            else:
                self.visit(node.value)
            self.visit(node.slice)
            return
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                self._visit_target(elt)
            return
        if isinstance(node, ast.Starred):
            self._visit_target(node.value)
            return
        self.visit(node)

    # -- statements --------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        for t in node.targets:
            self._visit_target(t)
        # bookkeeping on simple `name = ...` bindings
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if _is_thread_ctor(node.value):
                self.threads[name] = {"line": node.lineno, "started": False,
                                      "registered": 0}
            elif self._rooted_in_self(node.value):
                self.derived.add(name)
        # publishing a local Thread into shared state before .start()
        for t in node.targets:
            self._note_registration(t, node.value, node.lineno)

    def _note_registration(self, target: ast.AST, value: ast.AST,
                           lineno: int) -> None:
        if not (isinstance(value, ast.Name) and value.id in self.threads):
            return
        rec = self.threads[value.id]
        stored_shared = False
        if isinstance(target, ast.Subscript):
            stored_shared = self._rooted_in_self(target.value)
        if stored_shared and not rec["started"]:
            rec["registered"] = lineno

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        field = _self_attr(node.target)
        if field is not None:
            # += is a read-modify-write; record the write (stricter)
            self._access(field, "write", node.lineno)
        else:
            self._visit_target(node.target)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
        self._visit_target(node.target)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                base = _self_attr(t.value)
                if base is not None:
                    self._access(base, "mutate", t.lineno)
                    self.visit(t.slice)
                    continue
            self._visit_target(t)

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            self.visit(item.context_expr)
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                if self.held:
                    self.cls.lock_edges.append(
                        (self.held[-1], lock, node.lineno))
                else:
                    self.info.toplevel_locks.add(lock)
                self.held.append(lock)
                acquired.append(lock)
            if item.optional_vars is not None:
                self._visit_target(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        field = _self_attr(expr)
        if field is not None and field in self.cls.locks:
            return field
        # `with self._lock.acquire_timeout():`-style helpers are not
        # tracked; neither are non-self locks (module-level singletons)
        return None

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        if (isinstance(node.target, ast.Name)
                and self._rooted_in_self(node.iter)):
            self.derived.add(node.target.id)
        self._visit_target(node.target)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested function (closure): scanned as a pseudo-method named
        # `outer.inner`; if the name escapes (Thread target, submitted
        # to an executor) its accesses are thread-reachable
        sub = _MethodScanner(self.cls, f"{self.info.name}.{node.name}",
                             node.lineno)
        sub.derived = set(self.derived)
        for stmt in node.body:
            sub.visit(stmt)
        sub._finish_threads()
        self.cls.methods[sub.info.name] = sub.info
        self.cls.nested_of.setdefault(self.info.name, set()).add(
            sub.info.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)

    # -- calls and reads ---------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        handled_func = False
        field = _self_attr(fn)
        if field is not None:
            if field in self.cls.methods or field in self.cls.method_names:
                self.info.self_calls.append(
                    SelfCall(field, node.lineno, tuple(self.held)))
            else:
                # calling a callable stored on self: a read, and — under
                # a lock — a callback-under-lock candidate when the
                # field was injected via the ctor or smells like a hook
                self._access(field, "read", node.lineno)
                if self.held and (field in self.cls.ctor_param_attrs
                                  or _CALLBACK_NAME_RE.search(field)):
                    self.info.callback_calls.append(CallbackCall(
                        f"self.{field}", node.lineno, tuple(self.held)))
            handled_func = True
        elif isinstance(fn, ast.Attribute):
            base = _self_attr(fn.value)
            if base is not None and base not in self.cls.locks:
                kind = "mutate" if fn.attr in _MUTATORS else "read"
                self._access(base, kind, fn.value.lineno)
                if fn.attr in _MUTATORS:
                    # self._workers.append(t): publishing a local Thread
                    # into shared state counts as a registration
                    for arg in node.args:
                        if (isinstance(arg, ast.Name)
                                and arg.id in self.threads
                                and not self.threads[arg.id]["started"]):
                            self.threads[arg.id]["registered"] = node.lineno
                handled_func = True
            elif isinstance(fn.value, ast.Name):
                name = fn.value.id
                if name in self.threads:
                    if fn.attr == "start":
                        self.threads[name]["started"] = True
                        if self.threads[name]["registered"]:
                            pass   # registration already noted
                    elif fn.attr == "join":
                        self.threads[name]["joined"] = node.lineno
                    handled_func = True
        elif isinstance(fn, ast.Name):
            if self.held and fn.id in self.derived:
                self.info.callback_calls.append(CallbackCall(
                    fn.id, node.lineno, tuple(self.held)))

        if not handled_func:
            self.visit(fn)
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)
        # a Thread bound to a kwarg-visible local target method makes
        # that method a thread entry point — handled via escapes below

    def visit_Attribute(self, node: ast.Attribute) -> None:
        field = _self_attr(node)
        if field is None:
            self.visit(node.value)
            return
        if field in self.cls.locks:
            return
        if field in self.cls.method_names:
            if field in self.cls.properties:
                # property read = a self-call into the getter
                self.info.self_calls.append(
                    SelfCall(field, node.lineno, tuple(self.held)))
            else:
                # bare method reference (Thread target, subscribe arg,
                # route-table value): the method escapes this class and
                # becomes a thread entry point
                self.info.escapes.add(field)
            return
        kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) \
            else "read"
        self._access(field, kind, node.lineno)

    def visit_Name(self, node: ast.Name) -> None:
        pass

    # -- wrap-up -----------------------------------------------------------

    def _finish_threads(self) -> None:
        for name, rec in self.threads.items():
            if rec["registered"]:
                # registered into shared state; if start() came after
                # the registration line (or never), a concurrent reader
                # (close()/join sweep) can see a never-started Thread
                self.info.join_findings.append((
                    f"Thread {name!r} published into shared state at line "
                    f"{rec['registered']} before .start()",
                    rec["registered"], name))
            joined = rec.get("joined")
            if joined and not rec["started"]:
                self.info.join_findings.append((
                    f"Thread {name!r} joined at line {joined} but never "
                    f"started in this function", joined, name))


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.locks: Set[str] = set()
        self.method_names: Set[str] = set()
        self.properties: Set[str] = set()
        self.methods: Dict[str, MethodInfo] = {}
        self.nested_of: Dict[str, Set[str]] = {}
        self.ctor_param_attrs: Set[str] = set()
        self.lock_edges: List[Tuple[str, str, int]] = []
        self.annotations: Dict[str, str] = {}   # field -> lock (guarded-by)
        self.field_allows: Dict[str, Set[str]] = {}  # field -> allowed rules
        self.lineno = 0


def _prescan_class(node: ast.ClassDef, guarded_lines: Dict[int, str],
                   allow_lines: Optional[Dict[int, Set[str]]] = None
                   ) -> _ClassInfo:
    cls = _ClassInfo(node.name)
    allow_lines = allow_lines or {}
    cls.lineno = node.lineno
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls.method_names.add(stmt.name)
            for dec in stmt.decorator_list:
                dname = dec.attr if isinstance(dec, ast.Attribute) else (
                    dec.id if isinstance(dec, ast.Name) else "")
                if dname in ("property", "cached_property"):
                    cls.properties.add(stmt.name)
    init = next((s for s in node.body
                 if isinstance(s, ast.FunctionDef) and s.name == "__init__"),
                None)
    init_params = set()
    if init is not None:
        init_params = {a.arg for a in init.args.args + init.args.kwonlyargs
                       if a.arg != "self"}
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Assign):
            continue
        for t in sub.targets:
            field = _self_attr(t)
            if field is None:
                continue
            if _is_lock_factory(sub.value):
                cls.locks.add(field)
            if sub.lineno in guarded_lines:
                cls.annotations[field] = guarded_lines[sub.lineno]
            if sub.lineno in allow_lines:
                # an allow on the field's assignment line opts the whole
                # FIELD out of that rule (one annotation, not one per
                # read site)
                cls.field_allows.setdefault(field, set()).update(
                    allow_lines[sub.lineno])
            # `self.on_trip = on_trip` (possibly `x or default`)
            v = sub.value
            if isinstance(v, ast.BoolOp):
                v = v.values[0]
            if isinstance(v, ast.Name) and v.id in init_params \
                    and v.id == field:
                cls.ctor_param_attrs.add(field)
    return cls


# --------------------------------------------------------------------------
# per-file analysis
# --------------------------------------------------------------------------


@dataclasses.dataclass
class FileAnalysis:
    """Everything extracted from one module: the per-file report plus
    the lock-order edges the package aggregator consumes."""
    report: LintReport
    lock_edges: List[Tuple[str, str, str]]   # (ClassA.lock, ClassB.lock, loc)


class _Allower:
    """Answers "is this rule suppressed at this site" from the comment
    map: the offending line, its def line, its class line, or a
    module-wide allow on lines 1-2."""

    def __init__(self, allows: Dict[int, Set[str]]):
        self.allows = allows
        self.module_rules: Set[str] = set()
        for line in (1, 2):
            self.module_rules |= allows.get(line, set())

    @staticmethod
    def _matches(rule: str, entries: Set[str]) -> bool:
        fam = rule.split(":")[0]
        return rule in entries or fam in entries or "all" in entries

    def __call__(self, rule: str, *linenos: int) -> bool:
        if self._matches(rule, self.module_rules):
            return True
        for ln in linenos:
            if ln and self._matches(rule, self.allows.get(ln, set())):
                return True
        return False


def check_source(src: str, filename: str = "<source>",
                 subject: str = "runtime") -> FileAnalysis:
    """Analyze one module's source → :class:`FileAnalysis`."""
    report = LintReport(subject)
    guarded_lines, allow_lines = _comment_maps(src)
    allowed = _Allower(allow_lines)
    tree = ast.parse(src, filename=filename)

    edges: List[Tuple[str, str, str]] = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            cls = _analyze_class(node, guarded_lines, allow_lines)
            _report_class(cls, report, allowed, filename)
            for a, b, line in cls.lock_edges:
                edges.append((f"{cls.name}.{a}", f"{cls.name}.{b}",
                              f"{filename}:{line}"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # module-level function: thread-lifecycle rules still apply
            dummy = _ClassInfo("")
            scanner = _MethodScanner(dummy, node.name, node.lineno)
            for stmt in node.body:
                scanner.visit(stmt)
            scanner._finish_threads()
            for msg, line, _ in scanner.info.join_findings:
                if not allowed("thread:join-unstarted", line, node.lineno):
                    report.add("thread:join-unstarted", "warning", msg,
                               where=node.name, line=line)
    return FileAnalysis(report=report, lock_edges=edges)


def check_file(path: str, subject: str = "") -> FileAnalysis:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    return check_source(src, filename=path, subject=subject or path)


def _analyze_class(node: ast.ClassDef, guarded_lines: Dict[int, str],
                   allow_lines: Optional[Dict[int, Set[str]]] = None
                   ) -> _ClassInfo:
    cls = _prescan_class(node, guarded_lines, allow_lines)
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scanner = _MethodScanner(cls, stmt.name, stmt.lineno)
            for inner in stmt.body:
                scanner.visit(inner)
            scanner._finish_threads()
            cls.methods[stmt.name] = scanner.info
            # guarded-by annotations can also sit on a write line inside
            # any method, not just __init__
            for acc in scanner.info.accesses:
                if acc.kind == "write" and acc.lineno in guarded_lines:
                    cls.annotations.setdefault(acc.field,
                                               guarded_lines[acc.lineno])
    # one-level cross-method lock-order expansion: caller holds A and
    # calls a method whose body acquires B at top level → A→B
    for info in cls.methods.values():
        for call in info.self_calls:
            if not call.held:
                continue
            callee = cls.methods.get(call.callee)
            if callee is None:
                continue
            for inner_lock in callee.toplevel_locks:
                cls.lock_edges.append(
                    (call.held[-1], inner_lock, call.lineno))
    return cls


def _guarded_fields(cls: _ClassInfo) -> Dict[str, str]:
    """field → lock. Annotation wins; otherwise inferred when every
    non-``__init__`` write happens under exactly one lock."""
    inferred: Dict[str, str] = dict(cls.annotations)
    if not cls.locks:
        return inferred
    writes_under: Dict[str, Set[str]] = {}
    writes_bare: Set[str] = set()
    for mname, info in cls.methods.items():
        if mname == "__init__" or mname.endswith("_locked"):
            # `*_locked` names the repo's caller-holds-the-lock
            # convention: its writes are lock-held by contract, but we
            # cannot attribute WHICH lock — they neither prove nor
            # disprove guarding
            continue
        for acc in info.accesses:
            if acc.kind == "read":
                continue
            if acc.held:
                writes_under.setdefault(acc.field, set()).add(acc.held[-1])
            else:
                writes_bare.add(acc.field)
    for field, locks in writes_under.items():
        if field in inferred or field in writes_bare or len(locks) != 1:
            continue
        inferred[field] = next(iter(locks))
    return inferred


def _reachable_methods(cls: _ClassInfo) -> Set[str]:
    """Methods that can run on a non-constructor thread: escapes
    (Thread targets, registered callbacks) closed over the self-call
    graph, plus any method that itself takes a class lock (it declared
    itself concurrency-aware)."""
    entries: Set[str] = set()
    for info in cls.methods.values():
        entries |= info.escapes & set(cls.methods)
        if info.toplevel_locks or any(a.held for a in info.accesses):
            entries.add(info.name)
        # nested closures that escape by name (Thread(target=loop))
        for nested in cls.nested_of.get(info.name, ()):
            entries.add(nested)
    entries.discard("__init__")
    seen: Set[str] = set()
    frontier = list(entries)
    while frontier:
        m = frontier.pop()
        if m in seen or m == "__init__":
            continue
        seen.add(m)
        info = cls.methods.get(m)
        if info is None:
            continue
        for call in info.self_calls:
            if call.callee not in seen:
                frontier.append(call.callee)
    return seen


def _report_class(cls: _ClassInfo, report: LintReport, allowed: _Allower,
                  filename: str) -> None:
    guarded = _guarded_fields(cls)
    reachable = _reachable_methods(cls)
    # fields whose REFERENCE is reassigned outside __init__: plain reads
    # of those can observe a torn compound update, so they are flagged.
    # A field only ever container-mutated keeps a stable reference —
    # reading it (`if self._seg is not None`) is the deliberate
    # check-then-lock idiom, not a race; only its unguarded *mutations*
    # are findings. An explicit `# guarded-by:` opts into strict mode
    # (every unguarded access flagged).
    reassigned = {acc.field
                  for mname, info in cls.methods.items()
                  if mname != "__init__"
                  for acc in info.accesses if acc.kind == "write"}
    reassigned |= set(cls.annotations)

    for mname, info in cls.methods.items():
        if mname == "__init__":
            # ctor runs single-threaded; closures defined IN it
            # (`__init__.loop` pseudo-methods) do not and are checked
            continue
        if any(seg.endswith("_locked") for seg in mname.split(".")):
            # caller-holds-the-lock convention (see _guarded_fields)
            in_scope = False
        else:
            in_scope = mname in reachable
        for acc in info.accesses:
            lock = guarded.get(acc.field)
            if lock is None or not in_scope:
                continue
            if lock in acc.held:
                continue
            if acc.kind == "read" and acc.field not in reassigned:
                continue
            if "thread:unguarded-access" in cls.field_allows.get(
                    acc.field, ()) or "thread" in cls.field_allows.get(
                    acc.field, ()):
                continue
            if allowed("thread:unguarded-access", acc.lineno, info.lineno,
                       cls.lineno):
                continue
            report.add(
                "thread:unguarded-access", "warning",
                f"{acc.kind} of {cls.name}.{acc.field} (guarded by "
                f"self.{lock}) without holding it "
                f"({filename}:{acc.lineno})",
                where=f"{cls.name}.{mname}:{acc.field}",
                line=acc.lineno, lock=lock)
        for cb in info.callback_calls:
            if allowed("thread:callback-under-lock", cb.lineno, info.lineno,
                       cls.lineno):
                continue
            report.add(
                "thread:callback-under-lock", "warning",
                f"{cb.desc}() invoked while holding self.{cb.held[-1]} — "
                f"user callbacks must run outside the lock "
                f"({filename}:{cb.lineno})",
                where=f"{cls.name}.{mname}",
                line=cb.lineno, lock=cb.held[-1])
        for msg, line, _ in info.join_findings:
            if allowed("thread:join-unstarted", line, info.lineno,
                       cls.lineno):
                continue
            report.add(
                "thread:join-unstarted", "warning",
                f"{msg} ({filename}:{line})",
                where=f"{cls.name}.{mname}", line=line)


# --------------------------------------------------------------------------
# package-wide lock-order graph
# --------------------------------------------------------------------------


def lock_cycles(edges: List[Tuple[str, str, str]]
                ) -> List[List[str]]:
    """Find elementary cycles in the acquisition digraph (iterative
    DFS; the graphs here are tiny). Each cycle is returned as a node
    list rotated so its lexicographically-smallest node leads — a
    stable identity for fingerprints."""
    graph: Dict[str, Set[str]] = {}
    for a, b, _ in edges:
        if a != b:
            graph.setdefault(a, set()).add(b)
    cycles: List[List[str]] = []
    seen_keys: Set[Tuple[str, ...]] = set()

    def dfs(node: str, path: List[str], on_path: Set[str],
            visited: Set[str]) -> None:
        visited.add(node)
        on_path.add(node)
        path.append(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):]
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                if canon not in seen_keys:
                    seen_keys.add(canon)
                    cycles.append(list(canon))
            elif nxt not in visited:
                dfs(nxt, path, on_path, visited)
        path.pop()
        on_path.discard(node)

    visited: Set[str] = set()
    for start in sorted(graph):
        if start not in visited:
            dfs(start, [], set(), visited)
    return cycles


def lock_order_report(edges: List[Tuple[str, str, str]],
                      subject: str = "runtime:locks") -> LintReport:
    """Package-level ``thread:lock-order`` findings from the merged
    per-file edge lists."""
    report = LintReport(subject)
    by_pair: Dict[Tuple[str, str], str] = {}
    for a, b, loc in edges:
        by_pair.setdefault((a, b), loc)
    for cyc in lock_cycles(edges):
        ring = " -> ".join(cyc + [cyc[0]])
        locs = [by_pair.get((cyc[i], cyc[(i + 1) % len(cyc)]), "?")
                for i in range(len(cyc))]
        report.add(
            "thread:lock-order", "warning",
            f"inconsistent lock acquisition order: {ring} "
            f"(acquisition sites: {', '.join(locs)})",
            where=ring, path=ring)
    return report
