"""Tests for the fluid compat surfaces: transpiler module, backward,
program_guard/scopes, weight norm, reader decorators, datasets, image
utils, ChunkEvaluator, profiler controls, io aliases."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu import metrics as M
from paddle_tpu.data import datasets as D
from paddle_tpu.data import image as IMG


def test_distribute_transpiler_shapes_strategy():
    t = pt.DistributeTranspiler()
    prog = pt.build(lambda x: {"loss": L.mean(x)})
    t.transpile(trainer_id=0, program=prog, pservers="h1:6174,h2:6174", trainers=2)
    p, strategy = t.get_trainer_program()
    assert p is prog
    assert strategy.reduce_strategy == "sharded"  # param-slicing capability
    p2, s2 = t.get_pserver_program("h1:6174")
    assert p2 is prog
    assert not s2.async_mode
    # sync_mode=False → async pserver capability (parallel.async_ps)
    t.transpile(0, prog, "h1:6174", 2, sync_mode=False)
    _, s3 = t.get_trainer_program()
    assert s3.async_mode


def test_ps_dispatchers():
    from paddle_tpu.transpiler import HashName, RoundRobin
    eps = ["a", "b", "c"]
    rr = RoundRobin(eps)
    assert rr.dispatch(list("wxyz")) == ["a", "b", "c", "a"]
    hn = HashName(eps)
    d1 = hn.dispatch(["p1", "p2"])
    assert d1 == hn.dispatch(["p1", "p2"])  # stable
    assert set(d1) <= set(eps)


def test_memory_optimize_returns_remat_strategy():
    s = pt.memory_optimize()
    assert s.remat is True
    assert pt.release_memory(None) is None


def test_append_backward_param_grads():
    x = np.random.randn(4, 3).astype(np.float32)
    prog = pt.build(lambda a: {"loss": L.mean(L.fc(a, 2, name="f"))})
    params, state = prog.init(jax.random.PRNGKey(0), x)
    grad_fn = pt.append_backward(prog, "loss")
    loss, pg = grad_fn(params, state, x)
    names = [n for n, _ in pg]
    assert "f/w" in names and "f/b" in names
    gb = dict(pg)["f/b"]
    # loss = mean over 4*2 outputs; each bias column feeds 4 of them
    np.testing.assert_allclose(np.asarray(gb), np.full(2, 0.5), rtol=1e-5)

    # parameter_list restriction
    loss2, pg2 = pt.append_backward(prog, "loss", parameter_list=["f/w"])(params, state, x)
    assert [n for n, _ in pg2] == ["f/w"]


def test_calc_gradient():
    prog = pt.build(lambda a: {"y": (a ** 2).sum()})
    params, state = prog.init(jax.random.PRNGKey(0), np.ones((2,), np.float32))
    g = pt.calc_gradient(prog, "y", ["a"])(params, state, {"a": jnp.asarray([3.0, 4.0])})
    np.testing.assert_allclose(np.asarray(g["a"]), [6.0, 8.0], rtol=1e-6)


def test_program_guard_and_scopes():
    prog = pt.build(lambda x: x)
    assert pt.default_main_program() is None
    with pt.program_guard(prog):
        assert pt.default_main_program() is prog
        assert pt.default_startup_program() is prog
    assert pt.default_main_program() is None

    s = pt.Scope()
    g0 = pt.global_scope()
    with pt.scope_guard(s):
        assert pt.global_scope() is s
    assert pt.global_scope() is g0


def test_weight_norm_param_attr():
    x = np.random.randn(4, 6).astype(np.float32)
    prog = pt.build(lambda a: L.fc(a, 3, name="wn",
                                   param_attr=pt.WeightNormParamAttr(dim=1)))
    params, state = prog.init(jax.random.PRNGKey(0), x)
    assert "wn/w@wn_g" in params
    v = np.asarray(params["wn/w"])
    g = np.asarray(params["wn/w@wn_g"])
    # g initialized to ||v|| per output column -> first forward == plain fc
    np.testing.assert_allclose(g, np.linalg.norm(v, axis=0), rtol=1e-5)
    out, _ = prog.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(out), x @ v + np.asarray(params["wn/b"]),
                               rtol=1e-4, atol=1e-5)
    # scaling g scales the effective weight
    params2 = dict(params)
    params2["wn/w@wn_g"] = params["wn/w@wn_g"] * 2.0
    out2, _ = prog.apply(params2, state, x)
    np.testing.assert_allclose(np.asarray(out2 - np.asarray(params["wn/b"])),
                               2 * (np.asarray(out) - np.asarray(params["wn/b"])),
                               rtol=1e-4, atol=1e-5)


def test_reader_decorators_fake_pipe_multiprocess():
    from paddle_tpu.data import Fake, PipeReader, multiprocess_reader

    def r():
        for i in range(5):
            yield (i,)

    fk = Fake(r, 2)
    assert list(fk()) == [(0,), (0,)]

    pr = PipeReader("echo a\nb\nc")
    lines = list(pr.get_line())
    assert "b" in "".join(lines)

    def r2():
        for i in range(10, 13):
            yield (i,)
    merged = sorted(s[0] for s in multiprocess_reader([r, r2])())
    assert merged == [0, 1, 2, 3, 4, 10, 11, 12]


def test_new_datasets_yield_and_learnable_shapes():
    s = next(iter(D.cifar100()()))
    assert s[0].shape == (3 * 32 * 32,) and 0 <= s[1] < 100
    f = next(iter(D.flowers(image_hw=(32, 32))()))
    assert f[0].shape == (3 * 32 * 32,) and 0 <= f[1] < 102
    img, mask = next(iter(D.voc2012(image_hw=(32, 32))()))
    assert img.shape == (3, 32, 32) and mask.shape == (32, 32) and mask.max() > 0

    grams = list(D.imikolov(synthetic_size=4, n=3)())
    assert all(len(g) == 3 for g in grams)
    src, trg = next(iter(D.imikolov(synthetic_size=2, data_type=D.DataType.SEQ)()))
    assert len(src) == len(trg)

    ids, y = next(iter(D.sentiment()()))
    assert y in (0, 1) and len(ids) > 0

    s14 = next(iter(D.wmt14(synthetic_size=4)()))
    assert len(s14) == 3 and s14[1][0] == 1  # trg starts with <s>

    pt_, sc = next(iter(D.mq2007(format="pointwise")()))
    assert pt_.shape == (46,)
    hi, lo = next(iter(D.mq2007(format="pairwise")()))
    assert hi.shape == lo.shape == (46,)
    labels, feats = next(iter(D.mq2007(format="listwise")()))
    assert len(labels) == len(feats) == 8


def test_image_utils():
    im = (np.random.rand(40, 60, 3) * 255).astype(np.uint8)
    r = IMG.resize_short(im, 20)
    assert min(r.shape[:2]) == 20 and r.shape[1] == 30
    c = IMG.center_crop(im, 30)
    assert c.shape[:2] == (30, 30)
    rc = IMG.random_crop(im, 16, rng=np.random.RandomState(0))
    assert rc.shape[:2] == (16, 16)
    fl = IMG.left_right_flip(im)
    np.testing.assert_array_equal(fl[:, 0], im[:, -1])
    chw = IMG.to_chw(im)
    assert chw.shape == (3, 40, 60)
    t = IMG.simple_transform(im, 32, 24, is_train=False, mean=np.array([1.0, 2.0, 3.0]))
    assert t.shape == (3, 24, 24) and t.dtype == np.float32


def test_chunk_evaluator():
    ce = M.ChunkEvaluator()
    ce.update(num_infer_chunks=4, num_label_chunks=5, num_correct_chunks=3)
    ce.update(num_infer_chunks=2, num_label_chunks=1, num_correct_chunks=1)
    p, r, f1 = ce.eval()
    np.testing.assert_allclose(p, 4 / 6, rtol=1e-6)
    np.testing.assert_allclose(r, 4 / 6, rtol=1e-6)
    np.testing.assert_allclose(f1, 4 / 6, rtol=1e-6)


def test_profiler_controls():
    from paddle_tpu.core import profiler as P
    P.start_profiler()
    with P.record_event("op_x"):
        pass
    rows = P.stop_profiler()
    assert any(r["name"] == "op_x" for r in rows)
    P.reset_profiler()
    with pytest.raises(NotImplementedError):
        P.cuda_profiler()


def test_io_aliases_roundtrip(tmp_path):
    from paddle_tpu import io as pio
    params = {"a/w": jnp.ones((2, 2)), "b": jnp.zeros((3,))}
    d = str(tmp_path / "ckpt")
    pio.save_params(d, params)
    loaded = pio.load_params(d)
    np.testing.assert_allclose(np.asarray(loaded["a/w"]), 1.0)
    pio.save_vars(d, params)
    assert set(pio.load_vars(d)) == set(params)


def test_create_lod_tensor():
    vals, lens, seg = L.sequence.create_lod_tensor(
        np.arange(10, dtype=np.float32).reshape(5, 2), [[2, 3]])
    np.testing.assert_array_equal(np.asarray(lens), [2, 3])
    np.testing.assert_array_equal(np.asarray(seg), [0, 0, 1, 1, 1])
    v2, l2, s2 = L.sequence.create_random_int_lodtensor([[1, 2]], (3,), low=0, high=4)
    assert v2.shape == (3, 3) and np.asarray(v2).max() <= 4


def test_init_on_cpu_flag():
    from paddle_tpu import initializer as I
    assert I.force_init_on_cpu() is False
    with I.init_on_cpu():
        assert I.force_init_on_cpu() is True
    assert I.force_init_on_cpu() is False


def test_fit_a_line_converges_and_roundtrips(tmp_path):
    """Book chapter 1 (test_fit_a_line.py): train -> save -> load ->
    infer round trip on uci_housing."""
    from paddle_tpu import optimizer as opt
    from paddle_tpu import io as pio
    from paddle_tpu.models import fit_a_line
    from paddle_tpu.data import datasets, reader as rd

    prog = pt.build(fit_a_line.make_model())
    train_reader = rd.batch(datasets.uci_housing("train"), 32, drop_last=True)

    def to_feed(b):
        xs, ys = zip(*b)
        return {"x": np.stack(xs).astype(np.float32),
                "y": np.asarray(ys, np.float32).reshape(-1, 1)}

    batches = [to_feed(b) for b in train_reader()]
    tr = pt.Trainer(prog, opt.SGD(0.01), loss_name="loss")
    tr.startup(sample_feed=batches[0])
    first = float(tr.step(batches[0])["loss"])
    for _ in range(3):
        for b in batches:
            out = tr.step(b)
    assert float(out["loss"]) < first * 0.5

    d = str(tmp_path / "fit_a_line")
    pio.save_persistables(d, tr.scope.params, tr.scope.state)
    params, state, _, _ = pio.load_persistables(d)
    pred, _ = prog.apply(params, state, **batches[0])
    assert np.isfinite(np.asarray(pred["pred"])).all()


def test_timeline_dump(tmp_path):
    from paddle_tpu.core import profiler as P
    import json
    P.start_profiler()
    with P.record_event("step"):
        with P.record_event("fwd"):
            pass
    P.stop_profiler()
    path = str(tmp_path / "tl.json")
    n = P.timeline(path)
    assert n == 2
    ev = json.load(open(path))["traceEvents"]
    assert {e["name"] for e in ev} == {"step", "fwd"}


def test_review_fixes_reader_and_dispatch(tmp_path):
    from paddle_tpu.data import reader as rd

    # fake honors n; empty reader errors
    def r():
        yield (1,)
    assert len(list(rd.fake(r, 3)())) == 3
    with pytest.raises(ValueError):
        list(rd.fake(lambda: iter(()), 2)())

    # compose raises on misalignment when check_alignment
    def r5():
        yield from [(i,) for i in range(5)]
    def r3():
        yield from [(i,) for i in range(3)]
    with pytest.raises(rd.ComposeNotAligned):
        list(rd.compose(r5, r3)())
    assert len(list(rd.compose(r5, r3, check_alignment=False)())) == 3

    # multiprocess_reader propagates worker exceptions
    def bad():
        yield (1,)
        raise IOError("disk gone")
    with pytest.raises(IOError):
        list(rd.multiprocess_reader([bad])())

    # PipeReader rejects unknown file_type, decompresses gzip — incl.
    # concatenated members (cat a.gz b.gz)
    with pytest.raises(ValueError):
        rd.PipeReader("echo x", file_type="zstd")
    import gzip as _gz
    p1, p2 = str(tmp_path / "a.gz"), str(tmp_path / "b.gz")
    with _gz.open(p1, "wb") as f:
        f.write(b"hello\nworld\n")
    with _gz.open(p2, "wb") as f:
        f.write(b"again\n")
    lines = [l for l in rd.PipeReader(f"cat {p1} {p2}", file_type="gzip").get_line() if l]
    assert lines == ["hello", "world", "again"]

    # HashName stable across instances (md5, not salted hash)
    from paddle_tpu.transpiler import HashName
    assert HashName(["a", "b"]).dispatch(["w1"]) == HashName(["a", "b"]).dispatch(["w1"])


def test_append_backward_empty_parameter_list():
    prog = pt.build(lambda a: {"loss": L.mean(L.fc(a, 2, name="g"))})
    x = np.random.randn(2, 3).astype(np.float32)
    params, state = prog.init(jax.random.PRNGKey(0), x)
    _, pg = pt.append_backward(prog, "loss", parameter_list=[])(params, state, x)
    assert pg == []  # empty list means "no params", not "all params"


def test_save_params_forwards_state(tmp_path):
    from paddle_tpu import io as pio
    d = str(tmp_path / "sp")
    pio.save_params(d, {"w": jnp.ones(2)}, state={"bn/mean": jnp.zeros(3)})
    _, state, _, _ = pio.load_persistables(d)
    assert "bn/mean" in state


def test_chunk_eval_counts_vs_bruteforce():
    """In-graph chunk_eval (IOB/IOBES/plain) vs a python span extractor."""
    rng = np.random.RandomState(3)

    def extract(tags, length, num_types, scheme):
        """Independent chain-based span extractor: token j+1 joins the
        chunk of token j iff same type and the scheme's (prev_tag,
        next_tag) link rule holds; spans are maximal chains."""
        tag_num = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
        tags = list(tags[:length])

        def info(t):
            if 0 <= t < num_types * tag_num:
                return t // tag_num, t % tag_num
            return None

        def links(ptag, ntag, scheme):
            if scheme == "IOB":
                return ntag == 1 and ptag in (0, 1)
            if scheme == "IOE":
                return ptag == 0
            if scheme == "IOBES":
                return ntag in (1, 2) and ptag in (0, 1)
            return True  # plain

        spans, i = set(), 0
        while i < length:
            cur = info(tags[i])
            if cur is None:
                i += 1
                continue
            ctype, tag = cur
            if scheme == "IOBES" and tag in (2, 3):   # E/S close immediately
                spans.add((i, i, ctype))
                i += 1
                continue
            j = i
            while j + 1 < length:
                nxt = info(tags[j + 1])
                ptag = info(tags[j])[1]
                if nxt is None or nxt[0] != ctype or not links(ptag, nxt[1], scheme):
                    break
                j += 1
                if scheme == "IOBES" and info(tags[j])[1] == 2:   # E closes
                    break
            spans.add((i, j, ctype))
            i = j + 1
        return spans

    for scheme in ("IOB", "IOE", "IOBES", "plain"):
        tag_num = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
        num_types = 3
        b, t = 4, 12
        vocab = num_types * tag_num + 2            # includes O ids
        hyp = rng.randint(0, vocab, (b, t))
        ref = rng.randint(0, vocab, (b, t))
        lengths = rng.randint(5, t + 1, (b,))
        nh, nr, nc = M.chunk_eval_counts(jnp.asarray(hyp), jnp.asarray(ref),
                                         jnp.asarray(lengths), num_types, scheme)
        eh = er = ec = 0
        for i in range(b):
            sh = extract(hyp[i], lengths[i], num_types, scheme)
            sr = extract(ref[i], lengths[i], num_types, scheme)
            eh += len(sh); er += len(sr); ec += len(sh & sr)
        assert (int(nh), int(nr), int(nc)) == (eh, er, ec), scheme


def test_op_frequence_and_memory_usage():
    from paddle_tpu import debugger
    x = np.random.randn(4, 8).astype(np.float32)
    prog = pt.build(lambda a: {"loss": L.mean(L.fc(a, 16, act="relu"))})
    params, state = prog.init(jax.random.PRNGKey(0), x)
    freq = debugger.op_frequence(prog, params, state, x)
    assert freq.get("dot_general", 0) >= 1
    uni, adj = debugger.op_frequence(prog, params, state, x,
                                     with_adjacent=True)
    assert uni == freq
    # fc = dot + bias-add + relu: the add must consume the dot's output
    assert any(k.startswith("dot_general,") for k in adj), adj
    assert all(v >= 1 for v in adj.values())
    mem = debugger.memory_usage(prog, params, state, x)
    assert mem["param_mb"] > 0 and mem["activation_sum_mb"] > 0
    assert mem["param_with_optimizer_mb"] == pytest.approx(3 * mem["param_mb"])


def test_weight_norm_default_dim_scalar_g():
    """dim=None norms over ALL axes (scalar g), matching the reference's
    layer_helper __norm_except_dim(dim=None)."""
    x = np.random.randn(4, 6).astype(np.float32)
    prog = pt.build(lambda a: L.fc(a, 3, name="wn0",
                                   param_attr=pt.WeightNormParamAttr()))
    params, state = prog.init(jax.random.PRNGKey(0), x)
    g = np.asarray(params["wn0/w@wn_g"])
    assert g.shape == (), f"expected scalar g, got shape {g.shape}"
    v = np.asarray(params["wn0/w"])
    np.testing.assert_allclose(g, np.linalg.norm(v), rtol=1e-5)
    out, _ = prog.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(out), x @ v + np.asarray(params["wn0/b"]),
                               rtol=1e-4, atol=1e-5)
