"""Distributed/execution strategy objects.

Analog of ExecutionStrategy/BuildStrategy (pybind.cc:675/:757,
details/build_strategy.h:34) and DistributeTranspilerConfig
(distribute_transpiler.py:127) — the knob surface, as a dataclass.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass
class DistStrategy:
    # multi_batch_merge_pass analog: microbatch gradient accumulation.
    accum_steps: int = 1
    # how accumulated gradients are exchanged across the data axes:
    # - "gspmd" (default): the model runs under GSPMD inside the
    #   microbatch scan; the partitioner reduces EVERY microbatch's
    #   gradients (it does not hoist the exchange past the accumulator
    #   — measured, see SCALING.md §2), so accumulation is a memory
    #   lever only. Fully general (any sharding rules, stateful
    #   models).
    # - "hoisted": the microbatch loop runs shard_map-LOCAL per data
    #   shard and the summed gradients are pmean'd ONCE per optimizer
    #   step — accum_steps becomes a wire lever (the DCN-scaling
    #   recipe). Requires fully replicated params (no fsdp/tp/pp/sp),
    #   stateless models (no BN running stats), and divisible batches;
    #   dropout masks decorrelate per shard via axis-index rng folds
    #   (same-in-distribution as GSPMD, not bitwise).
    accum_exchange: str = "gspmd"
    # kAllReduce vs kReduce (build_strategy.h:55): 'allreduce' replicates
    # params; 'sharded' (fsdp) shards params+optimizer state.
    reduce_strategy: str = "allreduce"
    # donation / rematerialization knobs (memory_optimize analog).
    # remat flips framework.remat_mode during the Trainer's trace: zoo
    # models' maybe_remat blocks become per-block jax.checkpoint.
    donate_buffers: bool = True
    remat: bool = False
    # what checkpointed blocks KEEP: None/'nothing' = full recompute,
    # 'dots' = save matmul outputs (skip MXU recompute, drop elementwise
    # intermediates), 'dots_no_batch', 'everything', or a
    # jax.checkpoint_policies callable
    remat_policy: Any = None
    # store float optimizer accumulators (Adam moments etc.) in this
    # dtype ('bfloat16' halves optimizer HBM); update math stays f32
    opt_state_dtype: Optional[str] = None
    # loss scaling for mixed precision: a float enables scaling at that
    # initial value; dynamic_loss_scale grows/shrinks it from overflow
    # history (non-finite grads always skip the step when enabled).
    loss_scale: Optional[float] = None
    dynamic_loss_scale: bool = False
    loss_scale_growth_interval: int = 1000
    # debug dump of the compiled HLO (debug_graphviz_path analog).
    dump_hlo_path: Optional[str] = None
    # pipeline parallelism: >0 routes zoo models' stacked block stacks
    # through parallel.pipeline.pipeline_apply with this many
    # microbatches (Trainer enters framework.pipeline_mode when the mesh
    # has a 'pp' axis). Bubble fraction = (pp-1)/(m+pp-1); see
    # parallel.pipeline.bubble_fraction.
    pp_microbatches: int = 0
    # virtual pipeline stages per rank (Megatron interleaved schedule):
    # >1 splits each rank's layer span into this many non-adjacent
    # chunks, shrinking the bubble by the same factor at the cost of
    # proportionally more neighbor-hop activation traffic. Layers must
    # divide by pp·pp_interleave.
    pp_interleave: int = 1
    # sequence/context parallelism: sp-aware zoo models (models/gpt.py)
    # run their attention over the mesh's 'sp' axis. Mutually exclusive
    # with pp_microbatches on the same stack. sp_impl picks the scheme:
    # 'ring' = zigzag ring attention, activations kept in zigzag layout
    # end-to-end (no head-count constraint); 'ulysses' = all-to-all
    # head<->sequence reshard (needs num_heads % sp == 0; full-sequence
    # inner kernel).
    sequence_parallel: bool = False
    sp_impl: str = "ring"
    # quantized gradient exchange (EQuARX lineage, PAPERS.md): "int8" /
    # "int4" replaces the per-step gradient all-reduce with the block-
    # scaled quantized ring (parallel.quantized_collectives) — and, in
    # async PS mode, routes gradient pushes through the block-scaled
    # PUSHQB wire verb. "none" (default) keeps today's exact exchange,
    # bit-identically. The collective path runs the grad exchange
    # shard_map-local over the data axes (same preconditions as
    # accum_exchange="hoisted": fully replicated params, stateless
    # model, divisible batch) so the ring carries int8/int4 on the wire
    # instead of letting GSPMD insert a f32 all-reduce.
    quantized_allreduce: str = "none"
    # elements per f32 abs-max scale block; one outlier only flattens
    # its own block's resolution. Smaller = tighter error, more scale
    # bytes (overhead 4/block_size of the int8 payload).
    quant_block_size: int = 256
    # carry the per-rank quantization error (grad - its wire roundtrip)
    # in the step/scan carry and add it back into the NEXT step's
    # gradient before encoding — error telescopes across the fused
    # K-step program instead of compounding (1-bit SGD / EF-SGD
    # lineage). Residual lives in UNSCALED gradient units and is rolled
    # back on skipped (non-finite) steps.
    error_feedback: bool = True
    # stochastic rounding on the encode path, keyed off the step rng:
    # floor(x/scale*qmax + u), unbiased per element. Applied to the
    # initial quantization and reduce-scatter hops only — all-gather
    # hops stay deterministic, preserving cross-rank bitwise identity.
    quant_stochastic_rounding: bool = False
    # ZeRO-style cross-replica sharded weight update ("Automatic
    # Cross-Replica Sharding of Weight Update in Data-Parallel
    # Training", PAPERS.md): each data-parallel replica owns a 1/N
    # flat shard of params + optimizer state, applies the optimizer
    # update to its shard only, and fresh params are all-gathered at
    # the top of every (fused-scan) step — optimizer HBM drops ~N×.
    # Same preconditions as accum_exchange="hoisted": a mesh with data
    # axes, fully replicated params (no fsdp/tp/pp/sp), stateless
    # models. Composes with accum_exchange, quantized_allreduce,
    # dynamic loss scaling, and remat; checkpoints become shard-aware
    # (per-shard manifest entries, meta.zero_axes) with an explicit
    # gather-then-repartition elastic door for N→M restores. False
    # keeps today's replicated update bit-identically.
    zero_sharding: bool = False
    # async parameter-server mode (listen_and_serv RunAsyncLoop analog):
    # barrier-free grad push / param pull through the C++ pserver
    # (parallel.async_ps) instead of SPMD collectives. Set by
    # DistributeTranspiler(sync_mode=False); consumed by driver code that
    # routes the program to AsyncPSTrainer.
    async_mode: bool = False
