"""Out-of-process fleet replica entrypoint::

    python -m paddle_tpu.fleet.replica_main <config.json>

Runs ONE :class:`~paddle_tpu.serving.PredictorServer` over the
artifact named in the config and serves the framed fleet wire
(:mod:`paddle_tpu.fleet.remote` documents the verbs) on a TCP
listener — one handler thread per connection, the same accept
discipline as ``native/pserver.cc``. Prints ``PORT <n>`` on stdout
once the server is warmed and the listener is up (the parent's
``ReplicaProcess.wait_ready`` handshake).

Contract-critical ordering: the ``DISPATCHED <id>`` lifecycle line is
written when the local server's worker picks the request up —
observed via a journal subscriber on the ``serving.dispatch`` event,
which the worker emits BEFORE executing. A client that never received
``DISPATCHED`` from a process that then died knows the request never
produced an observable effect (SIGKILL still delivers bytes written
before death), so the router may reroute it; once ``DISPATCHED`` is
on the wire the request is at-most-once.

Trace tokens: a ``trace=<span>`` field on the SUBMIT header is
adopted as the request's span (``PredictorServer.submit(span=...)``),
so this process's journal and the front door's carry one trace id —
and the ``JOURNAL`` verb ships this ring back for
``RunJournal.ingest``.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
import traceback
from typing import Any, Dict, Optional


def _reply_json(conn: socket.socket, obj: Dict[str, Any]) -> None:
    from ..telemetry.journal import _json_default

    body = json.dumps(obj, default=_json_default).encode()
    conn.sendall(b"OK %d\n" % len(body) + body)


def _reply_err(conn: socket.socket, exc: BaseException) -> None:
    from .remote import error_payload

    name, detail = error_payload(exc)
    body = json.dumps(detail, default=repr).encode()
    conn.sendall(f"ERR {name} {len(body)}\n".encode() + body)


class _ReplicaService:
    """The verb dispatcher around one local ``PredictorServer``."""

    def __init__(self, server, journal, artifact_root: Optional[str] = None):
        from .remote import ArtifactStore

        self.server = server
        self.journal = journal
        # the host-side artifact cache behind FETCH/ARTIFACT: a router
        # on another machine streams save_inference_model dirs here
        # before RELOADing them (agent-spawned replicas share the
        # agent's cache, so one ship covers every replica on the host)
        if artifact_root is None:
            import tempfile
            artifact_root = os.path.join(tempfile.gettempdir(),
                                         f"pdtpu_artifacts_{os.getpid()}")
        self.artifacts = ArtifactStore(artifact_root)
        # SUBMIT feed byte accounting: wire (what crossed the link)
        # vs logical (what a passthrough transfer would have cost)
        self._wire_lock = threading.Lock()
        self._wire_counters = {"wire_bytes": 0, "logical_bytes": 0}
        self._rid_lock = threading.Lock()
        self._next_rid = 0
        # span -> fire callback, armed by SUBMIT handlers, invoked by
        # the journal subscriber when that span's serving.dispatch
        # event lands. The subscriber runs SYNCHRONOUSLY on the worker
        # thread between the dispatch emit and the execution (and
        # fires regardless of journal sampling — subscribe() is not a
        # sink), so the DISPATCHED wire write completes BEFORE the
        # executable runs: "no DISPATCHED received ⇒ never began
        # executing" is exact for a killed process, which is what
        # makes the client's reroute classification safe.
        self._dispatch_waiters: Dict[str, Any] = {}
        self._waiters_lock = threading.Lock()
        self._sub = journal.subscribe(self._on_journal_event)
        self.stopping = threading.Event()

    def _on_journal_event(self, event: Dict[str, Any]) -> None:
        if event.get("kind") != "serving.dispatch":
            return
        span = event.get("span")
        if span is None:
            return
        with self._waiters_lock:
            fire = self._dispatch_waiters.get(span)
        if fire is not None:
            fire()

    def _rid(self) -> str:
        with self._rid_lock:
            self._next_rid += 1
            return str(self._next_rid)

    # -- verbs ---------------------------------------------------------------

    def handle_submit(self, conn: socket.socket, parts) -> None:
        # retry: at-most-once — a replayed SUBMIT runs inference twice
        from ..parallel.async_ps import read_exact
        from .remote import error_payload, pack_tree, unpack_tree

        meta_len, payload_len = int(parts[1]), int(parts[2])
        deadline = None if parts[3] == "-" else float(parts[3])
        span = None
        for tok in parts[4:]:
            if tok.startswith("trace="):
                span = tok[len("trace="):]
        if span is None:
            # a client that sent no trace token still needs the
            # DISPATCHED ordering (the at-most-once classification
            # hangs off it) — mint the span server-side so the
            # dispatch subscriber has something to match
            span = self.journal.new_span()
        counters: Dict[str, int] = {}
        feed = unpack_tree(read_exact(conn, meta_len),
                           read_exact(conn, payload_len), counters=counters)
        with self._wire_lock:
            for k, v in counters.items():
                self._wire_counters[k] = self._wire_counters.get(k, 0) + v
        rid = self._rid()
        wlock = threading.Lock()   # serializes every write on this conn
        state = {"ok_sent": False, "fire_early": False,
                 "dispatched_sent": False}

        def _send_dispatched_locked() -> None:
            if state["dispatched_sent"]:
                return
            state["dispatched_sent"] = True
            try:
                # the worker thread writes this: cap a pathological
                # stalled client so it cannot head-of-line-block the
                # whole replica behind one dead peer
                conn.settimeout(2.0)
                conn.sendall(f"DISPATCHED {rid}\n".encode())
            except OSError:
                # a timed-out/failed send may have written PART of the
                # line: the stream is unrecoverable — close it so the
                # later DONE/FAIL write fails instead of appending to
                # a torn frame (the client classifies the lost
                # connection at-most-once, which is the truthful
                # outcome)
                try:
                    conn.close()
                except OSError:
                    pass
            finally:
                try:
                    conn.settimeout(None)
                except OSError:
                    pass

        def fire() -> None:
            # invoked by the journal subscriber ON the worker thread,
            # after the serving.dispatch emit and BEFORE the
            # executable runs — the wire write completes before
            # execution begins. The one exception: a dispatch so fast
            # it beats the handler's OK write queues behind it
            # (fire_early) and is written by the handler immediately
            # after OK — a microsecond window in which a kill would
            # reroute work whose execution died unobserved with the
            # process (still safe, just not wire-exact).
            with wlock:
                if not state["ok_sent"]:
                    state["fire_early"] = True
                    return
                _send_dispatched_locked()

        with self._waiters_lock:
            self._dispatch_waiters[span] = fire
        try:
            try:
                pending = self.server.submit(feed, deadline=deadline,
                                             span=span)
            except BaseException as e:
                _reply_err(conn, e)
                return
            with wlock:
                conn.sendall(f"OK {rid}\n".encode())
                state["ok_sent"] = True
                if state["fire_early"]:
                    _send_dispatched_locked()
            done_evt = pending._req.done
            while not done_evt.wait(0.5):
                if self.stopping.is_set():
                    done_evt.wait(5.0)   # shutdown grace, then bail
                    break
            try:
                value = pending.result(timeout=0.001)
            except BaseException as e:
                name, detail = error_payload(e)
                body = json.dumps(detail, default=repr).encode()
                with wlock:
                    conn.sendall(f"FAIL {rid} {name} {len(body)}\n".encode()
                                 + body)
            else:
                meta, payload = pack_tree(value)
                with wlock:
                    conn.sendall(f"DONE {rid} {len(meta)} "
                                 f"{len(payload)}\n".encode()
                                 + meta + payload)
        finally:
            with self._waiters_lock:
                self._dispatch_waiters.pop(span, None)

    def handle_health(self, conn: socket.socket) -> None:
        h = self.server.health()
        h["pid"] = os.getpid()
        _reply_json(conn, h)

    def handle_report(self, conn: socket.socket) -> None:
        rep = self.server.report()
        with self._wire_lock:
            rep["feed_wire"] = dict(self._wire_counters)
        _reply_json(conn, rep)

    def handle_metrics(self, conn: socket.socket) -> None:
        from ..telemetry import get_registry

        _reply_json(conn, get_registry().snapshot())

    def handle_journal(self, conn: socket.socket, since: int) -> None:
        events = [e for e in self.journal.recent()
                  if int(e.get("seq", 0)) > since]
        _reply_json(conn, {"run": self.journal.run_id, "events": events})

    def handle_fetch(self, conn: socket.socket, parts) -> None:
        """Artifact negotiate/commit (see ``remote.ArtifactStore``)."""
        from ..parallel.async_ps import read_exact

        token = parts[1]
        body = read_exact(conn, int(parts[2]))
        _reply_json(conn, self.artifacts.handle_fetch(token, body))

    def handle_artifact(self, conn: socket.socket, parts) -> None:
        """One pipelined artifact chunk frame — no reply (the sender
        streams; commit-time CRC validation reports bad files)."""
        from ..parallel.async_ps import read_exact

        token, fname = parts[1], parts[2]
        off, nbytes = int(parts[3]), int(parts[4])
        crc = int(parts[5], 16)
        data = read_exact(conn, nbytes)
        self.artifacts.handle_chunk(token, fname, off, crc, data)

    def handle_reload(self, conn: socket.socket, body: bytes) -> None:
        dirname = json.loads(body)["dirname"]
        try:
            self.server.reload(dirname, block=True)
        except BaseException as e:
            _reply_err(conn, e)
            return
        _reply_json(conn, {"generation": self.server.generation})

    def handle_kill(self, conn: socket.socket, body: bytes) -> None:
        reason = json.loads(body).get("reason", "killed over the wire")
        # kill() fails dispatched work ReplicaDied / queued work
        # ServerClosed — their SUBMIT handlers wake and push the FAIL
        # frames; the grace sleep lets those flushes land before the
        # process dies (a client that misses one classifies the lost
        # connection to the SAME typed outcome, so the race is benign)
        self.server.kill(reason=reason)
        try:
            _reply_json(conn, {})
        except OSError:
            pass
        time.sleep(0.2)
        os._exit(0)

    def handle_shutdown(self, conn: socket.socket, body: bytes) -> None:
        cfg = json.loads(body)
        self.stopping.set()
        self.server.close(drain=bool(cfg.get("drain", True)),
                          timeout=cfg.get("timeout"))
        try:
            _reply_json(conn, {})
        except OSError:
            pass
        time.sleep(0.1)
        os._exit(0)

    # -- connection loop -----------------------------------------------------

    def serve_conn(self, conn: socket.socket) -> None:
        from ..parallel.async_ps import read_exact, read_line

        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self.stopping.is_set():
                try:
                    line = read_line(conn)
                except (ConnectionError, OSError):
                    return
                parts = line.split()
                if not parts or parts[0] == "QUIT":
                    return
                verb = parts[0]
                try:
                    if verb == "SUBMIT":
                        self.handle_submit(conn, parts)
                    elif verb == "HEALTH":
                        self.handle_health(conn)
                    elif verb == "REPORT":
                        self.handle_report(conn)
                    elif verb == "METRICS":
                        self.handle_metrics(conn)
                    elif verb == "JOURNAL":
                        self.handle_journal(
                            conn, int(parts[1]) if len(parts) > 1 else 0)
                    elif verb == "FETCH":
                        self.handle_fetch(conn, parts)
                    elif verb == "ARTIFACT":
                        self.handle_artifact(conn, parts)
                    elif verb == "RELOAD":
                        self.handle_reload(conn,
                                           read_exact(conn, int(parts[1])))
                    elif verb == "KILL":
                        self.handle_kill(conn,
                                         read_exact(conn, int(parts[1])))
                    elif verb == "SHUTDOWN":
                        self.handle_shutdown(
                            conn, read_exact(conn, int(parts[1])))
                    else:
                        _reply_err(conn, RuntimeError(
                            f"unknown verb {verb!r}"))
                except (ConnectionError, OSError):
                    return
                except BaseException as e:  # a verb crashed: reply, keep conn
                    try:
                        _reply_err(conn, e)
                    except OSError:
                        return
        finally:
            try:
                conn.close()
            except OSError:
                pass


def _build_server(cfg: Dict[str, Any]):
    import numpy as np

    from ..io import load_inference_model
    from ..serving import BreakerPolicy, PredictorServer
    from .batching import BatchPolicy

    kw = dict(cfg.get("server_kw") or {})
    if cfg.get("batch_policy"):
        kw["batch_policy"] = BatchPolicy(**cfg["batch_policy"])
    if cfg.get("breaker"):
        kw["breaker"] = BreakerPolicy(**cfg["breaker"])
    if cfg.get("golden_feed"):
        with np.load(cfg["golden_feed"]) as z:
            kw["golden_feed"] = {k: z[k] for k in z.files}
    pred = load_inference_model(cfg["dirname"])
    return PredictorServer(pred, **kw)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m paddle_tpu.fleet.replica_main "
              "<config.json>", file=sys.stderr)
        return 2
    with open(argv[0], "r", encoding="utf-8") as f:
        cfg = json.load(f)
    from ..telemetry import get_journal

    try:
        server = _build_server(cfg)
    except BaseException:
        traceback.print_exc()
        print(f"REPLICA_FAILED {cfg.get('dirname')!r}", file=sys.stderr)
        return 1
    service = _ReplicaService(server, get_journal(),
                              artifact_root=cfg.get("artifact_root"))
    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    # the bind knob: off-host reachability is opt-in (config "bind" or
    # PDTPU_BIND_ADDR, e.g. "0.0.0.0"); the default stays loopback
    bind = (cfg.get("bind") or os.environ.get("PDTPU_BIND_ADDR")
            or cfg.get("host", "127.0.0.1"))
    ls.bind((bind, int(cfg.get("port", 0))))
    ls.listen(128)
    # the readiness handshake: the parent blocks on this exact line
    print(f"PORT {ls.getsockname()[1]}", flush=True)
    while not service.stopping.is_set():
        try:
            conn, _ = ls.accept()
        except OSError:
            break
        threading.Thread(target=service.serve_conn, args=(conn,),
                         daemon=True).start()
    return 0


if __name__ == "__main__":
    sys.exit(main())
