"""Checkpoint save/load + inference export.

Analog of python/paddle/fluid/io.py: save_vars/save_persistables
(io.py:89/:252 — a program of save ops per var), load_persistables
(io.py:464), save/load_inference_model (io.py:544/:669 — prune +
serialized ProgramDesc). Here persistable state is name-keyed pytrees →
a single .npz per collection (+ JSON meta); the inference model is a
serialized ``jax.export`` StableHLO artifact next to its weights — the
ProgramDesc-file analog, portable across processes and (with matching
XLA version) machines.

Resharding on load (the pserver slice/merge analog,
io.py:881 _load_slice_up_vars): arrays are saved unsharded (fully
gathered); loading places them per the current mesh/rules, so mesh
reshapes between save and load work by construction.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core.errors import enforce

SEP = "||"  # path separator for nested pytree keys (param names use '/')

# numpy's npz format stores ml_dtypes extension types (bfloat16, fp8) as
# raw void bytes that can't round-trip; encode them as a same-width
# integer view with a "@dtype" key suffix instead.
_EXOTIC_DTYPES = {"bfloat16": np.uint16,
                  "float8_e4m3fn": np.uint8, "float8_e5m2": np.uint8}


# -- pytree <-> flat dict ----------------------------------------------------


def _mangle_leaf(prefix: str, arr: np.ndarray):
    """Single source of truth for leaf-key mangling: the npz member name
    written by _flatten and the meta.json name written by
    _flat_leaves_in_tree_order must stay byte-identical (the native
    predictor looks meta names up in the npz table)."""
    if arr.dtype.name in _EXOTIC_DTYPES:
        return f"{prefix}@{arr.dtype.name}", arr.view(_EXOTIC_DTYPES[arr.dtype.name])
    if (prefix.endswith("@raw")
            or any(prefix.endswith(f"@{dt}") and arr.dtype == enc
                   for dt, enc in _EXOTIC_DTYPES.items())):
        # a genuine integer param whose NAME ends in '@bfloat16' etc.
        # (or '@raw' itself) would be indistinguishable from our
        # encoding on load — escape with a '@raw' marker (load strips
        # exactly one suffix, so escaping nests safely)
        return f"{prefix}@raw", arr
    return prefix, arr


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif tree is None:
        pass
    else:
        key, val = _mangle_leaf(prefix, np.asarray(tree))
        out[key] = val
    return out


def _flat_leaves_in_tree_order(tree: Any, prefix: str = ""):
    """(npz_key, value) pairs in jax's pytree flatten order (per-level
    sorted ORIGINAL keys, depth-first) — NOT sorted mangled npz keys,
    which diverge ('a2' vs 'a||x' sorts differently than 'a' vs 'a2';
    '@bfloat16' suffixes shift order). Used by save_inference_model to
    bind npz members to executable argument positions."""
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree, key=str):
            out += _flat_leaves_in_tree_order(
                tree[k], f"{prefix}{SEP}{k}" if prefix else str(k))
    elif tree is None:
        pass
    else:
        out.append(_mangle_leaf(prefix, np.asarray(tree)))
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    import ml_dtypes

    out: Dict[str, Any] = {}
    for key, v in flat.items():
        if "@" in key:
            maybe_key, _, dtname = key.rpartition("@")
            # only strip the suffix for markers *we* appended on save; a
            # user param literally named "x@foo" passes through intact,
            # and "x@bfloat16" of genuine integer dtype arrives escaped
            # as "x@bfloat16@raw"
            if dtname == "raw":
                key = maybe_key
            elif dtname in _EXOTIC_DTYPES and v.dtype == _EXOTIC_DTYPES[dtname]:
                key = maybe_key
                v = v.view(np.dtype(getattr(ml_dtypes, dtname)))
        parts = key.split(SEP)
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


# -- persistables ------------------------------------------------------------


def save_persistables(dirname: str, params: Dict[str, jax.Array],
                      state: Optional[Dict[str, jax.Array]] = None,
                      opt_state: Optional[Dict[str, Any]] = None,
                      meta: Optional[Dict[str, Any]] = None) -> Dict[str, Dict[str, Any]]:
    """Save all persistable vars (save_persistables analog, io.py:252).
    Sharded arrays are gathered to host first. Returns the flat
    shape/dtype spec per npz file ({filename: {flat key: {"shape",
    "dtype"}}}) — ``save_trainer`` records it in the checkpoint
    manifest."""
    os.makedirs(dirname, exist_ok=True)
    spec: Dict[str, Dict[str, Any]] = {}

    def _dump(name, tree):
        flat = _flatten(jax.device_get(tree))
        np.savez(os.path.join(dirname, name), **flat)
        spec[name] = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                      for k, v in flat.items()}

    _dump("params.npz", params)
    if state is not None:
        _dump("state.npz", state)
    if opt_state is not None:
        _dump("opt_state.npz", opt_state)
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump(meta or {}, f)
    return spec


def load_persistables(dirname: str) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray],
                                             Optional[Dict[str, Any]], Dict[str, Any]]:
    """Load (params, state, opt_state, meta) (load_persistables analog)."""

    def _load(name):
        p = os.path.join(dirname, name)
        if not os.path.exists(p):
            return None
        with np.load(p, allow_pickle=False) as z:
            # fresh writable copies, NOT the npz-backed views: jax's CPU
            # backend zero-copies device_put of host arrays when it can,
            # and a Trainer later DONATES those buffers — in-place XLA
            # reuse of memory owned by the zip reader corrupts values
            # transiently (observed as NaN losses after resume; the
            # fault-injection suite pins this via resume continuity)
            return _unflatten({k: np.array(z[k]) for k in z.files})

    params = _load("params.npz") or {}
    state = _load("state.npz") or {}
    opt_state = _load("opt_state.npz")
    if opt_state is not None:
        # empty sub-dicts ("global"/"accums" for stateless optimizers)
        # flatten to nothing on save — restore the keys
        opt_state.setdefault("global", {})
        opt_state.setdefault("accums", {})
    meta_path = os.path.join(dirname, "meta.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    return params, state, opt_state, meta


def _fsync_tree(dirname: str) -> None:
    """fsync every regular file in ``dirname`` (and the dir itself):
    the atomic-rename commit is only meaningful if the data it commits
    has reached the disk."""
    for name in os.listdir(dirname):
        p = os.path.join(dirname, name)
        if not os.path.isfile(p):
            continue
        fd = os.open(p, os.O_RDONLY)
        try:
            os.fsync(fd)
        except OSError:
            pass  # fs without fsync support (tmpfs variants): best effort
        finally:
            os.close(fd)
    _fsync_dir(dirname)


def _fsync_dir(dirname: str) -> None:
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_trainer(dirname: str, trainer,
                 extra_meta: Optional[Dict[str, Any]] = None) -> None:
    """Checkpoint a Trainer (params+state+opt_state+step) — the
    CheckpointConfig/save_checkpoint analog (contrib/trainer.py:100).

    **Atomic + validated**: the collections are written to a
    ``<dirname>.tmp.<pid>`` sibling, fsynced, covered by a
    ``manifest.json`` (format version, global_step, per-file CRC32 +
    size, flat shape/dtype spec), and renamed into place. A crash at
    ANY point (see the ``save_trainer:*`` crash points in
    ``testing.faults``) leaves either the previous committed checkpoint
    or the new one — never a torn directory that ``load_trainer``
    trusts. ``extra_meta`` entries ride in the checkpoint meta (``fit``
    stores epoch/epoch_step for resume)."""
    import shutil

    from . import resilience

    meta = {"global_step": trainer.global_step}
    ls = getattr(trainer.scope, "loss_scale_state", None)
    if ls:
        meta["loss_scale_state"] = {k: float(v) for k, v in ls.items()}
    if extra_meta:
        meta.update(extra_meta)
    # checkpoints always store logical layer order: undo the trainer's
    # interleaved pipeline rest layout (no-op otherwise)
    params, opt_state = trainer.stacked_to_logical(
        trainer.scope.params, trainer.scope.opt_state)
    path = os.path.abspath(dirname)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    # clean ANY stale tmp for this tag (a prior process's torn save
    # leaves <tag>.tmp.<other-pid> behind; fit also sweeps the whole
    # dir at startup with the unfiltered form)
    resilience.sweep_tmp_dirs(parent, tag=os.path.basename(path))
    tmp = f"{path}{resilience.TMP_MARKER}{os.getpid()}"
    spec = save_persistables(tmp, params, trainer.scope.state,
                             opt_state, meta=meta)
    resilience.crash_point("save_trainer:files-written")
    _fsync_tree(tmp)
    resilience.write_manifest(tmp, meta=meta, arrays=spec)
    resilience.crash_point("save_trainer:manifest-written")
    if os.path.isdir(path):
        # overwrite of an existing tag: the old dir must vanish before
        # the rename (rename onto a non-empty dir fails). The window
        # where neither exists only loses THIS tag — older tags are
        # untouched and the resume scanner falls back to them.
        shutil.rmtree(path)
    os.rename(tmp, path)
    _fsync_dir(parent)


def load_trainer(dirname: str, trainer) -> None:
    """Restore a Trainer in place, re-placing arrays on the trainer's
    device/mesh (resharding-on-load).

    The checkpoint is validated against its manifest first (CRC32 per
    file, format version); any mismatch — or an npz that fails to parse
    — raises a structured :class:`~paddle_tpu.resilience.CheckpointCorrupt`
    instead of a random decoder error. Pre-manifest (legacy) directories
    load without validation."""
    from . import resilience

    manifest = resilience.validate_checkpoint(dirname)  # None for legacy
    try:
        params, state, opt_state, meta = load_persistables(dirname)
    except Exception as e:
        raise resilience.CheckpointCorrupt(
            dirname, f"unreadable collection: {type(e).__name__}: {e}") from e
    if not params:
        raise resilience.CheckpointCorrupt(
            dirname, "no parameters found (params.npz missing or empty)")
    if manifest:
        _check_arrays_spec(manifest, dirname, params=params, state=state,
                           opt_state=opt_state)
    if opt_state is not None:
        # stateless-optimizer per-param accums are empty dicts, which
        # flatten to nothing on save — restore the per-param keys
        for k in params:
            opt_state["accums"].setdefault(k, {})
    # checkpoints are logical layer order; a trainer running the
    # interleaved pipeline layout re-permutes on the way in (no-op
    # otherwise)
    params, opt_state = trainer.stacked_from_logical(params, opt_state)
    if trainer.mesh is not None:
        from .parallel import api as par_api
        params, state, opt_state = par_api.shard_scope(
            trainer.mesh, trainer.sharding_rules, params, state, opt_state)
    else:
        dev = trainer.place.device()
        params = jax.device_put(params, dev)
        state = jax.device_put(state, dev)
        opt_state = jax.device_put(opt_state, dev) if opt_state is not None else None
    # restore exact leaf dtypes (npz roundtrips are exact, but int scalars
    # may come back as 0-d arrays)
    if opt_state is not None:
        opt_state["step"] = jnp.asarray(opt_state["step"], jnp.int32)
    trainer.scope.params, trainer.scope.state, trainer.scope.opt_state = params, state, opt_state
    trainer.global_step = int(meta.get("global_step", 0))
    # kept for fit(resume=True): epoch/epoch_step and anything else the
    # saver stored ride here (resilience.restore_latest reads it)
    trainer._last_loaded_meta = dict(meta)
    _restore_loss_scale(trainer, meta, dirname)


def _check_arrays_spec(manifest: Dict[str, Any], dirname: str,
                       **collections) -> None:
    """Verify the loaded trees against the manifest's flat shape/dtype
    spec — the per-leaf half of checkpoint validation (CRC32 guarantees
    the bytes; this guarantees the decoded structure matches what the
    saver recorded, catching a manifest/npz pair that drifted out of
    sync). Costs a dict re-flatten of data already in memory."""
    from . import resilience

    spec = manifest.get("arrays") or {}
    fname = {"params": "params.npz", "state": "state.npz",
             "opt_state": "opt_state.npz"}
    for coll, tree in collections.items():
        want = spec.get(fname[coll])
        if want is None or tree is None:
            continue
        got = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
               for k, v in _flatten(tree).items()}
        if set(got) != set(want):
            missing = sorted(set(want) - set(got))[:3]
            extra = sorted(set(got) - set(want))[:3]
            raise resilience.CheckpointCorrupt(
                dirname, f"{fname[coll]} members diverge from manifest "
                f"(missing: {missing}, unexpected: {extra})")
        for k, w in want.items():
            if got[k] != w:
                raise resilience.CheckpointCorrupt(
                    dirname, f"{fname[coll]}:{k} is {got[k]} on disk but "
                    f"the manifest records {w}")


def _restore_loss_scale(trainer, meta: Dict[str, Any], dirname: str) -> None:
    """Loss-scale state across checkpoint/trainer config drift: a
    checkpoint that predates dynamic loss scaling restored into a
    scaler-running trainer (or vice versa) must warn and fall back to
    the scaler's initial state, not KeyError."""
    import warnings

    ls_meta = meta.get("loss_scale_state")
    if trainer.loss_scaler is None:
        if ls_meta:
            warnings.warn(
                f"checkpoint {dirname!r} carries loss_scale_state but the "
                "trainer has no loss scaler — ignoring it (configure "
                "DistStrategy.loss_scale to adopt it)")
        return
    init = trainer.loss_scaler.init_state()
    if not ls_meta:
        warnings.warn(
            f"checkpoint {dirname!r} has no loss_scale_state but the "
            "trainer runs a loss scaler — falling back to the scaler's "
            "initial state (scale will re-calibrate)")
        ls_meta = {}
    missing = {"scale", "good_steps", "overflows"} - set(ls_meta)
    if ls_meta and missing:
        warnings.warn(
            f"checkpoint {dirname!r} loss_scale_state is missing "
            f"{sorted(missing)} — those fields fall back to the scaler's "
            "initial values")
    trainer.scope.loss_scale_state = jax.device_put({
        "scale": jnp.float32(ls_meta.get("scale", float(init["scale"]))),
        "good_steps": jnp.int32(ls_meta.get("good_steps",
                                            int(init["good_steps"]))),
        "overflows": jnp.int32(ls_meta.get("overflows",
                                           int(init["overflows"]))),
    })


# -- inference model (save/load_inference_model analog) ----------------------


def _in_spec(flat_sources, exported):
    """Flat (source, name) binding -> the ordered input spec native
    drivers consume. ONE emission point for both artifact kinds
    (save_inference_model / save_train_artifact): the invariant that
    spec names stay byte-identical to npz member names (via
    _mangle_leaf) and positionally aligned to exported.in_avals must
    not fork."""
    enforce(len(flat_sources) == len(exported.in_avals),
            f"export signature mismatch: {len(flat_sources)} leaves vs "
            f"{len(exported.in_avals)} in_avals")
    return [{"source": src, "name": name,
             "dtype": str(av.dtype), "shape": list(av.shape)}
            for (src, name), av in zip(flat_sources, exported.in_avals)]


def save_inference_model(dirname: str, program, params: Dict[str, jax.Array],
                         state: Dict[str, jax.Array], example_feed: Dict[str, Any]) -> None:
    """Export program.apply (inference mode, params baked as inputs) as a
    serialized StableHLO artifact + weights (io.py:544 analog: prune to
    feed/fetch + serialize ProgramDesc + save params)."""
    os.makedirs(dirname, exist_ok=True)
    feed_names = sorted(example_feed)

    def infer_fn(params_, state_, *feed_vals):
        feed = dict(zip(feed_names, feed_vals))
        out, _ = program.apply(params_, state_, training=False, **feed)
        return out

    example_vals = [jnp.asarray(np.asarray(example_feed[k])) for k in feed_names]
    host_params, host_state = jax.device_get(params), jax.device_get(state)
    exported = jax.export.export(jax.jit(infer_fn))(
        host_params, host_state, *example_vals)
    with open(os.path.join(dirname, "model.stablehlo"), "wb") as f:
        f.write(exported.serialize())
    np.savez(os.path.join(dirname, "params.npz"), **_flatten(host_params))
    np.savez(os.path.join(dirname, "state.npz"), **_flatten(host_state))
    # Python-free deployment artifact (inference/io.h:35 analog): the raw
    # StableHLO bytecode plus the flat call signature, so native/
    # predictor.cc can compile+run through the PJRT C API with no
    # libpython. Inputs are the flattened (params, state, *feeds) leaves
    # in exported.in_avals order; "source" tells the C++ loader which
    # npz member (or feed) supplies each argument.
    with open(os.path.join(dirname, "model.mlir"), "wb") as f:
        f.write(exported.mlir_module_serialized)
    param_leaves = _flat_leaves_in_tree_order(host_params)
    state_leaves = _flat_leaves_in_tree_order(host_state)
    flat_sources = ([("params.npz", k) for k, _ in param_leaves]
                    + [("state.npz", k) for k, _ in state_leaves]
                    + [("feed", k) for k in feed_names])
    flat_vals = ([v for _, v in param_leaves] + [v for _, v in state_leaves]
                 + [np.asarray(example_feed[k]) for k in feed_names])
    in_spec = _in_spec(flat_sources, exported)
    for (src, name), val, av in zip(flat_sources, flat_vals, exported.in_avals):
        enforce(tuple(val.shape) == tuple(av.shape),
                f"export arg order broke: {src}:{name} has shape {val.shape}, "
                f"aval expects {av.shape}")
        # npz members store exotic dtypes as integer views ('@bfloat16'
        # suffix); the ORIGINAL dtype must still match the aval
        if src != "feed" and "@" not in name:
            enforce(val.dtype.name == str(av.dtype),
                    f"export arg order broke: {src}:{name} is {val.dtype.name},"
                    f" aval expects {av.dtype}")
    out_spec = [{"dtype": str(av.dtype), "shape": list(av.shape)}
                for av in exported.out_avals]
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump({"feed_names": feed_names, "inputs": in_spec,
                   "outputs": out_spec}, f)


def save_train_artifact(dirname: str, trainer, example_feed: Dict[str, Any]) -> None:
    """Export ONE optimizer step of a started Trainer as a StableHLO
    artifact the Python-free native trainer (native/trainer.cc) can
    drive — train/demo/demo_trainer.cc parity, where the reference saves
    a ProgramDesc its C++ Executor replays.

    The exported function is
        step(params, opt_state, state, seed, *feeds)
          -> (params', opt_state', state', loss)
    with params/opt_state/state flattened in sorted-key order on BOTH
    sides, so output i is input i's next value for i < num_carry — the
    C++ loop swaps buffers positionally with no name resolution. The
    per-step RNG enters as a u32 scalar seed (PRNGKey built inside the
    traced step: threefry, so the artifact is backend-portable); the
    C++ driver feeds the step index.
    """
    program, optimizer = trainer.program, trainer.optimizer
    enforce(trainer.scope.params is not None, "save_train_artifact: call "
            "trainer.startup() first")
    enforce(getattr(trainer, "loss_scaler", None) is None,
            "save_train_artifact: dynamic loss scaling not supported in the "
            "native step (export a bfloat16/float32 trainer)")
    enforce(getattr(trainer, "mesh", None) is None,
            "save_train_artifact: single-device export only")
    loss_name = trainer.loss_name
    os.makedirs(dirname, exist_ok=True)
    feed_names = sorted(example_feed)

    def step(params_, opt_state_, state_, seed, *feed_vals):
        feed = dict(zip(feed_names, feed_vals))
        rng = jax.random.PRNGKey(seed)

        def loss_fn(p, st):
            out, new_state = program.apply(p, st, training=True, rng=rng,
                                           **feed)
            loss = out[loss_name] if isinstance(out, dict) else out
            return loss, new_state

        (loss, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params_, state_)
        new_params, new_opt = optimizer.update(grads, opt_state_, params_,
                                               program.param_info)
        return new_params, new_opt, new_state, loss.astype(jnp.float32)

    host = jax.device_get((trainer.scope.params, trainer.scope.opt_state,
                           trainer.scope.state))
    host_params, host_opt, host_state = host
    example_vals = [jnp.asarray(np.asarray(example_feed[k]))
                    for k in feed_names]
    exported = jax.export.export(jax.jit(step))(
        host_params, host_opt, host_state, np.uint32(0), *example_vals)
    with open(os.path.join(dirname, "train_step.mlir"), "wb") as f:
        f.write(exported.mlir_module_serialized)
    # the jax-side serialization as well (save_inference_model's
    # model.stablehlo analog): lets a Python process deserialize and
    # replay the IDENTICAL artifact (tests do), not a re-trace
    with open(os.path.join(dirname, "train_step.jaxexp"), "wb") as f:
        f.write(exported.serialize())
    np.savez(os.path.join(dirname, "params.npz"), **_flatten(host_params))
    np.savez(os.path.join(dirname, "opt.npz"), **_flatten(host_opt))
    np.savez(os.path.join(dirname, "state.npz"), **_flatten(host_state))

    param_leaves = _flat_leaves_in_tree_order(host_params)
    opt_leaves = _flat_leaves_in_tree_order(host_opt)
    state_leaves = _flat_leaves_in_tree_order(host_state)
    flat_sources = ([("params.npz", k) for k, _ in param_leaves]
                    + [("opt.npz", k) for k, _ in opt_leaves]
                    + [("state.npz", k) for k, _ in state_leaves]
                    + [("seed", "seed")]
                    + [("feed", k) for k in feed_names])
    num_carry = len(param_leaves) + len(opt_leaves) + len(state_leaves)
    enforce(len(exported.out_avals) == num_carry + 1,
            "train export must emit carry + loss")
    for (src, name), in_av, out_av in zip(
            flat_sources[:num_carry], exported.in_avals[:num_carry],
            exported.out_avals[:num_carry]):
        enforce(tuple(in_av.shape) == tuple(out_av.shape)
                and in_av.dtype == out_av.dtype,
                f"carry leaf {src}:{name} not shape/dtype-stable across the "
                f"step ({in_av} vs {out_av})")
    # feed .npy files must carry the CANONICALIZED aval dtype (e.g. an
    # int64 label feed traces as int32 with x64 off) or the native
    # driver's dtype check rejects them at staging time
    for k, av in zip(feed_names, exported.in_avals[num_carry + 1:]):
        np.save(os.path.join(dirname, f"feed_{k}.npy"),
                np.asarray(example_feed[k]).astype(av.dtype))
    in_spec = _in_spec(flat_sources, exported)
    with open(os.path.join(dirname, "meta_train.json"), "w") as f:
        json.dump({"feed_names": feed_names, "num_carry": num_carry,
                   "inputs": in_spec}, f)


class Predictor:
    """Loaded inference model (PaddlePredictor analog,
    paddle_inference_api.h:141: Run(inputs)->outputs; Clone is free —
    the executable is stateless and thread-safe).

    The executable is **AOT-compiled once** at construction
    (jit(exported.call).lower(...).compile() from the export's own
    in_avals — the NativePaddlePredictor Init/Prepare split,
    api_impl.cc:64): ``run()`` never re-enters tracing/compilation, it
    only device_puts the feeds and executes."""

    def __init__(self, exported, params, state, feed_names, _compiled=None):
        self._exported = exported
        self._params = jax.device_put(params)
        self._state = jax.device_put(state)
        self.feed_names = feed_names
        if _compiled is None:
            flat = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                    for a in exported.in_avals]
            try:
                args, kwargs = jax.tree.unflatten(exported.in_tree, flat)
                _compiled = jax.jit(exported.call).lower(*args, **kwargs).compile()
            except Exception:
                # fall back to the jit dispatch cache: first run() traces,
                # subsequent calls still skip tracing/compilation
                _compiled = jax.jit(exported.call)
        self._compiled = _compiled

    def run(self, feed: Dict[str, Any]):
        vals = [jnp.asarray(np.asarray(feed[k])) for k in self.feed_names]
        return self._compiled(self._params, self._state, *vals)

    def clone(self) -> "Predictor":
        # share the compiled executable and device-resident weights
        return Predictor(self._exported, self._params, self._state,
                         self.feed_names, _compiled=self._compiled)


def load_inference_model(dirname: str) -> Predictor:
    with open(os.path.join(dirname, "model.stablehlo"), "rb") as f:
        exported = jax.export.deserialize(f.read())
    params, state, _, meta = load_persistables(dirname)
    return Predictor(exported, params, state, meta["feed_names"])


def save_params(dirname: str, params, state=None, opt_state=None):
    """io.py:252 save_params analog — parameters (+state/opt_state when
    given)."""
    save_persistables(dirname, params, state or {}, opt_state)


def save_vars(dirname: str, vars: Dict[str, jax.Array], filename=None):
    """io.py:89 save_vars analog: save an arbitrary name→array dict."""
    save_persistables(dirname, dict(vars), {}, None)


def load_params(dirname: str):
    """io.py load_params analog: returns the parameter dict."""
    return load_persistables(dirname)[0]


def load_vars(dirname: str):
    """io.py:295 load_vars analog."""
    return load_persistables(dirname)[0]


# -- orbax backend: async + sharded checkpointing ----------------------------
# SURVEY §5's stated TPU plan ("orbax-style sharded async checkpoint of a
# pytree"): each host writes only its own array shards (scales to
# multi-host), and async mode overlaps serialization with the next train
# steps — the reference's per-pserver checkpoint block
# (_create_checkpoint_save_block) re-expressed for the SPMD world.


_async_checkpointer: Optional[Any] = None


def _orbax_checkpointer(async_save: bool):
    import orbax.checkpoint as ocp

    global _async_checkpointer
    if async_save:
        if _async_checkpointer is None:
            _async_checkpointer = ocp.AsyncCheckpointer(
                ocp.StandardCheckpointHandler())
        return _async_checkpointer
    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def save_sharded(dirname: str, tree: Dict[str, Any], async_save: bool = False):
    """Save a (possibly sharded) pytree via orbax. With async_save the
    call returns immediately after on-device arrays are snapshotted;
    call wait_for_checkpoints() (or save again) before reading the dir."""
    import orbax.checkpoint  # noqa: F401  (fail loudly if unavailable)

    wait_for_checkpoints()   # an in-flight async save may still own the dir
    path = os.path.abspath(dirname)
    if os.path.exists(path):
        import shutil
        shutil.rmtree(path)
    ckptr = _orbax_checkpointer(async_save)
    ckptr.save(path, tree)
    return ckptr


def load_sharded(dirname: str, target: Optional[Dict[str, Any]] = None):
    """Restore an orbax checkpoint. ``target`` (a pytree of arrays or
    ShapeDtypeStructs, optionally with shardings) directs dtypes/
    placement — pass the current scope to restore directly into the
    live mesh layout (checkpoint-across-mesh-reshape, io.py:881
    _load_slice_up_vars analog)."""
    import orbax.checkpoint as ocp

    wait_for_checkpoints()   # an in-flight async save may still own the dir
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    path = os.path.abspath(dirname)
    if target is None:
        return ckptr.restore(path)
    abstract = jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=getattr(v, "sharding", None))
        if hasattr(v, "shape") else v, target)
    return ckptr.restore(path, args=ocp.args.StandardRestore(abstract))


def wait_for_checkpoints():
    """Block until all async checkpoint writes finished (barrier before
    reading a checkpoint dir or exiting)."""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()


def save_trainer_sharded(dirname: str, trainer, async_save: bool = True):
    """Orbax-backed Trainer checkpoint (async by default): params, state,
    opt_state, step — each host writing its own shards."""
    # logical layer order on disk (matches save_trainer): the device-
    # side de-permute is one gather per stacked leaf per checkpoint —
    # noise next to the write itself
    params, opt_state = trainer.stacked_to_logical(
        trainer.scope.params, trainer.scope.opt_state or {})
    tree = {
        "params": params,
        "state": trainer.scope.state,
        "opt_state": opt_state,
        "meta": {"global_step": trainer.global_step},
    }
    ls = getattr(trainer.scope, "loss_scale_state", None)
    if ls:
        tree["loss_scale_state"] = ls
    return save_sharded(dirname, tree, async_save=async_save)


def load_trainer_sharded(dirname: str, trainer) -> None:
    """Restore from save_trainer_sharded into the trainer's current
    mesh/sharding layout (works across mesh reshapes)."""
    wait_for_checkpoints()
    target = {
        "params": trainer.scope.params,
        "state": trainer.scope.state,
        "opt_state": trainer.scope.opt_state or {},
        "meta": {"global_step": 0},
    }
    # key the optional loss-scaler entry off the CHECKPOINT's contents —
    # a structure mismatch with the target makes orbax raise
    import orbax.checkpoint as ocp
    meta_tree = ocp.Checkpointer(ocp.StandardCheckpointHandler()).metadata(
        os.path.abspath(dirname))
    saved_keys = set(getattr(meta_tree, "item_metadata", meta_tree) or {})
    if "loss_scale_state" in saved_keys:
        ls = getattr(trainer.scope, "loss_scale_state", None)
        target["loss_scale_state"] = ls or {"scale": jnp.float32(0),
                                            "good_steps": jnp.int32(0),
                                            "overflows": jnp.int32(0)}
    restored = load_sharded(dirname, target=target)
    params, opt_state = trainer.stacked_from_logical(
        restored["params"], restored["opt_state"])
    trainer.scope.params = params
    trainer.scope.state = restored["state"]
    trainer.scope.opt_state = opt_state or None
    trainer.global_step = int(restored["meta"]["global_step"])
    # only adopt scaler state if this trainer actually runs a scaler —
    # step() donates the buffer and only a scaler refreshes it, so a
    # scaler-less trainer holding it would pass deleted arrays on step 2
    if "loss_scale_state" in restored and trainer.loss_scaler is not None:
        trainer.scope.loss_scale_state = restored["loss_scale_state"]
