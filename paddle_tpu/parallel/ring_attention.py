"""Ring attention — sequence/context parallelism over the mesh ICI.

Gap-fill component (SURVEY §2.2/§5): the reference has NO sequence
parallelism — nothing distributes a single sequence. Here, attention
over a sequence sharded on the mesh's ``sp`` axis: each device holds a
query/key/value shard, K/V shards rotate around the ring via
``ppermute`` (neighbor ICI hops), and per-shard results merge in
log-space from the flash kernel's (out, lse) pairs.

Each ring step runs the pallas flash kernel (ops/flash_attention) on
the local Q shard against the visiting K/V shard, so per-chip memory is
O(S/n · d) for the shard buffers plus O(block²) inside the kernel —
never an S/n × S/n score matrix. The backward is a second ring pass
reusing the flash backward kernels with the COMBINED logsumexp
(flash-attention-2 style): dq accumulates locally, dk/dv accumulate on
buffers that travel with their K/V shard and arrive home after the full
cycle. Differentiable end-to-end via a custom VJP.

Causal ring schedule: the visiting shard is fully visible (earlier
ranks), causally visible (own rank), or invisible (later ranks) —
selected with lax.switch so invisible steps do no FLOPs. (Known load
imbalance: rank r does r+1 real steps; a zigzag block order would even
it out — future work.)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import flash_attention as fa
from .mesh import pvary

NEG_INF = -1e30


def _merge(acc, lse_c, out_i, lse_i):
    """Log-space merge of per-shard flash results."""
    lse_new = jnp.logaddexp(lse_c, lse_i)
    w_old = jnp.exp(lse_c - lse_new)[..., None]
    w_new = jnp.exp(lse_i - lse_new)[..., None]
    return acc * w_old + out_i.astype(jnp.float32) * w_new, lse_new


def _ring_fwd_body(q, k0, v0, *, axis_name, causal, varying_axes,
                   block_q, block_k):
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def full_step(k_cur, v_cur):
        return fa.flash_attention(q, k_cur, v_cur, causal=False,
                                  block_q=block_q, block_k=block_k,
                                  return_lse=True)

    def diag_step(k_cur, v_cur):
        return fa.flash_attention(q, k_cur, v_cur, causal=True,
                                  block_q=block_q, block_k=block_k,
                                  return_lse=True)

    def masked_step(k_cur, v_cur):
        return (jnp.zeros_like(q), jnp.full((b, h, sl), NEG_INF, jnp.float32))

    def step(carry, i):
        k_cur, v_cur, acc, lse_c = carry
        if causal:
            src = (idx - i) % n
            branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
            out_i, lse_i = jax.lax.switch(
                branch, [full_step, diag_step, masked_step], k_cur, v_cur)
        else:
            out_i, lse_i = full_step(k_cur, v_cur)
        acc, lse_c = _merge(acc, lse_c, out_i, lse_i)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, lse_c), None

    vaxes = tuple(varying_axes) or (axis_name,)
    acc0 = pvary(jnp.zeros((b, h, sl, d), jnp.float32), vaxes)
    lse0 = pvary(jnp.full((b, h, sl), NEG_INF, jnp.float32), vaxes)
    (_, _, acc, lse), _ = jax.lax.scan(step, (k0, v0, acc0, lse0), jnp.arange(n))
    return acc.astype(q.dtype), lse


def _ring_bwd_body(q, k0, v0, out, lse, g, *, axis_name, causal,
                   varying_axes, block_q, block_k):
    """Second ring pass: flash backward kernels with the combined lse.
    dk/dv ride with their shard and come home after n rotations."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    # delta is k/v-shard-invariant: compute once, not per ring step
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)

    def grads(k_cur, v_cur, caus):
        return fa._flash_bwd(q, k_cur, v_cur, None, None, None, caus,
                             out, lse, g, block_q, block_k,
                             interpret=jax.devices()[0].platform == "cpu",
                             delta=delta)

    def full_step(k_cur, v_cur):
        return grads(k_cur, v_cur, False)

    def diag_step(k_cur, v_cur):
        return grads(k_cur, v_cur, True)

    def masked_step(k_cur, v_cur):
        return (jnp.zeros_like(q), jnp.zeros_like(k_cur), jnp.zeros_like(v_cur))

    def step(carry, i):
        k_cur, v_cur, dk_cur, dv_cur, dq_acc = carry
        if causal:
            src = (idx - i) % n
            branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
            dq_i, dk_i, dv_i = jax.lax.switch(
                branch, [full_step, diag_step, masked_step], k_cur, v_cur)
        else:
            dq_i, dk_i, dv_i = full_step(k_cur, v_cur)
        dq_acc = dq_acc + dq_i.astype(jnp.float32)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq_acc), None

    vaxes = tuple(varying_axes) or (axis_name,)
    dk0 = pvary(jnp.zeros(k0.shape, jnp.float32), vaxes)
    dv0 = pvary(jnp.zeros(v0.shape, jnp.float32), vaxes)
    dq0 = pvary(jnp.zeros(q.shape, jnp.float32), vaxes)
    (_, _, dk, dv, dq), _ = jax.lax.scan(
        step, (k0, v0, dk0, dv0, dq0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k0.dtype), dv.astype(v0.dtype)


def _make_ring(axis_name, causal, varying_axes, block_q, block_k):
    @jax.custom_vjp
    def ring(q, k, v):
        out, _ = _ring_fwd_body(q, k, v, axis_name=axis_name, causal=causal,
                                varying_axes=varying_axes, block_q=block_q,
                                block_k=block_k)
        return out

    def ring_fwd(q, k, v):
        out, lse = _ring_fwd_body(q, k, v, axis_name=axis_name, causal=causal,
                                  varying_axes=varying_axes, block_q=block_q,
                                  block_k=block_k)
        return out, (q, k, v, out, lse)

    def ring_bwd(res, g):
        q, k, v, out, lse = res
        return _ring_bwd_body(q, k, v, out, lse, g, axis_name=axis_name,
                              causal=causal, varying_axes=varying_axes,
                              block_q=block_q, block_k=block_k)

    ring.defvjp(ring_fwd, ring_bwd)
    return ring


def ring_attention(
    q, k, v,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = False,
    batch_axes: Optional[tuple] = ("dp", "fsdp"),
    block_q: int = fa.DEFAULT_BLOCK_Q,
    block_k: int = fa.DEFAULT_BLOCK_K,
):
    """Attention over [b, h, s, d] with s sharded on ``axis_name``.

    Batch may additionally be sharded over ``batch_axes``; heads stay
    unsharded here (combine with TP by sharding h outside via shard_map
    composition)."""
    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        # degenerate ring: single-shard flash attention
        return fa.flash_attention(q, k, v, causal=causal,
                                  block_q=block_q, block_k=block_k)

    bspec = tuple(a for a in (batch_axes or ()) if a in mesh.axis_names)
    bshard = bspec if len(bspec) > 1 else (bspec[0] if bspec else None)
    spec = P(bshard, None, axis_name, None)

    body = _make_ring(axis_name, causal, tuple(mesh.axis_names), block_q, block_k)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
