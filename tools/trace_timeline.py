#!/usr/bin/env python
"""Offline cross-process trace timeline: render one span's waterfall
from journal JSONL — the same assembly the collector's
``/timeline?trace=<span>`` endpoint serves, usable post-mortem on a
flight dump's ``events.jsonl`` or any ``PDTPU_JOURNAL_PATH`` sink.

    python tools/trace_timeline.py events.jsonl --span 39390ddf00000001
    python tools/trace_timeline.py dump/events.jsonl other.jsonl --list
    python tools/trace_timeline.py events.jsonl --span ID --json

Multiple files merge into one event set (a trainer's sink + a shipped
replica ring dump side by side); events keep whatever ``origin`` field
ingestion stamped, defaulting to the file's basename so two processes'
sinks stay distinguishable. ``--list`` prints the spans present (event
count, origins, duration) newest-first instead of rendering one.

Exit status: **0** rendered (or listed); **2** the span has no events
/ no readable input; **3** the tool itself crashed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

EXIT_CLEAN, EXIT_EMPTY, EXIT_INTERNAL = 0, 2, 3


def _load_events(paths):
    events, bad = [], 0
    for path in paths:
        tag = os.path.basename(path).rsplit(".", 1)[0]
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        bad += 1
                        continue
                    if isinstance(e, dict) and "kind" in e:
                        e.setdefault("origin", tag)
                        events.append(e)
        except OSError as err:
            print(f"trace_timeline: cannot read {path}: {err}",
                  file=sys.stderr)
    return events, bad


def _list_spans(events):
    by_span = {}
    for e in events:
        span = e.get("span")
        if span is None:
            continue
        d = by_span.setdefault(span, {"n": 0, "origins": set(),
                                      "t0": None, "t1": None})
        d["n"] += 1
        d["origins"].add(e.get("origin", "local"))
        t = e.get("t")
        if t is not None:
            d["t0"] = t if d["t0"] is None else min(d["t0"], t)
            d["t1"] = t if d["t1"] is None else max(d["t1"], t)
    rows = sorted(by_span.items(), key=lambda kv: kv[1]["t1"] or 0,
                  reverse=True)
    for span, d in rows:
        dur = ((d["t1"] - d["t0"]) * 1e3
               if d["t0"] is not None and d["t1"] is not None else 0.0)
        print(f"{span}  {d['n']:4d} event(s)  {dur:9.3f} ms  "
              f"origins={','.join(sorted(d['origins']))}")
    print(f"{len(rows)} span(s) across {len(events)} event(s)")
    return bool(rows)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/trace_timeline.py",
        description="render one trace span's cross-process waterfall "
                    "from journal JSONL")
    ap.add_argument("files", nargs="+", help="journal JSONL file(s) "
                    "(flight-dump events.jsonl, PDTPU_JOURNAL_PATH sinks)")
    ap.add_argument("--span", default="", help="trace id to render")
    ap.add_argument("--list", action="store_true",
                    help="list spans present instead of rendering one")
    ap.add_argument("--json", action="store_true",
                    help="emit the assembled timeline as JSON")
    ap.add_argument("--width", type=int, default=40,
                    help="waterfall bar width (text mode)")
    args = ap.parse_args(argv)

    try:
        from paddle_tpu.telemetry.collector import (assemble_timeline,
                                                    render_timeline_text)

        events, bad = _load_events(args.files)
        if bad:
            print(f"trace_timeline: skipped {bad} unparseable line(s)",
                  file=sys.stderr)
        if not events:
            print("trace_timeline: no journal events found",
                  file=sys.stderr)
            return EXIT_EMPTY
        if args.list:
            return EXIT_CLEAN if _list_spans(events) else EXIT_EMPTY
        if not args.span:
            ap.error("pass --span <id> (or --list to see what exists)")
        tl = assemble_timeline(events, args.span)
        if not tl["events"]:
            print(f"trace_timeline: no events carry span {args.span!r}",
                  file=sys.stderr)
            return EXIT_EMPTY
        if args.json:
            print(json.dumps(tl, sort_keys=True, default=repr, indent=1))
        else:
            sys.stdout.write(render_timeline_text(tl, width=args.width))
        return EXIT_CLEAN
    except SystemExit:
        raise
    except Exception:
        traceback.print_exc()
        print("trace_timeline: internal error (exit 3)", file=sys.stderr)
        return EXIT_INTERNAL


if __name__ == "__main__":
    sys.exit(main())
