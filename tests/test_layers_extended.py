"""Tests for the layer-parity batch: vision/misc ops, sequence conv
family, RNN units, RoI/RPN detection family, control-flow classes,
layers.io surface — each against a numpy brute-force reference
(SURVEY §4 op_test pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu import metrics as M

from test_layers import run_layer


# ---------------------------------------------------------------------------
# misc nn ops
# ---------------------------------------------------------------------------


def test_affine_channel():
    x = np.random.randn(2, 3, 4, 5).astype(np.float32)
    s = np.random.randn(3).astype(np.float32)
    b = np.random.randn(3).astype(np.float32)
    out = L.affine_channel(jnp.asarray(x), jnp.asarray(s), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), x * s[None, :, None, None] + b[None, :, None, None], rtol=1e-6)


def test_affine_grid_identity_sampling():
    # identity theta -> grid_sampler reproduces the input
    x = np.random.randn(2, 3, 5, 7).astype(np.float32)
    theta = np.tile(np.array([[1.0, 0, 0], [0, 1.0, 0]], np.float32), (2, 1, 1))
    grid = L.affine_grid(jnp.asarray(theta), (2, 3, 5, 7))
    out = L.grid_sampler(jnp.asarray(x), grid)
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5, atol=1e-5)


def test_crop():
    x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    out = L.crop(jnp.asarray(x), shape=(1, 2, 2), offsets=(1, 0, 1))
    np.testing.assert_allclose(np.asarray(out), x[1:2, 0:2, 1:3])


def test_random_crop_shape_and_content():
    x = np.arange(100).reshape(1, 10, 10).astype(np.float32)
    out = np.asarray(L.random_crop(jnp.asarray(x), (4, 4), seed=3))
    assert out.shape == (1, 4, 4)
    # rows must be contiguous slices of the original
    flat = set(x.reshape(-1).tolist())
    assert set(out.reshape(-1).tolist()) <= flat


def test_dice_loss_matches_numpy():
    probs = np.random.rand(4, 3).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    label = np.random.randint(0, 3, (4, 1))
    out = float(L.dice_loss(jnp.asarray(probs), jnp.asarray(label), epsilon=1e-5))
    oh = np.eye(3, dtype=np.float32)[label[:, 0]]
    inse = (probs * oh).sum(1)
    ref = np.mean(1 - 2 * inse / ((probs.sum(1) + oh.sum(1)) + 1e-5))
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_mean_iou():
    pred = np.array([0, 1, 1, 2, 2, 2])
    lab = np.array([0, 1, 2, 2, 2, 1])
    miou, wrong, correct = L.mean_iou(jnp.asarray(pred), jnp.asarray(lab), 3)
    # class0: i=1 u=1; class1: i=1 u=3; class2: i=2 u=4
    np.testing.assert_allclose(float(miou), (1 + 1 / 3 + 0.5) / 3, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(correct), [1, 1, 2])


def test_hash_deterministic_in_range():
    ids = np.random.randint(0, 1000, (6, 3)).astype(np.int64)
    h1 = np.asarray(L.hash(jnp.asarray(ids), hash_size=97, num_hash=4))
    h2 = np.asarray(L.hash(jnp.asarray(ids), hash_size=97, num_hash=4))
    assert h1.shape == (6, 4)
    np.testing.assert_array_equal(h1, h2)
    assert h1.min() >= 0 and h1.max() < 97
    # different seeds give different hashes somewhere
    assert (h1[:, 0] != h1[:, 1]).any()


def test_add_position_encoding():
    x = np.zeros((1, 4, 6), np.float32)
    out = np.asarray(L.add_position_encoding(jnp.asarray(x), alpha=1.0, beta=1.0))
    # position 0: sin(0)=0, cos(0)=1
    np.testing.assert_allclose(out[0, 0, :3], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[0, 0, 3:], 1.0, atol=1e-6)


def test_multiplex():
    a = np.random.randn(4, 3).astype(np.float32)
    b = np.random.randn(4, 3).astype(np.float32)
    idx = np.array([[0], [1], [1], [0]])
    out = np.asarray(L.multiplex([jnp.asarray(a), jnp.asarray(b)], jnp.asarray(idx)))
    ref = np.stack([a[0], b[1], b[2], a[3]])
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_pool3d_max_and_avg():
    x = np.random.randn(1, 2, 4, 4, 4).astype(np.float32)
    out = np.asarray(L.pool3d(jnp.asarray(x), pool_size=2, pool_type="max", pool_stride=2))
    ref = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max((3, 5, 7))
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    out_a = np.asarray(L.pool3d(jnp.asarray(x), pool_size=2, pool_type="avg", pool_stride=2))
    ref_a = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean((3, 5, 7))
    np.testing.assert_allclose(out_a, ref_a, rtol=1e-5)


def test_conv3d_transpose_shape_and_grad():
    x = np.random.randn(1, 2, 3, 3, 3).astype(np.float32)
    out, params = run_layer(L.conv3d_transpose, x, num_filters=4, filter_size=2, stride=2)
    assert out.shape == (1, 4, 6, 6, 6)


def test_im2sequence():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    vals, lengths = L.im2sequence(jnp.asarray(x), filter_size=2, stride=2)
    assert vals.shape == (4, 4)
    np.testing.assert_array_equal(np.asarray(lengths), [4])
    np.testing.assert_allclose(np.asarray(vals)[0], [0, 1, 4, 5])


def test_row_conv_matches_numpy():
    b, t, d, k = 2, 5, 3, 2
    x = np.random.randn(b, t, d).astype(np.float32)
    lengths = np.array([5, 3])
    out, params = run_layer(L.row_conv, x, future_context_size=k,
                            lengths=jnp.asarray(lengths))
    w = np.asarray(params["row_conv_0/w"])
    ref = np.zeros_like(x)
    xm = x.copy()
    xm[1, 3:] = 0
    for bb in range(b):
        for tt in range(t):
            for i in range(k + 1):
                if tt + i < t:
                    ref[bb, tt] += xm[bb, tt + i] * w[i]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_image_resize_short():
    x = np.random.randn(1, 3, 8, 16).astype(np.float32)
    out = L.image_resize_short(jnp.asarray(x), 4)
    assert out.shape == (1, 3, 4, 8)


def test_gaussian_random_batch_size_like():
    x = np.zeros((7, 2), np.float32)
    prog = pt.build(lambda a: L.gaussian_random_batch_size_like(a, [-1, 5]))
    params, state = prog.init(jax.random.PRNGKey(0), x)
    out, _ = prog.apply(params, state, x, rng=jax.random.PRNGKey(1))
    assert out.shape == (7, 5)


# ---------------------------------------------------------------------------
# sequence family
# ---------------------------------------------------------------------------


def test_sequence_conv_matches_bruteforce():
    # two sequences of lengths 3 and 2 packed into 5 rows
    vals = np.random.randn(5, 4).astype(np.float32)
    seg = np.array([0, 0, 0, 1, 1], np.int32)
    out, params = run_layer(
        lambda v: L.sequence_conv(v, jnp.asarray(seg), num_filters=6, filter_size=3,
                                  bias_attr=False), vals)
    w = np.asarray(params["sequence_conv_0/w"])  # [3*4, 6]
    ref = np.zeros((5, 6), np.float32)
    seqs = [(0, 3), (3, 5)]
    for start, end in seqs:
        for t in range(start, end):
            ctx = []
            for off in (-1, 0, 1):
                s = t + off
                ctx.append(vals[s] if start <= s < end else np.zeros(4, np.float32))
            ref[t] = np.concatenate(ctx) @ w
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_sequence_expand_as_and_reshape_and_scatter():
    x = np.array([[1.0], [2.0]], np.float32)
    out = L.sequence_expand_as(jnp.asarray(x), jnp.asarray([2, 3]), 5)
    np.testing.assert_allclose(np.asarray(out)[:, 0], [1, 1, 2, 2, 2])

    vals = np.arange(12, dtype=np.float32).reshape(3, 4)
    out2, lens2 = L.sequence_reshape(jnp.asarray(vals), jnp.asarray([1, 2]), 2)
    assert out2.shape == (6, 2)
    np.testing.assert_array_equal(np.asarray(lens2), [2, 4])

    x3 = np.zeros((2, 5), np.float32)
    ids = np.array([0, 2, 1], np.int32)
    seg = np.array([0, 0, 1], np.int32)
    upd = np.array([1.0, 2.0, 3.0], np.float32)
    out3 = L.sequence_scatter(jnp.asarray(x3), ids, seg, jnp.asarray(upd))
    ref3 = np.zeros((2, 5), np.float32)
    ref3[0, 0], ref3[0, 2], ref3[1, 1] = 1, 2, 3
    np.testing.assert_allclose(np.asarray(out3), ref3)


def test_lod_reset_and_reorder_by_rank():
    x = np.random.randn(6, 2).astype(np.float32)
    _, seg = L.lod_reset(jnp.asarray(x), [2, 4])
    np.testing.assert_array_equal(np.asarray(seg), [0, 0, 1, 1, 1, 1])

    padded = np.random.randn(3, 4, 2).astype(np.float32)
    lengths = np.array([2, 4, 3])
    p2, l2, perm = L.reorder_lod_tensor_by_rank(jnp.asarray(padded), jnp.asarray(lengths))
    np.testing.assert_array_equal(np.asarray(l2), [4, 3, 2])
    np.testing.assert_allclose(np.asarray(p2[0]), padded[1])
    inv = np.argsort(np.asarray(perm))
    np.testing.assert_allclose(np.asarray(p2)[inv], padded)


# ---------------------------------------------------------------------------
# rnn units
# ---------------------------------------------------------------------------


def test_lstm_unit_and_gru_unit():
    x = np.random.randn(3, 4).astype(np.float32)
    h = np.random.randn(3, 5).astype(np.float32)
    c = np.random.randn(3, 5).astype(np.float32)
    prog = pt.build(lambda a, hh, cc: L.lstm_unit(a, hh, cc))
    params, state = prog.init(jax.random.PRNGKey(0), x, h, c)
    (h2, c2), _ = prog.apply(params, state, x, h, c)
    assert h2.shape == (3, 5) and c2.shape == (3, 5)
    assert np.isfinite(np.asarray(h2)).all()

    xg = np.random.randn(3, 15).astype(np.float32)  # gru_unit takes projected input 3*dim
    hg = np.random.randn(3, 5).astype(np.float32)
    prog2 = pt.build(lambda a, hh: L.gru_unit(a, hh, 15))
    params2, state2 = prog2.init(jax.random.PRNGKey(0), xg, hg)
    (nh, rhp, gate), _ = prog2.apply(params2, state2, xg, hg)
    assert nh.shape == (3, 5) and rhp.shape == (3, 5) and gate.shape == (3, 15)


def test_dynamic_lstmp_shapes_and_masking():
    x = np.random.randn(2, 6, 3).astype(np.float32)
    lengths = np.array([6, 4])
    prog = pt.build(lambda a: L.dynamic_lstmp(a, size=8, proj_size=4,
                                              sequence_length=jnp.asarray(lengths)))
    params, state = prog.init(jax.random.PRNGKey(0), x)
    (outs, (r_last, c_last)), _ = prog.apply(params, state, x)
    assert outs.shape == (2, 6, 4)
    # state frozen past sequence end for row 1
    np.testing.assert_allclose(np.asarray(outs[1, 3]), np.asarray(r_last[1]), rtol=1e-5)


# ---------------------------------------------------------------------------
# tensor / lr helpers
# ---------------------------------------------------------------------------


def test_create_global_var_and_step_counter():
    def f(x):
        g = L.create_global_var([1], 3.0)
        step = L.autoincreased_step_counter()
        return x + g, step

    prog = pt.build(f)
    x = np.zeros((1,), np.float32)
    params, state = prog.init(jax.random.PRNGKey(0), x)
    (out, step), new_state = prog.apply(params, state, x)
    assert float(out[0]) == 3.0
    assert int(step[0]) == 1
    (out2, step2), new_state2 = prog.apply(params, new_state, x)
    assert int(step2[0]) == 2


def test_sums():
    xs = [np.random.randn(3).astype(np.float32) for _ in range(3)]
    out = L.sums([jnp.asarray(x) for x in xs])
    np.testing.assert_allclose(np.asarray(out), sum(xs), rtol=1e-6)


def test_append_LARS():
    from paddle_tpu import lr_scheduler as lrs
    p = jnp.ones((4,)) * 2.0
    g = jnp.ones((4,)) * 0.5
    (lr,) = lrs.append_LARS([(p, g)], 0.1, weight_decay=0.0)
    np.testing.assert_allclose(float(lr), 0.1 * 4.0 / 1.0, rtol=1e-5)


def test_auc_layer_streams_state():
    preds = np.array([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7], [0.6, 0.4]], np.float32)
    labels = np.array([0, 1, 1, 0])

    prog = pt.build(lambda p, l: M.auc(p, l, num_thresholds=200))
    params, state = prog.init(jax.random.PRNGKey(0), preds, labels)
    (auc_v, batch_auc), new_state = prog.apply(params, state, preds, labels)
    # perfectly separable -> AUC 1.0 (endpoint-anchored sweep is exact here)
    np.testing.assert_allclose(float(auc_v), 1.0, atol=1e-5)
    # feed a second, inverted batch: accumulated auc drops, state advanced
    (auc_v2, _), _ = prog.apply(params, new_state, preds, 1 - labels)
    assert float(auc_v2) < 0.8


# ---------------------------------------------------------------------------
# beam_search_decode
# ---------------------------------------------------------------------------


def test_beam_search_decode_backtracks():
    # T=3, B=1, K=2.  parents[t][k] = lane at t-1 that token (t,k) extended.
    # lane0 path: 9 <- lane1@t1 (8) <- lane0@t0 (5)
    ids = np.array([[[5, 6]], [[7, 8]], [[9, 4]]], np.int32)      # [T,1,2]
    parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], np.int32)
    seqs, valid = L.beam_search_decode(ids, parents, end_id=8)
    seqs, valid = np.asarray(seqs), np.asarray(valid)
    assert seqs.shape == (1, 2, 3)
    np.testing.assert_array_equal(seqs[0, 0], [5, 8, 9])  # backtracked through lane1
    np.testing.assert_array_equal(seqs[0, 1], [5, 7, 4])
    # valid covers tokens up to and including the first end_id
    np.testing.assert_array_equal(valid[0, 0], [True, True, False])
    np.testing.assert_array_equal(valid[0, 1], [True, True, True])


# ---------------------------------------------------------------------------
# detection: RoI / RPN family
# ---------------------------------------------------------------------------


def test_roi_pool_bruteforce():
    x = np.random.randn(1, 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 3, 3], [2, 2, 7, 7]], np.float32)
    bidx = np.array([0, 0])
    out = np.asarray(L.roi_pool(jnp.asarray(x), jnp.asarray(rois), jnp.asarray(bidx),
                                pooled_height=2, pooled_width=2, spatial_scale=1.0))
    assert out.shape == (2, 2, 2, 2)
    # roi0 spans rows/cols 0..3 -> bins are 2x2 blocks
    ref00 = x[0, :, 0:2, 0:2].max((1, 2))
    np.testing.assert_allclose(out[0, :, 0, 0], ref00, rtol=1e-5)
    ref11 = x[0, :, 2:4, 2:4].max((1, 2))
    np.testing.assert_allclose(out[0, :, 1, 1], ref11, rtol=1e-5)


def test_roi_align_constant_map():
    x = np.full((1, 3, 6, 6), 2.5, np.float32)
    rois = np.array([[1.0, 1.0, 4.0, 4.0]], np.float32)
    out = np.asarray(L.roi_align(jnp.asarray(x), jnp.asarray(rois), jnp.asarray([0]),
                                 pooled_height=2, pooled_width=2))
    np.testing.assert_allclose(out, 2.5, rtol=1e-5)


def test_anchor_generator():
    x = np.zeros((1, 8, 4, 6), np.float32)
    anchors, variances = L.anchor_generator(jnp.asarray(x), anchor_sizes=[64, 128],
                                            aspect_ratios=[0.5, 1.0], stride=[16, 16])
    assert anchors.shape == (4, 6, 4, 4)
    assert variances.shape == (4, 6, 4, 4)
    a = np.asarray(anchors)
    # centers advance by stride along w
    np.testing.assert_allclose(a[0, 1, 0, 0] - a[0, 0, 0, 0], 16.0, rtol=1e-5)
    # aspect 1.0 anchors are square
    widths = a[..., 2] - a[..., 0]
    heights = a[..., 3] - a[..., 1]
    np.testing.assert_allclose(widths[0, 0, 2:], heights[0, 0, 2:], rtol=1e-4)


def test_generate_proposals():
    np.random.seed(1)
    h = w = 4
    a = 2
    scores = np.random.rand(1, a, h, w).astype(np.float32)
    deltas = (np.random.randn(1, 4 * a, h, w) * 0.1).astype(np.float32)
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    x = np.zeros((1, 8, h, w), np.float32)
    anchors, variances = L.anchor_generator(jnp.asarray(x), anchor_sizes=[16, 32],
                                            aspect_ratios=[1.0], stride=[16, 16])
    rois, probs, valid = L.generate_proposals(
        jnp.asarray(scores), jnp.asarray(deltas), jnp.asarray(im_info),
        anchors, variances, pre_nms_top_n=20, post_nms_top_n=5, nms_thresh=0.7)
    assert rois.shape == (1, 5, 4)
    r = np.asarray(rois)[np.asarray(valid)]
    assert (r[:, 0] >= 0).all() and (r[:, 2] <= 63).all()
    assert (r[:, 2] >= r[:, 0]).all() and (r[:, 3] >= r[:, 1]).all()


def test_rpn_target_assign_caps_and_labels():
    x = np.zeros((1, 8, 4, 4), np.float32)
    anchors, _ = L.anchor_generator(jnp.asarray(x), anchor_sizes=[32],
                                    aspect_ratios=[1.0], stride=[16, 16])
    anchors = anchors.reshape(-1, 4)
    gt = np.array([[[8.0, 8.0, 40.0, 40.0]]], np.float32)
    gtv = np.array([[True]])
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    labels, tgt, fg, bg = L.rpn_target_assign(
        anchors, jnp.asarray(gt), jnp.asarray(gtv), jnp.asarray(im_info),
        rpn_batch_size_per_im=8, rng_key=jax.random.PRNGKey(0))
    labels = np.asarray(labels)[0]
    assert (np.asarray(fg)[0].sum() + np.asarray(bg)[0].sum()) <= 8
    assert (labels == 1).sum() >= 1  # best anchor for the gt is fg
    assert set(np.unique(labels)) <= {-1, 0, 1}


def test_generate_proposal_labels():
    rois = np.array([[[8, 8, 40, 40], [0, 0, 10, 10], [50, 50, 60, 60]]], np.float32)
    rv = np.array([[True, True, True]])
    gcls = np.array([[3]], np.int32)
    gbox = np.array([[[10, 10, 38, 38]]], np.float32)
    gv = np.array([[True]])
    labels, tgt, fg, sampled = L.generate_proposal_labels(
        jnp.asarray(rois), jnp.asarray(rv), jnp.asarray(gcls), jnp.asarray(gbox),
        jnp.asarray(gv), batch_size_per_im=3, fg_fraction=0.5,
        rng_key=jax.random.PRNGKey(0))
    labels = np.asarray(labels)[0]
    assert labels[0] == 3          # high-IoU roi gets the gt class
    assert (labels[1:] <= 0).all()  # others are bg or unsampled


def test_target_assign():
    x = np.random.randn(2, 3, 4).astype(np.float32)
    mi = np.array([[0, -1], [2, 1]], np.int32)
    out, wt = L.target_assign(jnp.asarray(x), jnp.asarray(mi), mismatch_value=9.0)
    np.testing.assert_allclose(np.asarray(out[0, 0]), x[0, 0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out[0, 1]), 9.0)
    np.testing.assert_allclose(np.asarray(out[1, 0]), x[1, 2], rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(wt[:, :, 0]), [[1, 0], [1, 1]])


def test_polygon_box_transform():
    x = np.random.randn(1, 4, 3, 5).astype(np.float32)
    out = np.asarray(L.polygon_box_transform(jnp.asarray(x)))
    wi, hi = np.meshgrid(np.arange(5), np.arange(3))
    for g in range(4):
        ref = (4.0 * wi - x[0, g]) if g % 2 == 0 else (4.0 * hi - x[0, g])
        np.testing.assert_allclose(out[0, g], ref, rtol=1e-5)


def test_roi_perspective_transform_axis_aligned():
    # an axis-aligned quad == plain resize-crop of that rect
    x = np.random.randn(1, 1, 8, 8).astype(np.float32)
    quad = np.array([[1, 1, 4, 1, 4, 4, 1, 4]], np.float32)  # corners cw
    out = np.asarray(L.roi_perspective_transform(
        jnp.asarray(x), jnp.asarray(quad), jnp.asarray([0]), 4, 4))
    assert out.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(out[0, 0, 0, 0], x[0, 0, 1, 1], rtol=1e-4)
    np.testing.assert_allclose(out[0, 0, 3, 3], x[0, 0, 4, 4], rtol=1e-4)


def test_detection_output():
    priors = np.array([[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]], np.float32)
    pvar = np.full((2, 4), 0.1, np.float32)
    loc = np.zeros((1, 2, 4), np.float32)
    scores = np.array([[[0.1, 0.9], [0.8, 0.2]]], np.float32)
    out, valid = L.detection_output(jnp.asarray(loc), jnp.asarray(scores),
                                    jnp.asarray(priors), jnp.asarray(pvar),
                                    keep_top_k=3)
    out = np.asarray(out)
    valid = np.asarray(valid)
    # both class-1 detections survive (background suppressed)
    assert valid[0].sum() == 2
    best = out[0, 0]
    assert best[0] == 1.0  # class label
    np.testing.assert_allclose(best[1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(best[2:], priors[0], atol=1e-4)


def test_multi_box_head_shapes():
    f1 = np.random.randn(2, 8, 4, 4).astype(np.float32)
    f2 = np.random.randn(2, 8, 2, 2).astype(np.float32)
    img = np.zeros((2, 3, 64, 64), np.float32)

    prog = pt.build(lambda a, b, im: L.detection.multi_box_head(
        [a, b], im, base_size=64, num_classes=4,
        aspect_ratios=[[2.0], [2.0]], min_sizes=[10.0, 30.0], max_sizes=[20.0, 60.0]))
    params, state = prog.init(jax.random.PRNGKey(0), f1, f2, img)
    (locs, confs, boxes, variances), _ = prog.apply(params, state, f1, f2, img)
    total = boxes.shape[0]
    assert locs.shape == (2, total, 4)
    assert confs.shape == (2, total, 4)
    assert variances.shape == (total, 4)


def test_detection_map_function():
    dets = [[(0, 0.9, 0, 0, 10, 10)]]
    gt_label = [[0]]
    gt_box = [[(0, 0, 10, 10)]]
    mAP = L.detection_map(dets, gt_label, gt_box, class_num=1)
    np.testing.assert_allclose(mAP, 1.0, rtol=1e-6)


# ---------------------------------------------------------------------------
# control-flow classes + io surface
# ---------------------------------------------------------------------------


def test_while_class():
    out = L.While(lambda v: v[0] < 5)(lambda v: (v[0] + 1, v[1] * 2.0), (0, 1.0))
    assert out[0] == 5 and float(out[1]) == 32.0


def test_ifelse_rowwise():
    x = np.array([[1.0], [2.0], [3.0]], np.float32)
    cond = np.array([True, False, True])
    out = L.IfElse(cond)(lambda a: a * 10, lambda a: a - 1, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out)[:, 0], [10, 1, 30])


def test_switch_class():
    lr = L.Switch().case(jnp.asarray(False), lambda: jnp.float32(0.1)) \
                   .case(jnp.asarray(True), lambda: jnp.float32(0.2)) \
                   .default(lambda: jnp.float32(0.3))()
    np.testing.assert_allclose(float(lr), 0.2)


def test_static_and_dynamic_rnn_classes():
    x = np.random.randn(2, 4, 3).astype(np.float32)

    def cell(state, x_t):
        new = state + x_t.sum(-1)
        return new, new

    outs, last = L.StaticRNN()(cell, jnp.asarray(x), jnp.zeros((2,)))
    assert outs.shape == (2, 4)
    np.testing.assert_allclose(np.asarray(last), x.sum((1, 2)), rtol=1e-5)

    outs2, last2 = L.DynamicRNN()(cell, jnp.asarray(x), jnp.zeros((2,)),
                                  sequence_length=jnp.asarray([4, 2]))
    np.testing.assert_allclose(np.asarray(last2)[1], x[1, :2].sum(), rtol=1e-5)


def test_layers_io_surface():
    def reader():
        for i in range(10):
            yield (np.full((2,), i, np.float32),)

    b = L.batch(reader, 4)
    batches = list(b())
    assert len(batches) == 3 and len(batches[0]) == 4

    s = L.shuffle(reader, buffer_size=10)
    assert len(list(s())) == 10

    first = L.read_file(reader)
    np.testing.assert_allclose(first[0], 0.0)

    r = L.random_data_generator(0.0, 1.0, shapes=[(2, 3)])
    sample = L.read_file(r)
    assert sample[0].shape == (2, 3)

    pre = L.Preprocessor(reader)(lambda t: (t[0] * 2,))
    np.testing.assert_allclose(L.read_file(pre)[0], 0.0)

    pr = L.py_reader(capacity=4, shapes=[(2,)], dtypes=["float32"],
                     use_double_buffer=False)
    pr.decorate_paddle_reader(reader)
    got = list(pr.start())
    assert len(got) == 10

    ph = L.data("x", shape=[3, 4], dtype="float32")
    assert tuple(ph.shape) == (1, 3, 4)


# ---------------------------------------------------------------------------
# FD grad checks for new ops (op_test.py check_grad pattern)
# ---------------------------------------------------------------------------

from op_test import check_grad


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_grad_roi_align():
    x = np.random.randn(1, 2, 6, 6).astype(np.float32)
    rois = np.array([[1.0, 1.0, 4.0, 4.0]], np.float32)
    check_grad(lambda im: L.roi_align(im, jnp.asarray(rois), jnp.asarray([0]), 2, 2),
               [x])


def test_grad_roi_pool():
    x = np.random.randn(1, 2, 6, 6).astype(np.float32)
    rois = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
    check_grad(lambda im: L.roi_pool(im, jnp.asarray(rois), jnp.asarray([0]), 2, 2),
               [x])


def test_grad_row_conv_weights():
    # FD-check the REAL layer: grad wrt its created filter param
    x = np.random.randn(2, 4, 3).astype(np.float32)
    prog = pt.build(lambda a: L.row_conv(a, 2))
    params, state = prog.init(jax.random.PRNGKey(0), x)
    (wname,) = params.keys()

    def fn(wv):
        out, _ = prog.apply({wname: wv}, state, jnp.asarray(x))
        return out
    check_grad(fn, [np.asarray(params[wname])])


def test_grad_sequence_conv_input_and_weights():
    # FD-check the REAL layer: grads wrt input and created weight
    seg = jnp.asarray(np.array([0, 0, 1, 1, 1], np.int32))
    vals = np.random.randn(5, 3).astype(np.float32)
    prog = pt.build(lambda v: L.sequence_conv(v, seg, num_filters=4, filter_size=3,
                                              bias_attr=False))
    params, state = prog.init(jax.random.PRNGKey(0), vals)
    (wname,) = params.keys()

    def fn_input(v):
        out, _ = prog.apply(params, state, v)
        return out
    check_grad(fn_input, [vals])

    def fn_weight(wv):
        out, _ = prog.apply({wname: wv}, state, jnp.asarray(vals))
        return out
    check_grad(fn_weight, [np.asarray(params[wname])])


def test_grad_polygon_and_affine():
    x = np.random.randn(1, 2, 3, 4).astype(np.float32)
    check_grad(lambda a: L.polygon_box_transform(a), [x])
    theta = np.tile(np.array([[1.0, 0.1, 0], [0, 1.0, -0.1]], np.float32), (1, 1, 1))
    check_grad(lambda t: L.affine_grid(t, (1, 2, 3, 4)), [theta])


def test_grad_fused_ce_hidden():
    from paddle_tpu.ops.fused_ce import chunked_softmax_cross_entropy
    h = np.random.randn(4, 6).astype(np.float32)
    w = jnp.asarray(np.random.randn(6, 10).astype(np.float32))
    lab = jnp.asarray(np.array([1, 3, 9, 0]))
    check_grad(lambda hv: chunked_softmax_cross_entropy(hv, w, None, lab, 0.1, 4), [h])


def test_random_crop_oversize_raises():
    from paddle_tpu.core.errors import EnforceError
    x = np.zeros((1, 4, 4), np.float32)
    with pytest.raises(EnforceError):
        L.random_crop(jnp.asarray(x), (8, 8), seed=0)


def test_step_counter_int32_no_x64_warning():
    import warnings

    def f(x):
        return x, L.autoincreased_step_counter()

    prog = pt.build(f)
    x = np.zeros((1,), np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any truncation UserWarning fails
        params, state = prog.init(jax.random.PRNGKey(0), x)
        (_, step), _ = prog.apply(params, state, x)
    assert int(np.asarray(step)[0]) == 1
