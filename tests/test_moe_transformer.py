"""MoE transformer as a TRAINING PATH: the GShard-style zoo model trains
through the Trainer on a dp×ep mesh with experts sharded and tokens
all-to-all-dispatched — the model-level realization of parallel/moe.py
(exists ≠ integrated guard, like the pp/sp siblings)."""

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.parallel import moe_ep_rules
from paddle_tpu.parallel.sharding import ShardingRules
from paddle_tpu.models import moe_transformer


def _cfg(**kw):
    base = dict(vocab_size=64, max_len=32, d_model=32, d_inner=64,
                d_expert=64, num_heads=4, num_layers=2, num_experts=8,
                top_k=2, moe_every=2, fused_ce=False)
    base.update(kw)
    return moe_transformer.base_config(**base)


def _feed(bs, seq=16, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, vocab, (bs, seq)).astype(np.int32)
    labels = np.concatenate([ids[:, 1:], np.full((bs, 1), 2)], axis=1).astype(np.int32)
    return {"ids": ids, "labels": labels}


@pytest.mark.slow
def test_moe_lm_trains_dense():
    prog = pt.build(moe_transformer.make_model(_cfg()))
    feed = _feed(4)
    tr = pt.Trainer(prog, opt.Adam(1e-2), loss_name="loss",
                    fetch_list=["loss", "ce_loss", "aux_loss"])
    tr.startup(sample_feed=feed)
    first = float(tr.step(tr._put_feed(feed))["loss"])
    for _ in range(10):
        out = tr.step(tr._put_feed(feed))
    assert float(out["loss"]) < first
    assert float(out["aux_loss"]) > 0  # routing actually happened


@pytest.mark.slow
def test_moe_lm_ep_mesh_parity_with_dense():
    """dp2×ep4 expert-parallel training == dense single-device training
    step for step (aux off, ample capacity → identical routing)."""
    feeds = [_feed(8, seed=i) for i in range(2)]
    kw = dict(aux_weight=0.0, capacity_factor=4.0)

    prog_ref = pt.build(moe_transformer.make_model(_cfg(**kw)))
    tr_ref = pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss")
    tr_ref.startup(sample_feed=feeds[0])
    ref = [float(tr_ref.step(f)["loss"]) for f in feeds]

    mesh = pt.make_mesh({"dp": 2, "ep": 4})
    prog_ep = pt.build(moe_transformer.make_model(_cfg(**kw), mesh=mesh))
    tr_ep = pt.Trainer(
        prog_ep, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
        sharding_rules=ShardingRules(list(moe_ep_rules()), default=None))
    tr_ep.startup(sample_feed=feeds[0])
    got = [float(tr_ep.step(f)["loss"]) for f in feeds]

    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_moe_expert_params_sharded_over_ep():
    mesh = pt.make_mesh({"dp": 2, "ep": 4})
    prog = pt.build(moe_transformer.make_model(_cfg(), mesh=mesh))
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    sharding_rules=ShardingRules(list(moe_ep_rules()),
                                                 default=None))
    tr.startup(sample_feed=_feed(8))
    ew = [k for k in tr.scope.params if k.endswith("expert_w1")]
    assert ew, sorted(tr.scope.params)[:10]
    assert tr.scope.params[ew[0]].sharding.spec[0] == "ep"
