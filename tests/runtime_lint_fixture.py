"""Planted-defect fixture for the runtime concurrency analyzer.

Every class below carries exactly ONE deliberate instance of a
``thread:*`` rule — the golden findings ``tests/test_analysis_runtime.py``
pins (rule, ``where``, fingerprint stability). This module is analyzed
as SOURCE (``paddle_tpu.analysis.concurrency`` never imports it); it is
import-safe only so pytest collection machinery can't trip over it.

Never "fix" these: each one is the test oracle for its rule.
"""

import threading


class GuardedCounter:
    """Planted: ``thread:unguarded-access`` (snapshot reads ``_count``
    bare) and ``thread:callback-under-lock`` (``on_full`` fires inside
    the lock)."""

    def __init__(self, on_full=None):
        self._lock = threading.Lock()
        self._count = 0
        self._routes = {}
        self.on_full = on_full

    def start(self):
        # snapshot escapes into a route table -> thread-reachable
        self._routes["snapshot"] = self.snapshot
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def _loop(self):
        with self._lock:
            self._count += 1
            if self._count >= 10 and self.on_full is not None:
                self.on_full()          # planted: callback-under-lock

    def snapshot(self):
        return self._count              # planted: unguarded-access


class RegisterBeforeStart:
    """Planted: ``thread:join-unstarted`` — the worker Thread is
    published into ``self._workers`` before ``.start()`` (the
    ``_spawn_worker`` bug class)."""

    def __init__(self):
        self._workers = []

    def spawn(self):
        t = threading.Thread(target=self._run, daemon=True)
        self._workers.append(t)         # planted: registered unstarted
        t.start()

    def _run(self):
        pass


class InvertedLocks:
    """Planted: ``thread:lock-order`` — ``transfer`` takes a then b,
    ``refund`` takes b then a."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def transfer(self):
        with self._a:
            with self._b:
                pass

    def refund(self):
        with self._b:
            with self._a:
                pass
