"""Loss scaling (amp.py): scaler dynamics, overflow-skip in the Trainer,
static-scale equivalence, checkpoint roundtrip."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, optimizer as opt
from paddle_tpu.amp import LossScaler
from paddle_tpu.parallel import DistStrategy


def test_scaler_dynamics():
    sc = LossScaler(init_scale=1024.0, dynamic=True, growth_interval=3, factor=2.0)
    ls = sc.init_state()
    ls = sc.update(ls, jnp.bool_(False))             # overflow → halve
    assert float(ls["scale"]) == 512.0 and int(ls["good_steps"]) == 0
    assert int(ls["overflows"]) == 1
    for _ in range(2):
        ls = sc.update(ls, jnp.bool_(True))
    assert float(ls["scale"]) == 512.0               # not yet at interval
    ls = sc.update(ls, jnp.bool_(True))              # 3rd good step → grow
    assert float(ls["scale"]) == 1024.0 and int(ls["good_steps"]) == 0


def test_scaler_static_mode():
    sc = LossScaler(init_scale=128.0, dynamic=False)
    ls = sc.init_state()
    ls = sc.update(ls, jnp.bool_(False))
    assert float(ls["scale"]) == 128.0 and int(ls["overflows"]) == 1


def _mlp_trainer(strategy=None, seed=0):
    def net(x, label):
        h = layers.fc(x, 32, act="relu", name="h")
        logits = layers.fc(h, 4, name="out")
        return {"loss": layers.mean(layers.softmax_with_cross_entropy(logits, label))}

    prog = pt.build(net)
    tr = pt.Trainer(prog, opt.SGD(0.1), loss_name="loss", strategy=strategy)
    rng = np.random.RandomState(seed)
    feed = {"x": rng.randn(16, 8).astype(np.float32),
            "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}
    tr.startup(sample_feed=feed)
    return tr, feed


def test_overflow_skips_step_and_shrinks_scale():
    tr, feed = _mlp_trainer(DistStrategy(dynamic_loss_scale=True, loss_scale=1024.0))
    p0 = {k: np.asarray(v) for k, v in tr.scope.params.items()}

    bad = dict(feed)
    bad["x"] = feed["x"].copy()
    bad["x"][0, 0] = np.nan
    out = tr.step(bad)
    assert float(out["loss_scale"]) == 512.0
    for k, v in tr.scope.params.items():
        np.testing.assert_array_equal(np.asarray(v), p0[k], err_msg=k)

    out = tr.step(feed)                              # clean batch → params move
    assert float(out["loss_scale"]) == 512.0
    moved = any(not np.array_equal(np.asarray(v), p0[k])
                for k, v in tr.scope.params.items())
    assert moved
    assert int(tr.scope.loss_scale_state["overflows"]) == 1


def test_static_scale_matches_unscaled_training():
    tr_a, feed = _mlp_trainer()
    tr_b, _ = _mlp_trainer(DistStrategy(loss_scale=1024.0))
    for i in range(3):
        rng = jax.random.PRNGKey(7 + i)
        tr_a.step(feed, rng=rng)
        tr_b.step(feed, rng=rng)
    for k in tr_a.scope.params:
        np.testing.assert_allclose(np.asarray(tr_a.scope.params[k]),
                                   np.asarray(tr_b.scope.params[k]),
                                   atol=1e-6, rtol=1e-6, err_msg=k)


def test_loss_scale_checkpoint_roundtrip(tmp_path):
    from paddle_tpu import io
    tr, feed = _mlp_trainer(DistStrategy(dynamic_loss_scale=True, loss_scale=256.0))
    bad = dict(feed)
    bad["x"] = feed["x"].copy()
    bad["x"][0, 0] = np.inf
    tr.step(bad)
    io.save_trainer(str(tmp_path / "ck"), tr)

    tr2, _ = _mlp_trainer(DistStrategy(dynamic_loss_scale=True, loss_scale=256.0))
    io.load_trainer(str(tmp_path / "ck"), tr2)
    assert float(tr2.scope.loss_scale_state["scale"]) == 128.0
    assert int(tr2.scope.loss_scale_state["overflows"]) == 1
    tr2.step(feed)  # still steppable after restore
