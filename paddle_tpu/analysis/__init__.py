"""paddle_tpu.analysis — jaxpr-level static program checker.

The IR-pass layer of the framework (graph_viz_pass / memory_usage_calc /
ProgramDesc-validator analog, SURVEY §3): a walker over ``Program.desc``
— the jaxpr IS the ProgramDesc here — that produces a structured
:class:`LintReport` before anything compiles. Seven rule families:

1. collective placement — reduction collectives inside scan/while
   bodies (the unhoisted-accumulation hazard) with per-step comm-byte
   estimates, plus config-level detection of the per-microbatch GSPMD
   gradient exchange;
2. dtype flow — f32 MXU ops surviving under an amp compute dtype, f64
   leaks, no-op cast round-trips;
3. whole-program sharding audit — rules matching no parameter, spec
   axes that don't divide shapes, large params left replicated on an
   fsdp mesh (placement-time ``_validate`` only sees one name at a
   time);
4. dead / frozen parameters — initialized-but-never-read params and
   trainable params with structurally-zero gradients;
5. donation aliasing — fetched step outputs that ARE donated inputs
   passed through (the donated-buffer-reuse footgun, sharpened by the
   fused K-step dispatch donating the whole training carry);
6. recompilation hazards — weak python scalars and unhashable objects
   in the traced argument signature;
7. feed wire-format candidates — float32 feed inputs whose first
   in-program uses are a cast/normalize, static evidence the field
   could cross the host→device link as uint8/bf16 wire with the decode
   fused into the step (data/wire.py).

Two further families reach past the single program:

8. MoE routing capacity — static ``capacity_factor``/``top_k`` combos
   whose expected token drop rate exceeds a threshold (``moe:capacity``);
9. replicated optimizer state — opt-state accumulators fully replicated
   across a data axis above a size threshold, the ZeRO trigger
   (``sharding:replicated-optstate``).

And the checker's cross-ARTIFACT layer, :mod:`.contracts`
(:func:`check_artifacts`): static compatibility proofs between trainer
programs, checkpoint manifests, serving artifacts, and mesh specs —
``ckpt:*`` / ``artifact:*`` findings whose runtime counterparts are
crashes (``CheckpointCorrupt``, ``ReloadFailed``, sharding aborts).

Beyond the program level, :mod:`.runtime` (:func:`check_runtime`) turns
the same finding machinery on the framework's OWN Python source: lock-
discipline rules (``thread:unguarded-access`` / ``callback-under-lock``
/ ``lock-order`` / ``join-unstarted``, :mod:`.concurrency`) and framed-
wire contract rules (``wire:schema-drift`` / ``retry-unsafe`` /
``unknown-verb``, :mod:`.wire_contracts`) over the three client↔server
verb surfaces, including the C side of ``native/pserver.cc``.

Four front doors: programmatic :func:`check` / :func:`check_trainer` /
:func:`check_artifacts` / :func:`check_runtime`,
``Trainer.startup(lint="warn"|"error")``, the CLI ``python -m
paddle_tpu.analysis --model mnist`` (also ``tools/lint_program.py``;
``--wire-table`` prints the extracted verb table), and the CI gate
``tools/lint_gate.py --ci`` (stable finding fingerprints + a committed
baseline file + SARIF), whose ``--runtime`` sweep runs the source-level
rules.
"""

from .check import check, check_trainer
from .contracts import (check_artifacts, check_reload_compat, serving_spec,
                        trainer_specs)
from .runtime import check_runtime, lock_edges, runtime_sources
from .wire_contracts import (check_wire, render_verb_table_md,
                             scrape_surface, verb_table)
from .report import (Finding, LintError, LintReport, LintWarning,
                     active_report, apply_severity, baseline_key,
                     collect_into, load_baseline, new_findings, to_sarif,
                     write_baseline)
from .walker import (COLLECTIVES, PERMUTE_COLLECTIVES,
                     REDUCTION_COLLECTIVES, aval_bytes, eqn_subjaxprs,
                     iter_eqns, walk_jaxprs)

__all__ = [
    "check", "check_trainer",
    "check_artifacts", "check_reload_compat", "serving_spec",
    "trainer_specs",
    "check_runtime", "lock_edges", "runtime_sources",
    "check_wire", "render_verb_table_md", "scrape_surface", "verb_table",
    "Finding", "LintError", "LintReport", "LintWarning",
    "active_report", "collect_into",
    "apply_severity", "baseline_key", "load_baseline", "new_findings",
    "to_sarif", "write_baseline",
    "COLLECTIVES", "PERMUTE_COLLECTIVES", "REDUCTION_COLLECTIVES",
    "aval_bytes", "eqn_subjaxprs", "iter_eqns", "walk_jaxprs",
]
