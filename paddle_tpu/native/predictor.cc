// Python-free native predictor over the PJRT C API.
//
// Capability parity with the reference's C++ inference entry
// (inference/io.h:35 LoadInferenceModel; api_impl.cc:64
// NativePaddlePredictor::Init — load a saved model + params and run it
// from C++ with no Python in the process). Our export artifact
// (io.py save_inference_model) is:
//   model.mlir   — raw StableHLO bytecode of the inference function
//   params.npz / state.npz — weights (uncompressed zip of .npy members)
//   meta.json    — ordered flat input signature: which npz member (or
//                  runtime feed) supplies each executable argument
// This binary dlopens a PJRT plugin (libtpu.so on TPU hosts; any
// GetPjrtApi-exporting .so), compiles the StableHLO, stages weights and
// feeds as device buffers, executes, and prints per-output checksums.
//
//   predictor <artifact_dir> <plugin.so> [--probe]
//
// --probe stops after the Python-free half that needs no accelerator:
// plugin dlopen + PJRT version handshake + full artifact load/validation
// (meta.json vs npz shapes/dtypes/sizes). The full run requires a local
// device for the plugin (the CI box reaches its TPU through an IFRT
// proxy tunnel, which is not a PJRT C API endpoint — see
// DESIGN.md "native predictor").
//
// Build (test_native_predictor.py does this):
//   g++ -O2 -std=c++17 -I$TF_INCLUDE predictor.cc -o predictor -ldl

#include "pjrt_common.h"

int main(int argc, char** argv) {
  g_tool = "predictor";
  if (argc < 3) {
    fprintf(stderr,
            "usage: predictor <artifact_dir> <pjrt_plugin.so> [--probe]\n");
    return 2;
  }
  std::string dir = argv[1], plugin = argv[2];
  bool probe = argc > 3 && std::string(argv[3]) == "--probe";

  // ---- artifact load + validation (no accelerator needed) ---------------
  std::string mlir = ReadFileOrDie(dir + "/model.mlir");
  std::string meta = ReadFileOrDie(dir + "/meta.json");
  std::string params_blob = ReadFileOrDie(dir + "/params.npz");
  std::string state_blob = ReadFileOrDie(dir + "/state.npz");
  auto params = ParseNpz(params_blob, "params.npz");
  std::map<std::string, Array> state;
  if (state_blob.size() > 4 && rd32(state_blob.data()) == 0x04034b50)
    state = ParseNpz(state_blob, "state.npz");
  auto inputs = ParseMetaInputs(meta);

  size_t feed_args = 0, weight_bytes = 0;
  for (const auto& sp : inputs) {
    DType dt = DtypeOrDie(sp.dtype);
    size_t want = dt.size;
    for (int64_t d : sp.shape) want *= size_t(d);
    if (sp.source == "feed") { ++feed_args; continue; }
    auto& table = sp.source == "params.npz" ? params : state;
    auto it = table.find(sp.name);
    if (it == table.end()) Die("meta input " + sp.name + " missing from " +
                               sp.source);
    const Array& got = it->second;
    if (got.nbytes != want)
      Die("weight " + sp.name + " is " + std::to_string(got.nbytes) +
          " bytes, signature expects " + std::to_string(want));
    if (got.dtype != dt.npy)
      Die("weight " + sp.name + " stored as npy '" + got.dtype +
          "', signature expects '" + dt.npy + "' (" + sp.dtype + ")");
    if (got.shape != sp.shape) {
      std::string g, w;
      for (int64_t v : got.shape) g += std::to_string(v) + ",";
      for (int64_t v : sp.shape) w += std::to_string(v) + ",";
      Die("weight " + sp.name + " has shape [" + g +
          "], signature expects [" + w + "]");
    }
    weight_bytes += want;
  }
  fprintf(stderr,
          "predictor: artifact ok — %zu args (%zu weights %.1f MB, %zu feeds), "
          "stablehlo %zu bytes\n",
          inputs.size(), inputs.size() - feed_args,
          weight_bytes / 1048576.0, feed_args, mlir.size());

  // ---- plugin handshake -------------------------------------------------
  void* lib = dlopen(plugin.c_str(), RTLD_NOW | RTLD_LOCAL);
  if (!lib) Die(std::string("dlopen failed: ") + dlerror());
  auto get_api = reinterpret_cast<const PJRT_Api* (*)()>(
      dlsym(lib, "GetPjrtApi"));
  if (!get_api) Die("plugin has no GetPjrtApi symbol");
  g_api = get_api();
  if (!g_api) Die("GetPjrtApi returned null");
  fprintf(stderr, "predictor: plugin PJRT API v%d.%d (header v%d.%d)\n",
          g_api->pjrt_api_version.major_version,
          g_api->pjrt_api_version.minor_version, PJRT_API_MAJOR,
          PJRT_API_MINOR);
  if (g_api->pjrt_api_version.major_version != PJRT_API_MAJOR)
    Die("PJRT major version mismatch");

  if (probe) {
    printf("PROBE OK\n");
    return 0;
  }

  PJRT_Plugin_Initialize_Args pi;
  memset(&pi, 0, sizeof pi);
  pi.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  Check(g_api->PJRT_Plugin_Initialize(&pi), "plugin init");

  PJRT_Client_Create_Args cc;
  memset(&cc, 0, sizeof cc);
  cc.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  Check(g_api->PJRT_Client_Create(&cc), "client create");
  PJRT_Client* client = cc.client;

  PJRT_Client_AddressableDevices_Args ad;
  memset(&ad, 0, sizeof ad);
  ad.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  ad.client = client;
  Check(g_api->PJRT_Client_AddressableDevices(&ad), "devices");
  if (ad.num_addressable_devices == 0) Die("no addressable devices");
  PJRT_Device* dev = ad.addressable_devices[0];
  fprintf(stderr, "predictor: %zu addressable device(s)\n",
          ad.num_addressable_devices);

  // ---- compile ----------------------------------------------------------
  PJRT_Program prog;
  memset(&prog, 0, sizeof prog);
  prog.struct_size = PJRT_Program_STRUCT_SIZE;
  prog.code = mlir.data();
  prog.code_size = mlir.size();
  static const char kFmt[] = "mlir";
  prog.format = kFmt;
  prog.format_size = 4;
  std::string copts = MinimalCompileOptions();
  PJRT_Client_Compile_Args comp;
  memset(&comp, 0, sizeof comp);
  comp.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  comp.client = client;
  comp.program = &prog;
  comp.compile_options = copts.data();
  comp.compile_options_size = copts.size();
  Check(g_api->PJRT_Client_Compile(&comp), "compile");
  fprintf(stderr, "predictor: stablehlo compiled\n");

  // ---- stage inputs (weights from npz; feeds zero-filled or from
  //      <dir>/feed_<name>.npy if present) --------------------------------
  std::vector<PJRT_Buffer*> arg_bufs;
  std::vector<std::string> feed_storage;
  for (const auto& sp : inputs) {
    DType dt = DtypeOrDie(sp.dtype);
    size_t nbytes = dt.size;
    for (int64_t d : sp.shape) nbytes *= size_t(d);
    const char* data;
    if (sp.source == "feed") {
      std::string path = dir + "/feed_" + sp.name + ".npy";
      FILE* f = fopen(path.c_str(), "rb");
      if (f) {
        fclose(f);
        std::string blob = ReadFileOrDie(path);
        feed_storage.push_back(std::move(blob));
        Array a = ParseNpy(feed_storage.back().data(),
                           feed_storage.back().size(), path);
        if (a.nbytes != nbytes) Die("feed " + sp.name + " wrong size");
        if (a.dtype != dt.npy)
          Die("feed " + sp.name + " is npy '" + a.dtype + "', signature "
              "expects '" + dt.npy + "' (" + sp.dtype + ")");
        if (a.shape != sp.shape) Die("feed " + sp.name + " wrong shape");
        data = a.data;
      } else {
        feed_storage.emplace_back(nbytes, '\0');
        data = feed_storage.back().data();
      }
    } else {
      auto& table = sp.source == "params.npz" ? params : state;
      data = table.at(sp.name).data;
    }
    PJRT_Client_BufferFromHostBuffer_Args hb;
    memset(&hb, 0, sizeof hb);
    hb.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    hb.client = client;
    hb.data = data;
    hb.type = dt.pjrt;
    hb.dims = sp.shape.data();
    hb.num_dims = sp.shape.size();
    hb.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
    hb.device = dev;
    Check(g_api->PJRT_Client_BufferFromHostBuffer(&hb),
          ("h2d " + sp.name).c_str());
    AwaitAndDestroy(hb.done_with_host_buffer, "h2d done");
    arg_bufs.push_back(hb.buffer);
  }

  // ---- execute ----------------------------------------------------------
  PJRT_LoadedExecutable_GetExecutable_Args ge;
  memset(&ge, 0, sizeof ge);
  ge.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
  ge.loaded_executable = comp.executable;
  Check(g_api->PJRT_LoadedExecutable_GetExecutable(&ge), "get executable");
  PJRT_Executable_NumOutputs_Args no;
  memset(&no, 0, sizeof no);
  no.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
  no.executable = ge.executable;
  Check(g_api->PJRT_Executable_NumOutputs(&no), "num outputs");

  std::vector<PJRT_Buffer*> outs(no.num_outputs, nullptr);
  PJRT_Buffer** out_list = outs.data();
  PJRT_Buffer* const* arg_list = arg_bufs.data();
  PJRT_ExecuteOptions eo;
  memset(&eo, 0, sizeof eo);
  eo.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;
  PJRT_Event* done = nullptr;
  PJRT_LoadedExecutable_Execute_Args ex;
  memset(&ex, 0, sizeof ex);
  ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
  ex.executable = comp.executable;
  ex.options = &eo;
  ex.argument_lists = &arg_list;
  ex.num_devices = 1;
  ex.num_args = arg_bufs.size();
  ex.output_lists = &out_list;
  ex.device_complete_events = &done;
  ex.execute_device = dev;
  Check(g_api->PJRT_LoadedExecutable_Execute(&ex), "execute");
  AwaitAndDestroy(done, "execute done");

  // ---- fetch outputs, print checksums ------------------------------------
  for (size_t i = 0; i < outs.size(); ++i) {
    PJRT_Buffer_ToHostBuffer_Args th;
    memset(&th, 0, sizeof th);
    th.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    th.src = outs[i];
    Check(g_api->PJRT_Buffer_ToHostBuffer(&th), "d2h size query");
    std::vector<char> host(th.dst_size);
    th.dst = host.data();
    Check(g_api->PJRT_Buffer_ToHostBuffer(&th), "d2h");
    AwaitAndDestroy(th.event, "d2h done");
    PJRT_Buffer_ElementType_Args et;
    memset(&et, 0, sizeof et);
    et.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
    et.buffer = outs[i];
    Check(g_api->PJRT_Buffer_ElementType(&et), "element type");
    double sum = 0;
    if (et.type == PJRT_Buffer_Type_F32) {
      const float* v = reinterpret_cast<const float*>(host.data());
      for (size_t k = 0; k < host.size() / 4; ++k) sum += v[k];
    }
    printf("OUTPUT %zu bytes=%zu f32sum=%.6f\n", i, host.size(), sum);
  }
  printf("RUN OK\n");
  return 0;
}
