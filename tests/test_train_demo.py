"""C++ train demo (native/train_demo.cc = train/demo/demo_trainer.cc
analog): compile with g++ and run end-to-end — C++ owns data
generation, RecordIO IO, batching and the epoch loop; the embedded
interpreter only loads the XLA runtime."""

import os
import shutil
import subprocess

import pytest

NATIVE = os.path.join(os.path.dirname(__file__), "..", "paddle_tpu", "native")
REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.mark.skipif(shutil.which("g++") is None, reason="g++ unavailable")
@pytest.mark.slow
def test_cpp_train_demo_compiles_and_converges(tmp_path):
    import sys
    import sysconfig

    binary = str(tmp_path / "train_demo")
    # derive embed flags from THE RUNNING interpreter — a PATH
    # python3-config may describe a different python whose libpython
    # can't import this venv's jax
    ver = f"{sys.version_info.major}.{sys.version_info.minor}"
    includes = [f"-I{sysconfig.get_path('include')}"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ldflags = [f"-L{libdir}", f"-lpython{ver}", "-ldl", "-lm"]
    subprocess.check_call(
        ["g++", "-O3", "-std=c++17", os.path.join(NATIVE, "train_demo.cc"),
         os.path.join(NATIVE, "recordio.cc")] + includes + ldflags + ["-lz", "-o", binary])
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)  # single CPU device is fine for the demo
    out = subprocess.run([binary], env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PASS" in out.stdout
