"""Weight-decay regularizers.

Analog of python/paddle/fluid/regularizer.py: in the reference these
append penalty ops to each param's gradient during
``Optimizer.minimize``; here they are pure ``(param, grad) -> grad``
transforms the optimizer applies inside the jitted update (XLA fuses
them into the update kernel — the reference needed separate ops).
Per-parameter regularizers set via ParamAttr override the optimizer's
global one, matching the reference's precedence (regularizer.py:36).
"""

from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def apply(self, param, grad):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    """L2 weight decay: grad += coeff * param (L2DecayRegularizer)."""

    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = float(regularization_coeff)

    def apply(self, param, grad):
        return grad + self.coeff * param


class L1Decay(WeightDecayRegularizer):
    """L1 decay: grad += coeff * sign(param) (L1DecayRegularizer)."""

    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = float(regularization_coeff)

    def apply(self, param, grad):
        return grad + self.coeff * jnp.sign(param)


# fluid aliases
L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
