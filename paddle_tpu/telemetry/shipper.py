"""Background telemetry shipper: the PUSH half of the collector story.

Any process — a trainer, an out-of-process serving replica, a fleet
router — attaches ONE process-wide :class:`Shipper` that streams its
observability to a :class:`~paddle_tpu.telemetry.collector.
TelemetryCollector` over the framed wire:

- **journal events**, captured live through ``RunJournal.subscribe``
  (subscribers fire for EVERY event regardless of ring/sink sampling,
  so the shipped stream is complete) into a bounded buffer and flushed
  as ``EVENTS`` batches every ``flush_interval``;
- **registry snapshots** (``registry.snapshot()``, the full
  families_snapshot) as ``SNAPSHOT`` pushes every
  ``snapshot_interval`` — the samples the collector's time-series
  rings and alert rules run on.

The hot path NEVER blocks on the collector: the subscriber callback is
a lock + deque append (the <2%-of-a-K=16-dispatch budget is
test-pinned); all wire I/O happens on the shipper's daemon thread.
When the collector is unreachable the buffer holds what fits and the
overflow is counted — ``paddle_tpu_shipper_dropped_total`` — never
raised. Event batches are deduplicated server-side by ``(origin, run,
seq)``, so flush retries are safe (idempotent sends, no at-most-once
dance on a telemetry path).

Attachment is zero-code: every ``Trainer``, ``PredictorServer``, and
``FleetRouter`` constructor calls :func:`maybe_auto_ship`, which
starts the process shipper iff ``PDTPU_TELEMETRY_ADDR=host:port`` is
set (the env var is inherited by spawned replica processes, so a
remote fleet ships per-process automatically). Explicit attachment is
:func:`ship_to` — also exposed as ``.ship_to(addr)`` on all three.

**Collector HA**: ``PDTPU_TELEMETRY_ADDR`` (and every addr-taking
door here) accepts a comma-separated failover list —
``"host1:p1,host2:p2"``. Flushes stick to the first address that
accepts them; a flush error rotates to the next and retries within
the SAME tick (counted as ``paddle_tpu_shipper_flushes_total{outcome=
"failover"}``). The server-side ``(origin, run, sseq)`` dedupe that
makes retries safe makes failover safe too: a standby collector that
replayed the shared segment log carries the same high-water marks, so
the batch a dead primary never acknowledged is resent to the standby
and lands exactly once.

Knobs (env defaults in parentheses): ``origin`` — the label this
process's series carry at the collector (``PDTPU_TELEMETRY_ORIGIN``,
else ``<hostname>-<pid>`` — pids collide across machines the moment a
fleet spans hosts, so the default origin carries the sanitized
hostname); ``flush_interval`` (``PDTPU_TELEMETRY_FLUSH_S``, 0.25s) —
each shipper adds a deterministic per-origin phase offset
(:func:`flush_jitter`) so K replicas spawned in the same second don't
synchronize their pushes into the collector; ``buffer_events``
(``PDTPU_TELEMETRY_BUFFER``, 4096).

:class:`ReplicationClient` is the OTHER puller on this wire: a
cross-host standby collector's client for the primary's ``SEGMENTS``
verb (segment-log listing + raw segment/tail fetches — see
``telemetry/collector.py``'s replication story).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple, Union

from .journal import RunJournal, get_journal
from .registry import MetricsRegistry, get_registry

AddrLike = Union[str, Tuple[str, int]]


def _log():
    import logging
    return logging.getLogger("paddle_tpu.telemetry.shipper")


def default_origin() -> str:
    """``<hostname>-<pid>``: the origin a shipper uses when neither
    ``origin=`` nor ``PDTPU_TELEMETRY_ORIGIN`` names one. Pids are
    only unique per machine — two replicas on different hosts of a
    cross-host fleet can share a pid, and their series must not merge
    under one origin label. The hostname is sanitized to the label
    charset (anything outside ``[A-Za-z0-9._-]`` becomes ``-``) so
    the merged ``/metrics`` naming contract holds."""
    import socket as _socket

    host = "".join(c if (c.isalnum() or c in "._-") else "-"
                   for c in _socket.gethostname()) or "host"
    return f"{host}-{os.getpid()}"


def flush_jitter(origin: str, interval: float, frac: float = 0.25) -> float:
    """Deterministic per-origin offset added to every flush wait:
    ``hash(origin)`` mapped into ``[0, frac * interval)``. A scale-up
    that spawns K replicas in the same second gives all K the same
    flush cadence — without jitter their pushes synchronize into the
    collector as a K-wide thundering herd every tick. Keying the
    jitter on the origin (stable per process across restarts, distinct
    across replicas by construction — see :func:`default_origin`)
    desynchronizes them deterministically: no RNG, so the schedule is
    reproducible and two same-period shippers provably never share a
    phase unless they share an origin."""
    import hashlib

    h = hashlib.sha1(origin.encode("utf-8", "surrogatepass")).digest()[:8]
    u = int.from_bytes(h, "big") / float(2 ** 64)   # [0, 1)
    return u * float(frac) * float(interval)


def parse_addr(addr: AddrLike) -> Tuple[str, int]:
    """``"host:port"`` (the env-var shape) or ``(host, port)``."""
    if isinstance(addr, str):
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"bad telemetry collector addr {addr!r} (want host:port)")
        return (host, int(port))
    host, port = addr
    return (str(host), int(port))


def parse_addrs(addr) -> Tuple[Tuple[str, int], ...]:
    """The HA shape: a comma-separated failover list
    (``"h1:p1,h2:p2"`` — what ``PDTPU_TELEMETRY_ADDR`` accepts), a
    list/tuple of addr-likes, or one addr. Order is priority: the
    shipper sticks to the first address that accepts flushes and fails
    over down (then around) the list on flush errors."""
    if isinstance(addr, str):
        parts = [p.strip() for p in addr.split(",") if p.strip()]
        if not parts:
            raise ValueError(f"bad telemetry collector addr {addr!r}")
        return tuple(parse_addr(p) for p in parts)
    if isinstance(addr, (list, tuple)):
        if len(addr) == 2 and isinstance(addr[1], int):
            return (parse_addr(addr),)   # one (host, port) pair
        return tuple(parse_addr(a) for a in addr)
    return (parse_addr(addr),)


class ShipperClient:
    """Framed-wire client for the collector's push verbs (a thin
    :class:`~paddle_tpu.parallel.async_ps.FramedClient` wrapper with
    the retry budget a BACKGROUND path wants: short timeout, few
    retries — a missed flush is retried by the next tick, not by
    spinning here)."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 5.0):
        from ..parallel.async_ps import FramedClient

        class _Client(FramedClient):
            peer_name = "telemetry collector"

        self._cli = _Client(addr, timeout=timeout, retries=2,
                            retry_backoff=0.05, retry_backoff_max=0.2,
                            connect=False)

    def _call(self, header: str, body: bytes) -> int:
        resp = self._cli._request(f"{header} {len(body)}", body)
        return int(resp.split()[1])

    def ship_events(self, origin: str, run: str, events) -> int:
        # the journal's own encoder: a numpy-valued detail field must
        # ship as the NUMBER the local JSONL sink writes, not a repr
        # string (fleet-wide timeline == per-process sink, byte-alike)
        from .journal import _json_default

        body = json.dumps({"run": run, "events": list(events)},
                          default=_json_default).encode()
        return self._call(f"EVENTS {origin}", body)

    def ship_snapshot(self, origin: str, snapshot: Dict[str, Any]) -> int:
        from .journal import _json_default

        body = json.dumps({"families": snapshot},
                          default=_json_default).encode()
        return self._call(f"SNAPSHOT {origin}", body)

    def ping(self) -> None:
        self._cli._request("PING")

    def stats(self) -> Dict[str, Any]:
        """The collector's ``STATS`` verb: its ingest/store counters as
        one JSON object riding the reply line (``OK {...}``) — what the
        bench rows delta to price store ingest-writes."""
        resp = self._cli._request("STATS")
        return json.loads(resp.split(" ", 1)[1])

    def close(self) -> None:
        self._cli.close()


class ReplicationClient:
    """A cross-host standby collector's puller for the primary's
    ``SEGMENTS`` verb: one framed request (``SEGMENTS <len>`` + json)
    per call, one framed reply body back — the segment-log listing
    (json) or raw segment bytes, depending on the request form. The
    bytes are NOT trusted off the wire: the standby re-verifies every
    sealed segment against the sidecar CRC the listing carried before
    anything touches its store."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 10.0):
        from ..parallel.async_ps import FramedClient

        class _Client(FramedClient):
            peer_name = "primary collector"

        self._cli = _Client(addr, timeout=timeout, retries=2,
                            retry_backoff=0.05, retry_backoff_max=0.2,
                            connect=False)

    def _segments(self, req: Dict[str, Any]) -> bytes:
        body = json.dumps(req, separators=(",", ":")).encode()
        resp, payload = self._cli._request(
            f"SEGMENTS {len(body)}", body,
            body_len=lambda r: int(r.split()[1]))
        return payload

    def listing(self) -> Dict[str, Any]:
        """The primary's sealed segments (name + CRC sidecar doc each)
        and its active segment's name/size."""
        return json.loads(self._segments({"list": True}))

    def fetch(self, name: str, offset: int = 0,
              limit: Optional[int] = None) -> bytes:
        """Raw bytes of one segment file from ``offset`` (the whole
        file for a sealed segment, the unseen tail for the open
        one)."""
        req: Dict[str, Any] = {"fetch": name, "offset": int(offset)}
        if limit is not None:
            req["limit"] = int(limit)
        return self._segments(req)

    def ping(self) -> None:
        """The promotion fence's liveness probe of the primary."""
        self._cli._request("PING")

    def close(self) -> None:
        self._cli.close()


class Shipper:
    """One process's push pipeline to a collector (see module
    docstring). ``close()`` flushes what it can and detaches."""

    def __init__(self, addr: AddrLike, origin: Optional[str] = None,
                 journal: Optional[RunJournal] = None,
                 registry: Optional[MetricsRegistry] = None,
                 flush_interval: Optional[float] = None,
                 snapshot_interval: Optional[float] = None,
                 buffer_events: Optional[int] = None,
                 client_timeout: float = 5.0):
        # the HA failover list: flushes go to addrs[_addr_i]; a flush
        # error rotates to the next address and retries ONCE in the
        # same tick (server-side idempotent dedupe is what makes the
        # resend — to either collector — safe)
        self.addrs = parse_addrs(addr)
        self._addr_i = 0
        origin = origin or os.environ.get("PDTPU_TELEMETRY_ORIGIN") \
            or default_origin()
        if any(c.isspace() for c in origin):
            raise ValueError(f"origin {origin!r} must not contain "
                             "whitespace (it rides a framed header)")
        if origin == "collector":
            raise ValueError(
                "origin 'collector' is reserved for the collector's own "
                "series in the merged export")
        self.origin = origin
        self.journal = journal if journal is not None else get_journal()
        self.registry = registry if registry is not None else get_registry()
        self.flush_interval = float(
            flush_interval if flush_interval is not None
            else os.environ.get("PDTPU_TELEMETRY_FLUSH_S", 0.25))
        self.snapshot_interval = float(
            snapshot_interval if snapshot_interval is not None
            else max(self.flush_interval, 0.5))
        # per-origin phase offset on the flush wait: K replicas spawned
        # together would otherwise push in lockstep (see flush_jitter)
        self.flush_jitter = flush_jitter(self.origin, self.flush_interval)
        bound = int(buffer_events if buffer_events is not None
                    else os.environ.get("PDTPU_TELEMETRY_BUFFER", 4096))
        self._buf_lock = threading.Lock()
        # (ship_seq, event) tuples: the ship sequence is assigned under
        # THIS lock at append time, so it is monotonic in buffer order
        # even when journal subscribers land out of journal-seq order
        # (subscribe() runs outside the journal lock), and it is
        # stable across flush retries — the collector's dedupe
        # high-water runs on it
        self._buf: deque = deque()
        self._buf_bound = max(16, bound)
        self._sseq = 0
        # counters (read by the registry collector AND bench deltas)
        self._c_lock = threading.Lock()
        self._counts = {"events_shipped": 0, "events_dropped": 0,
                        "snapshots": 0, "flushes": 0, "flush_failures": 0,
                        "failovers": 0, "flush_seconds": 0.0}
        self._client_timeout = client_timeout
        self._client = ShipperClient(self.addr, timeout=client_timeout)
        self._stop = threading.Event()
        self._wake = threading.Event()
        # serializes _flush_once: a synchronous flush() on the caller's
        # thread must never interleave with the loop's tick on the ONE
        # underlying framed socket (FramedClient has no internal lock)
        self._flush_lock = threading.Lock()
        self._last_snapshot = 0.0
        self.telemetry_inst = self.registry.next_instance("shipper")
        self._sub = self.journal.subscribe(self._on_event)
        self._telemetry_cid = self.registry.add_collector(
            Shipper._families, owner=self)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pdtpu-telemetry-shipper")
        self._thread.start()
        # first flush IMMEDIATELY (not one interval in): the process
        # registers its origin with the collector the moment shipping
        # starts, so absence alerts cover even a process that dies
        # young — and operators see a spawned fleet appear promptly
        self._wake.set()

    @property
    def addr(self) -> Tuple[str, int]:
        """The address flushes currently go to (failover rotates it)."""
        return self.addrs[self._addr_i]

    def _failover_locked(self) -> None:
        """Rotate to the next collector in the list (called under
        ``_flush_lock`` after a flush error). The dead primary comes
        back into rotation if every other address fails too — a
        recovered primary is re-adopted within one lap."""
        try:
            self._client.close()
        except Exception:
            pass
        self._addr_i = (self._addr_i + 1) % len(self.addrs)
        self._client = ShipperClient(self.addr,
                                     timeout=self._client_timeout)
        with self._c_lock:
            self._counts["failovers"] += 1

    # -- hot path ------------------------------------------------------------

    def _on_event(self, event: Dict[str, Any]) -> None:
        """Journal-subscriber callback: runs on the EMITTER's thread —
        a bounded append, nothing else. A full buffer drops the OLDEST
        event (the collector wants the freshest context) and counts
        it; the wire is never touched here."""
        with self._buf_lock:
            if len(self._buf) >= self._buf_bound:
                self._buf.popleft()
                dropped = True
            else:
                dropped = False
            self._sseq += 1
            self._buf.append((self._sseq, event))
        if dropped:
            with self._c_lock:
                self._counts["events_dropped"] += 1

    # -- background flush ----------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval + self.flush_jitter)
            self._wake.clear()
            if self._stop.is_set():
                break
            self._flush_once()
        # final best-effort flush so a drained close ships the tail
        self._flush_once(final=True)
        try:
            self._client.close()
        except Exception:
            pass

    def _flush_once(self, final: bool = False) -> None:
        with self._flush_lock:
            self._flush_once_locked(final)

    def _flush_once_locked(self, final: bool) -> None:
        with self._buf_lock:
            batch = list(self._buf)
            self._buf.clear()
        now = time.monotonic()
        want_snap = final or (now - self._last_snapshot
                              >= self.snapshot_interval)
        if not batch and not want_snap:
            return
        t0 = time.perf_counter()

        def _send():
            if batch:
                self._client.ship_events(
                    self.origin, self.journal.run_id,
                    [dict(e, sseq=s) for s, e in batch])
            if want_snap:
                self._client.ship_snapshot(self.origin,
                                           self.registry.snapshot())

        try:
            try:
                _send()
            except Exception:
                if len(self.addrs) < 2:
                    raise
                # the HA half: fail over to the next collector and
                # retry THIS flush (a resend of an already-applied
                # batch is deduped server-side by the sseq high-water,
                # on the standby too once it has replayed the log)
                self._failover_locked()
                _send()
            if want_snap:
                self._last_snapshot = now
            with self._c_lock:
                self._counts["events_shipped"] += len(batch)
                if want_snap:
                    self._counts["snapshots"] += 1
                self._counts["flushes"] += 1
                self._counts["flush_seconds"] += time.perf_counter() - t0
        except Exception as e:
            # collector unreachable / reply lost: put the batch back
            # (bounded — overflow is counted, the hot path never
            # blocks) and try again next tick. Idempotent server-side
            # dedupe makes a partially-applied resend safe.
            with self._buf_lock:
                for event in reversed(batch):
                    self._buf.appendleft(event)
                overflow = len(self._buf) - self._buf_bound
                for _ in range(max(0, overflow)):
                    self._buf.popleft()
            with self._c_lock:
                if overflow > 0:
                    self._counts["events_dropped"] += overflow
                self._counts["flush_failures"] += 1
                self._counts["flushes"] += 1
                self._counts["flush_seconds"] += time.perf_counter() - t0
            if not final:
                _log().debug("telemetry flush to %s failed: %s: %s",
                             self.addr, type(e).__name__, e)

    def flush(self) -> None:
        """Synchronous flush (tests/drills): ship buffered events and
        a fresh snapshot NOW on the caller's thread (serialized
        against the background loop's tick)."""
        with self._flush_lock:
            self._last_snapshot = 0.0
            self._flush_once_locked(final=True)

    # -- observability -------------------------------------------------------

    def counters(self) -> Dict[str, float]:
        """Flat monotonic counters (the bench delta surface): events
        shipped/dropped, snapshots, flushes, flush failures, and the
        cumulative flush seconds (latency = seconds/flushes)."""
        with self._c_lock:
            return dict(self._counts)

    def report(self) -> Dict[str, Any]:
        out = self.counters()
        with self._buf_lock:
            out["buffered"] = len(self._buf)
        out["origin"] = self.origin
        out["addr"] = f"{self.addr[0]}:{self.addr[1]}"
        out["addrs"] = [f"{h}:{p}" for h, p in self.addrs]
        return out

    def collector_stats(self) -> Optional[Dict[str, Any]]:
        """The attached collector's ingest/store counters (``STATS``
        wire verb), or None when it is unreachable — serialized against
        the flush loop (one framed socket). The bench rows delta this
        to price the collector-side store ingest-writes a measured
        window caused."""
        with self._flush_lock:
            try:
                return self._client.stats()
            except Exception:
                return None

    def _families(self):
        from .registry import counter_family

        c = self.counters()
        labels = {"inst": self.telemetry_inst}
        return [
            counter_family("paddle_tpu_shipper_shipped_total",
                           "Journal events shipped to the collector",
                           [(labels, c["events_shipped"])]),
            counter_family(
                "paddle_tpu_shipper_dropped_total",
                "Journal events dropped by the bounded ship buffer "
                "(collector unreachable or buffer too small)",
                [(labels, c["events_dropped"])]),
            counter_family("paddle_tpu_shipper_snapshots_total",
                           "Registry snapshots shipped to the collector",
                           [(labels, c["snapshots"])]),
            counter_family("paddle_tpu_shipper_flushes_total",
                           "Shipper flush attempts (by outcome; a "
                           "'failover' marks a flush that rotated to "
                           "the next collector in the HA list)",
                           [({**labels, "outcome": "ok"},
                             c["flushes"] - c["flush_failures"]),
                            ({**labels, "outcome": "failed"},
                             c["flush_failures"]),
                            ({**labels, "outcome": "failover"},
                             c["failovers"])]),
            counter_family("paddle_tpu_shipper_flush_seconds_total",
                           "Shipper thread seconds spent flushing",
                           [(labels, round(c["flush_seconds"], 6))]),
        ]

    # -- lifecycle -----------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        self.journal.unsubscribe(self._sub)
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout)
        self.registry.remove_collector(self._telemetry_cid)


# -- the process-wide shipper -------------------------------------------------

_lock = threading.Lock()
_active: Optional[Shipper] = None
_explicit = False   # was the active shipper attached via ship_to()?


def ship_to(addr: AddrLike, origin: Optional[str] = None,
            **kw) -> Shipper:
    """Attach THE process shipper to a collector. Idempotent for the
    same address AND origin (returns the running shipper); a different
    address — or an explicitly different ``origin`` — closes the old
    shipper and starts a new one (a requested origin must never be
    silently dropped: alert keys and dashboards are built on it)."""
    return _ship(addr, origin, explicit=True, **kw)


def _ship(addr: AddrLike, origin: Optional[str], explicit: bool,
          **kw) -> Shipper:
    global _active, _explicit
    target = parse_addrs(addr)
    # construction happens UNDER the lock (it is cheap: no connect —
    # the client is lazy), so two racing first-time callers (a Trainer
    # and a PredictorServer built concurrently, both auto-shipping)
    # can never both install a shipper and leak the loser's thread +
    # journal subscription. Closing the displaced shipper (joins its
    # thread) happens outside.
    with _lock:
        if _active is not None:
            if _active.addrs == target and \
                    (origin is None or origin == _active.origin):
                _explicit = _explicit or explicit
                return _active
            if not explicit and _explicit:
                # the env-var DEFAULT yields to an explicit ship_to():
                # a later-constructed Trainer/server must not silently
                # reroute a deliberately redirected process back to
                # PDTPU_TELEMETRY_ADDR (the redirected collector would
                # page origin-down for a live process)
                return _active
        shipper = Shipper(target, origin=origin, **kw)
        old, _active = _active, shipper
        _explicit = explicit
    if old is not None:
        old.close()
    return shipper


def active_shipper() -> Optional[Shipper]:
    with _lock:
        return _active


def stop_shipping() -> None:
    """Close + detach the process shipper (tests; idempotent)."""
    global _active, _explicit
    with _lock:
        shipper, _active = _active, None
        _explicit = False
    if shipper is not None:
        shipper.close()


def maybe_auto_ship() -> Optional[Shipper]:
    """Start the process shipper iff ``PDTPU_TELEMETRY_ADDR`` is set —
    called by every ``Trainer``/``PredictorServer``/``FleetRouter``
    constructor, so pointing a whole fleet at a collector is ONE env
    var and zero code. An EXPLICITLY attached shipper (``ship_to``) is
    never displaced by the env default. Never raises: telemetry must
    not take down the process it observes."""
    addr = os.environ.get("PDTPU_TELEMETRY_ADDR")
    if not addr:
        return None
    try:
        return _ship(addr, None, explicit=False)
    except Exception as e:
        _log().warning("PDTPU_TELEMETRY_ADDR=%r: could not start the "
                       "telemetry shipper (%s: %s)", addr,
                       type(e).__name__, e)
        return None


__all__ = ["ReplicationClient", "Shipper", "ShipperClient",
           "active_shipper", "default_origin", "flush_jitter",
           "maybe_auto_ship", "parse_addr", "parse_addrs", "ship_to",
           "stop_shipping"]
