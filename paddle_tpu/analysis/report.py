"""Lint findings and reports.

The structured output of the static program checker — the analog of the
reference's pass-level diagnostics (graph_viz_pass annotations, the
ProgramDesc validators' error strings) made machine-readable: each
:class:`Finding` carries a ``family:rule`` code, a severity, a message,
and the program location (param name / eqn / argument) it anchors to.

A :class:`LintReport` is also a *collector*: while one is installed via
:func:`collect_into`, cooperating subsystems (``parallel.sharding``'s
rule-drop warnings) append findings instead of emitting ad-hoc
``warnings.warn`` calls, so a single ``analysis.check`` run gathers
everything the trace touched.

CI surface: every finding carries a stable :attr:`Finding.fingerprint`
(``family:rule|subject|shape`` — same key scheme as the profiler's
fusion diff keys), reports dedupe on it (repeated identical findings
bump :attr:`Finding.count` instead of accumulating), and the module
provides the machine consumers a gate needs: a baseline suppression
file (:func:`load_baseline` / :func:`write_baseline` /
:func:`new_findings`), per-code severity overrides
(:func:`apply_severity`), and a SARIF 2.1.0 emitter (:func:`to_sarif`).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import threading
import warnings
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..core.errors import EnforceError, enforce

SEVERITIES = ("info", "warning", "error")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

# data keys that participate in the fingerprint's shape signature: the
# STRUCTURAL identity of a finding (what it is about), never the
# measurements (byte counts, fractions) that legitimately drift run to
# run and would make baseline keys unstable. "path" (the named-jaxpr
# nesting a collective sits in) is structural too: without it every
# `collective:in-scan` psum in a program shares one fingerprint, and a
# baseline accepting one loop's exchange would silently suppress a NEW
# one introduced in a different loop
_FINGERPRINT_DATA_KEYS = ("shape", "shapes", "dtype", "axis", "bucket",
                          "buckets", "expected", "got", "path")


class LintError(EnforceError):
    """Raised by :meth:`LintReport.enforce_clean` (Trainer ``lint="error"``)."""

    def __init__(self, report: "LintReport", level: str):
        self.report = report
        super().__init__(
            f"program lint failed at level {level!r}:\n{report.render()}")


class LintWarning(UserWarning):
    """Category for findings surfaced through the warnings module
    (Trainer ``lint="warn"``)."""


@dataclasses.dataclass
class Finding:
    """One diagnostic: ``code`` is ``family:rule`` (e.g.
    ``"collective:in-scan"``), ``where`` names the anchor (parameter,
    equation, feed key), ``data`` holds rule-specific measurements
    (comm-byte estimates, shapes). ``count`` is the number of identical
    occurrences merged into this entry (reports dedupe on
    :attr:`fingerprint`)."""

    code: str
    severity: str
    message: str
    where: str = ""
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)
    count: int = 1

    def __post_init__(self):
        assert self.severity in SEVERITIES, self.severity

    @property
    def fingerprint(self) -> str:
        """Stable identity key ``family:rule|subject|shape``: the code,
        the anchor, and the structural data keys (shapes/dtypes/axes —
        never byte measurements). Two findings with the same fingerprint
        are THE SAME finding (dedupe merges them; baselines suppress by
        this key); the message text is free to improve between versions
        without invalidating every baseline."""
        sig = ",".join(f"{k}={self.data[k]!r}"
                       for k in _FINGERPRINT_DATA_KEYS if k in self.data)
        return f"{self.code}|{self.where}|{sig}"

    def __str__(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        mult = f" (x{self.count})" if self.count > 1 else ""
        return (f"{self.severity.upper():<8} {self.code:<28}{loc} "
                f"{self.message}{mult}")


class LintReport:
    """Ordered collection of findings for one checked program,
    deduplicated by :attr:`Finding.fingerprint`: re-adding an identical
    finding (startup lint + an explicit ``check_trainer`` re-run merged
    via :meth:`extend`, or a rule that fires once per trace of the same
    layer) bumps ``count`` on the existing entry instead of
    accumulating — baselines need exactly one stable key per finding."""

    def __init__(self, subject: str = "program"):
        self.subject = subject
        self.findings: List[Finding] = []
        self._by_fingerprint: Dict[Tuple[str, str], Finding] = {}

    # -- building ----------------------------------------------------------
    def add(self, code: str, severity: str, message: str, where: str = "",
            **data) -> Finding:
        return self.merge(Finding(code=code, severity=severity,
                                  message=message, where=where,
                                  data=dict(data)))

    def merge(self, f: Finding) -> Finding:
        """Add ``f``, deduplicating by fingerprint (count accumulates).
        A same-fingerprint finding at a *different* severity is kept
        separate — severity overrides must never silently swallow an
        escalated duplicate."""
        key = (f.fingerprint, f.severity)
        existing = self._by_fingerprint.get(key)
        if existing is not None:
            existing.count += f.count
            return existing
        self.findings.append(f)
        self._by_fingerprint[key] = f
        return f

    def extend(self, other: "LintReport") -> "LintReport":
        for f in other.findings:
            self.merge(dataclasses.replace(f, data=dict(f.data)))
        return self

    # -- querying ----------------------------------------------------------
    def codes(self) -> set:
        return {f.code for f in self.findings}

    def by_code(self, code: str) -> List[Finding]:
        return [f for f in self.findings if f.code == code]

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    def at_least(self, level: str) -> List[Finding]:
        rank = _SEV_RANK[level]
        return [f for f in self.findings if _SEV_RANK[f.severity] >= rank]

    def ok(self, level: str = "warning") -> bool:
        """Clean at ``level``: no findings of that severity or above."""
        return not self.at_least(level)

    # -- output ------------------------------------------------------------
    def render(self, level: str = "info") -> str:
        shown = self.at_least(level)
        if not shown:
            return f"{self.subject}: clean (no findings at level >= {level})"
        c = self.counts()
        head = (f"{self.subject}: {len(self.findings)} finding(s) "
                f"({c['error']} error, {c['warning']} warning, {c['info']} info)")
        return "\n".join([head] + [f"  {f}" for f in shown])

    def to_dict(self) -> Dict[str, Any]:
        return {
            "subject": self.subject,
            "counts": self.counts(),
            "findings": [dict(dataclasses.asdict(f),
                              fingerprint=f.fingerprint)
                         for f in self.findings],
        }

    def enforce_clean(self, level: str = "warning") -> "LintReport":
        """Raise :class:`LintError` unless :meth:`ok` at ``level``."""
        if not self.ok(level):
            raise LintError(self, level)
        return self

    def emit_warnings(self, level: str = "warning") -> "LintReport":
        """Surface findings at/above ``level`` as :class:`LintWarning`."""
        for f in self.at_least(level):
            warnings.warn(str(f), LintWarning, stacklevel=2)
        return self

    def __len__(self) -> int:
        return len(self.findings)

    def __repr__(self) -> str:
        return f"<LintReport {self.subject!r}: {self.counts()}>"


# --------------------------------------------------------------------------
# collector context — lets non-analysis subsystems contribute findings
# --------------------------------------------------------------------------

_tls = threading.local()


def active_report() -> Optional[LintReport]:
    """The innermost report installed by :func:`collect_into`, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def collect_into(report: LintReport):
    """Route cooperating subsystems' diagnostics (e.g.
    ``parallel.sharding._warn_drop``) into ``report`` for the duration
    of the block instead of the warnings module."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(report)
    try:
        yield report
    finally:
        stack.pop()


# --------------------------------------------------------------------------
# CI surface: severity overrides, baseline suppression, SARIF
# --------------------------------------------------------------------------


def apply_severity(report: LintReport,
                   overrides: Optional[Dict[str, str]] = None) -> LintReport:
    """Re-severity findings per a config mapping: keys are exact codes
    (``"moe:capacity"``) or whole families (``"collective"``); exact
    codes win. Lets a deployment promote a lint to a gate-blocking
    error (or demote a known-noisy one) without forking the rules."""
    if not overrides:
        return report
    for sev in overrides.values():
        enforce(sev in SEVERITIES,
                f"severity override must be one of {SEVERITIES}, got {sev!r}")
    old = report.findings
    report.findings = []
    report._by_fingerprint = {}
    for f in old:
        sev = overrides.get(f.code) or overrides.get(f.code.split(":")[0])
        if sev:
            f.severity = sev
        report.merge(f)   # re-merge: overrides may collapse severity splits
    return report


BASELINE_VERSION = 1


def baseline_key(subject: str, finding: Finding) -> str:
    """The key a finding is suppressed under: the checked subject (zoo
    config id / program name) scoping the finding's fingerprint — the
    same finding on two different programs is two baseline entries."""
    return f"{subject}::{finding.fingerprint}"


def write_baseline(path: str,
                   reports: Iterable[Tuple[str, LintReport]]) -> Dict[str, Any]:
    """Write a baseline suppression file covering every finding in
    ``reports`` (an iterable of ``(subject, report)``). Committing the
    file freezes today's findings as accepted debt; the gate then fails
    only on NEW fingerprints."""
    entries: Dict[str, Any] = {}
    for subject, report in reports:
        for f in report.findings:
            key = baseline_key(subject, f)
            prev = entries.get(key)
            entries[key] = {
                "code": f.code,
                "severity": f.severity,
                "where": f.where,
                "count": f.count + (prev["count"] if prev else 0),
            }
    doc = {"version": BASELINE_VERSION,
           "tool": "paddle_tpu.analysis",
           "baseline": dict(sorted(entries.items()))}
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return doc


def load_baseline(path: Optional[str]) -> Dict[str, Any]:
    """Parse a baseline file → {baseline_key: entry}. ``None`` or a
    missing file reads as the empty baseline (every finding is new)."""
    if not path:
        return {}
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return {}
    enforce(isinstance(doc, dict) and isinstance(doc.get("baseline"), dict),
            f"baseline file {path!r} is not a "
            "{'version':..,'baseline':{...}} document")
    ver = doc.get("version")
    enforce(isinstance(ver, int) and ver <= BASELINE_VERSION,
            f"baseline file {path!r} has version {ver!r}; this build reads "
            f"<= {BASELINE_VERSION}")
    return doc["baseline"]


def new_findings(subject: str, report: LintReport,
                 baseline: Dict[str, Any],
                 level: str = "warning") -> List[Finding]:
    """Findings at/above ``level`` whose baseline key is NOT suppressed
    — what a CI gate fails on. Suppression is by key presence: a
    baselined finding whose count grew is still suppressed (counts are
    measurements, not identity)."""
    return [f for f in report.at_least(level)
            if baseline_key(subject, f) not in baseline]


_SARIF_LEVEL = {"info": "note", "warning": "warning", "error": "error"}


def to_sarif(reports: Iterable[Tuple[str, LintReport]]) -> Dict[str, Any]:
    """Render ``(subject, report)`` pairs as one SARIF 2.1.0 run —
    the interchange format CI annotators (GitHub code scanning et al.)
    ingest. Rules are the distinct finding codes; each result carries
    the stable fingerprint under ``partialFingerprints`` so re-runs
    update rather than duplicate annotations."""
    rules: Dict[str, Dict[str, Any]] = {}
    results: List[Dict[str, Any]] = []
    for subject, report in reports:
        for f in report.findings:
            rules.setdefault(f.code, {
                "id": f.code,
                "shortDescription": {"text": f.code},
                "defaultConfiguration": {
                    "level": _SARIF_LEVEL[f.severity]},
            })
            results.append({
                "ruleId": f.code,
                "level": _SARIF_LEVEL[f.severity],
                "message": {"text": f"[{subject}] {f.message}"},
                "partialFingerprints": {
                    "paddleTpuLint/v1": baseline_key(subject, f)},
                "occurrenceCount": f.count,
                "locations": [{
                    "logicalLocations": [{
                        "name": f.where or subject,
                        "fullyQualifiedName": f"{subject}::{f.where}"
                                              if f.where else subject,
                    }],
                }],
            })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "paddle_tpu.analysis",
                "informationUri": "https://example.invalid/paddle_tpu",
                "rules": [rules[k] for k in sorted(rules)],
            }},
            "results": results,
        }],
    }
