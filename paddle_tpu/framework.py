"""Framework core: parameter scope, build context, Program.

This is the TPU-native redesign of the reference's central machinery:

- Reference (SURVEY §1 L1/L4): a protobuf ``ProgramDesc`` built by Python
  layer calls via ``LayerHelper.append_op`` (framework.py:1199), holding
  ``VarDesc``/``OpDesc``; parameters live in a C++ ``Scope``
  (scope.h:41) keyed by name; an Executor interprets the program.

- Here: a *function* is the program. Layer calls inside it request
  parameters by stable unique names from a build-context scope
  (:class:`BuildContext`); ``Program.init`` traces the function once to
  materialize the parameter pytree (startup-program analog), and
  ``Program.apply`` traces it for execution under ``jax.jit`` — the
  jaxpr is the ProgramDesc analog (see :meth:`Program.desc`).

Parameters are a flat ``{name: jax.Array}`` dict — the Scope — so the
reference's name-keyed variable semantics (save/load by name, per-param
attributes, selective trainability) carry over directly, while the whole
thing stays a pytree that jax.grad / pjit understand.

State variables (batch-norm moving stats etc., the reference's
non-trainable persistable vars) live in a separate collection and are
threaded functionally: ``apply`` returns ``(outputs, new_state)``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import inspect
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .core import unique_name as _unique_name
from .core.dtypes import DEFAULT_DTYPE, convert_dtype
from .core.errors import EnforceError, NotFoundError, enforce

Params = Dict[str, jax.Array]
State = Dict[str, jax.Array]


# --------------------------------------------------------------------------
# ParamAttr — per-parameter attributes (param_attr.py analog)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ParamAttr:
    """Parameter attributes (python/paddle/fluid/param_attr.py analog).

    ``regularizer`` is an object with ``apply(param, grad) -> grad`` (see
    paddle_tpu.regularizer); ``learning_rate`` is a per-param LR multiplier;
    ``trainable=False`` freezes the parameter (stop_gradient analog).
    """

    name: Optional[str] = None
    initializer: Optional[Any] = None
    learning_rate: float = 1.0
    regularizer: Optional[Any] = None
    trainable: bool = True

    @staticmethod
    def to_attr(attr: Any) -> "ParamAttr":
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return ParamAttr(trainable=False)
        raise ValueError(f"Cannot interpret param_attr: {attr!r}")


@dataclasses.dataclass
class ParamInfo:
    """Static metadata recorded at init for each parameter."""

    shape: Tuple[int, ...]
    dtype: Any
    trainable: bool = True
    learning_rate: float = 1.0
    regularizer: Optional[Any] = None
    is_distributed: bool = False  # sharded-embedding marker (distributed lookup table analog)


# --------------------------------------------------------------------------
# BuildContext — the live scope during a trace
# --------------------------------------------------------------------------


class BuildContext:
    """Per-trace context: parameter scope + name generator + RNG + mode.

    Mode 'init' creates parameters (startup program analog); mode 'apply'
    fetches them. Name generation is context-local so init/apply traces
    agree (the determinism requirement Program construction has in the
    reference too).
    """

    def __init__(
        self,
        mode: str,
        params: Params,
        state: State,
        rng: Optional[jax.Array],
        training: bool,
        param_info: Dict[str, ParamInfo],
    ):
        assert mode in ("init", "apply")
        self.mode = mode
        self.params = params
        self.state = state
        self.new_state: State = {}
        self.rng = rng
        self._rng_count = 0
        self.training = training
        self.param_info = param_info
        self.namer = _unique_name.UniqueNameGenerator()
        self.name_stack: List[str] = []

    # -- naming ------------------------------------------------------------
    def unique_name(self, key: str) -> str:
        return self.namer(key)

    def full_name(self, suffix: str) -> str:
        return "/".join(self.name_stack + [suffix]) if self.name_stack else suffix

    # -- rng ---------------------------------------------------------------
    def next_rng_key(self) -> jax.Array:
        enforce(
            self.rng is not None,
            "This program needs an RNG (dropout/random op) but none was passed; "
            "call apply(..., rng=key).",
        )
        self._rng_count += 1
        return jax.random.fold_in(self.rng, self._rng_count)

    def param_rng_key(self, name: str) -> jax.Array:
        # Deterministic per-name key: stable under call-order changes of
        # unrelated layers, mirrors per-var initializer seeds in the
        # reference's startup program (initializer.py).
        h = int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "little")
        return jax.random.fold_in(self.rng, h)


def compute_dtype():
    """Mixed-precision compute dtype (float16_transpiler/contrib float16
    analog, done right for TPU): master params stay float32; layers cast
    matmul/conv operands to this dtype — bfloat16 hits the MXU natively.
    Set via config flag 'default_compute_dtype' or amp_guard."""
    from .core.config import get_flag

    return convert_dtype(get_flag("default_compute_dtype"))


@contextlib.contextmanager
def amp_guard(dtype="bfloat16"):
    """Scoped mixed precision (fluid contrib float16 rewrite analog)."""
    from .core.config import get_flag, set_flag

    prev = get_flag("default_compute_dtype")
    set_flag("default_compute_dtype", dtype)
    try:
        yield
    finally:
        set_flag("default_compute_dtype", prev)


def cast_compute(*arrays):
    """Cast matmul/conv operands to the compute dtype. Float inputs only;
    integer arrays pass through."""
    cd = compute_dtype()
    out = tuple(a.astype(cd) if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
                else a for a in arrays)
    return out if len(out) > 1 else out[0]


_tls = threading.local()


def _ctx() -> BuildContext:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        raise EnforceError(
            "No build context active: layer functions must run inside "
            "Program.init/apply (pt.build(fn)) — the program_guard analog."
        )
    return ctx


def current_context() -> Optional[BuildContext]:
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def _use_ctx(ctx: BuildContext):
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


@contextlib.contextmanager
def reuse_names():
    """Replay unique-name counters on exit, so a block of layer calls
    invoked repeatedly (e.g. a decode step called once outside lax.scan
    to create params and again inside to reuse them) resolves to the
    SAME parameter names each time — the ParamAttr-name / while_op
    sub-block variable-reuse analog."""
    ctx = _ctx()
    snapshot = dict(ctx.namer.ids)
    try:
        yield
    finally:
        ctx.namer.ids.clear()
        ctx.namer.ids.update(snapshot)


@contextlib.contextmanager
def name_scope(name: str):
    """Hierarchical naming scope (fluid.name_scope analog)."""
    ctx = _ctx()
    ctx.name_stack.append(name)
    try:
        yield
    finally:
        ctx.name_stack.pop()


def in_training() -> bool:
    ctx = current_context()
    return bool(ctx and ctx.training)


def next_rng_key() -> jax.Array:
    return _ctx().next_rng_key()


@contextlib.contextmanager
def rng_fold(tag):
    """Fold ``tag`` (python int or traced int32) into the ambient rng
    stream for the duration of the block.

    The per-call counter in :meth:`BuildContext.next_rng_key` is a
    PYTHON int fixed at trace time, so a body traced once and executed
    many times — a ``lax.scan`` over stacked layers — would hand every
    iteration the same dropout keys. Wrapping each iteration in
    ``rng_fold(layer_index)`` decorrelates them (fold_in accepts traced
    operands). No-op when no build context / rng is active, so pure
    inference paths need no guard."""
    ctx = current_context()
    if ctx is None or ctx.rng is None:
        yield
        return
    old = ctx.rng
    ctx.rng = jax.random.fold_in(old, tag)
    try:
        yield
    finally:
        ctx.rng = old


@contextlib.contextmanager
def rng_scope(key):
    """REPLACE the ambient rng stream with ``key`` for the block.

    Where :func:`rng_fold` derives from the ambient key, this installs
    an explicitly-threaded one — the pipeline schedule needs it because
    its body runs under ``shard_map``, where the ambient key must enter
    as a replicated argument and be re-derived per (layer, microbatch,
    data-shard) inside the body. No-op when ``key`` is None or no build
    context is active."""
    ctx = current_context()
    if ctx is None or key is None:
        yield
        return
    old = ctx.rng
    ctx.rng = key
    try:
        yield
    finally:
        ctx.rng = old


# --------------------------------------------------------------------------
# Parameter / variable creation — the LayerHelper primitives
# --------------------------------------------------------------------------


def create_parameter(
    shape,
    dtype=None,
    name: Optional[str] = None,
    attr: Any = None,
    initializer: Optional[Any] = None,
    is_distributed: bool = False,
) -> jax.Array:
    """Create-or-fetch a named parameter (LayerHelper.create_parameter
    analog, layer_helper.py). In init mode runs the initializer; in apply
    mode fetches from the scope."""
    from . import initializer as _init_mod  # local import to avoid cycle

    ctx = _ctx()
    attr = ParamAttr.to_attr(attr)
    shape = tuple(int(s) for s in shape)
    dtype = convert_dtype(dtype) if dtype is not None else DEFAULT_DTYPE
    full = attr.name or ctx.full_name(name or "param")

    if ctx.mode == "init":
        if full not in ctx.params:
            init_fn = attr.initializer or initializer
            if init_fn is None:
                init_fn = _init_mod.Xavier()
            ctx.params[full] = init_fn(ctx.param_rng_key(full), shape, dtype)
            ctx.param_info[full] = ParamInfo(
                shape=shape,
                dtype=dtype,
                trainable=attr.trainable,
                learning_rate=attr.learning_rate,
                regularizer=attr.regularizer,
                is_distributed=is_distributed,
            )
    if full not in ctx.params:
        raise NotFoundError(
            f"Parameter {full!r} not found in scope (have: {sorted(ctx.params)[:20]}...)"
        )
    p = ctx.params[full]
    info = ctx.param_info.get(full)
    if info is not None and not info.trainable:
        p = jax.lax.stop_gradient(p)
    if isinstance(attr, WeightNormParamAttr):
        p = _weight_norm_reparam(p, attr, full, ctx)
    return p


def create_variable(
    shape,
    dtype=None,
    name: Optional[str] = None,
    initializer: Optional[Any] = None,
) -> jax.Array:
    """Create-or-fetch non-trainable persistable state (e.g. BN moving
    mean — the reference's persistable non-parameter vars)."""
    from . import initializer as _init_mod

    ctx = _ctx()
    shape = tuple(int(s) for s in shape)
    dtype = convert_dtype(dtype) if dtype is not None else DEFAULT_DTYPE
    full = ctx.full_name(name or "var")
    if ctx.mode == "init":
        if full not in ctx.state:
            init_fn = initializer or _init_mod.Constant(0.0)
            ctx.state[full] = init_fn(ctx.param_rng_key(full), shape, dtype)
    if full in ctx.new_state:
        return ctx.new_state[full]
    if full not in ctx.state:
        raise NotFoundError(f"State variable {full!r} not found in scope.")
    return ctx.state[full]


def assign_variable(name_suffix_or_full: str, value: jax.Array, full: bool = False) -> None:
    """Functional write to a state variable; new value is returned from
    apply() as part of new_state."""
    ctx = _ctx()
    full_name = name_suffix_or_full if full else ctx.full_name(name_suffix_or_full)
    ctx.new_state[full_name] = value


class LayerHelper:
    """Names a layer instance and scopes its parameters.

    Analog of python/paddle/fluid/layer_helper.py: each call site gets a
    unique instance name ("fc_0"); parameters created under it are
    "fc_0/w" etc.
    """

    def __init__(self, layer_type: str, name: Optional[str] = None):
        ctx = _ctx()
        self.name = name or ctx.unique_name(layer_type)

    def scope(self):
        return name_scope(self.name)

    def create_parameter(self, suffix: str, shape, dtype=None, attr=None, initializer=None,
                         is_distributed: bool = False) -> jax.Array:
        with self.scope():
            return create_parameter(
                shape, dtype=dtype, name=suffix, attr=attr, initializer=initializer,
                is_distributed=is_distributed,
            )

    def create_variable(self, suffix: str, shape, dtype=None, initializer=None) -> jax.Array:
        with self.scope():
            return create_variable(shape, dtype=dtype, name=suffix, initializer=initializer)

    def assign_variable(self, suffix: str, value: jax.Array) -> None:
        with self.scope():
            assign_variable(suffix, value)


# --------------------------------------------------------------------------
# Program — build/init/apply
# --------------------------------------------------------------------------


class Program:
    """A traced program: the ProgramDesc analog (framework.py:1404).

    ``fn`` is a pure-Python function of array inputs using
    paddle_tpu.layers ops; tracing it under init/apply materializes /
    consumes the parameter scope. ``param_info`` (populated by init)
    carries per-parameter attrs the optimizer consults — the OpRole /
    param-attr metadata of the reference.
    """

    def __init__(self, fn: Callable, name: Optional[str] = None):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "program")
        self.param_info: Dict[str, ParamInfo] = {}
        # capture the EFFECTIVE image layout at BUILD time and re-enter
        # it for every trace: pt.build(model) under layout_mode("NHWC")
        # pins the whole program to the TPU-native layout even though
        # tracing happens later (init / jitted apply / export). Programs
        # built outside any layout_mode pin NCHW — an ambient context
        # active at trace time must not leak in.
        self.layout = current_layout()

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array, *args, **kwargs) -> Tuple[Params, State]:
        """Run the startup-program analog: trace fn, create params/state.

        ``args``/``kwargs`` are example inputs (concrete or
        jax.ShapeDtypeStruct)."""
        params: Params = {}
        state: State = {}
        self.param_info = {}
        ctx = BuildContext("init", params, state, rng, training=False,
                          param_info=self.param_info)

        def _run(*a, **kw):
            with _use_ctx(ctx), layout_mode(self.layout):
                self.fn(*a, **kw)
            return 0

        args = tuple(_concretize(a) for a in args)
        kwargs = {k: _concretize(v) for k, v in kwargs.items()}
        _run(*args, **kwargs)
        return params, state

    # ------------------------------------------------------------------
    def apply(
        self,
        params: Params,
        state: Optional[State],
        *args,
        training: bool = False,
        rng: Optional[jax.Array] = None,
        **kwargs,
    ) -> Tuple[Any, State]:
        """Execute the program functionally. Returns (outputs, new_state)."""
        ctx = BuildContext(
            "apply", params, state or {}, rng, training, dict(self.param_info)
        )
        with _use_ctx(ctx), layout_mode(self.layout):
            out = self.fn(*args, **kwargs)
        new_state = dict(ctx.state)
        new_state.update(ctx.new_state)
        return out, new_state

    # ------------------------------------------------------------------
    def desc(self, params: Params, state: State, *args, **kwargs):
        """The jaxpr of this program — the ProgramDesc/debugger analog."""
        def f(p, s, *a, **kw):
            return self.apply(p, s, *a, **kw)

        return jax.make_jaxpr(f)(params, state, *args, **kwargs)

    def desc_flat(self, params: Params, state: State, *args,
                  training: bool = False, rng: Optional[jax.Array] = None,
                  **kwargs):
        """The jaxpr with NAMED inputs: returns ``(closed_jaxpr, names)``
        where ``names[i]`` is a ``(kind, name)`` pair for invar i — kind
        one of ``"param" | "state" | "arg" | "kwarg"`` — so analyses
        (paddle_tpu.analysis) can map jaxpr dataflow back to the scope's
        name-keyed variables, the way the reference's passes read
        VarDesc names off the ProgramDesc."""
        import jax.tree_util as jtu

        tree = (params, state or {}, args, kwargs)
        leaves, treedef = jax.tree.flatten(tree)
        keyed, _ = jtu.tree_flatten_with_path(tree)
        kinds = ("param", "state", "arg", "kwarg")

        def name_of(path) -> Tuple[str, str]:
            kind = kinds[path[0].idx]
            parts = []
            for k in path[1:]:
                if hasattr(k, "key"):
                    parts.append(str(k.key))
                elif hasattr(k, "idx"):
                    parts.append(str(k.idx))
                elif hasattr(k, "name"):
                    parts.append(str(k.name))
            return kind, "/".join(parts)

        def f(flat):
            p, s, a, kw = jax.tree.unflatten(treedef, flat)
            out, _ = self.apply(p, s, *a, training=training, rng=rng, **kw)
            return out

        closed = jax.make_jaxpr(f)(leaves)
        return closed, [name_of(path) for path, _ in keyed]

    def arg_names(self) -> List[str]:
        return list(inspect.signature(self.fn).parameters)

    def arg_signature(self, *args, **kwargs) -> Dict[str, Any]:
        """Bind an example call to ``fn``'s signature and return the
        name→value mapping — the traced-argument signature the
        recompilation-hazard lint (paddle_tpu.analysis) inspects before
        values are abstracted into avals."""
        try:
            bound = inspect.signature(self.fn).bind_partial(*args, **kwargs)
            return dict(bound.arguments)
        except TypeError:
            names = self.arg_names()
            out = {(names[i] if i < len(names) else f"arg{i}"): a
                   for i, a in enumerate(args)}
            out.update(kwargs)
            return out


def _concretize(x):
    if isinstance(x, jax.ShapeDtypeStruct):
        # canonicalize first: int64 specs under the default x64-off config
        # would otherwise emit a truncation UserWarning on every trace
        return jnp.zeros(x.shape, jax.dtypes.canonicalize_dtype(x.dtype))
    return x


def build(fn: Callable, name: Optional[str] = None) -> Program:
    """Wrap a layer-composition function into a Program."""
    return Program(fn, name=name)


# --------------------------------------------------------------------------
# default-program registry (framework.py default_main_program:1404 region /
# program_guard). In the traced design a Program is a function, not a
# mutable op list; the "default program" is a module slot driver code can
# swap with program_guard — the structural shape fluid scripts expect.
# --------------------------------------------------------------------------

_remat_mode = threading.local()


_layout_mode = threading.local()


@contextlib.contextmanager
def layout_mode(data_format: str = "NHWC"):
    """Ambient image-layout switch. TPU's MXU wants NHWC convolutions
    (channels on the 128-lane minor axis — NCHW graphs pay XLA
    layout-assignment transposes), but the reference API's default and
    most user model code say NCHW. Under ``layout_mode("NHWC")`` every
    conv/pool/BN layer whose ``data_format`` is left unspecified, and
    every zoo model's channel-axis bookkeeping (via
    :func:`current_layout`), follows the ambient layout — the whole
    model zoo runs TPU-native without per-model threading."""
    assert data_format in ("NCHW", "NHWC"), data_format
    old = getattr(_layout_mode, "fmt", None)
    _layout_mode.fmt = data_format
    try:
        yield
    finally:
        _layout_mode.fmt = old


def current_layout(explicit=None) -> str:
    """Resolve a layer's data_format: explicit argument wins, then the
    ambient :func:`layout_mode`, then the reference default NCHW."""
    if explicit is not None:
        return explicit
    return getattr(_layout_mode, "fmt", None) or "NCHW"


@contextlib.contextmanager
def remat_mode(enabled: bool = True, policy=None):
    """Ambient rematerialization switch (memory_optimization_transpiler
    analog, consumed at trace time). Trainer enters this around
    ``program.apply`` when ``DistStrategy.remat`` is set; zoo models
    check it via :func:`maybe_remat` around their repeated blocks, so
    ``memory_optimize()`` turns on per-block ``jax.checkpoint`` without
    the model config having to opt in.

    ``policy`` (a jax.checkpoint_policies callable or one of the names
    :func:`resolve_remat_policy` knows) tunes WHAT the checkpointed
    blocks keep: e.g. ``"dots"`` saves matmul outputs — skipping their
    MXU recompute in the backward pass while still dropping the cheap
    elementwise intermediates — the standard long-context middle ground
    between full remat and no remat."""
    resolved = resolve_remat_policy(policy)  # may raise: BEFORE any
    old = (getattr(_remat_mode, "on", False),     # thread-local writes
           getattr(_remat_mode, "policy", None))
    _remat_mode.on = bool(enabled)
    _remat_mode.policy = resolved
    try:
        yield
    finally:
        _remat_mode.on, _remat_mode.policy = old


def remat_enabled() -> bool:
    return getattr(_remat_mode, "on", False)


def remat_policy():
    return getattr(_remat_mode, "policy", None)


def resolve_remat_policy(policy):
    """Map a friendly name to a jax.checkpoint_policies callable (pass
    callables through, None means save-nothing — full recompute)."""
    if policy is None or callable(policy):
        return policy
    table = {
        "nothing": jax.checkpoint_policies.nothing_saveable,
        "everything": jax.checkpoint_policies.everything_saveable,
        "dots": jax.checkpoint_policies.dots_saveable,
        "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    enforce(policy in table,
            f"unknown remat policy {policy!r}; options: {sorted(table)}"
            " or any jax.checkpoint_policies callable")
    return table[policy]


_pipeline_mode = threading.local()


@contextlib.contextmanager
def pipeline_mode(mesh, microbatches: int, axis: str = "pp",
                  interleave: int = 1, param_layout: str = "stacked"):
    """Ambient pipeline-parallel switch (trace-time, like
    :func:`remat_mode`). Trainer enters this around ``program.apply``
    when ``DistStrategy.pp_microbatches`` is set and the mesh has a
    ``pp`` axis; zoo models route their stacked block stacks through
    ``layers.stacked.apply_stacked``, which consumes it and runs
    ``parallel.pipeline.pipeline_apply`` instead of a sequential scan.
    ``interleave`` selects the Megatron virtual-stage schedule (>1).
    ``param_layout="interleaved"`` declares that stacked param rows are
    ALREADY stored in the rank-major chunk order (Trainer.startup's
    Megatron layout, ``parallel.pipeline.interleave_perm``), so the
    schedule needs no per-step re-layout collective."""
    old = getattr(_pipeline_mode, "cfg", None)
    cfg = {"mesh": mesh, "microbatches": int(microbatches), "axis": axis,
           "interleave": max(1, int(interleave)),
           "param_layout": param_layout, "consumed": False}
    _pipeline_mode.cfg = cfg
    try:
        yield cfg
    finally:
        _pipeline_mode.cfg = old


def pipeline_config() -> Optional[dict]:
    """The active pipeline context, or None. Init-mode builds always see
    None: parameter creation must not run under shard_map."""
    ctx = current_context()
    if ctx is not None and ctx.mode == "init":
        return None
    cfg = getattr(_pipeline_mode, "cfg", None)
    if cfg is not None:
        cfg["consumed"] = True
    return cfg


_sp_mode = threading.local()


@contextlib.contextmanager
def sp_mode(mesh, axis: str = "sp", impl: str = "ring"):
    """Ambient sequence-parallel switch (trace-time, like
    :func:`pipeline_mode`). Trainer enters this around ``program.apply``
    when ``DistStrategy.sequence_parallel`` is set and the mesh has an
    ``sp`` axis; sp-aware zoo models (models/gpt.py) route their
    attention through ring attention (``impl="ring"``, zigzag layout) or
    all-to-all head-sharded attention (``impl="ulysses"``)."""
    enforce(impl in ("ring", "ulysses"),
            f"unknown sequence-parallel impl {impl!r} (ring|ulysses)")
    old = getattr(_sp_mode, "cfg", None)
    cfg = {"mesh": mesh, "axis": axis, "impl": impl, "consumed": False}
    _sp_mode.cfg = cfg
    try:
        yield cfg
    finally:
        _sp_mode.cfg = old


def sp_config() -> Optional[dict]:
    """The active sequence-parallel context, or None (always None during
    init-mode builds, mirroring :func:`pipeline_config`)."""
    ctx = current_context()
    if ctx is not None and ctx.mode == "init":
        return None
    cfg = getattr(_sp_mode, "cfg", None)
    if cfg is not None:
        cfg["consumed"] = True
    return cfg


def maybe_remat(fn: Callable, enabled: Optional[bool] = None,
                policy: Optional[Callable] = None) -> Callable:
    """Wrap ``fn`` in ``jax.checkpoint`` when remat is requested — either
    explicitly (``enabled=True``, e.g. a model config flag) or ambiently
    (``enabled=None`` and :func:`remat_enabled`). Activations inside the
    block are recomputed in the backward pass; only the block inputs (and
    anything ``policy`` saves) stay live — the TPU trade of HBM for MXU
    FLOPs that the reference's liveness-based var reuse approximated
    (memory_optimization_transpiler.py:456).

    Never wraps during init-mode builds: jax.checkpoint traces its body,
    and init-mode create_parameter writes eager arrays into the build
    context as a side effect — tracing would leak tracers into params."""
    ctx = current_context()
    if ctx is not None and ctx.mode == "init":
        return fn
    if enabled or (enabled is None and remat_enabled()):
        return jax.checkpoint(
            fn, policy=resolve_remat_policy(policy) or remat_policy())
    return fn


_default_programs: List["Program"] = []


def default_main_program() -> "Program":
    """framework.py default_main_program analog: the innermost
    program_guard program (or None outside any guard)."""
    return _default_programs[-1] if _default_programs else None


def default_startup_program() -> "Program":
    """Startup = init trace of the same Program (double-program
    convention collapses: Program.init IS the startup program)."""
    return default_main_program()


@contextlib.contextmanager
def program_guard(main_program: "Program", startup_program: Optional["Program"] = None):
    """framework.py program_guard analog."""
    _default_programs.append(main_program)
    try:
        yield main_program
    finally:
        _default_programs.pop()


class WeightNormParamAttr(ParamAttr):
    """param_attr.py WeightNormParamAttr: weight-norm reparameterization
    w = g·v/‖v‖ along ``dim`` (Salimans & Kingma). create_parameter
    detects this attr and returns the reparameterized weight; the stored
    trainables are v (under the layer's name) and g ("<name>@wn_g",
    initialized to ‖v_init‖ so the first forward equals plain init)."""

    def __init__(self, dim: Optional[int] = None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


def _weight_norm_reparam(p: jax.Array, attr: "WeightNormParamAttr", full: str,
                         ctx: "BuildContext") -> jax.Array:
    # dim=None = norm over ALL axes (scalar g), matching the reference's
    # layer_helper __norm_except_dim; an integer dim keeps a per-slice g
    dim = attr.dim
    if dim is None:
        axes = tuple(range(p.ndim))
        shape = [1] * p.ndim
    else:
        axes = tuple(a for a in range(p.ndim) if a != dim)
        shape = [1] * p.ndim
        shape[dim] = p.shape[dim]
    gname = full + "@wn_g"
    norm = jnp.sqrt(jnp.sum(jnp.square(p), axis=axes) + 1e-12)
    if ctx.mode == "init" and gname not in ctx.params:
        ctx.params[gname] = norm
        ctx.param_info[gname] = ParamInfo(
            shape=tuple(norm.shape), dtype=norm.dtype, trainable=attr.trainable,
            learning_rate=attr.learning_rate, regularizer=None,
            is_distributed=False)
    g = ctx.params[gname]
    return p / norm.reshape(shape) * g.reshape(shape)
