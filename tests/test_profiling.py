"""paddle_tpu.profiling — fusion-aware profiler + HBM/remat advisor.

Pinned here:
- the optimized-HLO text parse: computation/instruction recognition,
  fused-computation FLOP folding, while-body ``in_loop`` tagging,
  analytic dot/conv FLOPs, stable cross-run unit keys;
- golden fusion reports on three zoo models: deterministic top-k keys,
  cost monotonicity, source-level op names present, coverage in (0,1];
- the unified ``Trainer.profile_report()`` schema + the always-on
  dispatch timer, chrome-trace export, and the ``Event.profile``
  emission on ``end_epoch``;
- the HBM advisor: estimate fields, dp-shard division, the
  ``memory:fits`` / ``memory:remat-candidate`` / ``memory:over-budget``
  decision boundaries, and the remat suggestion verified against XLA's
  own ``temp_mb`` (``verify_remat``) — the suggested strategy must
  MEASURABLY reduce it on the zoo transformer;
- ``debugger.compiled_memory_usage`` never silently returns ``{}``:
  backends without ``memory_analysis()`` fall back to the jaxpr-level
  estimate with a named reason;
- the new analysis families: ``pipeline:*`` shape lints at startup and
  ``collective:hlo-*`` over the optimized HLO;
- the overhead contract: always-on report collection costs <2% of a
  K=16 fused dispatch.
"""

import json
import os
import tempfile
import time

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import analysis, debugger, optimizer as opt, profiling
from paddle_tpu.analysis import rules as _rules
from paddle_tpu.analysis.report import LintReport
from paddle_tpu.analysis.zoo import build_model
from paddle_tpu.data.feeder import stack_batches
from paddle_tpu.models import mnist
from paddle_tpu.parallel import DistStrategy
from paddle_tpu.profiling import fusion as _fusion
from paddle_tpu.profiling.steptime import StepTimer


# ---------------------------------------------------------------------------
# HLO text parse + unit attribution
# ---------------------------------------------------------------------------

_HLO_SIMPLE = """
HloModule jit_step

%fused_relu (param_0.1: f32[64,32]) -> f32[64,32] {
  %param_0.1 = f32[64,32]{1,0} parameter(0)
  %constant.0 = f32[] constant(0)
  %broadcast.0 = f32[64,32]{1,0} broadcast(f32[] %constant.0), dimensions={}
  ROOT %maximum.0 = f32[64,32]{1,0} maximum(f32[64,32]{1,0} %param_0.1, f32[64,32]{1,0} %broadcast.0), metadata={op_name="jit(step)/mlp/relu"}
}

ENTRY %main.9 (p0: f32[64,128], p1: f32[128,32]) -> f32[64,32] {
  %p0 = f32[64,128]{1,0} parameter(0)
  %p1 = f32[128,32]{1,0} parameter(1)
  %dot.1 = f32[64,32]{1,0} dot(f32[64,128]{1,0} %p0, f32[128,32]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(step)/mlp/dense/matmul"}
  ROOT %fusion.1 = f32[64,32]{1,0} fusion(f32[64,32]{1,0} %dot.1), kind=kLoop, calls=%fused_relu, metadata={op_name="jit(step)/mlp/relu"}
}
"""

_HLO_WHILE = """
HloModule jit_loop

%sum (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add.9 = f32[] add(f32[] %a, f32[] %b)
}

%body (param: (s32[], f32[256,256])) -> (s32[], f32[256,256]) {
  %param = (s32[], f32[256,256]) parameter(0)
  %gte.0 = s32[] get-tuple-element((s32[], f32[256,256]) %param), index=0
  %gte.1 = f32[256,256]{1,0} get-tuple-element((s32[], f32[256,256]) %param), index=1
  %all-reduce.1 = f32[256,256]{1,0} all-reduce(f32[256,256]{1,0} %gte.1), replica_groups={{0,1,2,3}}, to_apply=%sum, metadata={op_name="jit(step)/while/body/psum"}
  ROOT %tuple.1 = (s32[], f32[256,256]) tuple(s32[] %gte.0, f32[256,256]{1,0} %all-reduce.1)
}

%cond (param.1: (s32[], f32[256,256])) -> pred[] {
  %param.1 = (s32[], f32[256,256]) parameter(0)
  %gte.2 = s32[] get-tuple-element((s32[], f32[256,256]) %param.1), index=0
  %c.5 = s32[] constant(5)
  ROOT %lt.1 = pred[] compare(s32[] %gte.2, s32[] %c.5), direction=LT
}

ENTRY %main.20 (p: f32[256,256]) -> f32[256,256] {
  %p = f32[256,256]{1,0} parameter(0)
  %c.0 = s32[] constant(0)
  %tuple.0 = (s32[], f32[256,256]) tuple(s32[] %c.0, f32[256,256]{1,0} %p)
  %while.1 = (s32[], f32[256,256]) while((s32[], f32[256,256]) %tuple.0), condition=%cond, body=%body
  ROOT %gte.3 = f32[256,256]{1,0} get-tuple-element((s32[], f32[256,256]) %while.1), index=1
}
"""


def test_parse_hlo_module_computations_and_instructions():
    comps = _fusion.parse_hlo_module(_HLO_SIMPLE)
    assert set(comps) == {"fused_relu", "main.9"}
    assert comps["main.9"].is_entry and not comps["fused_relu"].is_entry
    ops = [i.opcode for i in comps["main.9"].instructions]
    assert ops == ["parameter", "parameter", "dot", "fusion"]
    dot = comps["main.9"].instructions[2]
    assert dot.operand_shapes == ["f32[64,128]", "f32[128,32]"]
    assert dot.op_name == "jit(step)/mlp/dense/matmul"


def test_unit_attribution_folds_fusion_and_counts_dot_flops():
    units = _fusion.module_units(_fusion.parse_hlo_module(_HLO_SIMPLE))
    by_op = {u.op: u for u in units}
    # dot: 2 * M*N*K analytic FLOPs; bytes = operands + result
    assert by_op["dot"].flops == 2.0 * 64 * 32 * 128
    assert by_op["dot"].bytes == (64 * 128 + 128 * 32 + 64 * 32) * 4
    # the fused relu's elementwise FLOPs fold into the fusion unit, and
    # the source op name survives the fold
    assert by_op["fusion"].flops == 64 * 32
    assert "mlp/relu" in by_op["fusion"].source_ops[0]
    # the absorbed computation's instructions are not units of their own
    assert all(u.computation != "fused_relu" for u in units)


def test_while_bodies_are_units_tagged_in_loop():
    units = _fusion.module_units(_fusion.parse_hlo_module(_HLO_WHILE))
    ar = [u for u in units if u.op == "all-reduce"]
    assert len(ar) == 1 and ar[0].in_loop
    assert ar[0].computation == "body"
    # the condition's compare is in-loop too; entry instructions are not
    cmp = [u for u in units if u.op == "compare"]
    assert cmp and cmp[0].in_loop
    assert all(not u.in_loop for u in units if u.computation == "main.20")


def test_unit_keys_are_stable_identities():
    units = _fusion.module_units(_fusion.parse_hlo_module(_HLO_SIMPLE))
    dot = next(u for u in units if u.op == "dot")
    # instruction NAMES are compile-dependent; the key is op|source|shape
    assert dot.key == "dot|mlp/dense/matmul|f32[64,32]"
    row = _fusion.unit_row(dot)
    assert set(row) == {"key", "name", "op", "kind", "computation",
                        "in_loop", "flops", "bytes", "out_bytes",
                        "source_ops", "cost_frac"}


def test_fusion_report_from_text_ranks_and_covers():
    rep = _fusion.fusion_report_from_text(_HLO_SIMPLE, top_k=2)
    assert rep["n_units"] == 2
    fracs = [r["cost_frac"] for r in rep["top_fusions"]]
    assert fracs == sorted(fracs, reverse=True)
    assert rep["coverage_top_k"] == pytest.approx(1.0)
    assert rep["total_flops"] == 2.0 * 64 * 32 * 128 + 64 * 32


# ---------------------------------------------------------------------------
# golden fusion reports over the zoo (the acceptance surface)
# ---------------------------------------------------------------------------


def _zoo_trainer(name, **kw):
    program, feed = build_model(name)
    tr = pt.Trainer(program, opt.Adam(1e-3), loss_name="loss", **kw)
    tr.startup(sample_feed=feed)
    return tr, feed


@pytest.mark.parametrize("name", ["mnist", "transformer", "gpt"])
def test_fusion_report_golden_zoo(name):
    tr, feed = _zoo_trainer(name)
    rep = tr.fusion_report(feed, top_k=6)
    top = rep["top_fusions"]
    assert rep["n_units"] > 0 and len(top) == min(6, rep["n_units"])
    # cost monotonicity: the ranking is by roofline cost, descending
    fracs = [r["cost_frac"] for r in top]
    assert fracs == sorted(fracs, reverse=True) and fracs[0] > 0
    assert 0 < rep["coverage_top_k"] <= 1.0
    assert rep["total_flops"] > 0 and rep["total_bytes"] > 0
    # every named unit attributes real bytes; units doing arithmetic
    # map back to source-level op names (pure data movement — a bare
    # copy — legitimately carries no metadata)
    for r in top:
        assert r["bytes"] > 0
        if r["flops"] > 0:
            assert r["source_ops"], r
    assert any(r["source_ops"] for r in top)
    # stable top-k identity: an identical recompile names the same keys
    rep2 = profiling.fusion_report(tr, feed, top_k=6)
    assert [r["key"] for r in rep2["top_fusions"]] == [r["key"] for r in top]
    # the report is cached for profile_report
    assert tr.profile_report()["fusion"]["top_fusions"] == top


# ---------------------------------------------------------------------------
# step-time breakdown + unified profile report
# ---------------------------------------------------------------------------


def _mnist_trainer(**kw):
    prog = pt.build(mnist.mlp)
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", **kw)
    return tr


def _mnist_feeds(n, bs=32, seed=0):
    r = np.random.RandomState(seed)
    return [{"image": r.randn(bs, 784).astype(np.float32),
             "label": r.randint(0, 10, (bs, 1)).astype(np.int64)}
            for _ in range(n)]


def test_step_timer_and_profile_report_schema():
    feeds = _mnist_feeds(3)
    tr = _mnist_trainer()
    tr.startup(sample_feed=feeds[0])
    for f in feeds:
        tr.step(f)
    rep = tr.profile_report()
    assert rep["steps"] == 3 and rep["dispatches"] == 3
    assert rep["avg_step_ms"] > 0 and rep["dispatch_s"] > 0
    assert set(rep["breakdown"]) == {"compute_s", "h2d_s", "host_encode_s",
                                     "reader_s", "starved_s"}
    assert rep["breakdown"]["compute_s"] > 0
    assert rep["bottleneck"] in rep["breakdown"]
    assert rep["pipeline"]["h2d_bytes"] > 0  # _put_feed recorded the puts
    assert rep["fusion"] is None             # none computed yet
    tr.reset_profile()
    assert tr.profile_report()["steps"] == 0


def test_run_steps_records_fused_dispatches():
    feeds = _mnist_feeds(4)
    tr = _mnist_trainer()
    tr.startup(sample_feed=feeds[0])
    stacked = tr._put_feed(stack_batches(feeds), stacked=True)
    tr.run_steps(stacked, k=4)
    rep = tr.step_timer.report()
    assert rep["steps"] == 4 and rep["dispatches"] == 1
    assert rep["avg_dispatch_ms"] >= rep["avg_step_ms"]


def test_export_chrome_trace():
    feeds = _mnist_feeds(2)
    tr = _mnist_trainer()
    tr.startup(sample_feed=feeds[0])
    for f in feeds:
        tr.step(f)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "trace.json")
        n = tr.export_trace(path)
        with open(path) as f:
            doc = json.load(f)
    events = doc["traceEvents"]
    assert n == len(events) >= 2
    names = {e["name"] for e in events}
    assert "trainer.step[1]" in names
    # chrome trace contract: complete events, sorted by timestamp
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in events)
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)


def test_fit_emits_profile_event_on_end_epoch():
    def reader():
        r = np.random.RandomState(0)
        for _ in range(4):
            yield [(r.randn(784).astype(np.float32),
                    np.asarray([r.randint(0, 10)], np.int64))
                   for _ in range(8)]

    tr = _mnist_trainer()
    tr.startup(sample_feed=_mnist_feeds(1, bs=8)[0])
    events = []
    pt.fit(tr, reader, num_epochs=1, feed_names=["image", "label"],
           dtypes=["float32", "int64"], event_handler=events.append)
    end = [e for e in events if e.kind == "end_epoch"]
    assert len(end) == 1
    prof = end[0].profile
    assert prof is not None and prof["steps"] == 4
    assert prof["bottleneck"] in prof["breakdown"]


def test_step_timer_span_ring_buffer_bounded():
    st = StepTimer()
    for i in range(10_000):
        st.record_dispatch(float(i), float(i) + 0.5, 1)
    assert st.dispatches == 10_000
    assert len(st.spans_us()) <= 8192  # a week-long fit must not grow RAM


def test_profiling_overhead_under_2pct_at_k16():
    """The always-on accounting contract: the per-dispatch cost of the
    recording machinery (two perf_counter reads + record_dispatch) is
    <2% of a measured K=16 fused dispatch. Measured as direct cost of
    the added calls vs the measured dispatch time — robust to CI load,
    unlike an A/B wall-clock diff of the whole loop."""
    k, n = 16, 6
    feeds = _mnist_feeds(4)
    tr = _mnist_trainer()
    tr.startup(sample_feed=feeds[0])
    stacked = tr._put_feed(
        stack_batches([feeds[i % len(feeds)] for i in range(k)]),
        stacked=True)
    out = tr.run_steps(stacked, k=k)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = tr.run_steps(stacked, k=k)
    jax.block_until_ready(out)
    dispatch_s = (time.perf_counter() - t0) / n

    st = StepTimer()
    reps = 10_000
    t0 = time.perf_counter()
    for _ in range(reps):
        st.record_dispatch(time.perf_counter(), time.perf_counter(), k,
                           "run_steps")
    per_record = (time.perf_counter() - t0) / reps
    assert per_record < 0.02 * dispatch_s, (per_record, dispatch_s)


# ---------------------------------------------------------------------------
# HBM / remat advisor
# ---------------------------------------------------------------------------


def test_memory_estimate_fields_and_remat_projection():
    tr, feed = _zoo_trainer("transformer")
    est = profiling.memory_estimate(tr, feed)
    assert est["param_bytes"] == est["param_bytes_logical"] > 0
    assert est["opt_state_bytes"] > est["param_bytes"]  # adam: 2 slots
    # the projected remat saving is the advisor's whole value prop:
    # the checkpointed trace must hold far fewer activation bytes
    assert est["activation_bytes_remat"] < 0.5 * est["activation_bytes"]
    assert est["est_total_bytes"] >= est["param_bytes"]


def test_memory_estimate_divides_by_data_shards():
    feed = _mnist_feeds(1)[0]
    tr0 = _mnist_trainer()
    tr0.startup(sample_feed=feed)
    mesh = pt.make_mesh({"dp": 8})
    tr8 = _mnist_trainer(mesh=mesh, sharding_rules=pt.parallel.replicated())
    tr8.startup(sample_feed=feed)
    e0 = profiling.memory_estimate(tr0, feed)
    e8 = profiling.memory_estimate(tr8, feed)
    assert e0["data_shards"] == 1 and e8["data_shards"] == 8
    # batch-sharded activations count per device; replicated params don't
    assert e8["activation_bytes"] <= e0["activation_bytes"] // 8 + 1
    assert e8["param_bytes"] == e0["param_bytes"]


def test_advisor_decision_boundaries():
    tr, feed = _zoo_trainer("transformer")
    est = profiling.memory_estimate(tr, feed)
    need = est["param_bytes"] + est["opt_state_bytes"]
    # generous budget -> fits
    rep = analysis.check_trainer(tr, feed, select={"memory"},
                                 hbm_budget_bytes=10 * est["est_total_bytes"])
    assert rep.codes() == {"memory:fits"}, rep.render()
    # budget that remat WOULD satisfy -> remat-candidate with numbers
    bud = int((need + est["activation_bytes"]) / 0.9) - 1
    rep = analysis.check_trainer(tr, feed, select={"memory"},
                                 hbm_budget_bytes=bud)
    assert rep.codes() == {"memory:remat-candidate"}, rep.render()
    f = rep.findings[0]
    assert f.data["projected_saving_bytes"] > 0
    assert f.data["suggested_policy"] == "dots"
    # remat already on + over budget: the advisor has no cheaper lever
    program, zfeed = build_model("transformer")
    tr2 = pt.Trainer(program, opt.Adam(1e-3), loss_name="loss",
                     strategy=DistStrategy(remat=True))
    tr2.startup(sample_feed=zfeed)
    rep = analysis.check_trainer(tr2, zfeed, select={"memory"},
                                 hbm_budget_bytes=need // 2)
    assert rep.codes() == {"memory:over-budget"}, rep.render()
    assert "remat already enabled" in rep.findings[0].message


def test_advisor_handles_wire_typed_feeds():
    """A trainer built with feed_wire receives wire-typed sample feeds
    (raw uint8 pixels); the advisor must trace at the LOGICAL dtype the
    way startup does — a review finding: the raw trace failed and every
    wire trainer degraded to memory:advisor-failed."""
    from paddle_tpu.data.wire import WireSpec

    r = np.random.RandomState(0)
    feed = {"image": r.randint(0, 256, (32, 784)).astype(np.uint8),
            "label": r.randint(0, 10, (32, 1)).astype(np.int64)}
    tr = _mnist_trainer(feed_wire={"image": WireSpec.image_uint8()})
    tr.startup(sample_feed=feed)
    est = profiling.memory_estimate(tr, feed)
    assert est["activation_bytes"] > 0
    rep = analysis.check_trainer(tr, feed, select={"memory"},
                                 hbm_budget_bytes=1 << 30)
    assert rep.codes() == {"memory:fits"}, rep.render()
    # verify_remat builds its second trainer with the same wire table
    v = profiling.verify_remat(tr, feed)
    assert v["temp_mb_before"] is not None


def test_advisor_inert_without_budget_on_cpu():
    tr, feed = _zoo_trainer("mnist")
    rep = analysis.check_trainer(tr, feed, select={"memory"})
    assert rep.codes() == set(), rep.render()


def test_verify_remat_reduces_temp_mb_pinned():
    """The advisor's suggestion measured against XLA's own number: on
    the zoo transformer (remat-wrapped encoder/decoder blocks), building
    the step under DistStrategy(remat=True) must shrink BOTH the
    jaxpr-level activation estimate (every backend) and the buffer
    assigner's temp_mb (pinned: this config measurably drops even on
    XLA:CPU)."""
    tr, feed = _zoo_trainer("transformer")
    v = profiling.verify_remat(tr, feed)
    assert v["est_activation_mb_after"] < 0.5 * v["est_activation_mb_before"]
    assert v["temp_mb_before"] is not None
    assert v["temp_mb_after"] < v["temp_mb_before"], v


def test_compiled_memory_usage_reports_source_and_falls_back(monkeypatch):
    """The old behavior silently returned {} when the backend hid
    memory_analysis(), starving the advisor; now the jaxpr estimate
    fills in with a named reason."""
    feed = _mnist_feeds(1)[0]
    tr = _mnist_trainer()
    tr.startup(sample_feed=feed)
    real = debugger.compiled_memory_usage(tr, feed)
    assert real["source"] == "xla" and real["temp_mb"] > 0

    class _NoMA:
        def compile(self):
            return self

        def memory_analysis(self):
            raise NotImplementedError("backend hides buffer stats")

    monkeypatch.setattr(debugger, "_lower_step", lambda t, f: _NoMA())
    fb = debugger.compiled_memory_usage(tr, feed)
    assert fb["source"] == "estimate"
    assert "NotImplementedError" in fb["reason"]
    assert fb["temp_mb"] > 0 and fb["argument_mb"] > 0


# ---------------------------------------------------------------------------
# new analysis families: pipeline shape + HLO collective placement
# ---------------------------------------------------------------------------


def _pipeline_report(strategy, mesh, feed):
    rep = LintReport(subject="pipeline")
    _rules.check_pipeline(strategy, mesh, feed, rep)
    return rep


def test_pipeline_lint_batch_indivisible():
    feed = {"x": np.zeros((10, 4), np.float32)}
    rep = _pipeline_report(DistStrategy(pp_microbatches=4), None, feed)
    assert rep.codes() == {"pipeline:batch-indivisible"}
    # divisible: clean (no pp axis in mesh -> no bubble row either)
    rep = _pipeline_report(DistStrategy(pp_microbatches=5), None, feed)
    assert rep.codes() == set()


def test_pipeline_lint_microbatch_vs_data_shards():
    mesh = pt.make_mesh({"dp": 8})
    feed = {"x": np.zeros((16, 4), np.float32)}
    # microbatch 16/4=4, dp=8: 4 % 8 != 0
    rep = _pipeline_report(DistStrategy(pp_microbatches=4), mesh, feed)
    assert "pipeline:microbatch-indivisible" in rep.codes()


def test_pipeline_lint_bubble_fraction():
    from paddle_tpu.parallel.pipeline import bubble_fraction
    mesh = pt.make_mesh({"pp": 4, "dp": 2})
    feed = {"x": np.zeros((8, 4), np.float32)}
    rep = _pipeline_report(DistStrategy(pp_microbatches=2), mesh, feed)
    bub = [f for f in rep.findings if f.code == "pipeline:bubble"]
    assert len(bub) == 1
    assert bub[0].severity == "warning"  # (4-1)/(2*1+4-1) = 60% > 20%
    assert bub[0].data["bubble_fraction"] == pytest.approx(
        bubble_fraction(4, 2, 1))
    # plenty of microbatches: info, not warning
    feed = {"x": np.zeros((64, 4), np.float32)}
    rep = _pipeline_report(DistStrategy(pp_microbatches=32), mesh, feed)
    bub = [f for f in rep.findings if f.code == "pipeline:bubble"]
    assert bub and bub[0].severity == "info"
    # an indivisible batch must not suppress the bubble estimate — the
    # schedule-shape warning is what tells the user the pp_microbatches
    # value itself is bad (review finding)
    feed = {"x": np.zeros((32, 4), np.float32)}
    rep = _pipeline_report(DistStrategy(pp_microbatches=3), mesh, feed)
    assert {"pipeline:batch-indivisible",
            "pipeline:bubble"} <= rep.codes(), rep.render()


def test_pipeline_lint_runs_from_check():
    """The family surfaces at startup lint time (check(strategy=...)),
    not only at pipeline_apply runtime — the whole point is naming the
    fix BEFORE anything compiles."""
    feed = _mnist_feeds(1, bs=10)[0]
    rep = analysis.check(pt.build(mnist.mlp), feed,
                         strategy=DistStrategy(pp_microbatches=4),
                         select={"pipeline"})
    assert "pipeline:batch-indivisible" in rep.codes(), rep.render()


@pytest.mark.filterwarnings("ignore::UserWarning")
def test_pipeline_lint_in_default_check_trainer_families():
    """The DEFAULT lint pass (Trainer.startup(lint=...) routes through
    check_trainer with no select) must include the pipeline family —
    a review finding: it was reachable only via an explicit select."""
    feed = _mnist_feeds(1, bs=10)[0]
    tr = _mnist_trainer(strategy=DistStrategy(pp_microbatches=4))
    tr.startup(sample_feed=feed)
    rep = analysis.check_trainer(tr, feed)
    assert "pipeline:batch-indivisible" in rep.codes(), rep.render()


def test_cli_pipeline_family(capsys):
    from paddle_tpu.analysis.__main__ import main as lint_main
    # batch 10 indivisible by 4: the CLI surfaces it and exits 1
    rc = lint_main(["--model", "mnist", "--batch", "10",
                    "--pp-microbatches", "4", "--select", "pipeline",
                    "--fail-on", "warning"])
    assert rc == 1
    assert "pipeline:batch-indivisible" in capsys.readouterr().out


def test_hlo_collective_lint_in_while_body():
    units = _fusion.module_units(_fusion.parse_hlo_module(_HLO_WHILE))
    rep = LintReport(subject="hlo")
    _rules.check_hlo_collectives(units, rep)
    assert rep.codes() == {"collective:hlo-in-while"}, rep.render()
    f = rep.findings[0]
    assert f.data["payload_bytes"] == 256 * 256 * 4
    assert "while/body/psum" in f.data["source"]


def test_hlo_collective_lint_unrolled_loop():
    """XLA:CPU unrolls small scans: N copies of the same source-level
    exchange, no while op left. The lint counts instances by source."""
    lines = ["ENTRY %main (p: f32[64]) -> f32[64] {",
             "  %p = f32[64]{0} parameter(0)"]
    for i in range(3):
        lines.append(
            f"  %ar.{i} = f32[64]{{0}} all-reduce(f32[64]{{0}} %p), "
            f"replica_groups={{{{0,1}}}}, to_apply=%sum, "
            f'metadata={{op_name="jit(f)/while/body/psum"}}')
    lines += ["  ROOT %cp = f32[64]{0} copy(f32[64]{0} %p)", "}"]
    units = _fusion.module_units(_fusion.parse_hlo_module("\n".join(lines)))
    rep = LintReport(subject="hlo")
    _rules.check_hlo_collectives(units, rep)
    assert rep.codes() == {"collective:hlo-unrolled-loop"}, rep.render()
    f = rep.findings[0]
    assert f.data["instances"] == 3
    assert f.data["payload_bytes"] == 3 * 64 * 4


def test_clean_op_name_preserves_loop_body_through_truncation():
    """Deeply nested loop-body sources keep their while/body marker
    through the 3-component display truncation — a review finding: the
    unrolled-loop lint silently missed collectives nested 2+ levels
    under the body."""
    deep = "jit(step)/while/body/transpose(jvp(model))/dense/psum"
    cleaned = _fusion._clean_op_name(deep)
    assert "while/body" in cleaned
    assert cleaned.endswith("transpose(jvp(model))/dense/psum")
    # shallow paths are untouched
    assert _fusion._clean_op_name("jit(f)/while/body/psum") == \
        "while/body/psum"
    assert _fusion._clean_op_name("jit(f)/mlp/dense/matmul") == \
        "mlp/dense/matmul"


def test_hlo_family_end_to_end_dp_grad_exchange():
    """check_trainer(hlo=True) on a dp-sharded trainer walks the real
    compiled step. The fused K>1 scan keeps its while loop (the in-while
    finding); the plain K=1 step on XLA:CPU either unrolls or hoists —
    the walk itself must complete and find the collective units."""
    feed = _mnist_feeds(1)[0]
    mesh = pt.make_mesh({"dp": 8})
    tr = _mnist_trainer(mesh=mesh, sharding_rules=pt.parallel.replicated())
    tr.startup(sample_feed=feed)
    rep = analysis.check_trainer(tr, feed, select={"hlo"}, hlo=True)
    # the walk completed (no hlo-walk-failed) — findings depend on how
    # XLA:CPU schedules the grad exchange, so only the failure mode and
    # the double-run determinism are pinned
    assert "collective:hlo-walk-failed" not in rep.codes(), rep.render()
