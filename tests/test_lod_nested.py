"""Multi-level (nested) LoD tests — lod_tensor.h:58-110 parity.

The reference's nested-LoD surface: create_lod_tensor with recursive
lengths (python/paddle/fluid/lod_tensor.py), level-selecting
sequence_expand (sequence_expand_op.cc ref_level attr), last-level
sequence_pool (sequence_pool_op.cc), and — the load-bearing consumer —
beam_search_decode emitting a (sentence-level, token-level) 2-level
LoD (beam_search_decode_op.cc), exercised end-to-end by the book
machine-translation test (test_machine_translation.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.layers as L
from paddle_tpu.layers.beam_search import (
    beam_search, beam_search_decode, beam_search_decode_lod)
from paddle_tpu.layers.sequence import LoDTensor


# ---------------------------------------------------------------------------
# structure: create / views / offsets
# ---------------------------------------------------------------------------


def test_nested_create_preserves_both_levels():
    # lod_tensor.h:58 example shape: 2 outer seqs; first holds 2 inner
    # (lens 3,2), second holds 1 inner (len 4). 9 rows total.
    data = np.arange(18, dtype=np.float32).reshape(9, 2)
    t = L.create_lod_tensor(data, [[2, 1], [3, 2, 4]])
    assert t.lod_level == 2
    assert t.recursive_sequence_lengths() == [[2, 1], [3, 2, 4]]
    assert t.lod() == [[0, 2, 3], [0, 3, 5, 9]]
    # outer level measured in rows: 3+2=5 and 4
    assert t.row_lengths(0) == [5, 4]
    np.testing.assert_array_equal(
        np.asarray(t.segment_ids(0)), [0] * 5 + [1] * 4)
    np.testing.assert_array_equal(
        np.asarray(t.segment_ids(1)), [0, 0, 0, 1, 1, 2, 2, 2, 2])


def test_single_level_triple_unpack_unchanged():
    vals, lens, seg = L.create_lod_tensor(
        np.arange(10, dtype=np.float32).reshape(5, 2), [[2, 3]])
    np.testing.assert_array_equal(np.asarray(lens), [2, 3])
    np.testing.assert_array_equal(np.asarray(seg), [0, 0, 1, 1, 1])


def test_nested_validation_rejects_inconsistent_levels():
    data = np.zeros((9, 1), np.float32)
    with pytest.raises(Exception, match="level 0"):
        LoDTensor(data, [[2, 2], [3, 2, 4]])  # 2+2 != 3 inner seqs
    with pytest.raises(Exception, match="innermost"):
        LoDTensor(data, [[2, 1], [3, 2, 3]])  # 3+2+3 != 9 rows


def test_three_level_row_lengths_compose():
    t = LoDTensor(np.zeros((10, 1), np.float32),
                  [[2], [1, 1], [4, 6]])
    assert t.row_lengths(0) == [10]
    assert t.row_lengths(1) == [4, 6]
    assert t.lod() == [[0, 2], [0, 1, 2], [0, 4, 10]]


def test_sequences_ragged_view():
    t = L.create_lod_tensor(np.arange(9, dtype=np.float32).reshape(9, 1),
                            [[2, 1], [3, 2, 4]])
    nested = t.sequences(0)
    assert len(nested) == 2 and len(nested[0]) == 2 and len(nested[1]) == 1
    np.testing.assert_array_equal(nested[0][1].ravel(), [3, 4])
    np.testing.assert_array_equal(nested[1][0].ravel(), [5, 6, 7, 8])


# ---------------------------------------------------------------------------
# level-aware ops
# ---------------------------------------------------------------------------


def test_pool_innermost_then_outer_matches_level0_sum():
    data = np.arange(9, dtype=np.float32).reshape(9, 1)
    t = L.create_lod_tensor(data, [[2, 1], [3, 2, 4]])
    # pool last level -> 3 rows, outer LoD remains (reference drops the
    # consumed level and keeps the rest)
    inner = t.pool("sum", level=-1)
    assert isinstance(inner, LoDTensor) and inner.lod_level == 1
    np.testing.assert_allclose(np.asarray(inner.values).ravel(), [3, 7, 26])
    # pooling the remaining level == pooling at level 0 directly
    outer = inner.pool("sum", level=0)
    direct = t.pool("sum", level=0)
    np.testing.assert_allclose(np.asarray(outer), np.asarray(direct))
    np.testing.assert_allclose(np.asarray(direct).ravel(), [10, 26])


def test_sequence_expand_ref_level_selects_counts():
    ref = L.create_lod_tensor(np.zeros((9, 1), np.float32),
                              [[2, 1], [3, 2, 4]])
    x = jnp.asarray([[10.0], [20.0]])
    # ref_level=0: counts are sub-sequence counts [2, 1]
    out0 = L.sequence_expand(x, ref, ref_level=0)
    np.testing.assert_array_equal(np.asarray(out0).ravel(), [10, 10, 20])
    # ref_level=1 (innermost): counts are token counts [3, 2, 4] over a
    # 3-row x
    x3 = jnp.asarray([[1.0], [2.0], [3.0]])
    out1 = L.sequence_expand(x3, ref, ref_level=1)
    np.testing.assert_array_equal(
        np.asarray(out1).ravel(), [1, 1, 1, 2, 2, 3, 3, 3, 3])


# ---------------------------------------------------------------------------
# beam-search decode -> 2-level LoD (the machine-translation round trip)
# ---------------------------------------------------------------------------


def _toy_translation_decode(batch=3, beam=2, max_len=6, vocab=7, eos=2):
    """Deterministic toy 'translation': per-source-row bias table makes
    the decode depend on the source, like the book demo's encoder
    states feeding the decoder."""
    rng = np.random.RandomState(7)
    src_bias = jnp.asarray(rng.randn(batch, vocab).astype(np.float32))
    table = jnp.asarray(jax.nn.log_softmax(
        jnp.asarray(rng.randn(vocab, vocab).astype(np.float32)), axis=-1))

    def step_fn(tokens, state):
        logp = jnp.take(table, tokens, axis=0)
        bias = jnp.repeat(src_bias, beam, axis=0)
        return jax.nn.log_softmax(logp + 0.5 * bias, axis=-1), state

    return beam_search(step_fn, {"s": jnp.zeros((batch * beam,))},
                       batch_size=batch, beam_size=beam, max_len=max_len,
                       eos_id=eos)


def test_beam_decode_emits_two_level_lod():
    eos = 2
    seqs, scores = _toy_translation_decode(eos=eos)
    valid = np.cumsum(np.asarray(seqs) == eos, axis=-1) \
        - (np.asarray(seqs) == eos)
    ids, sc = beam_search_decode_lod(seqs, valid == 0, scores=scores)

    # level 0: one group of K hypotheses per source sentence
    assert ids.lod_level == 2
    assert ids.recursive_sequence_lengths()[0] == [2, 2, 2]
    # level 1: per-hypothesis token counts; tokens match the trimmed rows
    hyp_lens = ids.recursive_sequence_lengths()[1]
    assert len(hyp_lens) == 6 and sum(hyp_lens) == ids.values.shape[0]
    nested = ids.sequences(0)
    for b in range(3):
        for k in range(2):
            ref_toks = np.asarray(seqs)[b, k][np.asarray(valid == 0)[b, k]]
            np.testing.assert_array_equal(nested[b][k].ravel(), ref_toks)
            # every finished hypothesis ends at its first EOS
            if eos in np.asarray(seqs)[b, k]:
                assert nested[b][k].ravel()[-1] == eos
    # scores LoD mirrors the hypothesis grouping, one score per hypothesis
    assert sc.recursive_sequence_lengths() == [[2, 2, 2], [1] * 6]
    np.testing.assert_allclose(np.asarray(sc.values),
                               np.asarray(scores).reshape(-1), rtol=1e-6)


def test_backtrack_decode_to_lod_round_trip():
    """beam_search_decode (backtracking form) output feeds the LoD
    packager too — the reference pipeline beam_search_op ->
    beam_search_decode_op."""
    t_steps, b, k, eos = 4, 2, 2, 2
    rng = np.random.RandomState(1)
    step_ids = rng.randint(3, 6, (t_steps, b, k)).astype(np.int32)
    step_ids[-1] = eos
    step_parents = rng.randint(0, k, (t_steps, b, k)).astype(np.int32)
    seqs, valid = beam_search_decode(step_ids, step_parents, end_id=eos)
    ids = beam_search_decode_lod(seqs, valid)
    assert ids.recursive_sequence_lengths()[0] == [k] * b
    # consume at level 0: first token of the first hypothesis per sentence
    firsts = [grp[0].ravel()[0] for grp in ids.sequences(0)]
    np.testing.assert_array_equal(
        firsts, np.asarray(seqs)[:, 0, 0])


# ---------------------------------------------------------------------------
# the full book machine-translation round trip: train -> beam decode ->
# 2-level LoD -> consume (test_machine_translation.py analog)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_seq2seq_train_decode_lod_round_trip():
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt
    from paddle_tpu.models import seq2seq

    V, E, H, S = 15, 16, 32, 5
    model = pt.build(seq2seq.make_model(src_vocab=V, trg_vocab=V, emb_dim=E,
                                        hidden=H))
    rng = np.random.RandomState(0)

    def batch(bs=16):
        src = rng.randint(3, V, (bs, S)).astype(np.int64)
        trg = np.zeros_like(src)
        trg[:, 0] = 1
        trg[:, 1:] = src[:, :-1]
        labels = np.concatenate([trg[:, 1:], np.full((bs, 1), 2)],
                                axis=1).astype(np.int64)
        return {"src_ids": src, "trg_ids": trg, "labels": labels,
                "src_lengths": np.full((bs,), S, np.int64)}

    trainer = pt.Trainer(model, opt.Adam(5e-3), loss_name="loss")
    trainer.startup(sample_feed=batch())
    for _ in range(120):
        out = trainer.step(batch())
    assert float(out["loss"]) < 1.0, float(out["loss"])

    # decode with the TRAINED params through the shared-name program
    K, T = 2, S + 2
    dec = pt.build(seq2seq.make_decoder(src_vocab=V, trg_vocab=V, emb_dim=E,
                                        hidden=H, max_len=T, beam_size=K))
    feed = batch(bs=4)
    out, _ = dec.apply(trainer.scope.params, trainer.scope.state,
                       jnp.asarray(feed["src_ids"]),
                       jnp.asarray(feed["src_lengths"]))
    seqs, scores = np.asarray(out["ids"]), np.asarray(out["scores"])
    assert seqs.shape == (4, K, T)

    # package as the reference's 2-level LoD decode output
    valid = (np.cumsum(seqs == 2, axis=-1) - (seqs == 2)) == 0
    ids, sc = beam_search_decode_lod(seqs, valid, scores=scores)
    assert ids.recursive_sequence_lengths()[0] == [K] * 4
    assert sc.recursive_sequence_lengths() == [[K] * 4, [1] * (4 * K)]

    # consume the nested output like the book demo: best hypothesis per
    # source sentence should mostly reproduce the copy task
    hits = total = 0
    for b, grp in enumerate(ids.sequences(0)):
        best = grp[0].ravel()
        want = feed["src_ids"][b][: len(best)]
        n = min(len(best), S)
        hits += (best[:n] == want[:n]).sum()
        total += n
    assert total > 0 and hits / total > 0.5, f"decode acc {hits}/{total}"


# ---------------------------------------------------------------------------
# property tests: structure invariants over random nested shapes
# ---------------------------------------------------------------------------

hypothesis = pytest.importorskip("hypothesis")  # optional dependency
from hypothesis import given, settings, strategies as st  # noqa: E402


@st.composite
def nested_lod(draw, min_len=0):
    """Random 2- or 3-level recursive_seq_lens (consistent by
    construction) + matching packed values."""
    levels = draw(st.integers(2, 3))
    top = draw(st.lists(st.integers(1, 3), min_size=1, max_size=3))
    lens = [top]
    for _ in range(levels - 1):
        n_units = sum(lens[-1])
        lens.append([draw(st.integers(min_len, 3)) for _ in range(n_units)])
    rows = sum(lens[-1])
    return lens, np.arange(rows * 2, dtype=np.float32).reshape(rows, 2)


@settings(max_examples=40, deadline=None)
@given(nested_lod())
def test_lod_structure_invariants(case):
    lens, values = case
    t = LoDTensor(values, lens)
    assert t.recursive_sequence_lengths() == [list(l) for l in lens]
    lod = t.lod()
    # offsets: monotone, start 0, each level's last offset counts the
    # units of the next level (rows for the innermost)
    for li, offs in enumerate(lod):
        assert offs[0] == 0 and all(a <= b for a, b in zip(offs, offs[1:]))
        nxt = len(lens[li + 1]) if li + 1 < len(lens) else values.shape[0]
        assert offs[-1] == nxt
    # row_lengths at EVERY level sums to the total rows, and has one
    # entry per sequence of that level
    for level in range(t.lod_level):
        rl = t.row_lengths(level)
        assert sum(rl) == values.shape[0]
        assert len(rl) == len(lens[level])


@settings(max_examples=40, deadline=None)
@given(nested_lod(min_len=1))
@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_lod_pool_composition_property(case):
    """sum-pool at the innermost level then sum-pooling the pooled rows
    at the outer level == sum-pooling level 0 directly — for ANY
    consistent nested structure (generalizes the one-case test above)."""
    lens, values = case
    t = LoDTensor(values, lens)
    inner = L.sequence_pool(t.values, t.segment_ids(-1),
                            t.num_seqs(-1), "sum")
    # group the innermost pooled rows by the composed outer structure
    outer_lens = lens[0] if t.lod_level == 2 else [
        sum(lens[1][pos:pos + n])
        for pos, n in zip(np.cumsum([0] + lens[0][:-1]), lens[0])]
    seg = np.repeat(np.arange(len(outer_lens)), outer_lens)
    direct = L.sequence_pool(t.values, t.segment_ids(0), t.num_seqs(0), "sum")
    via_inner = L.sequence_pool(inner, jnp.asarray(seg, jnp.int32),
                                len(outer_lens), "sum")
    np.testing.assert_allclose(np.asarray(via_inner), np.asarray(direct),
                               rtol=1e-5, atol=1e-5)
