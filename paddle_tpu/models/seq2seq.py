"""RNN encoder-decoder with attention — the book
rnn_encoder_decoder / machine_translation configs (test_machine_
translation.py; GRU encoder + attention decoder, the reference's only
in-tree attention, built from primitive ops).

``make_model`` is the teacher-forced training program; ``make_decoder``
is the generation program (beam/greedy over the same attention cell),
sharing parameter names with training — the reference's
machine-translation round trip trains, then decodes with
beam_search/beam_search_decode into the 2-level LoD output (pair with
``layers.beam_search_decode_lod``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import layers as L
from ..framework import LayerHelper, cast_compute
from ..layers.rnn import dynamic_gru, gru_cell_step
from .. import initializer as init


def _forward(src_ids, trg_ids, src_lengths, src_vocab, trg_vocab, emb_dim,
             hidden):
    """Shared builder: encoder + all decoder parameters + teacher-forced
    decode of ``trg_ids``. Returns (logits, aux) where aux carries the
    attention cell and the raw tensors generation needs. Parameter
    CREATION ORDER is identical for train and decode programs, so their
    names agree and a trained scope loads directly into the decoder."""
    helper = LayerHelper("seq2seq")
    # --- encoder: bi-GRU ---
    src_emb = L.embedding(src_ids, size=[src_vocab, emb_dim])
    fwd = dynamic_gru(src_emb, hidden, sequence_length=src_lengths)
    bwd = dynamic_gru(src_emb, hidden, sequence_length=src_lengths,
                      is_reverse=True)
    enc = jnp.concatenate([fwd, bwd], axis=-1)  # [b, s, 2h]
    src_mask = (jnp.arange(src_ids.shape[1])[None, :]
                < src_lengths[:, None])  # [b, s]

    # --- decoder parameters (explicit trg table so generation can step
    # token-by-token over it) ---
    trg_table = helper.create_parameter("trg_emb/w", (trg_vocab, emb_dim),
                                        jnp.float32,
                                        initializer=init.Xavier())
    w_att_enc = helper.create_parameter("att_enc/w", (2 * hidden, hidden),
                                        jnp.float32, initializer=init.Xavier())
    w_att_dec = helper.create_parameter("att_dec/w", (hidden, hidden),
                                        jnp.float32, initializer=init.Xavier())
    v_att = helper.create_parameter("att_v/w", (hidden, 1), jnp.float32,
                                    initializer=init.Xavier())
    w_x = helper.create_parameter("dec_gru_x/w", (emb_dim + 2 * hidden, 3 * hidden),
                                  jnp.float32, initializer=init.Xavier())
    w_h = helper.create_parameter("dec_gru_h/w", (hidden, 3 * hidden),
                                  jnp.float32, initializer=init.Xavier())
    b_g = helper.create_parameter("dec_gru/b", (3 * hidden,), jnp.float32,
                                  initializer=init.Constant(0.0))
    w_out = helper.create_parameter("dec_out/w", (hidden, trg_vocab), jnp.float32,
                                    initializer=init.Xavier())

    # compute-dtype carry: gru_cell_step returns the compute dtype, so
    # the scan carry must start there too
    h0 = cast_compute(jnp.tanh(L.fc(jnp.concatenate([fwd[:, -1], bwd[:, 0]],
                                                    axis=-1),
                                    hidden, name="init_state")))

    def cell(h, x_t, enc_t, enc_att_t, mask_t):
        """One decoder step: additive attention over ``enc_t`` + GRU.
        Takes the encoder tensors explicitly so generation can tile
        them per beam. Every matmul runs in the ambient compute dtype
        (the f32 weights would otherwise promote the bf16 scan carry
        and put the gate/attention dots on the slow f32 MXU path);
        attention scores soft-max in f32."""
        h, x_t, enc_t, enc_att_t, wad, va, wx, bg = cast_compute(
            h, x_t, enc_t, enc_att_t, w_att_dec, v_att, w_x, b_g)
        q = jnp.matmul(h, wad)[:, None, :]                       # [r,1,h]
        e = jnp.matmul(jnp.tanh(enc_att_t + q), va)[..., 0]      # [r,s]
        e = jnp.where(mask_t, e.astype(jnp.float32), -1e9)
        a = jax.nn.softmax(e, axis=-1).astype(enc_t.dtype)
        ctx = jnp.einsum("bs,bsd->bd", a, enc_t)                 # [r,2h]
        inp = jnp.concatenate([x_t, ctx], axis=-1)
        x_proj = jnp.matmul(inp, wx) + bg
        return gru_cell_step(x_proj, h, cast_compute(w_h))

    enc_att = jnp.matmul(enc, cast_compute(w_att_enc))  # precompute [b, s, h]

    def step(h, x_t):
        h_new = cell(h, x_t, enc, enc_att, src_mask)
        return h_new, h_new

    trg_emb = jnp.take(trg_table, trg_ids.astype(jnp.int32), axis=0)
    xs = jnp.swapaxes(trg_emb, 0, 1)
    _, hs = jax.lax.scan(step, h0, xs)
    hs = jnp.swapaxes(hs, 0, 1)  # [b, t, h]
    logits = jnp.matmul(hs, cast_compute(w_out))
    aux = {"cell": cell, "enc": enc, "enc_att": enc_att,
           "src_mask": src_mask, "h0": h0, "trg_table": trg_table,
           "w_out": w_out}
    return logits, aux


def make_model(src_vocab=2000, trg_vocab=2000, emb_dim=128, hidden=256):
    """Program fn: (src_ids [b,s], trg_ids [b,t], labels [b,t],
    src_lengths [b]) -> dict with token-mean CE loss."""

    def seq2seq(src_ids, trg_ids, labels, src_lengths):
        logits, _ = _forward(src_ids, trg_ids, src_lengths, src_vocab,
                             trg_vocab, emb_dim, hidden)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels.astype(jnp.int32)[..., None],
                                   axis=-1)[..., 0]
        nonpad = (labels != 0).astype(jnp.float32)
        loss = jnp.sum(nll * nonpad) / jnp.maximum(nonpad.sum(), 1.0)
        return {"loss": loss, "logits": logits}

    return seq2seq


def make_decoder(src_vocab=2000, trg_vocab=2000, emb_dim=128, hidden=256,
                 max_len=20, beam_size=1, bos_id=1, eos_id=2):
    """Generation program (the book machine-translation decode half):
    (src_ids [b,s], src_lengths [b]) -> {"ids" [b,K,max_len],
    "scores" [b,K]} best-first. Shares parameter names with
    ``make_model`` — apply it with a trained Trainer's params. Package
    the result as the reference's 2-level LoD with
    ``layers.beam_search_decode_lod(ids, valid, scores)``."""
    from ..layers.beam_search import beam_search

    def decode_program(src_ids, src_lengths):
        b = src_ids.shape[0]
        K = beam_size
        # identical layer-call sequence as training (dummy 1-token trg)
        # materializes every parameter under its training name
        dummy = jnp.full((b, 1), bos_id, jnp.int32)
        _, aux = _forward(src_ids, dummy, src_lengths, src_vocab, trg_vocab,
                          emb_dim, hidden)
        enc = jnp.repeat(aux["enc"], K, axis=0)
        enc_att = jnp.repeat(aux["enc_att"], K, axis=0)
        mask = jnp.repeat(aux["src_mask"], K, axis=0)
        h0 = jnp.repeat(aux["h0"], K, axis=0)
        cell, table, w_out = aux["cell"], aux["trg_table"], aux["w_out"]

        def step_fn(tokens, h):
            x_t = jnp.take(table, tokens, axis=0)
            h_new = cell(h, x_t, enc, enc_att, mask)
            # compute-dtype head (mirrors the train program): the
            # [r,h]x[h,V] dot is the largest matmul per decode step
            logits = jnp.matmul(h_new, cast_compute(w_out)).astype(jnp.float32)
            return jax.nn.log_softmax(logits, axis=-1), h_new

        seqs, scores = beam_search(step_fn, h0, batch_size=b, beam_size=K,
                                   max_len=max_len, bos_id=bos_id,
                                   eos_id=eos_id)
        return {"ids": seqs, "scores": scores}

    return decode_program
