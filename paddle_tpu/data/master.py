"""Fault-tolerant dataset task-queue — client + server manager for the
C++ master (native/master.cc).

Capability parity with the reference's Go master generation
(go/master/service.go + python/paddle/v2/master/client.py): trainers are
stateless task consumers — they lease data-shard tasks, process them,
and report finish/fail; the master requeues timed-out or failed tasks
(up to failure_max, then discards), snapshots its state to disk, and
recovers it on restart. The v2 client's reader integration
(master.client.paddle_start_get_records) maps to :func:`task_reader`.

Typical use for multi-host input sharding::

    srv = MasterServer(snapshot_path="/nfs/master.snap")   # one process
    c = MasterClient(srv.addr)                              # every trainer
    c.set_tasks([f"shard-{i}.recordio" for i in range(64)])
    reader = task_reader(c, lambda path: recordio.reader_creator(path))
"""

from __future__ import annotations

import os
import socket
import subprocess
import time
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

from ..native import build_native


def _build_server() -> str:
    return build_native("master.cc", "master_server")


class MasterServer:
    """Spawn-and-own a master_server process (etcd-backed Go master
    analog; snapshot file plays etcd's role)."""

    def __init__(self, port: int = 0, snapshot_path: Optional[str] = None,
                 failure_max: int = 3, lease_timeout_ms: int = 60000):
        binpath = _build_server()
        self._proc = subprocess.Popen(
            [binpath, str(port), snapshot_path or "-", str(failure_max),
             str(lease_timeout_ms)],
            stdout=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline().strip()
        if not line.startswith("PORT "):
            raise RuntimeError(f"master_server failed to start: {line!r}")
        self.port = int(line.split()[1])
        self.addr = ("127.0.0.1", self.port)

    def stop(self):
        if self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class MasterClient:
    """Socket client with retry/reconnect (trainers survive a master
    restart — the etcd re-registration story)."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 10.0,
                 retries: int = 30, retry_interval: float = 0.5):
        self.addr = tuple(addr)
        self.timeout = timeout
        self.retries = retries
        self.retry_interval = retry_interval
        self._sock: Optional[socket.socket] = None

    # -- transport ----------------------------------------------------------
    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def _readline(self) -> str:
        buf = bytearray()
        while True:
            c = self._sock.recv(1)
            if not c:
                raise ConnectionError("master closed connection")
            if c == b"\n":
                return buf.decode()
            buf += c

    def _read_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("master closed connection")
            out += chunk
        return bytes(out)

    def _request(self, line: str, payload: bytes = b"") -> str:
        last_err = None
        for _ in range(self.retries):
            try:
                if self._sock is None:
                    self._connect()
                self._sock.sendall(line.encode() + b"\n" + payload)
                return self._readline()
            except (OSError, ConnectionError) as e:
                last_err = e
                self._sock = None
                time.sleep(self.retry_interval)
        raise ConnectionError(f"master unreachable at {self.addr}: {last_err}")

    def close(self):
        if self._sock is not None:
            try:
                self._sock.sendall(b"QUIT\n")
            except OSError:
                pass
            self._sock.close()
            self._sock = None

    # -- task API -----------------------------------------------------------
    def add_task(self, payload) -> int:
        data = payload.encode() if isinstance(payload, str) else bytes(payload)
        resp = self._request(f"ADD {len(data)}", data)
        if not resp.startswith("OK"):
            raise RuntimeError(f"add_task: {resp}")
        return int(resp.split()[1])

    def set_tasks(self, payloads: Sequence) -> List[int]:
        return [self.add_task(p) for p in payloads]

    def get_task(self, wait: bool = True,
                 poll_interval: float = 0.2) -> Optional[Tuple[int, bytes]]:
        """Lease a task → (id, payload); None when the pass is complete.
        With ``wait``, blocks while other trainers hold the remaining
        leases (they may yet fail/time out and requeue)."""
        while True:
            resp = self._request("GET")
            if resp.startswith("TASK"):
                _, tid, ln = resp.split()
                return int(tid), self._read_exact(int(ln))
            if resp == "DONE":
                return None
            if resp == "WAIT":
                if not wait:
                    return None
                time.sleep(poll_interval)
                continue
            raise RuntimeError(f"get_task: {resp}")

    def finish_task(self, task_id: int):
        resp = self._request(f"FIN {task_id}")
        if not resp.startswith("OK"):
            raise RuntimeError(f"finish_task: {resp}")

    def fail_task(self, task_id: int):
        resp = self._request(f"FAIL {task_id}")
        if not resp.startswith("OK"):
            raise RuntimeError(f"fail_task: {resp}")

    def reset_pass(self) -> int:
        resp = self._request("RESET")
        return int(resp.split()[1])

    def status(self) -> dict:
        resp = self._request("STATUS")
        return {k: int(v) for k, v in
                (kv.split("=") for kv in resp[3:].split())}


def task_reader(client: MasterClient, make_reader: Callable[[str], Callable],
                reset_each_pass: bool = False) -> Callable:
    """Reader-combinator over leased tasks (v2 master-client reader
    analog): each task payload names a shard; ``make_reader(payload)``
    returns a reader creator over that shard. Finishes tasks on success,
    fails them on reader exceptions (→ retry on another trainer)."""

    def reader() -> Iterable:
        if reset_each_pass:
            client.reset_pass()
        while True:
            leased = client.get_task()
            if leased is None:
                return
            tid, payload = leased
            try:
                for sample in make_reader(payload.decode())():
                    yield sample
            except GeneratorExit:
                raise
            except Exception:
                client.fail_task(tid)
                continue
            client.finish_task(tid)

    return reader
