"""word2vec (skip-gram-ish CBOW) — the book/test_word2vec config:
N-gram context → next word, shared embedding."""

from __future__ import annotations

import jax.numpy as jnp

from .. import layers as L
from ..framework import ParamAttr


def make_model(dict_size=2000, emb_dim=32, hidden=256, context=4):
    def w2v(context_ids, label):
        """context_ids: [b, context] int64; label: [b, 1]."""
        embs = []
        for i in range(context):
            embs.append(L.embedding(context_ids[:, i], size=[dict_size, emb_dim],
                                    param_attr=ParamAttr(name="shared_emb/w")))
        x = L.concat(embs, axis=-1)
        x = L.fc(x, hidden, act="sigmoid")
        logits = L.fc(x, dict_size)
        loss = L.mean(L.softmax_with_cross_entropy(logits, label))
        return {"loss": loss, "logits": logits}

    return w2v
