"""Model zoo mirroring the reference's book/benchmark configs
(BASELINE.json: MNIST MLP, ResNet-50, Transformer-base, DeepFM,
BERT-base; plus VGG/LSTM from benchmark/fluid/models/)."""

from . import mnist

__all__ = ["mnist"]
