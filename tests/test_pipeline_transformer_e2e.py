"""Pipeline parallelism as a first-class training path (VERDICT r2 #4):
the zoo transformer's stacked blocks train through pipeline_apply via
Trainer + DistStrategy(pp_microbatches), with loss parity against the
same model trained without a mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.parallel import DistStrategy, transformer_tp_rules
from paddle_tpu.parallel.pipeline import bubble_fraction
from paddle_tpu.models import transformer


def _cfg(**kw):
    base = dict(src_vocab=64, trg_vocab=64, d_model=32, d_inner=64,
                num_heads=4, num_encoder_layers=4, num_decoder_layers=4,
                dropout=0.0, stacked=True)
    base.update(kw)
    return transformer.base_config(**base)


def _feed(bs, seq=12, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    src = rng.randint(3, vocab, (bs, seq)).astype(np.int32)
    trg = np.roll(src, 1, axis=1)
    trg[:, 0] = 1
    labels = np.concatenate([trg[:, 1:], np.full((bs, 1), 2)], axis=1).astype(np.int32)
    return {"src_ids": src, "trg_ids": trg, "labels": labels}


def _run_steps(trainer, feeds):
    trainer.startup(sample_feed=feeds[0])
    return [float(trainer.step(f)["loss"]) for f in feeds]


def test_stacked_matches_trainer_single_device():
    """The stacked representation itself trains and learns on one device
    (scan path)."""
    prog = pt.build(transformer.make_model(_cfg()))
    feeds = [_feed(4, seed=i) for i in range(3)]
    losses = _run_steps(pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss"), feeds)
    assert all(np.isfinite(l) for l in losses)


def _stack_from_unstacked(up, L_enc, L_dec):
    """Repack the unstacked transformer's per-layer params into the
    stacked program's param dict (fused qkv layout), so the two
    representations can be compared on identical weights."""

    def stk(names):
        return np.stack([np.asarray(up[n]) for n in names])

    sp = {}
    # encoder: per layer i → layer_norm_{2i} (ln1), mha_i, layer_norm_{2i+1},
    # ffn_i; final LN = layer_norm_{2·L_enc}
    for part, names in {
        "ln1": [f"encoder/layer_norm_{2 * i}" for i in range(L_enc)],
        "ln2": [f"encoder/layer_norm_{2 * i + 1}" for i in range(L_enc)],
    }.items():
        sp[f"encoder/encoder_stack/{part}/scale"] = stk([f"{n}/scale" for n in names])
        sp[f"encoder/encoder_stack/{part}/bias"] = stk([f"{n}/bias" for n in names])
    sp["encoder/encoder_stack/qkv/w"] = np.stack([
        np.stack([np.asarray(up[f"encoder/mha_{i}/{p}_proj/w"]) for p in "qkv"], axis=1)
        for i in range(L_enc)])
    sp["encoder/encoder_stack/qkv/b"] = np.stack([
        np.stack([np.asarray(up[f"encoder/mha_{i}/{p}_proj/b"]) for p in "qkv"])
        for i in range(L_enc)])
    sp["encoder/encoder_stack/out/w"] = stk([f"encoder/mha_{i}/out_proj/w" for i in range(L_enc)])
    sp["encoder/encoder_stack/out/b"] = stk([f"encoder/mha_{i}/out_proj/b" for i in range(L_enc)])
    for part in ("ffn_in", "ffn_out"):
        sp[f"encoder/encoder_stack/{part}/w"] = stk([f"encoder/ffn_{i}/{part}/w" for i in range(L_enc)])
        sp[f"encoder/encoder_stack/{part}/b"] = stk([f"encoder/ffn_{i}/{part}/b" for i in range(L_enc)])
    sp["encoder/layer_norm_0/scale"] = np.asarray(up[f"encoder/layer_norm_{2 * L_enc}/scale"])
    sp["encoder/layer_norm_0/bias"] = np.asarray(up[f"encoder/layer_norm_{2 * L_enc}/bias"])

    # decoder: LN numbering continues after the encoder's; mha/ffn
    # numbering is global across the program
    ln0 = 2 * L_enc + 1
    for part, off in (("ln1", 0), ("lnx", 1), ("ln2", 2)):
        names = [f"decoder/layer_norm_{ln0 + 3 * i + off}" for i in range(L_dec)]
        sp[f"decoder/decoder_stack/{part}/scale"] = stk([f"{n}/scale" for n in names])
        sp[f"decoder/decoder_stack/{part}/bias"] = stk([f"{n}/bias" for n in names])
    self_m = [f"decoder/mha_{L_enc + 2 * i}" for i in range(L_dec)]
    cross_m = [f"decoder/mha_{L_enc + 2 * i + 1}" for i in range(L_dec)]
    sp["decoder/decoder_stack/qkv/w"] = np.stack([
        np.stack([np.asarray(up[f"{m}/{p}_proj/w"]) for p in "qkv"], axis=1)
        for m in self_m])
    sp["decoder/decoder_stack/qkv/b"] = np.stack([
        np.stack([np.asarray(up[f"{m}/{p}_proj/b"]) for p in "qkv"]) for m in self_m])
    sp["decoder/decoder_stack/out/w"] = stk([f"{m}/out_proj/w" for m in self_m])
    sp["decoder/decoder_stack/out/b"] = stk([f"{m}/out_proj/b" for m in self_m])
    sp["decoder/decoder_stack/xq/w"] = stk([f"{m}/q_proj/w" for m in cross_m])
    sp["decoder/decoder_stack/xq/b"] = stk([f"{m}/q_proj/b" for m in cross_m])
    sp["decoder/decoder_stack/xkv/w"] = np.stack([
        np.stack([np.asarray(up[f"{m}/{p}_proj/w"]) for p in "kv"], axis=1)
        for m in cross_m])
    sp["decoder/decoder_stack/xkv/b"] = np.stack([
        np.stack([np.asarray(up[f"{m}/{p}_proj/b"]) for p in "kv"]) for m in cross_m])
    sp["decoder/decoder_stack/xout/w"] = stk([f"{m}/out_proj/w" for m in cross_m])
    sp["decoder/decoder_stack/xout/b"] = stk([f"{m}/out_proj/b" for m in cross_m])
    fin = ln0 + 3 * L_dec
    sp["decoder/layer_norm_1/scale"] = np.asarray(up[f"decoder/layer_norm_{fin}/scale"])
    sp["decoder/layer_norm_1/bias"] = np.asarray(up[f"decoder/layer_norm_{fin}/bias"])
    for part in ("ffn_in", "ffn_out"):
        sp[f"decoder/decoder_stack/{part}/w"] = stk(
            [f"decoder/ffn_{L_enc + i}/{part}/w" for i in range(L_dec)])
        sp[f"decoder/decoder_stack/{part}/b"] = stk(
            [f"decoder/ffn_{L_enc + i}/{part}/b" for i in range(L_dec)])
    for n in ("src/embedding_0/w", "trg/embedding_1/w", "logits_proj_0/w"):
        sp[n] = np.asarray(up[n])
    return {k: jnp.asarray(v) for k, v in sp.items()}


def test_stacked_matches_unstacked_semantics():
    """Same weights, both representations: identical losses and logits —
    pins mask handling, residual order, LN placement, fused-qkv layout
    against the per-layer reference implementation."""
    cfg_u = _cfg(stacked=False)
    cfg_s = _cfg()
    feed = _feed(4)

    prog_u = pt.build(transformer.make_model(cfg_u))
    up, _ = prog_u.init(jax.random.PRNGKey(0), **feed)
    prog_s = pt.build(transformer.make_model(cfg_s))
    sp0, _ = prog_s.init(jax.random.PRNGKey(0), **feed)
    sp = _stack_from_unstacked(up, cfg_u.num_encoder_layers, cfg_u.num_decoder_layers)
    assert set(sp) == set(sp0)
    for k in sp0:
        assert sp[k].shape == sp0[k].shape, k

    out_u, _ = prog_u.apply(up, {}, **feed)
    out_s, _ = prog_s.apply(sp, {}, **feed)
    np.testing.assert_allclose(float(out_s["loss"]), float(out_u["loss"]),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_s["logits"]),
                               np.asarray(out_u["logits"]), atol=1e-4, rtol=1e-4)


def test_stacked_decoder_is_causal():
    """Future target tokens must not influence earlier positions'
    logits (the stacked self-attention carries the causal mask)."""
    prog = pt.build(transformer.make_model(_cfg()))
    feed = _feed(4)
    params, _ = prog.init(jax.random.PRNGKey(0), **feed)
    out1, _ = prog.apply(params, {}, **feed)

    feed2 = dict(feed)
    trg = feed["trg_ids"].copy()
    trg[:, 6:] = (trg[:, 6:] + 7) % 61 + 3  # perturb the tail
    feed2["trg_ids"] = trg
    out2, _ = prog.apply(params, {}, **feed2)
    np.testing.assert_allclose(np.asarray(out1["logits"])[:, :6],
                               np.asarray(out2["logits"])[:, :6],
                               atol=1e-5, rtol=1e-5)
    # and the perturbation genuinely changed the tail
    assert not np.allclose(np.asarray(out1["logits"])[:, 6:],
                           np.asarray(out2["logits"])[:, 6:], atol=1e-3)


@pytest.mark.slow
def test_pipeline_transformer_e2e_loss_parity():
    """dp2×pp4 pipelined training == single-device training, step for
    step (same seed → same stacked init → same losses)."""
    feeds = [_feed(8, seed=i) for i in range(3)]

    prog_ref = pt.build(transformer.make_model(_cfg()))
    ref_losses = _run_steps(
        pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss"), feeds)

    mesh = pt.make_mesh({"dp": 2, "pp": 4})
    prog_pp = pt.build(transformer.make_model(_cfg()))
    pp_losses = _run_steps(
        pt.Trainer(prog_pp, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                   sharding_rules=transformer_tp_rules(),
                   strategy=DistStrategy(pp_microbatches=4)),
        feeds)

    np.testing.assert_allclose(pp_losses, ref_losses, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_pipeline_transformer_3d_dp_tp_pp():
    """dp2×tp2×pp2: stacked blocks tp-shard heads inside each stage and
    psum the projections; losses stay parity with single-device."""
    feeds = [_feed(8, seed=i) for i in range(2)]

    prog_ref = pt.build(transformer.make_model(_cfg()))
    ref_losses = _run_steps(
        pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss"), feeds)

    mesh = pt.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    prog_pp = pt.build(transformer.make_model(_cfg()))
    pp_losses = _run_steps(
        pt.Trainer(prog_pp, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                   sharding_rules=transformer_tp_rules(),
                   strategy=DistStrategy(pp_microbatches=4)),
        feeds)

    np.testing.assert_allclose(pp_losses, ref_losses, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_pipeline_transformer_interleaved_loss_parity():
    """dp2×pp2 with pp_interleave=2 (Megatron virtual stages): each rank
    holds two non-adjacent block chunks; losses stay parity with
    single-device, step for step."""
    feeds = [_feed(8, seed=i) for i in range(3)]

    prog_ref = pt.build(transformer.make_model(_cfg()))
    ref_losses = _run_steps(
        pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss"), feeds)

    mesh = pt.make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    prog_pp = pt.build(transformer.make_model(_cfg()))
    pp_losses = _run_steps(
        pt.Trainer(prog_pp, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                   sharding_rules=transformer_tp_rules(),
                   strategy=DistStrategy(pp_microbatches=4,
                                         pp_interleave=2)),
        feeds)

    np.testing.assert_allclose(pp_losses, ref_losses, atol=2e-4, rtol=2e-4)


def test_stacked_params_sharded_over_pp():
    """Structural check: the stacked leaves actually land pp-sharded
    (leading layer dim) under the rule table — exists ≠ integrated was
    the r2 finding; this pins the integration."""
    mesh = pt.make_mesh({"dp": 2, "pp": 4})
    prog = pt.build(transformer.make_model(_cfg()))
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    sharding_rules=transformer_tp_rules(),
                    strategy=DistStrategy(pp_microbatches=4))
    tr.startup(sample_feed=_feed(8))
    qkv = [k for k in tr.scope.params if k.endswith("encoder_stack/qkv/w")]
    assert qkv, sorted(tr.scope.params)[:20]
    spec = tr.scope.params[qkv[0]].sharding.spec
    assert spec[0] == "pp", spec


@pytest.mark.slow
def test_interleaved_rest_layout_checkpoints_logical(tmp_path):
    """Trainer with pp_interleave=2 stores stacked rows chunk-
    interleaved at rest (Megatron layout, no per-step re-layout), but
    checkpoints in LOGICAL order: a single-device trainer restores the
    npz directly and matches eval; the interleaved trainer restores its
    own checkpoint and keeps training."""
    from paddle_tpu import io as pio

    feed = _feed(8, seed=13)
    mesh = pt.make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    prog = pt.build(transformer.make_model(_cfg()))
    tr = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                    sharding_rules=transformer_tp_rules(),
                    strategy=DistStrategy(pp_microbatches=4,
                                          pp_interleave=2))
    tr.startup(sample_feed=feed)
    assert tr._pp_perm, "interleaved trainer should have permuted leaves"
    tr.step(feed)
    ev = float(tr.eval(feed)["loss"])
    pio.save_trainer(str(tmp_path / "ck"), tr)

    # logical on disk: a pp-less trainer restores and agrees
    prog_s = pt.build(transformer.make_model(_cfg()))
    tr_s = pt.Trainer(prog_s, opt.Adam(1e-3), loss_name="loss")
    tr_s.startup(sample_feed=feed)
    # a mesh change is explicit now: reshard_restore is the door (the
    # {dp,pp} -> single-device restore is the dp N->1 elastic case)
    pt.resilience.reshard_restore(str(tmp_path / "ck"), tr_s,
                                  sample_feed=feed)
    np.testing.assert_allclose(float(tr_s.eval(feed)["loss"]), ev,
                               atol=2e-4, rtol=2e-4)

    # and the interleaved trainer round-trips its own checkpoint
    before = {k: np.asarray(v) for k, v in tr.scope.params.items()
              if k in tr._pp_perm}
    pio.load_trainer(str(tmp_path / "ck"), tr)
    for k, v in before.items():
        np.testing.assert_allclose(np.asarray(tr.scope.params[k]), v,
                                   atol=1e-6)
    assert np.isfinite(float(tr.step(feed)["loss"]))


@pytest.mark.slow
def test_pipeline_composes_with_grad_accumulation():
    """pp_microbatches × accum_steps: the scan-microbatched feed halves
    feed the pipeline's own microbatching; parity vs plain single-device
    accumulation."""
    feeds = [_feed(16, seed=9)]

    prog_ref = pt.build(transformer.make_model(_cfg()))
    ref = _run_steps(
        pt.Trainer(prog_ref, opt.Adam(1e-3), loss_name="loss",
                   strategy=DistStrategy(accum_steps=2)), feeds)

    mesh = pt.make_mesh({"dp": 2, "pp": 4})
    prog_pp = pt.build(transformer.make_model(_cfg()))
    pp = _run_steps(
        pt.Trainer(prog_pp, opt.Adam(1e-3), loss_name="loss", mesh=mesh,
                   sharding_rules=transformer_tp_rules(),
                   strategy=DistStrategy(accum_steps=2, pp_microbatches=4)),
        feeds)
    np.testing.assert_allclose(pp, ref, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_pipeline_trained_model_eval_and_reshape_restore(tmp_path):
    """The pp-sharded stacked model evaluates (eval enters the same
    pipeline ctx as training, so its collectives ride the same mesh
    axes) and its sharded checkpoint restores onto a DIFFERENT mesh
    factoring with identical losses (the pserver slice/merge analog,
    io.py:881)."""
    from paddle_tpu import io as pio

    feed = _feed(16, seed=10)
    mesh_a = pt.make_mesh({"dp": 2, "pp": 4})
    prog = pt.build(transformer.make_model(_cfg()))
    tr_a = pt.Trainer(prog, opt.Adam(1e-3), loss_name="loss", mesh=mesh_a,
                      sharding_rules=transformer_tp_rules(),
                      strategy=DistStrategy(pp_microbatches=4))
    tr_a.startup(sample_feed=feed)
    tr_a.step(feed)
    ev = float(tr_a.eval(feed)["loss"])
    assert np.isfinite(ev)
    pio.save_trainer_sharded(str(tmp_path / "ck"), tr_a, async_save=False)

    mesh_b = pt.make_mesh({"dp": 4, "pp": 2})
    prog_b = pt.build(transformer.make_model(_cfg()))
    tr_b = pt.Trainer(prog_b, opt.Adam(1e-3), loss_name="loss", mesh=mesh_b,
                      sharding_rules=transformer_tp_rules(),
                      strategy=DistStrategy(pp_microbatches=4))
    tr_b.startup(sample_feed=feed)
    pio.load_trainer_sharded(str(tmp_path / "ck"), tr_b)
    np.testing.assert_allclose(float(tr_b.eval(feed)["loss"]), ev,
                               atol=1e-5, rtol=1e-5)
    # and training continues on the new factoring
    assert np.isfinite(float(tr_b.step(feed)["loss"]))


def test_stacked_dropout_trains_and_infers():
    """Dropout now works on the scan path (per-layer rng_fold): training
    produces a finite stochastic loss, inference is deterministic and
    matches the dropout-0 program exactly (same params)."""
    prog = pt.build(transformer.make_model(_cfg(dropout=0.3)))
    feed = _feed(4)
    params, state = prog.init(jax.random.PRNGKey(0), **feed)
    out1, _ = prog.apply(params, state, rng=jax.random.PRNGKey(1),
                         training=True, **feed)
    out2, _ = prog.apply(params, state, rng=jax.random.PRNGKey(2),
                         training=True, **feed)
    assert np.isfinite(float(out1["loss"]))
    # different rng -> different dropout masks -> different loss
    assert float(out1["loss"]) != float(out2["loss"])
    # inference: dropout is a no-op, so the dropout-0 program agrees
    ref = pt.build(transformer.make_model(_cfg(dropout=0.0)))
    out_inf, _ = prog.apply(params, state, training=False, **feed)
    ref_inf, _ = ref.apply(params, state, training=False, **feed)
    np.testing.assert_allclose(float(out_inf["loss"]),
                               float(ref_inf["loss"]), rtol=1e-6)


def test_stacked_dropout_masks_decorrelate_across_layers():
    """The scan body is traced once; without rng_fold every layer would
    get the SAME dropout mask. Statistical pin: an L-layer stack of
    dropout-only blocks keeps ~p^L of elements with independent masks
    vs ~p with a shared mask."""
    from paddle_tpu.layers import stacked as S

    p_keep = 0.5
    L, n = 2, 20000

    def make_drop_block(num_heads, use_flash, causal, tp_axis, sp_cfg,
                        dropout_rate=0.0):
        def block(x, lp):
            return S._drop(x, dropout_rate)
        return block

    def net(x):
        stack = {"dummy": jnp.zeros((L, 1))}
        return {"y": S.apply_stacked(x, stack, make_drop_block,
                                     dropout_rate=1 - p_keep)}

    prog = pt.build(net)
    x = np.ones((1, n), np.float32)
    params, state = prog.init(jax.random.PRNGKey(0), x=x)
    out, _ = prog.apply(params, state, rng=jax.random.PRNGKey(3),
                        training=True, x=x)
    frac = float((np.asarray(out["y"]) != 0).mean())
    # independent masks: E[frac]=0.25, sd~0.003; shared mask: 0.5
    assert abs(frac - p_keep ** L) < 0.03,         f"kept {frac:.3f}; shared-mask reuse would keep ~{p_keep}"


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_dropout_on_pipeline_path():
    """The pipeline schedule threads rng per (layer, microbatch,
    data-shard): training under pp with dropout>0 yields finite,
    step-deterministic, rng-sensitive losses; eval stays deterministic
    (round-4 verdict #5, closing layers/stacked.py's old TODO)."""
    from paddle_tpu.framework import pipeline_mode

    devs = jax.devices("cpu")[:2]
    mesh = jax.sharding.Mesh(np.array(devs).reshape(2), ("pp",))
    prog = pt.build(transformer.make_model(_cfg(dropout=0.3)))
    feed = _feed(4)
    params, state = prog.init(jax.random.PRNGKey(0), **feed)
    with pipeline_mode(mesh, microbatches=2):
        o1, _ = prog.apply(params, state, rng=jax.random.PRNGKey(1),
                           training=True, **feed)
        o1b, _ = prog.apply(params, state, rng=jax.random.PRNGKey(1),
                            training=True, **feed)
        o2, _ = prog.apply(params, state, rng=jax.random.PRNGKey(2),
                           training=True, **feed)
        # same key → same masks; different key → different masks
        np.testing.assert_allclose(float(o1["loss"]), float(o1b["loss"]),
                                   rtol=1e-6)
        assert abs(float(o1["loss"]) - float(o2["loss"])) > 1e-6
        # eval is deterministic (dropout no-op) and matches the scan
        # path bit-for-bit outside the pipeline ctx
        ev, _ = prog.apply(params, state, training=False, **feed)
    ev_scan, _ = prog.apply(params, state, training=False, **feed)
    np.testing.assert_allclose(np.asarray(ev["loss"]),
                               np.asarray(ev_scan["loss"]),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_pipeline_dropout_masks_decorrelate():
    """Distinct dropout masks per (layer, microbatch): a pp run of an
    identity stack with dropout must not reuse one mask across layers
    or across microbatches (the pre-fix failure mode: the scheduled
    body is traced once, so an unfolded key would repeat)."""
    from paddle_tpu.framework import pipeline_mode
    from paddle_tpu.layers.stacked import apply_stacked

    devs = jax.devices("cpu")[:2]
    mesh = jax.sharding.Mesh(np.array(devs).reshape(2), ("pp",))
    L, B, D = 2, 4, 64
    stacked = {"w": jnp.ones((L, 1), jnp.float32)}

    def make_block(num_heads, use_flash, causal, tp_axis, sp_cfg,
                   dropout_rate=0.0):
        def block(x, lp):
            from paddle_tpu.layers.nn import dropout
            return dropout(x * lp["w"][0], dropout_rate,
                           dropout_implementation="upscale_in_train")
        return block

    def net(x):
        h = apply_stacked(x, stacked, make_block, num_heads=1,
                          dropout_rate=0.5)
        return {"out": h, "loss": jnp.mean(h)}

    prog = pt.build(net)
    x = np.ones((B, D), np.float32)
    params, state = prog.init(jax.random.PRNGKey(0), x=x)
    with pipeline_mode(mesh, microbatches=2):
        out, _ = prog.apply(params, state, rng=jax.random.PRNGKey(7),
                            training=True, x=x)
    kept = np.asarray(out["out"]) != 0.0
    # microbatch 0 = rows [0,2), microbatch 1 = rows [2,4): the two
    # microbatches must see different composite masks
    assert not np.array_equal(kept[:2], kept[2:])
    # and the composite keep-rate of two layers of 0.5-dropout is ~0.25:
    # a single shared mask across layers would leave ~0.5 — distinguish
    rate = kept.mean()
    assert 0.1 < rate < 0.4, rate


def test_bubble_fraction():
    assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction(4, 16) == pytest.approx(3 / 19)
    assert bubble_fraction(1, 8) == 0.0
    # raising microbatches amortizes the bubble monotonically
    fs = [bubble_fraction(4, m) for m in (2, 4, 8, 16, 64)]
    assert fs == sorted(fs, reverse=True)
