"""Checkpoint save/load + inference export.

Analog of python/paddle/fluid/io.py: save_vars/save_persistables
(io.py:89/:252 — a program of save ops per var), load_persistables
(io.py:464), save/load_inference_model (io.py:544/:669 — prune +
serialized ProgramDesc). Here persistable state is name-keyed pytrees →
a single .npz per collection (+ JSON meta); the inference model is a
serialized ``jax.export`` StableHLO artifact next to its weights — the
ProgramDesc-file analog, portable across processes and (with matching
XLA version) machines.

Resharding on load (the pserver slice/merge analog,
io.py:881 _load_slice_up_vars): arrays are saved unsharded (fully
gathered); loading places them per the current mesh/rules, so mesh
reshapes between save and load work by construction. A mesh CHANGE is
gated, not implicit: ``load_trainer`` raises a structured
``resilience.ReshardError`` on a ``meta.mesh_axes`` mismatch, and
``resilience.reshard_restore`` is the explicit elastic door (static
feasibility proof + bit-exact re-placement).

Exception to "saved unsharded": ``DistStrategy(zero_sharding=True)``
checkpoints are SHARD-AWARE — params and partitioned optimizer leaves
live in per-shard ``*.zero{i}.npz`` files (one ``(k,)`` row each,
written gather-free from each owning device), with the shard count +
logical flat spec in ``meta.zero``. Same-N restore is shard-local; any
layout change (N→M, ZeRO↔replicated) trips the same ``ReshardError``
gate and goes through the elastic door, which gathers the rows back to
logical on the host (``load_persistables`` does this transparently)
and repartitions for the target.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core.errors import EnforceError, enforce

SEP = "||"  # path separator for nested pytree keys (param names use '/')


def _log():
    return logging.getLogger("paddle_tpu.io")


class InvalidRequest(EnforceError, ValueError):
    """A serving/inference feed failed structural validation: missing or
    extra feed key, shape or dtype mismatch, off-bucket batch size, or a
    non-finite payload. Carries ``field`` (the offending feed name) and
    ``reason`` so servers can answer with a structured error instead of
    a raw ``KeyError`` or an XLA abort."""

    def __init__(self, field: str, reason: str):
        super().__init__(f"invalid request: feed {field!r} {reason}")
        self.field = field
        self.reason = reason

# numpy's npz format stores ml_dtypes extension types (bfloat16, fp8) as
# raw void bytes that can't round-trip; encode them as a same-width
# integer view with a "@dtype" key suffix instead.
_EXOTIC_DTYPES = {"bfloat16": np.uint16,
                  "float8_e4m3fn": np.uint8, "float8_e5m2": np.uint8}


# -- pytree <-> flat dict ----------------------------------------------------


def _mangle_key(prefix: str, dtype: np.dtype):
    """(stored key, stored dtype) for a leaf of logical dtype ``dtype``
    named ``prefix`` — the key/dtype half of :func:`_mangle_leaf`,
    shared with the spec-only flattener (:func:`flat_spec`) so a spec
    computed without touching array data can never disagree with what
    ``save_persistables`` actually writes."""
    if dtype.name in _EXOTIC_DTYPES:
        return f"{prefix}@{dtype.name}", np.dtype(_EXOTIC_DTYPES[dtype.name])
    if (prefix.endswith("@raw")
            or any(prefix.endswith(f"@{dt}") and dtype == enc
                   for dt, enc in _EXOTIC_DTYPES.items())):
        # a genuine integer param whose NAME ends in '@bfloat16' etc.
        # (or '@raw' itself) would be indistinguishable from our
        # encoding on load — escape with a '@raw' marker (load strips
        # exactly one suffix, so escaping nests safely)
        return f"{prefix}@raw", dtype
    return prefix, dtype


def _mangle_leaf(prefix: str, arr: np.ndarray):
    """Single source of truth for leaf-key mangling: the npz member name
    written by _flatten and the meta.json name written by
    _flat_leaves_in_tree_order must stay byte-identical (the native
    predictor looks meta names up in the npz table)."""
    key, dtype = _mangle_key(prefix, arr.dtype)
    return key, (arr.view(dtype) if dtype != arr.dtype else arr)


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif tree is None:
        pass
    else:
        key, val = _mangle_leaf(prefix, np.asarray(tree))
        out[key] = val
    return out


def _flat_leaves_in_tree_order(tree: Any, prefix: str = ""):
    """(npz_key, value) pairs in jax's pytree flatten order (per-level
    sorted ORIGINAL keys, depth-first) — NOT sorted mangled npz keys,
    which diverge ('a2' vs 'a||x' sorts differently than 'a' vs 'a2';
    '@bfloat16' suffixes shift order). Used by save_inference_model to
    bind npz members to executable argument positions."""
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree, key=str):
            out += _flat_leaves_in_tree_order(
                tree[k], f"{prefix}{SEP}{k}" if prefix else str(k))
    elif tree is None:
        pass
    else:
        out.append(_mangle_leaf(prefix, np.asarray(tree)))
    return out


def flat_spec(tree: Any, prefix: str = "") -> Dict[str, Dict[str, Any]]:
    """The flat ``{npz key: {"shape": [...], "dtype": "..."}}`` spec
    :func:`save_persistables` would record for ``tree`` — computed from
    shapes/dtypes ONLY (no ``device_get``, no flattened copies): the
    trainer-side half of the static checkpoint-compatibility check in
    ``analysis.contracts``. Key mangling (exotic-dtype ``@bfloat16``
    suffixes, ``@raw`` escapes) goes through the same :func:`_mangle_key`
    the save path uses, so the two can never drift."""
    out: Dict[str, Dict[str, Any]] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(flat_spec(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif tree is None:
        pass
    else:
        shape = getattr(tree, "shape", None)
        dtype = getattr(tree, "dtype", None)
        if shape is None or dtype is None:
            arr = np.asarray(tree)
            shape, dtype = arr.shape, arr.dtype
        key, stored = _mangle_key(prefix, np.dtype(dtype))
        out[key] = {"shape": list(shape), "dtype": str(np.dtype(stored))}
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    import ml_dtypes

    out: Dict[str, Any] = {}
    for key, v in flat.items():
        if "@" in key:
            maybe_key, _, dtname = key.rpartition("@")
            # only strip the suffix for markers *we* appended on save; a
            # user param literally named "x@foo" passes through intact,
            # and "x@bfloat16" of genuine integer dtype arrives escaped
            # as "x@bfloat16@raw"
            if dtname == "raw":
                key = maybe_key
            elif dtname in _EXOTIC_DTYPES and v.dtype == _EXOTIC_DTYPES[dtname]:
                key = maybe_key
                v = v.view(np.dtype(getattr(ml_dtypes, dtname)))
        parts = key.split(SEP)
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


# -- persistables ------------------------------------------------------------


def save_persistables(dirname: str, params: Dict[str, jax.Array],
                      state: Optional[Dict[str, jax.Array]] = None,
                      opt_state: Optional[Dict[str, Any]] = None,
                      meta: Optional[Dict[str, Any]] = None) -> Dict[str, Dict[str, Any]]:
    """Save all persistable vars (save_persistables analog, io.py:252).
    Sharded arrays are gathered to host first. Returns the flat
    shape/dtype spec per npz file ({filename: {flat key: {"shape",
    "dtype"}}}) — ``save_trainer`` records it in the checkpoint
    manifest."""
    os.makedirs(dirname, exist_ok=True)
    spec: Dict[str, Dict[str, Any]] = {}

    def _dump(name, tree):
        flat = _flatten(jax.device_get(tree))
        np.savez(os.path.join(dirname, name), **flat)
        spec[name] = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                      for k, v in flat.items()}

    _dump("params.npz", params)
    if state is not None:
        _dump("state.npz", state)
    if opt_state is not None:
        _dump("opt_state.npz", opt_state)
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump(meta or {}, f)
    return spec


def _zero_split_flat(tree: Any, n: int, partitioned) -> Tuple[List[Dict[str, np.ndarray]],
                                                              Dict[str, np.ndarray]]:
    """Split a ZeRO-partitioned scope tree into n per-shard flat dicts
    (one host ``(k,)`` row each, read from ``addressable_shards`` — no
    all-gather on the save path) plus one flat dict of the replicated
    leaves. ``partitioned`` is the ZeroSpec's mangled-key set."""
    shard_flats: List[Dict[str, np.ndarray]] = [dict() for _ in range(n)]
    base: Dict[str, np.ndarray] = {}

    def walk(t, pfx):
        if isinstance(t, dict):
            for k, v in t.items():
                walk(v, f"{pfx}{SEP}{k}" if pfx else str(k))
            return
        if t is None:
            return
        key, _ = _mangle_key(pfx, np.dtype(t.dtype))
        if key not in partitioned:
            k2, val = _mangle_leaf(pfx, np.asarray(jax.device_get(t)))
            base[k2] = val
            return
        rows: List[Optional[np.ndarray]] = [None] * n
        for s in t.addressable_shards:
            lo = int(s.index[0].start or 0)
            data = np.asarray(s.data)
            for j in range(data.shape[0]):
                if rows[lo + j] is None:
                    rows[lo + j] = data[j]
        enforce(all(r is not None for r in rows),
                f"save_trainer(zero_sharding): shard rows of {pfx!r} are "
                "not all process-addressable — multi-host ZeRO saves need "
                "every host to write its own shard files (not implemented)")
        for i in range(n):
            shard_flats[i][key] = _mangle_leaf(pfx, rows[i])[1]

    walk(tree, "")
    return shard_flats, base


def _save_zero_persistables(dirname: str, trainer, params, state, opt_state,
                            meta) -> Dict[str, Dict[str, Any]]:
    """ZeRO variant of :func:`save_persistables`: partitioned leaves go
    to per-shard files ``params.zero{i}.npz`` / ``opt_state.zero{i}.npz``
    (each member one ``(k,)`` row, gather-free), replicated opt leaves
    keep the base ``opt_state.npz``. ``meta.zero`` records the shard
    count + the LOGICAL flat spec (the N→M gather's reassembly map and
    the contract checker's currency); the returned spec covers the REAL
    files for the manifest CRC pass."""
    os.makedirs(dirname, exist_ok=True)
    zero = trainer._zero
    spec: Dict[str, Dict[str, Any]] = {}

    def _write(name, flat):
        np.savez(os.path.join(dirname, name), **flat)
        spec[name] = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                      for k, v in flat.items()}

    pshards, pbase = _zero_split_flat(params, zero.n,
                                      zero.partitioned["params.npz"])
    enforce(not pbase, "zero_sharding partitions every param leaf")
    for i, flat in enumerate(pshards):
        _write(f"params.zero{i}.npz", flat)
    if state is not None:
        _write("state.npz", _flatten(jax.device_get(state)))
    if opt_state is not None:
        oshards, obase = _zero_split_flat(opt_state, zero.n,
                                          zero.partitioned["opt_state.npz"])
        _write("opt_state.npz", obase)
        if oshards[0]:
            for i, flat in enumerate(oshards):
                _write(f"opt_state.zero{i}.npz", flat)
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump(meta or {}, f)
    return spec


def _merge_nested(dst: Dict[str, Any], src: Dict[str, Any]) -> Dict[str, Any]:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge_nested(dst[k], v)
        else:
            dst[k] = v
    return dst


def _gather_zero_collection(dirname: str, stem: str,
                            zero_meta: Dict[str, Any]) -> Dict[str, Any]:
    """Concatenate a ZeRO checkpoint's per-shard ``(k,)`` rows back into
    logical leaves — the host-side gather of the N→M elastic fallback
    (``load_persistables`` calls this transparently, so every consumer
    of the gathered path — drift checks, reshard placement, predictors —
    sees the same logical trees a replicated checkpoint yields).
    Returns ``{}`` when the collection has no partitioned leaves."""
    n = int(zero_meta["shards"])
    spec = (zero_meta.get("arrays") or {}).get(f"{stem}.npz") or {}
    paths = [os.path.join(dirname, f"{stem}.zero{i}.npz") for i in range(n)]
    if not any(os.path.exists(p) for p in paths):
        return {}
    missing = [os.path.basename(p) for p in paths if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"ZeRO checkpoint is missing shard files {missing[:3]} "
            f"({len(missing)} of {n})")
    flat: Dict[str, np.ndarray] = {}
    flats: List[Dict[str, np.ndarray]] = []
    for p in paths:
        with np.load(p, allow_pickle=False) as z:
            flats.append({k: np.array(z[k]) for k in z.files})
    for key in flats[0]:
        ent = spec.get(key)
        if ent is None:
            raise KeyError(
                f"{stem} shard member {key!r} is absent from the "
                "checkpoint's meta.zero.arrays spec")
        shape = tuple(ent["shape"])
        size = int(np.prod(shape)) if shape else 1
        flat[key] = np.concatenate(
            [f[key] for f in flats])[:size].reshape(shape)
    return _unflatten(flat)


def load_persistables(dirname: str) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray],
                                             Optional[Dict[str, Any]], Dict[str, Any]]:
    """Load (params, state, opt_state, meta) (load_persistables analog).
    ZeRO checkpoints (``meta.zero``) are gathered to logical shapes on
    the host — the explicit N→M fallback; the gather-free same-N path
    lives in ``load_trainer``."""

    def _load(name):
        p = os.path.join(dirname, name)
        if not os.path.exists(p):
            return None
        with np.load(p, allow_pickle=False) as z:
            # fresh writable copies, NOT the npz-backed views: jax's CPU
            # backend zero-copies device_put of host arrays when it can,
            # and a Trainer later DONATES those buffers — in-place XLA
            # reuse of memory owned by the zip reader corrupts values
            # transiently (observed as NaN losses after resume; the
            # fault-injection suite pins this via resume continuity)
            return _unflatten({k: np.array(z[k]) for k in z.files})

    meta_path = os.path.join(dirname, "meta.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    zero = meta.get("zero")
    if zero:
        params = _gather_zero_collection(dirname, "params", zero)
        state = _load("state.npz") or {}
        opt_state = _load("opt_state.npz")
        opart = _gather_zero_collection(dirname, "opt_state", zero)
        if opart:
            opt_state = _merge_nested(opt_state if opt_state is not None
                                      else {}, opart)
    else:
        params = _load("params.npz") or {}
        state = _load("state.npz") or {}
        opt_state = _load("opt_state.npz")
    if opt_state is not None:
        # empty sub-dicts ("global"/"accums" for stateless optimizers)
        # flatten to nothing on save — restore the keys
        opt_state.setdefault("global", {})
        opt_state.setdefault("accums", {})
    return params, state, opt_state, meta


def _fsync_tree(dirname: str) -> None:
    """fsync every regular file in ``dirname`` (and the dir itself):
    the atomic-rename commit is only meaningful if the data it commits
    has reached the disk."""
    for name in os.listdir(dirname):
        p = os.path.join(dirname, name)
        if not os.path.isfile(p):
            continue
        fd = os.open(p, os.O_RDONLY)
        try:
            os.fsync(fd)
        except OSError:
            pass  # fs without fsync support (tmpfs variants): best effort
        finally:
            os.close(fd)
    _fsync_dir(dirname)


def _fsync_dir(dirname: str) -> None:
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_trainer(dirname: str, trainer,
                 extra_meta: Optional[Dict[str, Any]] = None) -> None:
    """Checkpoint a Trainer (params+state+opt_state+step) — the
    CheckpointConfig/save_checkpoint analog (contrib/trainer.py:100).

    **Atomic + validated**: the collections are written to a
    ``<dirname>.tmp.<pid>`` sibling, fsynced, covered by a
    ``manifest.json`` (format version, global_step, per-file CRC32 +
    size, flat shape/dtype spec), and renamed into place. A crash at
    ANY point (see the ``save_trainer:*`` crash points in
    ``testing.faults``) leaves either the previous committed checkpoint
    or the new one — never a torn directory that ``load_trainer``
    trusts. ``extra_meta`` entries ride in the checkpoint meta (``fit``
    stores epoch/epoch_step for resume)."""
    import shutil

    from . import resilience

    meta = {"global_step": trainer.global_step}
    ls = getattr(trainer.scope, "loss_scale_state", None)
    if ls:
        meta["loss_scale_state"] = {k: float(v) for k, v in ls.items()}
    # the mesh the checkpoint was WRITTEN at: arrays are stored
    # unsharded, but recording the axes lets the static contract
    # verifier (analysis.contracts) name the N->M reshard a restore at
    # a different mesh implies and judge its feasibility. Recorded
    # UNCONDITIONALLY ({} for a single-device trainer): a meshless
    # checkpoint restored at dp=N is the 1->N elastic case and must
    # trip the same ReshardError gate — only checkpoints that predate
    # this key (no mesh_axes at all) pass ungated
    meta["mesh_axes"] = resilience.trainer_mesh_axes(trainer) or {}
    # ZeRO checkpoints are shard-aware: meta.zero_axes gates the
    # implicit restore path (same-N only), meta.zero carries the shard
    # count + LOGICAL flat spec the N→M gather fallback reassembles by
    zero = getattr(trainer, "_zero", None)
    if zero is not None:
        meta["zero_axes"] = dict(zero.axes_dict)
        meta["zero"] = {"shards": zero.n, "axes": dict(zero.axes_dict),
                        "arrays": zero.arrays}
    if extra_meta:
        meta.update(extra_meta)
    # checkpoints always store logical layer order: undo the trainer's
    # interleaved pipeline rest layout (no-op otherwise)
    params, opt_state = trainer.stacked_to_logical(
        trainer.scope.params, trainer.scope.opt_state)
    path = os.path.abspath(dirname)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    # clean ANY stale tmp for this tag (a prior process's torn save
    # leaves <tag>.tmp.<other-pid> behind; fit also sweeps the whole
    # dir at startup with the unfiltered form)
    resilience.sweep_tmp_dirs(parent, tag=os.path.basename(path))
    tmp = f"{path}{resilience.TMP_MARKER}{os.getpid()}"
    if zero is not None:
        spec = _save_zero_persistables(tmp, trainer, params,
                                       trainer.scope.state, opt_state, meta)
    else:
        spec = save_persistables(tmp, params, trainer.scope.state,
                                 opt_state, meta=meta)
    resilience.crash_point("save_trainer:files-written")
    _fsync_tree(tmp)
    resilience.write_manifest(tmp, meta=meta, arrays=spec)
    resilience.crash_point("save_trainer:manifest-written")
    if os.path.isdir(path):
        # overwrite of an existing tag: the old dir must vanish before
        # the rename (rename onto a non-empty dir fails). The window
        # where neither exists only loses THIS tag — older tags are
        # untouched and the resume scanner falls back to them.
        shutil.rmtree(path)
    os.rename(tmp, path)
    _fsync_dir(parent)


def load_trainer(dirname: str, trainer, allow_reshard: bool = False) -> None:
    """Restore a Trainer in place, re-placing arrays on the trainer's
    device/mesh (resharding-on-load).

    The checkpoint is validated against its manifest first (CRC32 per
    file, format version); any mismatch — or an npz that fails to parse
    — raises a structured :class:`~paddle_tpu.resilience.CheckpointCorrupt`
    instead of a random decoder error. Pre-manifest (legacy) directories
    load without validation.

    A checkpoint whose recorded ``meta.mesh_axes`` differ from the
    trainer's mesh used to "load" and then die later — in ``put_batch``'s
    ``device_put`` or a retrace shape error deep inside the first step.
    It now raises a structured
    :class:`~paddle_tpu.resilience.ReshardError` at LOAD time naming the
    saved vs. target axes. A mesh change is a supported operation, just
    an explicit one: go through
    :func:`~paddle_tpu.resilience.reshard_restore` (or
    ``fit(resume=True, elastic=True)``), which proves feasibility with
    the static contract checker first — or pass ``allow_reshard=True``
    to skip the gate (the arrays are stored unsharded, so placement per
    the target rules is the whole reshard). Size-1 axes are normalized
    away: ``{"dp": 1}`` and no mesh place identically and do not trip
    the gate; checkpoints that predate mesh metadata pass through
    (the saved mesh is unknowable)."""
    from . import resilience

    # the mesh gate needs only the manifest META — run it BEFORE the
    # full per-file CRC pass, so a mesh-mismatched restore (which
    # reshard_restore will load again, paying the CRC sweep there) is
    # rejected from one cheap JSON read, not a double scan of the
    # checkpoint bytes
    if not allow_reshard:
        meta_man = resilience.read_manifest(dirname)  # None for legacy
        saved_axes = ((meta_man or {}).get("meta") or {}).get("mesh_axes")
        target_axes = resilience.trainer_mesh_axes(trainer)
        if saved_axes is not None and \
                resilience.normalize_mesh_axes(saved_axes) != \
                resilience.normalize_mesh_axes(target_axes):
            raise resilience.ReshardError(
                dirname, saved_axes, target_axes,
                f"checkpoint was saved at mesh axes {saved_axes} but the "
                f"target trainer runs "
                f"{target_axes or 'a single device'} — restoring across a "
                "mesh change is an elastic reshard; use "
                "resilience.reshard_restore(checkpoint_dir, trainer) or "
                "fit(resume=True, elastic=True) (or load_trainer("
                "allow_reshard=True) to skip the feasibility check)")
        # ZeRO gate: a shard-aware checkpoint restores implicitly only
        # at the same shard layout. A zero<->replicated flip or a
        # shard-count change (the static ckpt:zero-mismatch finding's
        # runtime counterpart) goes through the explicit elastic door,
        # which gathers the shards to logical and repartitions.
        if meta_man is not None:
            saved_zero = ((meta_man.get("meta") or {}).get("zero_axes")
                          or {})
            tz = getattr(trainer, "_zero", None)
            target_zero = dict(tz.axes_dict) if tz is not None else {}
            if resilience.normalize_mesh_axes(saved_zero) != \
                    resilience.normalize_mesh_axes(target_zero):
                raise resilience.ReshardError(
                    dirname, saved_axes, target_axes,
                    f"checkpoint zero_sharding axes "
                    f"{saved_zero or None} differ from the target "
                    f"trainer's {target_zero or None} — restoring across "
                    "a ZeRO shard-layout change is an elastic reshard "
                    "(gather-then-repartition); use "
                    "resilience.reshard_restore(checkpoint_dir, trainer) "
                    "or fit(resume=True, elastic=True) (or load_trainer("
                    "allow_reshard=True) to skip the feasibility check)")
    manifest = resilience.validate_checkpoint(dirname)  # None for legacy
    zero_meta = ((manifest or {}).get("meta") or {}).get("zero")
    tz = getattr(trainer, "_zero", None)
    if (tz is not None and zero_meta
            and resilience.normalize_mesh_axes(zero_meta.get("axes") or {})
            == resilience.normalize_mesh_axes(tz.axes_dict)
            and resilience.normalize_mesh_axes(
                ((manifest or {}).get("meta") or {}).get("mesh_axes") or {})
            == resilience.normalize_mesh_axes(
                resilience.trainer_mesh_axes(trainer) or {})):
        # same-N same-mesh ZeRO→ZeRO: shard-local restore, no gather on
        # the hot path (each device adopts its own rows)
        _load_trainer_zero_local(dirname, trainer, manifest)
        return
    try:
        params, state, opt_state, meta = load_persistables(dirname)
    except Exception as e:
        raise resilience.CheckpointCorrupt(
            dirname, f"unreadable collection: {type(e).__name__}: {e}") from e
    if not params:
        raise resilience.CheckpointCorrupt(
            dirname, "no parameters found (params.npz missing or empty)")
    if manifest:
        # a ZeRO manifest's "arrays" spec covers the per-shard files;
        # the gathered trees compare against the LOGICAL spec in
        # meta.zero.arrays instead
        man_arr = (dict(manifest, arrays=zero_meta.get("arrays") or {})
                   if zero_meta else manifest)
        _check_arrays_spec(man_arr, dirname, params=params, state=state,
                           opt_state=opt_state)
    _check_trainer_param_drift(dirname, trainer, params)
    if opt_state is not None:
        # stateless-optimizer per-param accums are empty dicts, which
        # flatten to nothing on save — restore the per-param keys
        for k in params:
            opt_state["accums"].setdefault(k, {})
    # checkpoints are logical layer order; a trainer running the
    # interleaved pipeline layout re-permutes on the way in (no-op
    # otherwise)
    params, opt_state = trainer.stacked_from_logical(params, opt_state)
    if tz is not None:
        # repartition the gathered logical trees into this trainer's
        # (N, k) rows — the second half of the N→M elastic fallback
        from jax.sharding import NamedSharding, PartitionSpec
        from .parallel import zero as zero_mod
        params = zero_mod.partition_params(params, tz, trainer.mesh)
        opt_state = (zero_mod.partition_opt_state(opt_state, tz,
                                                  trainer.mesh)
                     if opt_state is not None else None)
        state = jax.device_put(
            state, NamedSharding(trainer.mesh, PartitionSpec()))
    elif trainer.mesh is not None:
        from .parallel import api as par_api
        params, state, opt_state = par_api.shard_scope(
            trainer.mesh, trainer.sharding_rules, params, state, opt_state)
    else:
        dev = trainer.place.device()
        params = jax.device_put(params, dev)
        state = jax.device_put(state, dev)
        opt_state = jax.device_put(opt_state, dev) if opt_state is not None else None
    # restore exact leaf dtypes (npz roundtrips are exact, but int scalars
    # may come back as 0-d arrays)
    if opt_state is not None:
        opt_state["step"] = jnp.asarray(opt_state["step"], jnp.int32)
    trainer.scope.params, trainer.scope.state, trainer.scope.opt_state = params, state, opt_state
    trainer.global_step = int(meta.get("global_step", 0))
    # kept for fit(resume=True): epoch/epoch_step and anything else the
    # saver stored ride here (resilience.restore_latest reads it)
    trainer._last_loaded_meta = dict(meta)
    _restore_loss_scale(trainer, meta, dirname)


def _load_trainer_zero_local(dirname: str, trainer, manifest) -> None:
    """Same-N, same-mesh restore of a ZeRO checkpoint: every device
    adopts its own ``(k,)`` rows straight from the per-shard files via
    ``jax.make_array_from_callback`` — no gather on the restore path,
    mirroring the gather-free save. The CRC pass already ran
    (``validate_checkpoint``); this adds the logical-spec drift gate
    (same contract as :func:`_check_trainer_param_drift`)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from . import resilience
    from .parallel import zero as zero_mod

    zero = trainer._zero
    meta = (manifest.get("meta") or {})
    zm = meta.get("zero") or {}
    n = int(zm.get("shards") or zero.n)
    saved = (zm.get("arrays") or {}).get("params.npz") or {}
    want = zero.arrays["params.npz"]
    if {k: (tuple(v["shape"]), str(v["dtype"])) for k, v in saved.items()} \
            != {k: (tuple(v["shape"]), str(v["dtype"]))
                for k, v in want.items()}:
        missing = sorted(set(want) - set(saved))[:3]
        extra = sorted(set(saved) - set(want))[:3]
        raise resilience.CheckpointCorrupt(
            dirname, f"ZeRO checkpoint params diverge from the trainer's "
            f"logical spec (missing: {missing}, unexpected: {extra}) — "
            "the model config drifted since this checkpoint was written")

    def shard_trees(stem):
        paths = [os.path.join(dirname, f"{stem}.zero{i}.npz")
                 for i in range(n)]
        if not any(os.path.exists(p) for p in paths):
            return None
        out = []
        for p in paths:
            try:
                with np.load(p, allow_pickle=False) as z:
                    out.append(_unflatten({k: np.array(z[k])
                                           for k in z.files}))
            except Exception as e:
                raise resilience.CheckpointCorrupt(
                    dirname, f"unreadable shard file "
                    f"{os.path.basename(p)}: {type(e).__name__}: {e}") from e
        return out

    ns = zero_mod.shard_sharding(trainer.mesh, zero.axes)
    repl = NamedSharding(trainer.mesh, PartitionSpec())

    def rows_to_array(*rows):
        rows = [np.asarray(r) for r in rows]

        def cb(index):
            lo = int(index[0].start or 0)
            hi = index[0].stop
            hi = n if hi is None else int(hi)
            return np.stack(rows[lo:hi])

        return jax.make_array_from_callback((n,) + rows[0].shape, ns, cb)

    ptrees = shard_trees("params")
    if ptrees is None:
        raise resilience.CheckpointCorrupt(
            dirname, "ZeRO checkpoint has no params.zero*.npz shard files")
    params = jax.tree.map(rows_to_array, *ptrees)

    def _load_flat(name):
        p = os.path.join(dirname, name)
        if not os.path.exists(p):
            return None
        try:
            with np.load(p, allow_pickle=False) as z:
                return _unflatten({k: np.array(z[k]) for k in z.files})
        except Exception as e:
            raise resilience.CheckpointCorrupt(
                dirname, f"unreadable collection {name}: "
                f"{type(e).__name__}: {e}") from e

    state = jax.device_put(_load_flat("state.npz") or {}, repl)
    opt_state = _load_flat("opt_state.npz")
    otrees = shard_trees("opt_state")
    if opt_state is not None or otrees is not None:
        opt_state = jax.device_put(opt_state or {}, repl)
        if otrees is not None:
            _merge_nested(opt_state, jax.tree.map(rows_to_array, *otrees))
        opt_state.setdefault("global", {})
        opt_state.setdefault("accums", {})
        for k in zero.shapes:
            opt_state["accums"].setdefault(k, {})
        if "step" in opt_state:
            opt_state["step"] = jax.device_put(
                jnp.asarray(opt_state["step"], jnp.int32), repl)
    trainer.scope.params, trainer.scope.state, trainer.scope.opt_state = \
        params, state, opt_state
    trainer.global_step = int(meta.get("global_step", 0))
    trainer._last_loaded_meta = dict(meta)
    _restore_loss_scale(trainer, meta, dirname)


def _check_trainer_param_drift(dirname: str, trainer, params) -> None:
    """A checkpoint whose PARAMETER spec diverges from the trainer it is
    restored into (renamed layer, resized dim, dtype change — i.e. the
    model config drifted since the save) used to load "successfully" and
    then die as a shape error deep inside the next step's retrace, or
    worse, train garbage. Raise a structured
    :class:`~paddle_tpu.resilience.CheckpointCorrupt` at LOAD time
    naming the drifted entries instead — the runtime counterpart of the
    ``ckpt:*`` findings ``analysis.contracts.check_artifacts`` reports
    without touching the checkpoint. Only runs on a started trainer
    (``scope.params`` populated); state/opt-state drift stays a
    warning-level static finding (the runtime falls back by rebuilding
    them)."""
    from . import resilience

    have = getattr(getattr(trainer, "scope", None), "params", None)
    if not have:
        return
    # the trainer may hold the interleaved-pipeline row layout; that is
    # a row PERMUTATION of the logical layout — shapes/dtypes/names are
    # identical, so the spec comparison is layout-agnostic. A ZeRO
    # trainer's scope holds (N, k) rows; its LOGICAL spec was recorded
    # in the ZeroSpec at startup.
    tz = getattr(trainer, "_zero", None)
    want = (dict(tz.arrays["params.npz"]) if tz is not None
            else flat_spec(have))
    got = flat_spec(params)
    if set(want) != set(got):
        missing = sorted(set(want) - set(got))[:3]
        extra = sorted(set(got) - set(want))[:3]
        raise resilience.CheckpointCorrupt(
            dirname, f"checkpoint params diverge from the trainer's "
            f"(missing: {missing}, unexpected: {extra}) — the model "
            "config drifted since this checkpoint was written")
    drift = {k: (got[k], want[k]) for k in want if got[k] != want[k]}
    if drift:
        k, (g, w) = sorted(drift.items())[0]
        raise resilience.CheckpointCorrupt(
            dirname, f"checkpoint param {k!r} is {g} but the trainer "
            f"expects {w} ({len(drift)} drifted entr"
            f"{'y' if len(drift) == 1 else 'ies'} total) — the model "
            "config drifted since this checkpoint was written")


def _check_arrays_spec(manifest: Dict[str, Any], dirname: str,
                       **collections) -> None:
    """Verify the loaded trees against the manifest's flat shape/dtype
    spec — the per-leaf half of checkpoint validation (CRC32 guarantees
    the bytes; this guarantees the decoded structure matches what the
    saver recorded, catching a manifest/npz pair that drifted out of
    sync). Costs a dict re-flatten of data already in memory."""
    from . import resilience

    spec = manifest.get("arrays") or {}
    fname = {"params": "params.npz", "state": "state.npz",
             "opt_state": "opt_state.npz"}
    for coll, tree in collections.items():
        want = spec.get(fname[coll])
        if want is None or tree is None:
            continue
        got = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
               for k, v in _flatten(tree).items()}
        if set(got) != set(want):
            missing = sorted(set(want) - set(got))[:3]
            extra = sorted(set(got) - set(want))[:3]
            raise resilience.CheckpointCorrupt(
                dirname, f"{fname[coll]} members diverge from manifest "
                f"(missing: {missing}, unexpected: {extra})")
        for k, w in want.items():
            if got[k] != w:
                raise resilience.CheckpointCorrupt(
                    dirname, f"{fname[coll]}:{k} is {got[k]} on disk but "
                    f"the manifest records {w}")


def _restore_loss_scale(trainer, meta: Dict[str, Any], dirname: str) -> None:
    """Loss-scale state across checkpoint/trainer config drift: a
    checkpoint that predates dynamic loss scaling restored into a
    scaler-running trainer (or vice versa) must warn and fall back to
    the scaler's initial state, not KeyError."""
    import warnings

    ls_meta = meta.get("loss_scale_state")
    if trainer.loss_scaler is None:
        if ls_meta:
            warnings.warn(
                f"checkpoint {dirname!r} carries loss_scale_state but the "
                "trainer has no loss scaler — ignoring it (configure "
                "DistStrategy.loss_scale to adopt it)")
        return
    init = trainer.loss_scaler.init_state()
    if not ls_meta:
        warnings.warn(
            f"checkpoint {dirname!r} has no loss_scale_state but the "
            "trainer runs a loss scaler — falling back to the scaler's "
            "initial state (scale will re-calibrate)")
        ls_meta = {}
    missing = {"scale", "good_steps", "overflows"} - set(ls_meta)
    if ls_meta and missing:
        warnings.warn(
            f"checkpoint {dirname!r} loss_scale_state is missing "
            f"{sorted(missing)} — those fields fall back to the scaler's "
            "initial values")
    trainer.scope.loss_scale_state = jax.device_put({
        "scale": jnp.float32(ls_meta.get("scale", float(init["scale"]))),
        "good_steps": jnp.int32(ls_meta.get("good_steps",
                                            int(init["good_steps"]))),
        "overflows": jnp.int32(ls_meta.get("overflows",
                                           int(init["overflows"]))),
    })


# -- inference model (save/load_inference_model analog) ----------------------


def _in_spec(flat_sources, exported):
    """Flat (source, name) binding -> the ordered input spec native
    drivers consume. ONE emission point for both artifact kinds
    (save_inference_model / save_train_artifact): the invariant that
    spec names stay byte-identical to npz member names (via
    _mangle_leaf) and positionally aligned to exported.in_avals must
    not fork."""
    enforce(len(flat_sources) == len(exported.in_avals),
            f"export signature mismatch: {len(flat_sources)} leaves vs "
            f"{len(exported.in_avals)} in_avals")
    return [{"source": src, "name": name,
             "dtype": str(av.dtype), "shape": list(av.shape)}
            for (src, name), av in zip(flat_sources, exported.in_avals)]


def _recover_renamed_aside(path: str) -> None:
    """Crash recovery for the two-rename overwrite window: a save that
    died between rename-aside and commit leaves the only good artifact
    at ``<path>.tmp.<pid>.old`` with nothing at ``path``. Restore it
    BEFORE the tmp sweep — the sweep's ``<tag>.tmp.*`` pattern would
    otherwise delete the sole surviving copy while the replacement save
    could still fail before committing."""
    from . import resilience

    if os.path.isdir(path):
        return
    olds = sorted(p for p in
                  (os.path.join(os.path.dirname(path), n)
                   for n in os.listdir(os.path.dirname(path) or "."))
                  if p.startswith(f"{path}{resilience.TMP_MARKER}")
                  and p.endswith(".old") and os.path.isdir(p))
    if not olds:
        return
    newest = max(olds, key=os.path.getmtime)
    os.rename(newest, path)
    _log().warning("recovered artifact %s from interrupted overwrite (%s)",
                   path, os.path.basename(newest))


def _infer_batch_info(example_feed: Dict[str, Any]) -> Tuple[int, List[str]]:
    """(batch_size, batched_feed_names) of an example feed: the batch is
    the leading dim of the first (sorted) non-scalar feed; every feed
    sharing that leading dim is treated as batched — the axis shape
    buckets and request padding operate on."""
    batch = 0
    for k in sorted(example_feed):
        v = np.asarray(example_feed[k])
        if v.ndim >= 1:
            batch = int(v.shape[0])
            break
    batched = [k for k in sorted(example_feed)
               if np.asarray(example_feed[k]).ndim >= 1
               and np.asarray(example_feed[k]).shape[0] == batch]
    return batch, batched


def _resize_batch(v: np.ndarray, n: int) -> np.ndarray:
    """Example feed at a different bucket size: slice down or tile up
    along dim 0 (values only seed the trace — shapes/dtypes matter)."""
    if v.shape[0] >= n:
        return np.ascontiguousarray(v[:n])
    reps = -(-n // v.shape[0])  # ceil
    return np.ascontiguousarray(
        np.concatenate([v] * reps, axis=0)[:n])


def save_inference_model(dirname: str, program, params: Dict[str, jax.Array],
                         state: Dict[str, jax.Array], example_feed: Dict[str, Any],
                         batch_buckets: Optional[Sequence[int]] = None) -> None:
    """Export program.apply (inference mode, params baked as inputs) as a
    serialized StableHLO artifact + weights (io.py:544 analog: prune to
    feed/fetch + serialize ProgramDesc + save params).

    **Atomic + validated commit** (the ``save_trainer`` discipline
    applied to deployment artifacts): everything is written to a
    ``<dirname>.tmp.<pid>`` sibling, fsynced, covered by a
    ``resilience.write_manifest`` manifest (per-file CRC32 + size, flat
    shape/dtype spec of the weight collections), and renamed into place.
    A crash mid-EXPORT leaves the previous artifact committed; when
    OVERWRITING an existing artifact the old one is renamed aside first,
    so the only no-artifact-at-``dirname`` window is two renames wide
    (a crash inside it preserves the old artifact under a ``.tmp.*.old``
    marker, and a concurrent loader fails loudly rather than reading a
    torn tree). ``load_inference_model`` / a hot-reloading
    ``serving.PredictorServer`` reject torn or bit-flipped artifacts
    with a structured :class:`~paddle_tpu.resilience.CheckpointCorrupt`.

    ``batch_buckets`` exports ADDITIONAL fixed batch sizes of the same
    program (``model.b{N}.stablehlo`` siblings): the precompiled shape
    bucket set a :class:`~paddle_tpu.serving.PredictorServer` pads
    ragged request batches up to, so adversarial batch shapes can never
    trigger a recompile on the request path. The example feed's own
    batch size is always a bucket."""
    import shutil

    import jax.export  # noqa: F401  (jax 0.4.x: submodule needs explicit import)

    from . import resilience

    feed_names = sorted(example_feed)
    batch, batched_feeds = _infer_batch_info(example_feed)
    buckets = sorted(set(int(b) for b in (batch_buckets or [])) | {batch})
    enforce(all(b > 0 for b in buckets),
            f"batch_buckets must be positive, got {buckets}")

    def infer_fn(params_, state_, *feed_vals):
        feed = dict(zip(feed_names, feed_vals))
        out, _ = program.apply(params_, state_, training=False, **feed)
        return out

    host_params, host_state = jax.device_get(params), jax.device_get(state)

    def _export_at(feed):
        vals = [jnp.asarray(np.asarray(feed[k])) for k in feed_names]
        return jax.export.export(jax.jit(infer_fn))(
            host_params, host_state, *vals)

    exported = _export_at(example_feed)
    bucket_exports = {}
    for b in buckets:
        if b == batch:
            continue
        bucket_exports[b] = _export_at(
            {k: (_resize_batch(np.asarray(v), b) if k in batched_feeds
                 else np.asarray(v))
             for k, v in example_feed.items()})

    path = os.path.abspath(dirname)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    _recover_renamed_aside(path)
    resilience.sweep_tmp_dirs(parent, tag=os.path.basename(path))
    tmp = f"{path}{resilience.TMP_MARKER}{os.getpid()}"
    os.makedirs(tmp)

    with open(os.path.join(tmp, "model.stablehlo"), "wb") as f:
        f.write(exported.serialize())
    for b, exp in bucket_exports.items():
        with open(os.path.join(tmp, f"model.b{b}.stablehlo"), "wb") as f:
            f.write(exp.serialize())
    flat_params, flat_state = _flatten(host_params), _flatten(host_state)
    np.savez(os.path.join(tmp, "params.npz"), **flat_params)
    np.savez(os.path.join(tmp, "state.npz"), **flat_state)
    arrays_spec = {name: {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                          for k, v in flat.items()}
                   for name, flat in (("params.npz", flat_params),
                                      ("state.npz", flat_state))}
    # Python-free deployment artifact (inference/io.h:35 analog): the raw
    # StableHLO bytecode plus the flat call signature, so native/
    # predictor.cc can compile+run through the PJRT C API with no
    # libpython. Inputs are the flattened (params, state, *feeds) leaves
    # in exported.in_avals order; "source" tells the C++ loader which
    # npz member (or feed) supplies each argument.
    with open(os.path.join(tmp, "model.mlir"), "wb") as f:
        f.write(exported.mlir_module_serialized)
    param_leaves = _flat_leaves_in_tree_order(host_params)
    state_leaves = _flat_leaves_in_tree_order(host_state)
    flat_sources = ([("params.npz", k) for k, _ in param_leaves]
                    + [("state.npz", k) for k, _ in state_leaves]
                    + [("feed", k) for k in feed_names])
    flat_vals = ([v for _, v in param_leaves] + [v for _, v in state_leaves]
                 + [np.asarray(example_feed[k]) for k in feed_names])
    in_spec = _in_spec(flat_sources, exported)
    for (src, name), val, av in zip(flat_sources, flat_vals, exported.in_avals):
        enforce(tuple(val.shape) == tuple(av.shape),
                f"export arg order broke: {src}:{name} has shape {val.shape}, "
                f"aval expects {av.shape}")
        # npz members store exotic dtypes as integer views ('@bfloat16'
        # suffix); the ORIGINAL dtype must still match the aval
        if src != "feed" and "@" not in name:
            enforce(val.dtype.name == str(av.dtype),
                    f"export arg order broke: {src}:{name} is {val.dtype.name},"
                    f" aval expects {av.dtype}")
    out_spec = [{"dtype": str(av.dtype), "shape": list(av.shape)}
                for av in exported.out_avals]
    meta = {"feed_names": feed_names, "inputs": in_spec, "outputs": out_spec,
            "batch_size": batch, "batched_feeds": batched_feeds,
            "batch_buckets": buckets}
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f)
    resilience.crash_point("save_inference_model:files-written")
    _fsync_tree(tmp)
    resilience.write_manifest(tmp, meta={"kind": "inference_model"},
                              arrays=arrays_spec)
    resilience.crash_point("save_inference_model:manifest-written")
    old = None
    if os.path.isdir(path):
        # overwrite: move the committed artifact ASIDE (one rename)
        # rather than rmtree-ing it first — the no-artifact window is
        # two renames wide instead of a full recursive delete, and a
        # crash inside it leaves the previous artifact intact under the
        # .tmp marker (a concurrent load during the window fails
        # loudly; a hot-reloading PredictorServer rolls back and keeps
        # serving its in-memory model)
        old = f"{path}{resilience.TMP_MARKER}{os.getpid()}.old"
        os.rename(path, old)
        resilience.crash_point("save_inference_model:committing")
    os.rename(tmp, path)
    _fsync_dir(parent)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def artifact_fingerprint(dirname: str) -> Tuple[Dict[str, Any], str]:
    """(manifest, token) of a committed ``save_inference_model`` dir.

    The token is content-addressed — ``<basename>-<crc32:08x>`` over the
    sorted ``name:crc:size`` lines of the manifest's file table — so two
    hosts can agree an artifact is already present without moving bytes:
    the fleet's FETCH/ARTIFACT distribution keys its receive cache on it,
    making re-ships of an unchanged artifact a no-op negotiation."""
    import zlib

    from . import resilience

    path = os.path.abspath(dirname)
    man = resilience.read_manifest(path)
    enforce(man is not None,
            f"artifact_fingerprint: {dirname!r} has no manifest — only "
            "committed save_inference_model dirs can be distributed")
    lines = "\n".join(f"{name}:{spec['crc32']}:{spec['size']}"
                      for name, spec in sorted(man["files"].items()))
    crc = zlib.crc32(lines.encode()) & 0xFFFFFFFF
    return man, f"{os.path.basename(path)}-{crc:08x}"


def save_train_artifact(dirname: str, trainer, example_feed: Dict[str, Any]) -> None:
    """Export ONE optimizer step of a started Trainer as a StableHLO
    artifact the Python-free native trainer (native/trainer.cc) can
    drive — train/demo/demo_trainer.cc parity, where the reference saves
    a ProgramDesc its C++ Executor replays.

    The exported function is
        step(params, opt_state, state, seed, *feeds)
          -> (params', opt_state', state', loss)
    with params/opt_state/state flattened in sorted-key order on BOTH
    sides, so output i is input i's next value for i < num_carry — the
    C++ loop swaps buffers positionally with no name resolution. The
    per-step RNG enters as a u32 scalar seed (PRNGKey built inside the
    traced step: threefry, so the artifact is backend-portable); the
    C++ driver feeds the step index.
    """
    import jax.export  # noqa: F401  (jax 0.4.x: submodule needs explicit import)

    program, optimizer = trainer.program, trainer.optimizer
    enforce(trainer.scope.params is not None, "save_train_artifact: call "
            "trainer.startup() first")
    enforce(getattr(trainer, "loss_scaler", None) is None,
            "save_train_artifact: dynamic loss scaling not supported in the "
            "native step (export a bfloat16/float32 trainer)")
    enforce(getattr(trainer, "mesh", None) is None,
            "save_train_artifact: single-device export only")
    loss_name = trainer.loss_name
    os.makedirs(dirname, exist_ok=True)
    feed_names = sorted(example_feed)

    def step(params_, opt_state_, state_, seed, *feed_vals):
        feed = dict(zip(feed_names, feed_vals))
        rng = jax.random.PRNGKey(seed)

        def loss_fn(p, st):
            out, new_state = program.apply(p, st, training=True, rng=rng,
                                           **feed)
            loss = out[loss_name] if isinstance(out, dict) else out
            return loss, new_state

        (loss, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params_, state_)
        new_params, new_opt = optimizer.update(grads, opt_state_, params_,
                                               program.param_info)
        return new_params, new_opt, new_state, loss.astype(jnp.float32)

    host = jax.device_get((trainer.scope.params, trainer.scope.opt_state,
                           trainer.scope.state))
    host_params, host_opt, host_state = host
    example_vals = [jnp.asarray(np.asarray(example_feed[k]))
                    for k in feed_names]
    exported = jax.export.export(jax.jit(step))(
        host_params, host_opt, host_state, np.uint32(0), *example_vals)
    with open(os.path.join(dirname, "train_step.mlir"), "wb") as f:
        f.write(exported.mlir_module_serialized)
    # the jax-side serialization as well (save_inference_model's
    # model.stablehlo analog): lets a Python process deserialize and
    # replay the IDENTICAL artifact (tests do), not a re-trace
    with open(os.path.join(dirname, "train_step.jaxexp"), "wb") as f:
        f.write(exported.serialize())
    np.savez(os.path.join(dirname, "params.npz"), **_flatten(host_params))
    np.savez(os.path.join(dirname, "opt.npz"), **_flatten(host_opt))
    np.savez(os.path.join(dirname, "state.npz"), **_flatten(host_state))

    param_leaves = _flat_leaves_in_tree_order(host_params)
    opt_leaves = _flat_leaves_in_tree_order(host_opt)
    state_leaves = _flat_leaves_in_tree_order(host_state)
    flat_sources = ([("params.npz", k) for k, _ in param_leaves]
                    + [("opt.npz", k) for k, _ in opt_leaves]
                    + [("state.npz", k) for k, _ in state_leaves]
                    + [("seed", "seed")]
                    + [("feed", k) for k in feed_names])
    num_carry = len(param_leaves) + len(opt_leaves) + len(state_leaves)
    enforce(len(exported.out_avals) == num_carry + 1,
            "train export must emit carry + loss")
    for (src, name), in_av, out_av in zip(
            flat_sources[:num_carry], exported.in_avals[:num_carry],
            exported.out_avals[:num_carry]):
        enforce(tuple(in_av.shape) == tuple(out_av.shape)
                and in_av.dtype == out_av.dtype,
                f"carry leaf {src}:{name} not shape/dtype-stable across the "
                f"step ({in_av} vs {out_av})")
    # feed .npy files must carry the CANONICALIZED aval dtype (e.g. an
    # int64 label feed traces as int32 with x64 off) or the native
    # driver's dtype check rejects them at staging time
    for k, av in zip(feed_names, exported.in_avals[num_carry + 1:]):
        np.save(os.path.join(dirname, f"feed_{k}.npy"),
                np.asarray(example_feed[k]).astype(av.dtype))
    in_spec = _in_spec(flat_sources, exported)
    with open(os.path.join(dirname, "meta_train.json"), "w") as f:
        json.dump({"feed_names": feed_names, "num_carry": num_carry,
                   "inputs": in_spec}, f)


# process-wide count of predictor AOT compiles: the serving tests pin
# this across warmed-up traffic to prove off-bucket/adversarial request
# shapes can never reach a recompile on the request path
_aot_compiles = 0


def aot_compile_count() -> int:
    """Number of predictor AOT compiles performed by this process."""
    return _aot_compiles


def _aot_compile(exported):
    """AOT-compile an Exported at its own in_avals (the
    NativePaddlePredictor Init/Prepare split, api_impl.cc:64)."""
    global _aot_compiles
    flat = [jax.ShapeDtypeStruct(a.shape, a.dtype)
            for a in exported.in_avals]
    args, kwargs = jax.tree.unflatten(exported.in_tree, flat)
    compiled = jax.jit(exported.call).lower(*args, **kwargs).compile()
    _aot_compiles += 1
    return compiled


class Predictor:
    """Loaded inference model (PaddlePredictor analog,
    paddle_inference_api.h:141: Run(inputs)->outputs; Clone is free —
    the executable is stateless and thread-safe).

    The executable is **AOT-compiled once** per shape bucket at
    construction: ``run()`` never re-enters tracing/compilation, it only
    validates + device_puts the feeds and executes. ``run`` validates
    the feed structurally first — a missing/extra key or a shape/dtype
    mismatch raises a typed :class:`InvalidRequest` naming the offending
    field instead of a raw ``KeyError`` or an XLA shape abort.

    ``batch_buckets`` maps each precompiled batch size to its
    executable; ``run`` dispatches on the request's batch dim (exact
    match only — padding ragged batches up to a bucket is the serving
    layer's job, :class:`paddle_tpu.serving.PredictorServer`)."""

    def __init__(self, exported, params, state, feed_names, _compiled=None,
                 bucket_exports: Optional[Dict[int, Any]] = None,
                 batch_size: Optional[int] = None,
                 batched_feeds: Optional[Sequence[str]] = None,
                 _buckets: Optional[Dict[int, Any]] = None):
        self._exported = exported
        self._params = jax.device_put(params)
        self._state = jax.device_put(state)
        self.feed_names = list(feed_names)
        # feed avals are the trailing in_avals (flat order is
        # (params..., state..., *feeds) with feeds in sorted-name order)
        self._feed_avals = dict(zip(self.feed_names,
                                    list(exported.in_avals)[-len(self.feed_names):]))
        if batch_size is None or batched_feeds is None:
            batch_size, batched_feeds = _infer_batch_info(
                {k: np.zeros(a.shape, np.int8)
                 for k, a in self._feed_avals.items()})
        self.batch_size = int(batch_size)
        self.batched_feeds = frozenset(batched_feeds)
        if _compiled is None:
            try:
                _compiled = _aot_compile(exported)
            except Exception as e:
                # fall back to the jit dispatch cache: first run() traces,
                # subsequent calls still skip tracing/compilation. This
                # reintroduces trace-on-request — say so loudly instead
                # of silently degrading the serving latency contract.
                _log().warning(
                    "Predictor AOT compile failed (%s: %s); falling back to "
                    "the jit dispatch cache — the first run() of each feed "
                    "shape will trace+compile ON the request path",
                    type(e).__name__, e)
                _compiled = jax.jit(exported.call)
        self._compiled = _compiled
        if _buckets is not None:           # clone(): share everything
            self._buckets = _buckets
        else:
            self._buckets = {self.batch_size: self._compiled}
            for b, exp in (bucket_exports or {}).items():
                if int(b) == self.batch_size:
                    continue
                try:
                    self._buckets[int(b)] = _aot_compile(exp)
                except Exception as e:
                    _log().warning(
                        "bucket %d AOT compile failed (%s: %s); falling back "
                        "to the jit dispatch cache for that bucket",
                        b, type(e).__name__, e)
                    self._buckets[int(b)] = jax.jit(exp.call)

    @property
    def batch_buckets(self) -> List[int]:
        """Precompiled batch sizes, ascending."""
        return sorted(self._buckets)

    def feed_spec(self, batch: Optional[int] = None) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
        """{feed name: (shape, dtype)} at bucket ``batch`` (default: the
        export's own batch size)."""
        batch = self.batch_size if batch is None else int(batch)
        out = {}
        for k, a in self._feed_avals.items():
            shape = tuple(a.shape)
            if k in self.batched_feeds:
                shape = (batch,) + shape[1:]
            out[k] = (shape, np.dtype(str(a.dtype)))
        return out

    def validate_feed(self, feed: Dict[str, Any],
                      allow_padding: bool = False) -> Tuple[int, int]:
        """Structural request validation. Returns ``(n, bucket)`` — the
        request's batch size and the precompiled bucket that serves it
        (``n == bucket`` unless ``allow_padding``, where the smallest
        bucket >= n is chosen). Raises :class:`InvalidRequest` naming
        the offending field for missing/extra keys, shape or dtype
        mismatches, and off-bucket batch sizes."""
        for k in self.feed_names:
            if k not in feed:
                raise InvalidRequest(k, "is missing from the feed "
                                     f"(expected keys: {self.feed_names})")
        for k in sorted(feed):
            if k not in self._feed_avals:
                raise InvalidRequest(
                    k, "is not a feed of this model "
                    f"(expected keys: {self.feed_names})")
        buckets = self.batch_buckets
        n = None
        arrs = {k: np.asarray(feed[k]) for k in self.feed_names}
        for k in self.feed_names:
            if k not in self.batched_feeds:
                continue
            v = arrs[k]
            if v.ndim < 1:
                raise InvalidRequest(k, "must be batched (got a scalar)")
            if n is None:
                n = int(v.shape[0])
            elif int(v.shape[0]) != n:
                raise InvalidRequest(
                    k, f"batch dim {v.shape[0]} disagrees with the "
                    f"request's batch size {n}")
        if n is None:
            n = self.batch_size
        if n == 0:
            raise InvalidRequest(
                sorted(self.batched_feeds)[0] if self.batched_feeds
                else self.feed_names[0], "has an empty batch")
        if allow_padding:
            fits = [b for b in buckets if b >= n]
            if not fits:
                raise InvalidRequest(
                    sorted(self.batched_feeds)[0] if self.batched_feeds
                    else self.feed_names[0],
                    f"batch size {n} exceeds the largest precompiled "
                    f"bucket (buckets: {buckets})")
            bucket = fits[0]
        else:
            if n not in self._buckets:
                raise InvalidRequest(
                    sorted(self.batched_feeds)[0] if self.batched_feeds
                    else self.feed_names[0],
                    f"batch size {n} is not a precompiled bucket "
                    f"(buckets: {buckets})")
            bucket = n
        spec = self.feed_spec(n)  # request-sized: padding happens later
        for k in self.feed_names:
            v = arrs[k]
            want_shape, want_dtype = spec[k]
            if tuple(v.shape) != want_shape:
                raise InvalidRequest(
                    k, f"has shape {tuple(v.shape)}, expected {want_shape}")
            got = v.dtype
            if got != want_dtype and \
                    jax.dtypes.canonicalize_dtype(got) != want_dtype:
                raise InvalidRequest(
                    k, f"has dtype {got}, expected {want_dtype}")
        return n, bucket

    def run(self, feed: Dict[str, Any]):
        n, bucket = self.validate_feed(feed, allow_padding=False)
        vals = [jnp.asarray(np.asarray(feed[k])) for k in self.feed_names]
        return self._buckets[bucket](self._params, self._state, *vals)

    def clone(self) -> "Predictor":
        # share the compiled executables and device-resident weights
        return Predictor(self._exported, self._params, self._state,
                         self.feed_names, _compiled=self._compiled,
                         batch_size=self.batch_size,
                         batched_feeds=self.batched_feeds,
                         _buckets=self._buckets)


def load_inference_model(dirname: str) -> Predictor:
    """Load + AOT-compile a :class:`Predictor` from a
    ``save_inference_model`` artifact.

    The artifact is validated against its manifest first (per-file
    CRC32 + size) — a torn or bit-flipped artifact raises a structured
    :class:`~paddle_tpu.resilience.CheckpointCorrupt` instead of a
    random decoder error three frames deep. Pre-manifest (legacy)
    directories load without validation."""
    import jax.export  # noqa: F401  (jax 0.4.x: submodule needs explicit import)

    from . import resilience

    resilience.validate_checkpoint(dirname)  # None for legacy dirs
    try:
        with open(os.path.join(dirname, "model.stablehlo"), "rb") as f:
            exported = jax.export.deserialize(f.read())
        params, state, _, meta = load_persistables(dirname)
    except (resilience.CheckpointCorrupt, FileNotFoundError):
        raise
    except Exception as e:
        raise resilience.CheckpointCorrupt(
            dirname, f"unreadable artifact: {type(e).__name__}: {e}") from e
    bucket_exports = {}
    for b in meta.get("batch_buckets", []):
        p = os.path.join(dirname, f"model.b{b}.stablehlo")
        if not os.path.exists(p):
            continue
        try:
            with open(p, "rb") as f:
                bucket_exports[int(b)] = jax.export.deserialize(f.read())
        except Exception as e:
            raise resilience.CheckpointCorrupt(
                dirname, f"unreadable bucket export model.b{b}.stablehlo: "
                f"{type(e).__name__}: {e}") from e
    return Predictor(exported, params, state, meta["feed_names"],
                     bucket_exports=bucket_exports,
                     batch_size=meta.get("batch_size"),
                     batched_feeds=meta.get("batched_feeds"))


def read_artifact_meta(dirname: str) -> Dict[str, Any]:
    """Static metadata surface of a ``save_inference_model`` artifact:
    the parsed ``meta.json`` (feed names, flat input/output specs,
    batch buckets), the manifest (flat weight spec — read WITHOUT the
    CRC pass), and which per-bucket StableHLO files actually exist on
    disk. No deserialization, no AOT compile, no device work — this is
    what ``analysis.contracts`` and the serving pre-reload check reason
    over. Raises :class:`~paddle_tpu.resilience.CheckpointCorrupt` for
    a directory that is not a readable artifact."""
    from . import resilience

    if not os.path.isdir(dirname):
        raise resilience.CheckpointCorrupt(dirname, "not a directory")
    mpath = os.path.join(dirname, "meta.json")
    if not os.path.exists(mpath):
        raise resilience.CheckpointCorrupt(
            dirname, "no meta.json (not a save_inference_model artifact)")
    try:
        with open(mpath) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise resilience.CheckpointCorrupt(
            dirname, f"unreadable meta.json: {e}") from e
    manifest = resilience.read_manifest(dirname)  # None for legacy
    batch = int(meta.get("batch_size", 0) or 0)
    bucket_files = {}
    for b in meta.get("batch_buckets", []) or []:
        b = int(b)
        # the export's own batch size lives in model.stablehlo itself
        name = ("model.stablehlo" if b == batch
                else f"model.b{b}.stablehlo")
        bucket_files[b] = os.path.isfile(os.path.join(dirname, name))
    return {
        "path": dirname,
        "meta": meta,
        "manifest": manifest,
        "bucket_files": bucket_files,
        "model_file": os.path.isfile(os.path.join(dirname,
                                                  "model.stablehlo")),
    }


def artifact_feed_spec(meta: Dict[str, Any],
                       batch: Optional[int] = None) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    """``{feed name: (shape, dtype)}`` at bucket ``batch`` (default:
    the export's own batch size), reconstructed from an artifact's
    ``meta.json`` dict alone — byte-for-byte the spec
    :meth:`Predictor.feed_spec` computes from the deserialized export,
    so a static pre-reload check and the live server can never
    disagree."""
    feeds = {e["name"]: e for e in meta.get("inputs", [])
             if e.get("source") == "feed"}
    enforce(set(feeds) == set(meta.get("feed_names", [])),
            f"artifact meta is inconsistent: inputs name feeds "
            f"{sorted(feeds)} but feed_names is {meta.get('feed_names')}")
    batch = int(meta["batch_size"]) if batch is None else int(batch)
    batched = set(meta.get("batched_feeds", []))
    out = {}
    for k, e in feeds.items():
        shape = tuple(int(d) for d in e["shape"])
        if k in batched:
            shape = (batch,) + shape[1:]
        out[k] = (shape, np.dtype(str(e["dtype"])))
    return out


def save_params(dirname: str, params, state=None, opt_state=None):
    """io.py:252 save_params analog — parameters (+state/opt_state when
    given)."""
    save_persistables(dirname, params, state or {}, opt_state)


def save_vars(dirname: str, vars: Dict[str, jax.Array], filename=None):
    """io.py:89 save_vars analog: save an arbitrary name→array dict."""
    save_persistables(dirname, dict(vars), {}, None)


def load_params(dirname: str):
    """io.py load_params analog: returns the parameter dict."""
    return load_persistables(dirname)[0]


def load_vars(dirname: str):
    """io.py:295 load_vars analog."""
    return load_persistables(dirname)[0]


# -- orbax backend: async + sharded checkpointing ----------------------------
# SURVEY §5's stated TPU plan ("orbax-style sharded async checkpoint of a
# pytree"): each host writes only its own array shards (scales to
# multi-host), and async mode overlaps serialization with the next train
# steps — the reference's per-pserver checkpoint block
# (_create_checkpoint_save_block) re-expressed for the SPMD world.


_async_checkpointer: Optional[Any] = None


def _orbax_checkpointer(async_save: bool):
    import orbax.checkpoint as ocp

    global _async_checkpointer
    if async_save:
        if _async_checkpointer is None:
            _async_checkpointer = ocp.AsyncCheckpointer(
                ocp.StandardCheckpointHandler())
        return _async_checkpointer
    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def save_sharded(dirname: str, tree: Dict[str, Any], async_save: bool = False):
    """Save a (possibly sharded) pytree via orbax. With async_save the
    call returns immediately after on-device arrays are snapshotted;
    call wait_for_checkpoints() (or save again) before reading the dir."""
    import orbax.checkpoint  # noqa: F401  (fail loudly if unavailable)

    wait_for_checkpoints()   # an in-flight async save may still own the dir
    path = os.path.abspath(dirname)
    if os.path.exists(path):
        import shutil
        shutil.rmtree(path)
    ckptr = _orbax_checkpointer(async_save)
    ckptr.save(path, tree)
    return ckptr


def load_sharded(dirname: str, target: Optional[Dict[str, Any]] = None):
    """Restore an orbax checkpoint. ``target`` (a pytree of arrays or
    ShapeDtypeStructs, optionally with shardings) directs dtypes/
    placement — pass the current scope to restore directly into the
    live mesh layout (checkpoint-across-mesh-reshape, io.py:881
    _load_slice_up_vars analog)."""
    import orbax.checkpoint as ocp

    wait_for_checkpoints()   # an in-flight async save may still own the dir
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    path = os.path.abspath(dirname)
    if target is None:
        return ckptr.restore(path)
    abstract = jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=getattr(v, "sharding", None))
        if hasattr(v, "shape") else v, target)
    return ckptr.restore(path, args=ocp.args.StandardRestore(abstract))


def wait_for_checkpoints():
    """Block until all async checkpoint writes finished (barrier before
    reading a checkpoint dir or exiting)."""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()


def save_trainer_sharded(dirname: str, trainer, async_save: bool = True):
    """Orbax-backed Trainer checkpoint (async by default): params, state,
    opt_state, step — each host writing its own shards."""
    # logical layer order on disk (matches save_trainer): the device-
    # side de-permute is one gather per stacked leaf per checkpoint —
    # noise next to the write itself
    params, opt_state = trainer.stacked_to_logical(
        trainer.scope.params, trainer.scope.opt_state or {})
    tree = {
        "params": params,
        "state": trainer.scope.state,
        "opt_state": opt_state,
        "meta": {"global_step": trainer.global_step},
    }
    ls = getattr(trainer.scope, "loss_scale_state", None)
    if ls:
        tree["loss_scale_state"] = ls
    return save_sharded(dirname, tree, async_save=async_save)


def load_trainer_sharded(dirname: str, trainer) -> None:
    """Restore from save_trainer_sharded into the trainer's current
    mesh/sharding layout (works across mesh reshapes)."""
    wait_for_checkpoints()
    target = {
        "params": trainer.scope.params,
        "state": trainer.scope.state,
        "opt_state": trainer.scope.opt_state or {},
        "meta": {"global_step": 0},
    }
    # key the optional loss-scaler entry off the CHECKPOINT's contents —
    # a structure mismatch with the target makes orbax raise
    import orbax.checkpoint as ocp
    meta_tree = ocp.Checkpointer(ocp.StandardCheckpointHandler()).metadata(
        os.path.abspath(dirname))
    saved_keys = set(getattr(meta_tree, "item_metadata", meta_tree) or {})
    if "loss_scale_state" in saved_keys:
        ls = getattr(trainer.scope, "loss_scale_state", None)
        target["loss_scale_state"] = ls or {"scale": jnp.float32(0),
                                            "good_steps": jnp.int32(0),
                                            "overflows": jnp.int32(0)}
    restored = load_sharded(dirname, target=target)
    params, opt_state = trainer.stacked_from_logical(
        restored["params"], restored["opt_state"])
    trainer.scope.params = params
    trainer.scope.state = restored["state"]
    trainer.scope.opt_state = opt_state or None
    trainer.global_step = int(restored["meta"]["global_step"])
    # only adopt scaler state if this trainer actually runs a scaler —
    # step() donates the buffer and only a scaler refreshes it, so a
    # scaler-less trainer holding it would pass deleted arrays on step 2
    if "loss_scale_state" in restored and trainer.loss_scaler is not None:
        trainer.scope.loss_scale_state = restored["loss_scale_state"]
