"""Test config: force an 8-device virtual CPU mesh (SURVEY §4's
"multi-place in-process fixtures" analog — the XLA host-device-count
trick) so sharding paths are exercised without TPU hardware."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the session's axon/TPU default
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

# The axon sitecustomize boot hook force-updates jax_platforms to
# "axon,cpu" (axon/register/ifrt.py), which beats the env var — undo it
# before any backend is initialized.
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache (VERDICT r3 #3): the suite's cost is
# dominated by hundreds of small-model compiles that are identical from
# run to run. Cache them on disk so only the first run on a box pays.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

# jaxlib 0.9's CPU runtime cannot reliably RELOAD serialized
# multi-device executables: cpu_aot_loader rejects the cached machine
# features ("+prefer-no-scatter ... not supported on the host"), one
# partition thread dies, and the surviving threads deadlock at the
# collective rendezvous until its 40s termination timeout aborts the
# whole process ("Fatal Python error: Aborted" at an array fetch).
# Fresh compiles are fine — only the disk->executable round trip is
# broken — so gate persistent-cache READS to single-device programs:
# sharded tests recompile once per process (they are small models),
# every other program keeps the cache.
from jax._src import compiler as _jax_compiler

_orig_cache_read = _jax_compiler._cache_read


def _single_device_cache_read(module_name, cache_key, compile_options,
                              backend, *rest, **kw):
    # signature-tolerant: older jaxlibs call _cache_read without
    # executable_devices (and don't have the multi-device reload bug
    # this shim works around — let those read the cache unconditionally)
    devices = rest[0] if rest else kw.get("executable_devices")
    if devices is not None and len(devices) > 1:
        return None, None
    # The same runtime also mis-reloads DONATING executables: a
    # disk-reloaded train step occasionally loses the donation alias
    # info and a fetched output reads clobbered memory (observed as a
    # sporadic garbage/NaN loss right after a checkpoint save in the
    # resume-continuity tests — reproducible only with a warm cache,
    # never with fresh compiles). Gate the trainer's donating step
    # programs (train_step / run_k_steps) out of cache reads too;
    # forward/eval/infer programs keep the big cache win.
    try:  # one predicate, shared with the production gate
        from paddle_tpu.executor import DONATING_STEP_MODULE_TAGS as _tags
    except Exception:
        _tags = ("train_step", "run_k_steps")
    if any(tag in (module_name or "") for tag in _tags):
        return None, None
    return _orig_cache_read(module_name, cache_key, compile_options,
                            backend, *rest, **kw)


_jax_compiler._cache_read = _single_device_cache_read

import numpy as np
import pytest

assert jax.devices()[0].platform == "cpu", "tests must run on the virtual CPU mesh"
assert jax.device_count() == 8, "xla_force_host_platform_device_count=8 not in effect"


@pytest.fixture
def rng():
    import jax
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def _seed_numpy():
    np.random.seed(0)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Persist per-test call durations for the smoke-budget checker
    (tools/smoke_budget.py; VERDICT r4 #9: the tier keeps absorbing new
    tests — without a CI-visible timing record it drifts back past the
    10-minute goal). Only full-ish runs are recorded so a single-test
    debug invocation never overwrites the tier's record."""
    import json
    import os

    stats = terminalreporter.stats
    calls = [r for r in stats.get("passed", []) + stats.get("failed", [])
             if getattr(r, "when", "call") == "call"]
    if len(calls) < 100:
        return
    rec = {
        "total_s": round(sum(r.duration for r in calls), 1),
        "num_tests": len(calls),
        "markexpr": str(config.option.markexpr or ""),
        "durations": {r.nodeid: round(r.duration, 2)
                      for r in sorted(calls, key=lambda r: -r.duration)[:60]},
    }
    path = os.path.join(os.path.dirname(__file__), ".last_run_durations.json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
