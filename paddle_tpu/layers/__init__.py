"""Layer library — the ``fluid.layers`` surface (python/paddle/fluid/layers/)."""

from . import nn, ops, tensor
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
