"""Whole-zoo bf16-amp invariant: every model family builds, traces, and
takes one optimizer step under ``amp_guard("bfloat16")``.

The bug class this pins: a hand-rolled scan cell (or any custom math)
that uses f32 parameters without ``cast_compute`` promotes the bf16
carry/activations — either a scan carry dtype error at trace time
(how the seq2seq decoder failed when it joined the bench) or silently
f32 matmuls at ~1/8 MXU rate. One step per family keeps it cheap;
train-path dtype CLEANLINESS (no f32×f32 dots) is pinned separately in
test_mxu_dtypes.py for the bench configs.
"""

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.framework import amp_guard

R = np.random.RandomState


def _seq_feed(rng, bs=2, s=6, vocab=32):
    src = rng.randint(3, vocab, (bs, s)).astype(np.int64)
    trg = np.zeros_like(src)
    trg[:, 0] = 1
    trg[:, 1:] = src[:, :-1]
    labels = np.concatenate([trg[:, 1:], np.full((bs, 1), 2)],
                            axis=1).astype(np.int64)
    return {"src_ids": src, "trg_ids": trg, "labels": labels,
            "src_lengths": np.full((bs,), s, np.int64)}


def _zoo():
    rng = R(0)

    def mnist():
        from paddle_tpu.models import mnist as m
        return m.conv_net, {
            "image": rng.randn(2, 1, 28, 28).astype(np.float32),
            "label": rng.randint(0, 10, (2, 1)).astype(np.int64)}

    def fit_a_line():
        from paddle_tpu.models import fit_a_line as m
        return m.make_model(), {
            "x": rng.randn(4, 13).astype(np.float32),
            "y": rng.randn(4, 1).astype(np.float32)}

    def resnet():
        from paddle_tpu.models import resnet as m
        return m.make_model(depth=50, class_num=4, image_size=32), {
            "image": rng.randn(2, 3, 32, 32).astype(np.float32),
            "label": rng.randint(0, 4, (2, 1)).astype(np.int64)}

    def vgg():
        from paddle_tpu.models import vgg as m
        return m.make_model(depth=16, class_num=4, fc_dim=64), {
            "image": rng.randn(2, 3, 32, 32).astype(np.float32),
            "label": rng.randint(0, 4, (2, 1)).astype(np.int64)}

    def lstm():
        from paddle_tpu.models import lstm as m
        return m.make_model(vocab_size=64, emb_dim=16, hidden_dim=16,
                            num_layers=2), {
            "word_ids": rng.randint(0, 64, (2, 6)).astype(np.int64),
            "label": rng.randint(0, 2, (2, 1)).astype(np.int64),
            "sequence_length": np.full((2,), 6, np.int64)}

    def transformer():
        from paddle_tpu.models import transformer as m
        cfg = m.base_config(src_vocab=64, trg_vocab=64, d_model=32,
                            d_inner=64, num_heads=2, num_encoder_layers=1,
                            num_decoder_layers=1, dropout=0.0,
                            dtype="bfloat16", fused_ce=True)
        f = _seq_feed(rng, vocab=64)
        f.pop("src_lengths")
        return m.make_model(cfg), {k: v.astype(np.int32) for k, v in f.items()}

    def seq2seq():
        from paddle_tpu.models import seq2seq as m
        return m.make_model(src_vocab=32, trg_vocab=32, emb_dim=8,
                            hidden=8), _seq_feed(rng)

    def gpt():
        from paddle_tpu.models import gpt as m
        cfg = m.base_config(vocab_size=64, d_model=32, d_inner=64,
                            num_heads=2, num_layers=1, max_len=8,
                            use_flash=False, fused_ce=True, dtype="bfloat16")
        ids = rng.randint(3, 64, (2, 8)).astype(np.int32)
        return m.make_model(cfg), {
            "ids": ids, "labels": np.roll(ids, -1, 1).astype(np.int32)}

    def bert():
        from paddle_tpu.models import bert as m
        cfg = m.base_config(vocab_size=64, d_model=32, d_inner=64,
                            num_heads=2, num_layers=1, max_len=16,
                            dropout=0.0, dtype="bfloat16")
        ids = rng.randint(3, 64, (2, 8)).astype(np.int32)
        return m.make_pretrain_model(cfg), {
            "input_ids": ids,
            "token_type_ids": np.zeros((2, 8), np.int32),
            "mlm_positions": rng.randint(0, 8, (2, 2)).astype(np.int32),
            "mlm_labels": rng.randint(0, 64, (2, 2, 1)).astype(np.int64),
            "nsp_label": rng.randint(0, 2, (2, 1)).astype(np.int64)}

    def moe():
        from paddle_tpu.models import moe_transformer as m
        cfg = m.base_config(vocab_size=64, d_model=32, num_heads=2,
                            num_layers=2, num_experts=2, max_len=8,
                            dtype="bfloat16")
        ids = rng.randint(3, 64, (2, 8)).astype(np.int32)
        return m.make_model(cfg), {
            "ids": ids, "labels": np.roll(ids, -1, 1).astype(np.int32)}

    def deepfm():
        from paddle_tpu.models import deepfm as m
        return m.make_model(num_sparse_fields=4, sparse_feature_dim=32,
                            embedding_size=4, num_dense=3,
                            hidden_dims=(8, 8)), {
            "dense": rng.randn(2, 3).astype(np.float32),
            "sparse_ids": rng.randint(0, 32, (2, 4)).astype(np.int32),
            "label": rng.randint(0, 2, (2, 1)).astype(np.int64)}

    def word2vec():
        from paddle_tpu.models import word2vec as m
        return m.make_model(dict_size=32, emb_dim=8, hidden=16, context=4), {
            "context_ids": rng.randint(0, 32, (2, 4)).astype(np.int64),
            "label": rng.randint(0, 32, (2, 1)).astype(np.int64)}

    def recommender():
        from paddle_tpu.models import recommender as m
        return m.make_model(emb_dim=8, fc_dim=16), {
            "user_id": rng.randint(1, 900, (2, 1)).astype(np.int64),
            "gender_id": rng.randint(0, 2, (2, 1)).astype(np.int64),
            "age_id": rng.randint(0, 7, (2, 1)).astype(np.int64),
            "job_id": rng.randint(0, 21, (2, 1)).astype(np.int64),
            "movie_id": rng.randint(1, 1600, (2, 1)).astype(np.int64),
            "category_ids": rng.randint(0, 18, (2, 3)).astype(np.int64),
            "title_ids": rng.randint(0, 1000, (2, 4)).astype(np.int64),
            "score": rng.rand(2, 1).astype(np.float32) * 5}

    def srl():
        from paddle_tpu.models import srl as m
        return m.make_model(vocab_size=64, num_labels=5, word_dim=8,
                            hidden_dim=16, depth=2), {
            "word_ids": rng.randint(0, 64, (2, 6)).astype(np.int64),
            "mark_ids": rng.randint(0, 2, (2, 6)).astype(np.int64),
            "label": rng.randint(0, 5, (2, 6)).astype(np.int64),
            "lengths": np.full((2,), 6, np.int64)}

    return {f.__name__: f for f in
            [mnist, fit_a_line, resnet, vgg, lstm, transformer, seq2seq,
             gpt, bert, moe, deepfm, word2vec, recommender, srl]}


_ZOO = _zoo()


_SLOW = {"resnet"}  # ~20s compile; the rest stay in the smoke tier


@pytest.mark.parametrize(
    "family", [pytest.param(f, marks=pytest.mark.slow) if f in _SLOW else f
               for f in sorted(_ZOO)])
def test_one_train_step_under_bf16_amp(family):
    with amp_guard("bfloat16"):
        model_fn, feed = _ZOO[family]()
        model = pt.build(model_fn)
        trainer = pt.Trainer(model, opt.Adam(1e-3), loss_name="loss")
        trainer.startup(sample_feed=feed)
        out = trainer.step(feed)
        loss = float(out["loss"])
    assert np.isfinite(loss), f"{family}: non-finite loss {loss} under amp"
