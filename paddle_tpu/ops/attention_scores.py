"""Mixed-precision QK^T scores for the dense (XLA) attention path.

Companion to the flash kernels (ops/flash_attention.py): default
autodiff of an (bf16, bf16)→f32 score einsum computes dq/dk as
(f32 cotangent)×(f32-upcast operand) dots — f32×f32 runs at ~1/8 MXU
rate, and the dense attention path pays it at every site. ``scores_mxu``
is a custom-VJP QK^T·scale that folds the scale into the f32 cotangent
and casts it to the input dtype before the backward einsums — the same
rounding the flash kernels apply in-kernel. Numerically a no-op for
f32 inputs.

Lives in ``ops`` (below ``layers``) so layers/attention.py and
layers/stacked.py import downward, keeping the ops←layers dependency
direction clean.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def scores_mxu(q, k, scale: float):
    """QK^T·scale over [b, h, s, d] with f32 accumulation and
    input-dtype backward matmuls."""
    return jnp.einsum("bhqd,bhkd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def _scores_fwd(q, k, scale):
    return scores_mxu(q, k, scale), (q, k)


def _scores_bwd(scale, res, ct):
    q, k = res
    ct = (ct * scale).astype(q.dtype)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ct, k,
                    preferred_element_type=jnp.float32)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ct, q,
                    preferred_element_type=jnp.float32)
    return dq.astype(q.dtype), dk.astype(k.dtype)


scores_mxu.defvjp(_scores_fwd, _scores_bwd)
