"""Flight recorder: flush the journal's recent-event ring to disk when
the process hits a crash-shaped trigger.

The profiler (PR 6) explains *why* a step is slow and the registry
says *that* something is wrong; this module captures *what the system
was doing in the seconds before it died*. The journal already retains
a bounded ring of recent events; on a trigger —

- guard escalation (``FloatingPointError`` from the NaN/Inf guard),
- serving watchdog ``WorkerHung``,
- circuit-breaker trip,
- SIGTERM/SIGINT preemption,
- ``ReshardError`` on restore,
- an unhandled ``fit`` exception

— :meth:`FlightRecorder.dump` writes it to a directory using the SAME
commit discipline as checkpoints: files land in a ``*.tmp.<pid>``
sibling, get fsynced, a ``resilience.write_manifest`` CRC manifest is
written LAST, and the directory is renamed into place — a dump can be
trusted or discarded, never half-read. ``tools/flight_dump.py``
pretty-prints/filters one.

Dumps rotate (oldest removed past ``max_dumps``): a crash-looping
process must not fill the disk with its own black boxes. Dump root:
``PDTPU_FLIGHT_DIR`` env, else ``<tmp>/paddle_tpu_flight`` —
``fit(checkpoint_config=...)`` re-roots the process recorder next to
the checkpoints so operators find both in one place.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from .journal import RunJournal, get_journal

EVENTS_NAME = "events.jsonl"
META_NAME = "flight.json"


def default_flight_dir() -> str:
    return os.environ.get(
        "PDTPU_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), "paddle_tpu_flight"))


class FlightRecorder:
    """Dump-on-trigger writer over a :class:`RunJournal`'s ring."""

    def __init__(self, journal: Optional[RunJournal] = None,
                 root: Optional[str] = None, max_dumps: int = 8):
        self._journal = journal
        self.root = root
        self.max_dumps = int(max_dumps)
        self._lock = threading.Lock()
        # serializes concurrent dumpers (two breakers tripping at the
        # same journal seq must not share a tmp dir) — separate from
        # _lock so set_root never waits on disk I/O
        self._dump_lock = threading.Lock()
        self._tmp_seq = 0
        self.dumps: List[str] = []  # paths written by THIS recorder

    @property
    def journal(self) -> RunJournal:
        return self._journal if self._journal is not None else get_journal()

    def set_root(self, root: Optional[str]) -> None:
        with self._lock:
            self.root = root

    def dump(self, trigger: str, detail: Optional[Dict[str, Any]] = None,
             span: Optional[str] = None,
             root: Optional[str] = None) -> Optional[str]:
        """Flush the ring to ``<root>/flight_<runid>_<seq>_<trigger>``
        (atomic, CRC-manifested). Returns the committed path, or None
        on failure — the recorder reports a crash, it must never BE
        the crash, so filesystem errors are swallowed into a log line.
        ``span``/``detail`` land in ``flight.json`` so the dump names
        the offending request/step without grepping."""
        journal = self.journal
        try:
            return self._dump(journal, trigger, detail, span, root)
        except Exception as e:  # pragma: no cover - defensive
            _log().warning("flight-recorder dump for %r failed: %s: %s",
                           trigger, type(e).__name__, e)
            return None

    def _dump(self, journal, trigger, detail, span, root) -> str:
        from .. import resilience
        from .registry import get_registry

        # serialize the whole write+rename: two threads dumping the
        # same trigger at the same seq would otherwise interleave
        # files in one tmp dir and commit a mixed-content black box
        with self._dump_lock:
            return self._dump_locked(journal, trigger, detail, span, root,
                                     resilience, get_registry())

    def _dump_locked(self, journal, trigger, detail, span, root,
                     resilience, registry) -> str:
        with self._lock:
            base = root or self.root or default_flight_dir()
            self._tmp_seq += 1
            tmp_seq = self._tmp_seq
        os.makedirs(base, exist_ok=True)
        events = journal.recent()
        tag = _safe_tag(trigger)
        final = os.path.join(
            base, f"flight_{journal.run_id}_{journal.seq:08d}_{tag}")
        tmp = f"{final}{resilience.TMP_MARKER}{os.getpid()}.{tmp_seq}"
        os.makedirs(tmp, exist_ok=True)
        meta = {
            "run": journal.run_id,
            "trigger": trigger,
            "wall_time": time.time(),
            "span": span,
            "detail": detail or {},
            "num_events": len(events),
            "first_seq": events[0]["seq"] if events else None,
            "last_seq": events[-1]["seq"] if events else None,
            # the registry snapshot rides along: the dump answers "what
            # were the counters at the moment of death" by itself
            "metrics": _metrics_snapshot(registry),
        }
        with open(os.path.join(tmp, EVENTS_NAME), "w",
                  encoding="utf-8") as f:
            for e in events:
                f.write(json.dumps(e, sort_keys=True, default=repr) + "\n")
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp, META_NAME), "w", encoding="utf-8") as f:
            json.dump(meta, f, sort_keys=True, default=repr)
            f.flush()
            os.fsync(f.fileno())
        resilience.write_manifest(
            tmp, meta={"global_step": 0, "flight_trigger": trigger,
                       "run": journal.run_id})
        if os.path.isdir(final):  # same-seq retrigger: replace
            shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)
        with self._lock:
            self.dumps.append(final)
        self._rotate(base)
        _log().error("flight recorder: dumped %d event(s) to %s "
                     "(trigger=%s)", len(events), final, trigger)
        return final

    def _rotate(self, base: str) -> None:
        try:
            dumps = sorted(
                d for d in os.listdir(base)
                if d.startswith("flight_") and ".tmp." not in d
                and os.path.isdir(os.path.join(base, d)))
        except OSError:
            return
        for stale in dumps[:-self.max_dumps] if self.max_dumps > 0 else []:
            shutil.rmtree(os.path.join(base, stale), ignore_errors=True)


def _metrics_snapshot(registry) -> Dict[str, Any]:
    try:
        return registry.snapshot()
    except Exception:  # a broken collector must not lose the dump
        return {}


def _safe_tag(trigger: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "-"
                   for c in trigger)[:48] or "trigger"


def _log():
    import logging
    return logging.getLogger("paddle_tpu.telemetry")


# -- the process-wide default recorder ----------------------------------------

_default_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """THE process flight recorder (rides the process journal)."""
    return _default_recorder


def flight_dump(trigger: str, detail: Optional[Dict[str, Any]] = None,
                span: Optional[str] = None,
                root: Optional[str] = None) -> Optional[str]:
    """Module-level convenience: dump via the process recorder."""
    return _default_recorder.dump(trigger, detail=detail, span=span,
                                  root=root)


__all__ = ["EVENTS_NAME", "META_NAME", "FlightRecorder",
           "default_flight_dir", "flight_dump", "get_recorder"]
