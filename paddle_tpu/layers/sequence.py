"""Variable-length sequence ops — the LoD equivalent.

The reference's answer to ragged batches is LoD (level-of-detail)
offsets on tensors (lod_tensor.h:58-110) with ~30 sequence_* ops
respecting them (sequence_pool/expand/pad/softmax/..., SURVEY §5).
LoD's dynamic offsets don't fit XLA's static-shape model, so the
TPU-native design (SURVEY §7 hard-part 1) uses two interchangeable
static-shape representations:

- **packed**: values [total, ...] + ``segment_ids`` [total] (row id per
  element, non-decreasing) with a static ``num_seqs``. The direct LoD
  analog; segment reductions lower to efficient one-hot matmuls /
  scatter-adds on TPU.
- **padded**: values [batch, max_len, ...] + ``lengths`` [batch].

Conversions (= sequence_pad/unpad ops) are provided, plus lod-offset
(row_splits) helpers matching the reference's recursive_sequence_lengths
API. All ops are jit-safe: shapes depend only on statics.

Multi-level (nested) LoD — lod_tensor.h:58-110 stores a *vector* of
levels so a tensor can be e.g. paragraphs→sentences→words — is carried
by :class:`LoDTensor`: packed device values + the per-level length
lists as host metadata (exactly where the reference keeps LoD: on the
CPU side of the tensor, never on device). Level views project any
level down to row-granular segment ids, so every packed op here works
at any level; `sequence_expand(..., ref_level=)` and
`LoDTensor.pool(level=)` give the reference's level-selecting ops, and
`beam_search_decode_lod` emits the reference's 2-level
(source-sentence → hypothesis → token) decode output
(beam_search_decode_op.cc).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.errors import enforce

# ---------------------------------------------------------------------------
# representation converters (LoD <-> segment ids <-> padded)
# ---------------------------------------------------------------------------


def lengths_to_offsets(lengths):
    """lengths [b] -> lod offsets/row_splits [b+1] (lod_tensor.h LoD level)."""
    return jnp.concatenate([jnp.zeros(1, lengths.dtype), jnp.cumsum(lengths)])


def offsets_to_lengths(offsets):
    return offsets[1:] - offsets[:-1]


def lengths_to_segment_ids(lengths, total: int):
    """lengths [b] -> segment ids [total]; positions past sum(lengths)
    get id b (one-past-last) so they drop out of segment reductions that
    use num_segments=b."""
    offsets = jnp.cumsum(lengths)
    pos = jnp.arange(total)
    return jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32)


def segment_ids_to_lengths(segment_ids, num_seqs: int):
    return jax.ops.segment_sum(jnp.ones_like(segment_ids), segment_ids,
                               num_segments=num_seqs)


def sequence_pad(packed, lengths, max_len: int, pad_value=0.0):
    """packed [total, ...] + lengths [b] -> (padded [b, max_len, ...],
    lengths) (sequence_pad_op.cc analog)."""
    total = packed.shape[0]
    b = lengths.shape[0]
    offsets = jnp.concatenate([jnp.zeros(1, lengths.dtype), jnp.cumsum(lengths)[:-1]])
    row = jnp.arange(b)[:, None]
    col = jnp.arange(max_len)[None, :]
    src = offsets[:, None] + col  # [b, max_len] gather indices
    valid = col < lengths[:, None]
    src = jnp.clip(src, 0, total - 1)
    out = packed[src]  # [b, max_len, ...]
    mask = valid.reshape(valid.shape + (1,) * (out.ndim - 2))
    return jnp.where(mask, out, pad_value), lengths


def sequence_unpad(padded, lengths):
    """padded [b, max_len, ...] + lengths -> packed [b*max_len, ...] with
    segment ids; invalid tail positions get segment id b (dropped by
    segment reductions). (sequence_unpad_op.cc analog — static total =
    b*max_len, the padded-capacity design.)"""
    b, t = padded.shape[0], padded.shape[1]
    flat = padded.reshape((b * t,) + padded.shape[2:])
    col = jnp.arange(t)[None, :]
    valid = col < lengths[:, None]
    seg = jnp.where(valid, jnp.arange(b)[:, None], b).reshape(-1).astype(jnp.int32)
    # order within capacity is row-major; reductions don't care about gaps
    return flat, seg


# ---------------------------------------------------------------------------
# segment reductions (sequence_pool family, sequence_pool_op.cc)
# ---------------------------------------------------------------------------


def sequence_pool(packed, segment_ids, num_seqs: int, pool_type: str = "average"):
    """Pool each sequence (sequence_pool_op.cc analog). pool_type ∈
    {sum, average, sqrt, max, min, first, last}. Elements with
    segment_id >= num_seqs are ignored."""
    pool_type = pool_type.lower()
    if pool_type in ("sum", "average", "sqrt"):
        s = jax.ops.segment_sum(packed, segment_ids, num_segments=num_seqs)
        if pool_type == "sum":
            return s
        cnt = jax.ops.segment_sum(jnp.ones((packed.shape[0],), packed.dtype),
                                  segment_ids, num_segments=num_seqs)
        cnt = jnp.maximum(cnt, 1.0).reshape((num_seqs,) + (1,) * (packed.ndim - 1))
        return s / cnt if pool_type == "average" else s / jnp.sqrt(cnt)
    if pool_type == "max":
        return jax.ops.segment_max(packed, segment_ids, num_segments=num_seqs)
    if pool_type == "min":
        return jax.ops.segment_min(packed, segment_ids, num_segments=num_seqs)
    if pool_type in ("first", "last"):
        total = packed.shape[0]
        pos = jnp.arange(total)
        if pool_type == "first":
            idx = jax.ops.segment_min(pos, segment_ids, num_segments=num_seqs)
        else:
            idx = jax.ops.segment_max(pos, segment_ids, num_segments=num_seqs)
        idx = jnp.clip(idx, 0, total - 1)
        return packed[idx]
    raise ValueError(f"unknown pool_type {pool_type}")


def sequence_first_step(packed, segment_ids, num_seqs: int):
    return sequence_pool(packed, segment_ids, num_seqs, "first")


def sequence_last_step(packed, segment_ids, num_seqs: int):
    return sequence_pool(packed, segment_ids, num_seqs, "last")


def sequence_softmax(packed, segment_ids, num_seqs: int):
    """Softmax within each sequence (sequence_softmax_op.cc analog):
    numerically stable segment-wise log-sum-exp."""
    m = jax.ops.segment_max(packed, segment_ids, num_segments=num_seqs)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    shifted = packed - m[segment_ids]
    e = jnp.exp(shifted)
    denom = jax.ops.segment_sum(e, segment_ids, num_segments=num_seqs)
    return e / jnp.maximum(denom[segment_ids], 1e-30)


def sequence_expand(x, ref_lengths, axis_total: int = None, ref_level: int = -1):
    """Repeat each row x[i] ref_lengths[i] times (sequence_expand_op.cc
    analog). ``axis_total`` = static output length (= padded capacity of
    sum(ref_lengths)). ``ref_lengths`` may be an :class:`LoDTensor`, in
    which case ``ref_level`` selects which of its LoD levels supplies the
    repeat counts — the op's ref_level attribute: level i's lengths count
    units of level i+1, so expanding by an outer level repeats x rows by
    sub-sequence counts, not token counts."""
    if isinstance(ref_lengths, LoDTensor):
        lens = ref_lengths.seq_lens[ref_lengths._level(ref_level)]
        if axis_total is None:
            axis_total = int(sum(lens))
        ref_lengths = jnp.asarray(lens, jnp.int32)
    enforce(axis_total is not None,
            "sequence_expand: axis_total required for array lengths")
    seg = lengths_to_segment_ids(ref_lengths, axis_total)
    seg = jnp.clip(seg, 0, x.shape[0] - 1)
    return x[seg]


def sequence_reverse(packed, segment_ids, num_seqs: int):
    """Reverse each sequence in place (sequence_reverse_op.cc analog)."""
    total = packed.shape[0]
    pos = jnp.arange(total)
    first = jax.ops.segment_min(pos, segment_ids, num_segments=num_seqs + 1)
    last = jax.ops.segment_max(pos, segment_ids, num_segments=num_seqs + 1)
    sid = jnp.clip(segment_ids, 0, num_seqs)
    mirrored = first[sid] + last[sid] - pos
    valid = segment_ids < num_seqs
    src = jnp.where(valid, mirrored, pos)
    return packed[src]


def sequence_concat(packed_list, segment_ids_list, num_seqs: int):
    """Concatenate sequences element-wise by segment (sequence_concat_op
    analog): all inputs share num_seqs; output packs seq0 of every input,
    then seq1, ... Returns (packed, segment_ids)."""
    packed = jnp.concatenate(packed_list, axis=0)
    seg = jnp.concatenate(segment_ids_list, axis=0)
    order = jnp.argsort(seg, stable=True)
    return packed[order], seg[order]


def sequence_enumerate(ids, win_size: int, pad_value: int = 0):
    """sequence_enumerate_op analog over padded [b, t] ids: sliding
    windows [b, t, win_size]."""
    b, t = ids.shape
    cols = []
    for w in range(win_size):
        shifted = jnp.pad(ids[:, w:], ((0, 0), (0, w)), constant_values=pad_value)
        cols.append(shifted)
    return jnp.stack(cols, axis=-1)


def sequence_mask(lengths, maxlen: int, dtype=jnp.float32):
    """sequence_mask op analog: [b, maxlen] 1/0 mask."""
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


def sequence_erase(packed, segment_ids, tokens_to_erase, num_seqs: int):
    """sequence_erase_op analog — static-shape variant: marks erased
    positions with segment id num_seqs (so reductions skip them) instead
    of compacting. Returns (packed, new_segment_ids)."""
    erase = jnp.zeros(packed.shape[0], jnp.bool_)
    for t in tokens_to_erase:
        erase = erase | (packed == t)
    new_seg = jnp.where(erase, num_seqs, segment_ids).astype(jnp.int32)
    return packed, new_seg


def sequence_slice(packed, segment_ids, num_seqs: int, offset, length,
                   total_out: int):
    """sequence_slice_op analog: per-sequence [offset, offset+length)
    window, repacked into capacity ``total_out`` with fresh segment ids."""
    pos = jnp.arange(packed.shape[0])
    first = jax.ops.segment_min(pos, segment_ids, num_segments=num_seqs + 1)[:num_seqs]
    out_seg = lengths_to_segment_ids(length, total_out)
    out_seg_c = jnp.clip(out_seg, 0, num_seqs - 1)
    out_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(length)[:-1].astype(jnp.int32)])
    within = jnp.arange(total_out) - out_off[out_seg_c]
    src = first[out_seg_c] + offset[out_seg_c] + within
    src = jnp.clip(src, 0, packed.shape[0] - 1)
    return packed[src], jnp.where(out_seg < num_seqs, out_seg, num_seqs).astype(jnp.int32)


def sequence_conv(packed, segment_ids, num_filters: int, filter_size: int = 3,
                  filter_stride: int = 1, padding=None, param_attr=None,
                  bias_attr=None, act=None, name=None):
    """Sequence (time) convolution on packed values + segment-ids
    (sequence_conv_op.cc; layers/nn.py:1349 sets context_start =
    -filter_size//2). Each output row t sees rows
    [t+context_start, t+context_start+filter_size) of its own sequence;
    positions crossing a boundary contribute zero — the im2col-over-time
    the reference does per LoD span, here as one shifted-matmul per tap
    so the MXU sees filter_size big GEMMs."""
    from ..framework import LayerHelper, cast_compute
    from .. import initializer as init
    from .ops import apply_activation

    enforce(filter_stride == 1, "sequence_conv: only stride 1 (reference semantics)")
    helper = LayerHelper("sequence_conv", name=name)
    total, d = packed.shape
    context_start = -(filter_size // 2)
    w = helper.create_parameter("w", (filter_size * d, num_filters), jnp.float32,
                                attr=param_attr, initializer=init.Xavier())
    x, w = cast_compute(packed, w)
    out = jnp.zeros((total, num_filters), x.dtype)
    pos = jnp.arange(total)
    for tap in range(filter_size):
        off = context_start + tap
        src = jnp.clip(pos + off, 0, total - 1)
        valid = ((pos + off >= 0) & (pos + off < total)
                 & (segment_ids[src] == segment_ids))[:, None]
        shifted = jnp.where(valid, x[src], 0.0)
        out = out + jnp.matmul(shifted, w[tap * d:(tap + 1) * d])
    if bias_attr is not False:
        b = helper.create_parameter("b", (num_filters,), jnp.float32, attr=bias_attr,
                                    initializer=init.Constant(0.0))
        out = out + b.astype(out.dtype)
    return apply_activation(out, act)


def sequence_expand_as(x, ref_lengths, axis_total: int):
    """sequence_expand_as_op analog: row i of x is repeated
    ref_lengths[i] times (each input sequence must have exactly one row —
    the common fluid usage). Same lowering as sequence_expand."""
    return sequence_expand(x, ref_lengths, axis_total)


def sequence_reshape(packed, lengths, new_dim: int):
    """sequence_reshape_op analog: refold each sequence's flat payload to
    width new_dim. lengths scale by old_dim/new_dim. Returns
    (packed2, lengths2)."""
    total, d = packed.shape
    enforce(total * d % new_dim == 0, "sequence_reshape: size not divisible")
    out = packed.reshape(total * d // new_dim, new_dim)
    new_lengths = (jnp.asarray(lengths) * d) // new_dim
    return out, new_lengths


def sequence_scatter(x, ids, ids_segment_ids, updates):
    """sequence_scatter_op analog: for packed (ids, updates) with
    segment-ids mapping each entry to a row of x:
    out[seg[j], ids[j]] += updates[j]."""
    seg = jnp.asarray(ids_segment_ids).astype(jnp.int32)
    idx = jnp.asarray(ids).reshape(-1).astype(jnp.int32)
    return x.at[seg, idx].add(updates.astype(x.dtype))


def lod_reset(x, target_lengths, capacity: Optional[int] = None):
    """lod_reset_op analog: keep values, re-segment. Returns
    (x, segment_ids) built from target_lengths over x's row capacity."""
    cap = capacity if capacity is not None else x.shape[0]
    return x, lengths_to_segment_ids(jnp.asarray(target_lengths), cap)


def reorder_lod_tensor_by_rank(padded, lengths):
    """reorder_lod_tensor_by_rank_op + lod_rank_table analog: permute the
    batch into descending-length order. Returns (padded', lengths', perm);
    invert with jnp.argsort(perm) — the reorder_lod_tensor_by_rank(X,
    RankTable) inverse the reference builds for restoring order."""
    lengths = jnp.asarray(lengths)
    perm = jnp.argsort(-lengths, stable=True)
    return padded[perm], lengths[perm], perm


class LoDTensor:
    """Packed values + nested LoD metadata (lod_tensor.h:58-110).

    ``recursive_seq_lens`` is a list of levels, outermost first; each
    level's entries count units of the next level (the innermost level
    counts value rows) — the reference's recursive_sequence_lengths()
    view of its offset vector-of-levels. Lengths are host python ints
    (static at trace time), values a device array: on TPU ragged
    structure must be static, and the reference itself keeps LoD on the
    host side of the tensor.

    Iterates as the classic single-level ``(values, lengths,
    segment_ids)`` triple (innermost level) so single-level callers are
    unchanged.
    """

    def __init__(self, values, recursive_seq_lens):
        import numpy as np
        enforce(len(recursive_seq_lens) > 0,
                "LoDTensor: recursive_seq_lens must have at least one level")
        if not isinstance(recursive_seq_lens[0], (list, tuple, np.ndarray)):
            recursive_seq_lens = [list(recursive_seq_lens)]
        self.values = jnp.asarray(values)
        self.seq_lens = [[int(v) for v in level] for level in recursive_seq_lens]
        for li in range(len(self.seq_lens) - 1):
            enforce(sum(self.seq_lens[li]) == len(self.seq_lens[li + 1]),
                    f"LoDTensor: level {li} lengths must sum to the number of "
                    f"level-{li + 1} sequences "
                    f"({sum(self.seq_lens[li])} != {len(self.seq_lens[li + 1])})")
        if self.seq_lens:
            enforce(sum(self.seq_lens[-1]) == int(self.values.shape[0]),
                    "LoDTensor: innermost lengths must sum to data rows")

    # -- reference API surface (lod_tensor.h accessors) --
    @property
    def lod_level(self) -> int:
        return len(self.seq_lens)

    def recursive_sequence_lengths(self):
        return [list(level) for level in self.seq_lens]

    def lod(self):
        """Offset form: each level's offsets index units of the next
        level (rows for the innermost) — LoD in lod_tensor.h:58."""
        out = []
        for level in self.seq_lens:
            offs, acc = [0], 0
            for n in level:
                acc += n
                offs.append(acc)
            out.append(offs)
        return out

    # -- level views --
    def _level(self, level: int) -> int:
        """Normalize a python-style level index, rejecting out-of-range
        values loudly (the reference op bound-checks its ref_level attr)
        instead of silently wrapping to the wrong level."""
        enforce(-self.lod_level <= level < self.lod_level,
                f"LoD level {level} out of range for lod_level={self.lod_level}")
        return level % self.lod_level

    def num_seqs(self, level: int = 0) -> int:
        return len(self.seq_lens[self._level(level)])

    def row_lengths(self, level: int = -1):
        """Lengths at ``level`` measured in value rows: compose every
        level below it. For lod [[2,1],[3,2,4]], row_lengths(0) = [5,4]."""
        level = self._level(level)
        lens = list(self.seq_lens[-1])
        for li in range(self.lod_level - 2, level - 1, -1):
            grouped, pos = [], 0
            for n in self.seq_lens[li]:
                grouped.append(sum(lens[pos:pos + n]))
                pos += n
            lens = grouped
        return lens

    def segment_ids(self, level: int = -1):
        """Row-granular segment ids mapping each value row to its
        ``level`` sequence — the projection that lets every packed op in
        this module operate at any LoD level."""
        lens = jnp.asarray(self.row_lengths(level), jnp.int32)
        return lengths_to_segment_ids(lens, int(self.values.shape[0]))

    def pool(self, pool_type: str = "average", level: int = -1):
        """sequence_pool at ``level``. Pooling the innermost level keeps
        the outer levels (each inner sequence becomes one row), matching
        the reference where sequence_pool consumes the last LoD level;
        pooling an outer level collapses everything below it in one
        segment reduction. Returns an LoDTensor while levels remain,
        else the plain pooled array."""
        level = self._level(level)
        pooled = sequence_pool(self.values, self.segment_ids(level),
                               self.num_seqs(level), pool_type)
        if level == 0:
            return pooled
        return LoDTensor(pooled, self.seq_lens[:level])

    def sequences(self, level: int = -1):
        """Host-side ragged view: nested python lists of numpy rows,
        split at ``level`` (and below) — the to-python escape hatch the
        reference's LoDTensor array interface provides."""
        import numpy as np
        vals = np.asarray(self.values)
        flat = np.split(vals, np.cumsum(self.row_lengths(-1))[:-1])
        level = self._level(level)
        for li in range(self.lod_level - 2, level - 1, -1):
            grouped, pos = [], 0
            for n in self.seq_lens[li]:
                grouped.append(flat[pos:pos + n])
                pos += n
            flat = grouped
        return flat

    def __iter__(self):
        lens = jnp.asarray(self.row_lengths(-1), jnp.int32)
        return iter((self.values, lens, self.segment_ids(-1)))

    def __repr__(self):
        return (f"LoDTensor(shape={tuple(self.values.shape)}, "
                f"lod={self.recursive_sequence_lengths()})")


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """lod_tensor.py create_lod_tensor analog. Returns an
    :class:`LoDTensor` carrying the FULL nested structure
    (lod_tensor.h:58 vector-of-levels); unpacking it as ``values, lens,
    seg`` yields the innermost-level triple, so one-level callers (the
    overwhelmingly common case) read exactly as before."""
    return LoDTensor(data, recursive_seq_lens)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low: int = 0, high: int = 1):
    """lod_tensor.py create_random_int_lodtensor analog."""
    import numpy as np
    lens = recursive_seq_lens
    while isinstance(lens[0], (list, tuple)):
        lens = lens[-1]
    total = int(np.sum(lens))
    data = np.random.randint(low, high + 1, (total,) + tuple(base_shape)).astype(np.int32)
    return create_lod_tensor(data, recursive_seq_lens, place)
