"""Detection-op tests (test_iou_similarity_op / test_box_coder_op /
test_multiclass_nms_op / test_prior_box_op family analog)."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.layers import detection as D


def test_iou_similarity():
    a = jnp.asarray([[0.0, 0.0, 2.0, 2.0]])
    b = jnp.asarray([[1.0, 1.0, 3.0, 3.0], [0.0, 0.0, 2.0, 2.0], [5.0, 5.0, 6.0, 6.0]])
    iou = np.asarray(D.iou_similarity(a, b))[0]
    np.testing.assert_allclose(iou, [1 / 7, 1.0, 0.0], rtol=1e-5)


def test_box_coder_roundtrip():
    rng = np.random.RandomState(0)
    priors = jnp.asarray(np.abs(rng.rand(6, 4)).astype(np.float32))
    priors = priors.at[:, 2:].set(priors[:, :2] + 0.5)
    targets = priors + 0.1
    var = jnp.ones((6, 4)) * jnp.asarray([0.1, 0.1, 0.2, 0.2])
    enc = D.box_coder(priors, var, targets, "encode_center_size")
    dec = D.box_coder(priors, var, enc, "decode_center_size")
    np.testing.assert_allclose(np.asarray(dec), np.asarray(targets), rtol=1e-4, atol=1e-5)


def test_prior_box_shapes_and_range():
    boxes, var = D.prior_box((4, 4), (64, 64), min_sizes=[16.0], max_sizes=[32.0],
                             aspect_ratios=[1.0, 2.0], flip=True, clip=True)
    assert boxes.shape[:2] == (4, 4) and boxes.shape[-1] == 4
    assert var.shape == boxes.shape
    b = np.asarray(boxes)
    assert b.min() >= 0.0 and b.max() <= 1.0
    # center of cell (0,0) prior ~ (8/64, 8/64)
    cx = (b[0, 0, 0, 0] + b[0, 0, 0, 2]) / 2
    assert abs(cx - 8 / 64) < 1e-5


def test_nms_suppresses_overlaps():
    boxes = jnp.asarray([[0, 0, 2, 2], [0.1, 0.1, 2.1, 2.1], [5, 5, 7, 7]],
                        jnp.float32)
    scores = jnp.asarray([0.9, 0.8, 0.7])
    out_boxes, out_scores, valid = D.nms(boxes, scores, max_out=3, iou_threshold=0.5)
    v = np.asarray(valid)
    assert v.sum() == 2  # the overlapping 0.8 box suppressed
    np.testing.assert_allclose(np.asarray(out_scores)[:2], [0.9, 0.7], rtol=1e-6)


def test_multiclass_nms():
    boxes = jnp.asarray([[0, 0, 2, 2], [5, 5, 7, 7]], jnp.float32)
    scores = jnp.asarray([[0.9, 0.1], [0.2, 0.8]])  # [c=2, n=2]
    ob, osc, lbl, valid = D.multiclass_nms(boxes, scores, max_per_class=2)
    assert ob.shape == (2, 2, 4)
    assert bool(valid[0, 0]) and float(osc[0, 0]) == pytest.approx(0.9)


def test_bipartite_match():
    dist = jnp.asarray([[0.9, 0.1], [0.2, 0.8]])
    idx, val = D.bipartite_match(dist)
    np.testing.assert_array_equal(np.asarray(idx), [0, 1])
    np.testing.assert_allclose(np.asarray(val), [0.9, 0.8])


def test_ssd_loss_runs_and_positive():
    rng = np.random.RandomState(0)
    n, p, c = 2, 8, 4
    loss = D.ssd_loss(
        jnp.asarray(rng.randn(n, p, 4).astype(np.float32)),
        jnp.asarray(rng.randn(n, p, c).astype(np.float32)),
        jnp.asarray(rng.randn(n, p, 4).astype(np.float32)),
        jnp.asarray(rng.randint(0, c, (n, p))),
        jnp.asarray((rng.rand(n, p) > 0.7).astype(np.float32)))
    assert float(loss) > 0


def test_yolo_box_shapes():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, 3 * 7, 4, 4).astype(np.float32))
    boxes, scores = D.yolo_box(x, (128, 128), anchors=[10, 13, 16, 30, 33, 23],
                               class_num=2)
    assert boxes.shape == (1, 48, 4)
    assert scores.shape == (1, 48, 2)

# -- property oracles (random boxes; COMPLEMENT the fixed-seed cases
# above — those pin exact IoU=1/0 boundaries this strategy can't hit) --

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402



@st.composite
def boxes(draw, n_max=5):
    n = draw(st.integers(1, n_max))
    rng = np.random.RandomState(draw(st.integers(0, 2 ** 16)))
    x1y1 = rng.rand(n, 2).astype(np.float32) * 0.5
    wh = rng.rand(n, 2).astype(np.float32) * 0.4 + 0.05
    return np.concatenate([x1y1, x1y1 + wh], axis=1)


@settings(max_examples=30, deadline=None)
@given(boxes(), boxes())
def test_iou_similarity_matches_scalar_oracle(a, b):
    got = np.asarray(D.iou_similarity(jnp.asarray(a), jnp.asarray(b)))
    for i in range(a.shape[0]):
        for j in range(b.shape[0]):
            ix1, iy1 = max(a[i, 0], b[j, 0]), max(a[i, 1], b[j, 1])
            ix2, iy2 = min(a[i, 2], b[j, 2]), min(a[i, 3], b[j, 3])
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            area = lambda bx: (bx[2] - bx[0]) * (bx[3] - bx[1])
            want = inter / (area(a[i]) + area(b[j]) - inter + 1e-10)
            np.testing.assert_allclose(got[i, j], want, rtol=1e-4, atol=1e-5)
    assert (got >= -1e-6).all() and (got <= 1 + 1e-6).all()


@settings(max_examples=30, deadline=None)
@given(boxes(), st.integers(0, 2 ** 16))
def test_box_coder_encode_decode_roundtrip(gt, prior_seed):
    """decode(encode(gt, prior), prior) == gt — the property the SSD
    loss depends on. Priors/vars draw their own hypothesis seed so they
    vary (and shrink) independently of the target boxes."""
    rng = np.random.RandomState(prior_seed)
    n = gt.shape[0]
    prior = np.concatenate([rng.rand(n, 2) * 0.5,
                            rng.rand(n, 2) * 0.4 + 0.55], 1).astype(np.float32)
    var = (rng.rand(n, 4).astype(np.float32) * 0.2 + 0.05)
    enc = D.box_coder(jnp.asarray(prior), jnp.asarray(var), jnp.asarray(gt),
                      code_type="encode_center_size")
    dec = D.box_coder(jnp.asarray(prior), jnp.asarray(var), enc,
                      code_type="decode_center_size")
    np.testing.assert_allclose(np.asarray(dec), gt, rtol=1e-3, atol=1e-4)
