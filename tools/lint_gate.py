#!/usr/bin/env python
"""CI lint gate: the whole analysis zoo vs a committed baseline.

    python tools/lint_gate.py --ci                      # the CI entry point
    python tools/lint_gate.py --runtime                 # source rules only
    python tools/lint_gate.py --write-baseline tools/analysis_baseline.json
    python tools/lint_gate.py --ci --sarif lint.sarif   # + CI annotations

Runs TWO sweeps against the committed baseline file:

- the **zoo sweep** — the static checker (``paddle_tpu.analysis.check``)
  over every :data:`GATE_CONFIGS` entry, the model-zoo acceptance
  surface;
- the **runtime sweep** (``paddle_tpu.analysis.check_runtime``) — the
  lock-discipline (``thread:*``) and framed-wire contract (``wire:*``)
  rules over the framework's OWN Python/C source.

``--ci`` (the default behavior) runs both; ``--runtime`` restricts the
run to the source-level sweep (fast: no model builds, no jax tracing).
A PR that introduces a NEW finding on either sweep fails fast with the
fingerprint named; the findings already frozen in the baseline (the gpt
amp-leak golden, the tight-MoE capacity golden) stay accepted debt
until someone fixes them and re-writes the baseline.

Exit status (same contract as ``python -m paddle_tpu.analysis``):

- **0** — no finding at/above ``--fail-on`` outside the baseline;
- **1** — new findings, each printed as ``subject::fingerprint``;
- **3** — the checker itself crashed on some config (a crash must never
  read as a pass or as the PR author's finding).

``--write-baseline`` freezes the current findings and exits 0; commit
the file. ``--sarif PATH`` additionally writes a SARIF 2.1.0 run for
code-scanning annotators.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

EXIT_CLEAN, EXIT_FINDINGS, EXIT_INTERNAL = 0, 1, 3

DEFAULT_BASELINE = os.path.join(ROOT, "tools", "analysis_baseline.json")

# The gated sweep. Every entry is device-free (no mesh) so the gate
# runs identically on a laptop, in CI, and on a TPU host. Adding a
# config here (or a finding to an existing one) requires re-writing
# the committed baseline — which is exactly the review conversation
# the gate exists to force.
GATE_CONFIGS = [
    {"subject": "mnist.mlp", "model": "mnist", "variant": "mlp"},
    {"subject": "mnist.conv", "model": "mnist", "variant": "conv"},
    {"subject": "transformer", "model": "transformer"},
    {"subject": "gpt", "model": "gpt"},
    # golden true positive: the non-fused lm-head f32 matmul under amp
    {"subject": "gpt.amp", "model": "gpt", "amp": "bfloat16"},
    {"subject": "moe_transformer", "model": "moe_transformer"},
    # golden true positive: under-capacitied router (expected ~50% drop)
    {"subject": "moe_transformer.tight", "model": "moe_transformer",
     "variant": "tight"},
]


def run_gate(configs=None):
    """Run the checker over ``configs`` (default :data:`GATE_CONFIGS`)
    → list of ``(subject, LintReport)``. Lets tests and other tools
    reuse the sweep without the process exit semantics."""
    from paddle_tpu import analysis
    from paddle_tpu.analysis.zoo import build_model

    out = []
    for cfg in configs if configs is not None else GATE_CONFIGS:
        program, feed = build_model(cfg["model"], cfg.get("variant", ""),
                                    cfg.get("batch", 8), cfg.get("seq", 16))
        report = analysis.check(program, feed, amp=cfg.get("amp"))
        out.append((cfg["subject"], report))
    return out


def run_runtime_gate():
    """The source-level sweep — lock-discipline and wire-contract rules
    over the framework's own source → ``(subject, LintReport)`` pairs
    (``runtime:<module>`` / ``runtime:locks`` / ``wire:<surface>``
    subjects)."""
    from paddle_tpu.analysis.runtime import check_runtime
    return check_runtime()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/lint_gate.py",
        description="CI lint gate: analysis zoo vs committed baseline")
    ap.add_argument("--ci", action="store_true",
                    help="gate mode (the default behavior; the flag "
                         "documents intent in CI scripts)")
    ap.add_argument("--runtime", action="store_true",
                    help="run ONLY the source-level runtime sweep "
                         "(thread:* / wire:* rules) — no model builds")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline file (default {DEFAULT_BASELINE})")
    ap.add_argument("--write-baseline", default="", metavar="PATH",
                    help="freeze the current findings to PATH and exit 0 "
                         "(covers the sweeps this run selects — under "
                         "--runtime that is the runtime sweep only, so "
                         "regenerate the committed baseline WITHOUT "
                         "--runtime)")
    ap.add_argument("--sarif", default="", metavar="PATH",
                    help="also write a SARIF 2.1.0 report to PATH")
    ap.add_argument("--fail-on", default="warning",
                    choices=("info", "warning", "error"))
    ap.add_argument("--severity", action="append", metavar="CODE=LEVEL",
                    help="override a code's/family's severity, repeatable")
    args = ap.parse_args(argv)

    try:
        from paddle_tpu.analysis.__main__ import _parse_severity
        from paddle_tpu.analysis.report import (apply_severity, baseline_key,
                                                load_baseline, new_findings,
                                                to_sarif, write_baseline)

        overrides = _parse_severity(args.severity)
        # both sweeps share one baseline file and one exit contract —
        # --runtime narrows the run, never changes the semantics
        reports = [] if args.runtime else run_gate()
        reports += run_runtime_gate()
        for _, report in reports:
            apply_severity(report, overrides)

        if args.sarif:
            with open(args.sarif, "w") as fh:
                json.dump(to_sarif(reports), fh, indent=1)
            print(f"wrote SARIF: {args.sarif}")
        if args.write_baseline:
            doc = write_baseline(args.write_baseline, reports)
            print(f"wrote baseline {args.write_baseline} "
                  f"({len(doc['baseline'])} suppressed fingerprints over "
                  f"{len(reports)} configs)")
            return EXIT_CLEAN

        baseline = load_baseline(args.baseline)
        fresh = [(subject, f) for subject, report in reports
                 for f in new_findings(subject, report, baseline,
                                       args.fail_on)]
        total = sum(len(r.findings) for _, r in reports)
        if not fresh:
            print(f"lint gate clean: {len(reports)} configs, {total} "
                  f"finding(s), all baselined "
                  f"({len(baseline)} suppressed fingerprints)")
            return EXIT_CLEAN
        print(f"lint gate FAILED: {len(fresh)} new finding(s) not in "
              f"{args.baseline}:")
        for subject, f in fresh:
            print(f"  {baseline_key(subject, f)}")
            print(f"    {f}")
        print("fix the finding, or accept it deliberately with: "
              f"python tools/lint_gate.py --write-baseline {args.baseline}")
        return EXIT_FINDINGS
    except Exception:
        # NOT BaseException: SystemExit keeps its own code and a ^C
        # stays a cancelled run, never "the checker is broken"
        traceback.print_exc()
        print("lint_gate: internal error (exit 3) — the checker crashed; "
              "this is NOT a lint verdict", file=sys.stderr)
        return EXIT_INTERNAL


if __name__ == "__main__":
    sys.exit(main())
