"""Chunked (logits-free) softmax cross-entropy for large vocabularies.

New first-class TPU component (SURVEY §7's N16 analog — the kernel work
the reference did with xbyak JIT, applied to the modern hot spot): the
projection-to-vocab + softmax CE at the top of a language model. The
naive path materializes logits [tokens, vocab] (0.5–1 GB/step at
bs·seq=8K, V=32K) purely to reduce them to one scalar. Here the vocab
axis is processed in chunks under ``lax.scan`` with an online
log-sum-exp — peak activation is [tokens, chunk] — and the backward pass
recomputes each chunk's logits from the hidden states (flash-attention's
recompute trick applied to the LM head).

Supports label smoothing over the uniform prior (the Transformer
objective): loss = (1−eps)·nll + eps·(lse − mean_logits) and the exact
matching gradient dlogits = softmax − ((1−eps)·onehot + eps/V).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _chunks(weight, chunk: int):
    d, v = weight.shape
    n = -(-v // chunk)
    pad = n * chunk - v
    wp = jnp.pad(weight, ((0, 0), (0, pad)))
    return wp.reshape(d, n, chunk).transpose(1, 0, 2), n, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def chunked_softmax_cross_entropy(hidden, weight, bias, labels,
                                  smooth_eps: float = 0.0,
                                  chunk: int = 4096,
                                  logit_dtype=jnp.float32):
    """Per-token CE of softmax(hidden @ weight + bias) vs labels.

    hidden: [n, d] (flatten batch/time first); weight: [d, V];
    bias: [V] or None; labels: [n] int. Returns nll [n] (f32).
    """
    nll, _ = _fwd_stats(hidden, weight, bias, labels, smooth_eps, chunk, logit_dtype)
    return nll


def _fwd_stats(hidden, weight, bias, labels, smooth_eps, chunk, logit_dtype):
    n_tok, d = hidden.shape
    v = weight.shape[1]
    wc, n_chunks, pad = _chunks(weight, chunk)
    bc = (jnp.pad(bias, (0, pad)) if bias is not None else jnp.zeros(n_chunks * chunk,
          weight.dtype)).reshape(n_chunks, chunk)
    lab = labels.astype(jnp.int32)

    def body(carry, inp):
        m, s, tgt, logit_sum = carry
        w_i, b_i, idx = inp
        # [n, chunk] — the only live logits block. Matmul in the model
        # dtype (bf16 on the MXU) with f32 accumulation.
        logits = jax.lax.dot_general(
            hidden, w_i, (((1,), (0,)), ((), ())),
            preferred_element_type=logit_dtype) + b_i.astype(logit_dtype)[None, :]
        base = idx * chunk
        col = jnp.arange(chunk)[None, :] + base
        valid = col < v                                   # mask the pad tail
        logits = jnp.where(valid, logits, -jnp.inf)
        # online logsumexp
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=1)
        # target logit lives in exactly one chunk
        in_chunk = (lab >= base) & (lab < base + chunk)
        local = jnp.clip(lab - base, 0, chunk - 1)
        tgt = jnp.where(in_chunk, jnp.take_along_axis(logits, local[:, None], axis=1)[:, 0], tgt)
        logit_sum = logit_sum + jnp.sum(jnp.where(valid, logits, 0.0), axis=1)
        return (m_new, s, tgt, logit_sum), None

    m0 = jnp.full((n_tok,), -jnp.inf, logit_dtype)
    s0 = jnp.zeros((n_tok,), logit_dtype)
    t0 = jnp.zeros((n_tok,), logit_dtype)
    ls0 = jnp.zeros((n_tok,), logit_dtype)
    (m, s, tgt, logit_sum), _ = jax.lax.scan(
        body, (m0, s0, t0, ls0), (wc, bc, jnp.arange(n_chunks)))
    lse = m + jnp.log(s)
    nll = (1.0 - smooth_eps) * (lse - tgt) + smooth_eps * (lse - logit_sum / v)
    return nll.astype(jnp.float32), (lse, m)


def _fwd(hidden, weight, bias, labels, smooth_eps, chunk, logit_dtype):
    nll, (lse, _) = _fwd_stats(hidden, weight, bias, labels, smooth_eps, chunk, logit_dtype)
    return nll, (hidden, weight, bias, labels, lse)


def _bwd(smooth_eps, chunk, logit_dtype, res, g):
    hidden, weight, bias, labels, lse = res
    n_tok, d = hidden.shape
    v = weight.shape[1]
    wc, n_chunks, pad = _chunks(weight, chunk)
    bc = (jnp.pad(bias, (0, pad)) if bias is not None else jnp.zeros(n_chunks * chunk,
          weight.dtype)).reshape(n_chunks, chunk)
    lab = labels.astype(jnp.int32)
    g32 = g.astype(logit_dtype)
    mm_dtype = hidden.dtype   # bf16 matmuls, f32 accumulation

    def body(dh, inp):
        w_i, b_i, idx = inp
        logits = jax.lax.dot_general(
            hidden, w_i, (((1,), (0,)), ((), ())),
            preferred_element_type=logit_dtype) + b_i.astype(logit_dtype)[None, :]
        base = idx * chunk
        col = jnp.arange(chunk)[None, :] + base
        valid = col < v
        p = jnp.exp(jnp.where(valid, logits, -jnp.inf) - lse[:, None])   # softmax chunk
        onehot = (col == lab[:, None]).astype(logit_dtype)
        dlogits = ((p - (1.0 - smooth_eps) * onehot
                    - jnp.where(valid, smooth_eps / v, 0.0)) * g32[:, None]).astype(mm_dtype)
        dh = dh + jax.lax.dot_general(dlogits, w_i, (((1,), (1,)), ((), ())),
                                      preferred_element_type=logit_dtype)
        dw_i = jax.lax.dot_general(hidden, dlogits, (((0,), (0,)), ((), ())),
                                   preferred_element_type=logit_dtype)   # [d, chunk]
        db_i = jnp.sum(dlogits.astype(logit_dtype), axis=0)
        return dh, (dw_i, db_i)

    dh0 = jnp.zeros((n_tok, d), logit_dtype)
    dh, (dw_chunks, db_chunks) = jax.lax.scan(
        body, dh0, (wc, bc, jnp.arange(n_chunks)))
    dw = dw_chunks.transpose(1, 0, 2).reshape(d, n_chunks * chunk)[:, :v]
    db = db_chunks.reshape(-1)[:v]
    d_bias = db.astype(bias.dtype) if bias is not None else None
    return (dh.astype(hidden.dtype), dw.astype(weight.dtype), d_bias, None)


chunked_softmax_cross_entropy.defvjp(_fwd, _bwd)
