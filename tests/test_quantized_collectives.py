"""Int8-quantized ring all-reduce (parallel.quantized_collectives) —
EQuARX-inspired compressed collective for bandwidth-limited axes.
Numerics vs exact lax.psum on the 8-device CPU mesh + wire evidence
(the traced hops carry int8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.parallel import quantized_collectives as qc
from paddle_tpu.parallel import quantized_pmean, quantized_psum


def _run(fn, per_rank, mesh_axes={"dp": 8}):
    mesh = pt.make_mesh(mesh_axes)
    stacked = jnp.stack(per_rank)  # [p, ...] — one slice per rank
    return jax.shard_map(
        lambda s: fn(s[0], "dp"), mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False)(stacked)


@pytest.mark.slow
def test_exact_when_quantization_grid_is_stable():
    """With identical per-rank inputs on the int8 grid, every partial
    sum k·v re-quantizes to the same int8 code (scale scales with k),
    so the ring is bit-exact — pins that NO error source exists beyond
    quantization itself (indexing/schedule bugs would break equality)."""
    rng = np.random.RandomState(0)
    v = rng.randint(-127, 128, (24,)).astype(np.float32) / 127.0
    v[::3] = 1.0  # every ring chunk's abs-max is exactly 1.0, so each
    # hop's scale is k·1 and k·(m/127)/scale·127 = m: requantization is
    # integer-exact at every step
    per_rank = [v.copy() for _ in range(8)]
    got = np.asarray(_run(quantized_psum, per_rank)).reshape(8, 24)
    want = 8.0 * v
    for r in range(8):  # every rank holds the identical full sum
        np.testing.assert_allclose(got[r], want, rtol=0, atol=1e-6)


@pytest.mark.slow
def test_close_to_exact_psum_on_random_data():
    rng = np.random.RandomState(1)
    per_rank = [rng.randn(1000).astype(np.float32) for _ in range(8)]
    got = np.asarray(_run(quantized_psum, per_rank)).reshape(8, 1000)
    want = np.sum(per_rank, axis=0)
    scale = np.abs(want).max()
    for r in range(8):
        err = np.abs(got[r] - want).max() / scale
        assert err < 0.05, err


@pytest.mark.slow
def test_padding_and_dtype_roundtrip():
    """Sizes not divisible by the ring size pad internally; bf16 in →
    bf16 out."""
    rng = np.random.RandomState(2)
    per_rank = [rng.randn(13).astype(np.float32) for _ in range(8)]
    got = np.asarray(_run(quantized_psum,
                          [p.astype(jnp.bfloat16) for p in per_rank])
                     .astype(np.float32)).reshape(8, 13)
    want = np.sum(per_rank, axis=0)
    assert got.shape[1] == 13
    np.testing.assert_allclose(got[0], want, rtol=0.1, atol=0.1)


@pytest.mark.slow
def test_pmean_averages():
    per_rank = [np.full((8,), float(r), np.float32) for r in range(8)]
    got = np.asarray(_run(quantized_pmean, per_rank)).reshape(8, 8)
    np.testing.assert_allclose(got[0], np.full(8, 3.5), atol=0.05)


def test_hops_carry_int8_on_the_wire():
    """The point of the component: ppermute payloads in the traced
    program are int8 vectors plus f32 SCALAR scales — no f32 vector
    rides the ring."""
    import re

    mesh = pt.make_mesh({"dp": 8})
    x = jnp.zeros((8, 64), jnp.float32)
    jaxpr = str(jax.make_jaxpr(jax.shard_map(
        lambda s: quantized_psum(s[0], "dp"), mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False))(x))
    # output dtype of each ppermute: i8[...] data or f32[] scalar scale
    out_types = re.findall(r"\w+:(\w+\[[\d,]*\]) = ppermute\[", jaxpr)
    assert out_types, jaxpr[:500]
    assert any(t.startswith("i8[") for t in out_types), out_types
    for t in out_types:
        assert t.startswith("i8[") or t == "f32[]", out_types
    # 2(P-1) hops, each one i8 payload + one f32[] scale
    assert len(out_types) == 2 * 7 * 2, out_types


@pytest.mark.slow
def test_all_ranks_bitwise_identical():
    """The all-reduce contract DP replicas rely on: every rank must end
    with the SAME array, bit for bit — including the chunk each rank
    owns (which must store the quantized roundtrip, not its exact f32).

    Deliberately the ONE numeric ring test in the smoke tier (each of
    these costs ~20s of 8-device shard_map compile): bitwise identity
    catches both schedule and divergence regressions, and the cheap
    jaxpr test above pins the wire structure; the remaining numeric
    variants run in the full tier."""
    rng = np.random.RandomState(4)
    per_rank = [rng.randn(96).astype(np.float32) for _ in range(8)]
    got = np.asarray(_run(quantized_psum, per_rank)).reshape(8, 96)
    for r in range(1, 8):
        np.testing.assert_array_equal(got[r], got[0])


def test_block_scales_ride_the_ring():
    """block_size=B upgrades the per-hop scale from f32[] to a f32
    VECTOR of per-block scales — still tiny next to the i8 payload.
    Pins the traced wire structure without paying a compile."""
    import re

    mesh = pt.make_mesh({"dp": 8})
    x = jnp.zeros((8, 8 * 64), jnp.float32)  # chunk=64 -> 2 blocks of 32
    jaxpr = str(jax.make_jaxpr(jax.shard_map(
        lambda s: quantized_psum(s[0], "dp", block_size=32), mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False))(x))
    out_types = re.findall(r"\w+:(\w+\[[\d,]*\]) = ppermute\[", jaxpr)
    assert len(out_types) == 2 * 7 * 2, out_types
    assert any(t.startswith("i8[") for t in out_types), out_types
    for t in out_types:
        assert t.startswith("i8[") or t == "f32[2]", out_types


def test_int4_packs_two_codes_per_byte():
    """bits=4 halves the payload: ppermute data hops are u8[chunk/2]
    (two bias-8 nibbles per byte), scales stay f32 per block."""
    import re

    mesh = pt.make_mesh({"dp": 8})
    x = jnp.zeros((8, 8 * 64), jnp.float32)  # chunk=64 -> u8[32]
    jaxpr = str(jax.make_jaxpr(jax.shard_map(
        lambda s: quantized_psum(s[0], "dp", bits=4, block_size=64),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))(x))
    out_types = re.findall(r"\w+:(\w+\[[\d,]*\]) = ppermute\[", jaxpr)
    assert len(out_types) == 2 * 7 * 2, out_types
    assert any(t == "u8[32]" for t in out_types), out_types
    for t in out_types:
        assert t in ("u8[32]", "f32[1]"), out_types


def test_zero_and_nonfinite_safe_scales():
    """Satellite regression: an all-zero block must encode EXACTLY to
    zeros (no 0/0 NaN from the abs-max scale), and a block containing a
    non-finite value poisons only ITSELF — the neighboring block's
    values survive bit-exact."""
    x = np.zeros(64, np.float32)
    out = np.asarray(qc.block_roundtrip(jnp.asarray(x), block_size=32))
    np.testing.assert_array_equal(out, x)  # zeros stay exact zeros

    y = np.linspace(-1, 1, 64).astype(np.float32)
    ref = np.asarray(qc.block_roundtrip(jnp.asarray(y), block_size=32))
    assert np.isfinite(ref).all()
    for bad in (np.nan, np.inf):
        z = y.copy()
        z[3] = bad  # poisons block 0 only
        out = np.asarray(qc.block_roundtrip(jnp.asarray(z), block_size=32))
        assert not np.isfinite(out[:32]).all(), out[:32]
        # block 1 is untouched: bit-identical to the clean roundtrip
        np.testing.assert_array_equal(out[32:], ref[32:])


def test_wire_codec_matches_device_roundtrip():
    """The numpy host codec (encode_wire_blocks/decode_wire_blocks —
    the PUSHQB payload) must dequantize to EXACTLY what the in-graph
    block_roundtrip produces: the pserver's view of a gradient equals
    the trainer's own quantized view."""
    rng = np.random.RandomState(7)
    g = (rng.randn(700) * 3).astype(np.float32)  # not a block multiple
    for bits in (8, 4):
        payload, scales = qc.encode_wire_blocks(g, bits=bits,
                                                block_size=128)
        pb, sb = qc.wire_block_bytes(g.size, bits=bits, block_size=128)
        assert (len(payload), len(scales.tobytes())) == (pb, sb)
        host = qc.decode_wire_blocks(payload, scales, g.size, bits=bits,
                                     block_size=128)
        dev = np.asarray(qc.block_roundtrip(jnp.asarray(g), bits=bits,
                                            block_size=128))
        np.testing.assert_array_equal(host, dev)


def test_ring_wire_bytes_attribution():
    """The collective-bytes accounting the acceptance gate reads: int8
    block-256 cuts ring bytes >= 3.5x vs the fp32 baseline; int4 cuts
    deeper than int8."""
    n, p = 199_210, 8  # the MNIST MLP grad size the bench row uses
    fp32 = qc.ring_wire_bytes(n, p)
    assert fp32 == 2 * (p - 1) * -(-n // p) * 4
    i8 = qc.ring_wire_bytes(n, p, bits=8, block_size=256)
    i4 = qc.ring_wire_bytes(n, p, bits=4, block_size=256)
    assert fp32 / i8 >= 3.5, fp32 / i8
    assert i4 < i8 < fp32


def test_stochastic_rounding_deterministic_and_unbiased():
    """rng=key makes the roundtrip stochastic-rounding: reproducible
    under the same key, and E[deq] ~ x (the bias of round-to-nearest
    vanishes in expectation — what error feedback relies on)."""
    x = jnp.full((64,), 0.3, jnp.float32)  # 0.3*127/1.27... off-grid
    x = x.at[::16].set(1.27)  # pin each block's abs-max on the grid
    k = jax.random.PRNGKey(3)
    a = np.asarray(qc.block_roundtrip(x, block_size=16, rng=k))
    b = np.asarray(qc.block_roundtrip(x, block_size=16, rng=k))
    np.testing.assert_array_equal(a, b)  # same key -> same draw
    det = np.asarray(qc.block_roundtrip(x, block_size=16))
    outs = np.stack([np.asarray(qc.block_roundtrip(
        x, block_size=16, rng=jax.random.fold_in(k, i)))
        for i in range(64)])
    assert (outs.std(axis=0) > 0).any()  # actually stochastic
    mean_err = abs(outs.mean() - 0.3 * 60 / 64 - 1.27 * 4 / 64)
    det_err = abs(det.mean() - 0.3 * 60 / 64 - 1.27 * 4 / 64)
    assert mean_err <= det_err + 1e-4, (mean_err, det_err)


@pytest.mark.slow
def test_block_scaled_ring_numerics():
    """Block scales localize the quantization grid: per-rank random
    data with a large outlier still reduces close to exact psum, and
    every rank stays bitwise identical (same contract as per-chunk)."""
    rng = np.random.RandomState(11)
    per_rank = [rng.randn(512).astype(np.float32) for _ in range(8)]
    per_rank[0][17] = 80.0  # outlier wrecks a PER-CHUNK grid
    got = np.asarray(_run(lambda v, ax: quantized_psum(
        v, ax, block_size=64), per_rank)).reshape(8, 512)
    want = np.sum(per_rank, axis=0)
    err = np.abs(got[0] - want)
    err[17] = 0.0  # the outlier's own block absorbs its coarse grid
    assert np.median(np.abs(got[0] - want)) < 0.05
    for r in range(1, 8):
        np.testing.assert_array_equal(got[r], got[0])


@pytest.mark.slow
def test_int4_ring_close_to_exact():
    """bits=4 is coarse (qmax=7) but must still track the exact psum
    within its grid and keep cross-rank bitwise identity."""
    rng = np.random.RandomState(12)
    per_rank = [rng.randn(256).astype(np.float32) for _ in range(8)]
    got = np.asarray(_run(lambda v, ax: quantized_psum(
        v, ax, bits=4, block_size=64), per_rank)).reshape(8, 256)
    want = np.sum(per_rank, axis=0)
    scale = np.abs(want).max()
    assert np.abs(got[0] - want).max() / scale < 0.35
    for r in range(1, 8):
        np.testing.assert_array_equal(got[r], got[0])


def test_degenerate_single_rank():
    x = jnp.arange(5, dtype=jnp.float32)
    # p==1 on an axis of size 1: identity
    mesh1 = pt.make_mesh({"one": 1, "dp": 8})
    out = jax.shard_map(lambda v: quantized_psum(v, "one"), mesh=mesh1,
                        in_specs=P(), out_specs=P(), check_vma=False)(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
