"""DataFeeder + device prefetch.

Analog of python/paddle/fluid/data_feeder.py (DataFeeder.feed:167 —
converts a list of per-sample tuples into batched dense arrays) and of
the py_reader/double_buffer device pipeline (operators/reader/
buffered_reader.cc, layers/io.py:478): ``DeviceFeeder`` runs the host
reader in a background thread and keeps N batches in flight on device so
host→HBM transfer overlaps with compute.

``DeviceFeeder(stack_k=K)`` additionally assembles K host batches into
one stacked super-batch ``{name: (K, batch, ...)}`` and transfers it in
ONE sharded put — the feed side of the fused multi-step dispatch
(``Trainer.run_steps`` / ``fit(steps_per_dispatch=K)``): one
host→device transfer and one launch per K optimizer steps instead of K.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core.dtypes import convert_dtype


class PipelineMetrics:
    """Input-pipeline stage accounting (thread-safe): per-stage wall
    time and byte counters accumulated by :class:`DeviceFeeder` (fill
    thread: reader / encode / stack / h2d / dispatch-wait) and by
    ``Trainer._put_feed`` on direct-step paths, surfaced through
    :meth:`report` / ``Trainer.pipeline_report()``.

    Stages:

    - ``reader``   — waiting on the host reader for the next batch;
    - ``encode``   — wire-format encode (quantize/cast) of host arrays;
    - ``stack``    — assembling K batches into a fused-dispatch
      super-batch;
    - ``h2d``      — the device put. On the DeviceFeeder fill thread
      this times the COMPLETED transfer (block_until_ready); the
      direct-step paths (``Trainer._put_feed`` / ``put_batch``) record
      submission time only, a lower bound on async backends;
    - ``dispatch`` — the fill thread blocked on a full prefetch queue,
      i.e. waiting for the consumer's dispatches to drain (the
      compute-bound signal).

    ``consumer_starved_s`` is the mirror image: time the training-loop
    thread waited for a batch (the input-bound signal). ``h2d_bytes``
    counts WIRE bytes (what actually crossed the link);
    ``encode_saved_bytes`` accumulates logical-minus-wire so the report
    can state the reduction honestly.

    Two overlap-era attributions (PR 15):

    - ``overlap_hidden_s`` — transfer seconds that ran CONCURRENTLY
      with host work / the consumer's dispatches under the
      :class:`_StagingRing` (the h2d stage keeps the full
      submit→complete transfer wall, so ``h2d_mbps`` still measures
      the link; hidden vs exposed says how much of it the pipeline
      actually waited for);
    - ``cache_hit_bytes`` / ``cache_hits`` — chunks served
      device-to-device from the HBM dataset cache
      (:class:`~paddle_tpu.data.device_cache.DeviceCache`). Cache hits
      touch neither ``h2d_bytes`` nor the h2d clock, so ``h2d_mbps``
      stays an honest LINK estimate that excludes cache-served chunks
      (they would otherwise report an infinite link)."""

    _STAGES = ("reader", "encode", "stack", "h2d", "dispatch")

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self.stage_s = {s: 0.0 for s in self._STAGES}
            self.h2d_bytes = 0
            self.encode_saved_bytes = 0
            self.consumer_starved_s = 0.0
            self.batches = 0
            self.chunks = 0
            self.overlap_hidden_s = 0.0
            self.cache_hit_bytes = 0
            self.cache_hits = 0

    def add(self, stage: str, seconds: float):
        with self._lock:
            self.stage_s[stage] += seconds

    def record_encode(self, seconds: float, logical_nbytes: int,
                      wire_nbytes: int):
        with self._lock:
            self.stage_s["encode"] += seconds
            self.encode_saved_bytes += max(0, logical_nbytes - wire_nbytes)

    def record_h2d(self, nbytes: int, seconds: float,
                   exposed_s: Optional[float] = None):
        """One completed transfer: ``seconds`` is the submit→complete
        wall. ``exposed_s`` (staging-ring path) is how long the fill
        thread actually stalled for it — the rest ran hidden under
        other work and accumulates as ``overlap_hidden_s``. ``None``
        (the blocking put / direct-step paths) means fully exposed."""
        with self._lock:
            self.stage_s["h2d"] += seconds
            if exposed_s is not None:
                self.overlap_hidden_s += max(0.0, seconds - exposed_s)
            self.h2d_bytes += nbytes
            self.chunks += 1

    def record_cache_hit(self, nbytes: int):
        """A chunk served device-to-device from the HBM dataset cache:
        ``nbytes`` of wire data did NOT cross the link. Deliberately
        touches neither ``h2d_bytes`` nor the h2d clock — see the class
        docstring's honesty note on ``h2d_mbps``."""
        with self._lock:
            self.cache_hit_bytes += nbytes
            self.cache_hits += 1

    def record_batch(self, reader_seconds: float):
        with self._lock:
            self.stage_s["reader"] += reader_seconds
            self.batches += 1

    def record_starved(self, seconds: float):
        with self._lock:
            self.consumer_starved_s += seconds

    def telemetry_families(self, inst: str = "0") -> list:
        """The same accumulators as registry metric families under the
        ``paddle_tpu_feeder_*`` names (scrape-time: the Trainer's
        telemetry collector calls this, so the exported series can
        never disagree with :meth:`report`)."""
        from ..telemetry.registry import counter_family

        with self._lock:
            stages = dict(self.stage_s)
            h2d_bytes, saved = self.h2d_bytes, self.encode_saved_bytes
            starved = self.consumer_starved_s
            batches, chunks = self.batches, self.chunks
            hidden = self.overlap_hidden_s
            cache_b, cache_n = self.cache_hit_bytes, self.cache_hits
        labels = {"inst": inst}
        return [
            counter_family(
                "paddle_tpu_feeder_stage_seconds_total",
                "Input-pipeline seconds per stage "
                "(reader/encode/stack/h2d/dispatch wait)",
                [({**labels, "stage": s}, round(v, 6))
                 for s, v in sorted(stages.items())]),
            counter_family(
                "paddle_tpu_feeder_batches_total",
                "Host batches pulled from the reader", [(labels, batches)]),
            counter_family(
                "paddle_tpu_feeder_chunks_total",
                "Device transfers (fused chunks count once)",
                [(labels, chunks)]),
            counter_family(
                "paddle_tpu_feeder_h2d_bytes_total",
                "Wire bytes moved host-to-device", [(labels, h2d_bytes)]),
            counter_family(
                "paddle_tpu_feeder_encode_saved_bytes_total",
                "Logical-minus-wire bytes the feed wire encode saved",
                [(labels, saved)]),
            counter_family(
                "paddle_tpu_feeder_consumer_starved_seconds_total",
                "Training-loop seconds spent waiting for input",
                [(labels, round(starved, 6))]),
            counter_family(
                "paddle_tpu_feeder_overlap_hidden_seconds_total",
                "Transfer seconds hidden under host work / compute by "
                "the double-buffered staging ring",
                [(labels, round(hidden, 6))]),
            counter_family(
                "paddle_tpu_feeder_cache_hit_bytes_total",
                "Wire bytes served device-to-device from the HBM "
                "dataset cache (never crossed the host link)",
                [(labels, cache_b)]),
            counter_family(
                "paddle_tpu_feeder_cache_hits_total",
                "Chunks served from the HBM dataset cache",
                [(labels, cache_n)]),
        ]

    def report(self) -> Dict[str, Any]:
        """Per-stage attribution + an effective-link estimate:
        ``h2d_mbps`` is wire bytes over transfer wall time — an honest
        LINK estimate that excludes cache-served chunks (they add
        neither bytes nor h2d seconds); ``overlap_hidden_s`` /
        ``h2d_exposed_s`` split the transfer wall into the part the
        staging ring hid under other work vs the part the pipeline
        stalled for; ``bottleneck`` names the stage with the most
        accumulated time, and ``input_bound`` says whether the training
        loop starved for data more than the fill thread waited on it."""
        with self._lock:
            stages = dict(self.stage_s)
            h2d_bytes = self.h2d_bytes
            saved = self.encode_saved_bytes
            starved = self.consumer_starved_s
            batches, chunks = self.batches, self.chunks
            hidden = self.overlap_hidden_s
            cache_b, cache_n = self.cache_hit_bytes, self.cache_hits
        logical = h2d_bytes + saved
        h2d_s = stages["h2d"]
        return {
            "stages_s": {k: round(v, 6) for k, v in stages.items()},
            "h2d_bytes": int(h2d_bytes),
            "logical_bytes": int(logical),
            "wire_reduction": (round(logical / h2d_bytes, 3)
                               if h2d_bytes else None),
            "h2d_mbps": (round(h2d_bytes / 1e6 / h2d_s, 2)
                         if h2d_s > 0 and h2d_bytes else None),
            "overlap_hidden_s": round(hidden, 6),
            "h2d_exposed_s": round(max(0.0, h2d_s - hidden), 6),
            "cache_hit_bytes": int(cache_b),
            "cache_hits": cache_n,
            "batches": batches,
            "chunks": chunks,
            "consumer_starved_s": round(starved, 6),
            "bottleneck": max(stages, key=stages.get) if any(
                v > 0 for v in stages.values()) else None,
            "input_bound": starved > stages["dispatch"],
        }


class DataFeeder:
    """Convert reader samples (tuples) into a named feed dict of batched
    numpy arrays (DataFeeder.feed analog, data_feeder.py:167)."""

    def __init__(self, feed_list: Sequence[str], dtypes: Optional[Sequence[Any]] = None):
        self.feed_list = list(feed_list)
        self.dtypes = list(dtypes) if dtypes is not None else [None] * len(self.feed_list)

    def feed(self, samples: Sequence[Tuple]) -> Dict[str, np.ndarray]:
        cols = list(zip(*samples))
        if len(cols) != len(self.feed_list):
            raise ValueError(
                f"sample arity {len(cols)} != feed_list arity {len(self.feed_list)}")
        out = {}
        for name, dt, col in zip(self.feed_list, self.dtypes, cols):
            arr = np.stack([np.asarray(v) for v in col])
            if dt is not None:
                arr = arr.astype(np.dtype(convert_dtype(dt).name))
            out[name] = arr
        return out


def stack_batches(bufs: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Stack K same-shape feed dicts into one ``{name: (K, ...)}``
    super-batch (the fused-dispatch super-batch layout)."""
    return {k: np.stack([np.asarray(b[k]) for b in bufs]) for k in bufs[0]}


def host_feed_nbytes(feed: Dict[str, Any]) -> int:
    """Bytes of the HOST arrays in a feed dict — what a device put of it
    moves across the link (device-resident arrays count zero: they are
    already there)."""
    total = 0
    for v in feed.values():
        if isinstance(v, jax.Array):
            continue
        total += np.asarray(v).nbytes
    return total


def _stackable(a: Dict[str, np.ndarray], b: Dict[str, np.ndarray]) -> bool:
    """Two batches can share a super-batch: same keys, shapes, dtypes
    (a short final reader batch must not poison the stack)."""
    if a.keys() != b.keys():
        return False
    for k in a:
        va, vb = np.asarray(a[k]), np.asarray(b[k])
        if va.shape != vb.shape or va.dtype != vb.dtype:
            return False
    return True


def _host_chunks(batches: Iterator[Dict[str, np.ndarray]], k: int,
                 metrics: Optional[PipelineMetrics] = None):
    """The one chunking state machine both feed paths share: yields
    ``(n, host_feed)`` — full K-chunks stacked (``n == k``),
    remainder/odd-shape batches singly (``n == 1``, unstacked) so they
    fall through to the compiled single-step function with no
    fused-program retrace. ``metrics`` attributes the stack time."""
    buf: List[Dict[str, np.ndarray]] = []
    for b in batches:
        if buf and not _stackable(buf[0], b):
            for s in buf:
                yield 1, s
            buf = []
        buf.append(b)
        if len(buf) == k:
            t0 = time.perf_counter()
            stacked = stack_batches(buf)
            if metrics is not None:
                metrics.add("stack", time.perf_counter() - t0)
            yield k, stacked
            buf = []
    for s in buf:
        yield 1, s


def iter_chunked(batches: Iterator[Dict[str, np.ndarray]], k: int,
                 put_fn: Callable, put_stacked_fn: Callable):
    """Synchronous chunker (the no-prefetch path of
    ``fit(steps_per_dispatch=K)``): ``_host_chunks`` plus the device
    put, yielding ``(n, device_feed)``."""
    for n, hb in _host_chunks(batches, k):
        yield n, (put_stacked_fn(hb) if n > 1 else put_fn(hb))


class _StagingRing:
    """Depth-bounded asynchronous h2d staging — the device-side half of
    the double_buffer analog. ``submit`` dispatches the put and returns
    immediately, so the fill thread reads/encodes/stacks chunk N+1
    while chunk N's transfer is still in flight; a waiter thread waits
    each transfer to completion in submission order (the device-event
    wait — ``jax.block_until_ready``, not a wall-clock of the submit)
    and only then delivers the chunk downstream, so a consumer never
    dispatches on a half-arrived batch and the recorded h2d time is the
    transfer's true submit→complete wall.

    At most ``depth`` transfers are in flight: the fill thread blocks
    in ``submit`` only when the ring is full. That stall (plus the
    submit call itself) is the EXPOSED transfer time; the rest of each
    transfer ran hidden under host work and the consumer's dispatches
    and accumulates as ``PipelineMetrics.overlap_hidden_s``.

    Donation-safe by construction: staged buffers are feed arrays, and
    the step programs never donate feeds — only the training carry
    (params/opt_state/state/loss-scale) is donated, so a buffer parked
    in the ring can never be aliased away under an in-flight transfer.

    ``wait_fn(dev, t_submit)`` is the completion wait;
    ``testing.faults.slow_h2d`` substitutes a throttled one to make a
    slow host→device link deterministic in tests and bench."""

    _END = object()

    def __init__(self, depth: int, deliver: Callable, stop: threading.Event,
                 metrics: Optional[PipelineMetrics] = None,
                 wait_fn: Optional[Callable] = None, journal=None,
                 on_error: Optional[Callable] = None):
        self.depth = max(1, int(depth))
        self._deliver = deliver      # (dev, n, span) -> bool (False: stop)
        self._stop = stop
        self._metrics = metrics
        self._wait_fn = wait_fn or (
            lambda dev, t_submit: jax.block_until_ready(dev))
        self._journal = journal
        self._on_error = on_error
        self._sem = threading.Semaphore(self.depth)
        self._q: _queue.Queue = _queue.Queue()
        self._lock = threading.Lock()
        self._stall_s = 0.0          # fill-thread seconds blocked here
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _take_stall(self) -> float:
        with self._lock:
            s, self._stall_s = self._stall_s, 0.0
            return s

    def submit(self, n: int, host_feed, putter: Callable) -> bool:
        """Dispatch one chunk's put into the ring. Returns False when
        the stop flag fired (the chunk was not submitted)."""
        t_a = time.perf_counter()
        while not self._sem.acquire(timeout=0.1):
            if self._stop.is_set():
                return False
        stall = time.perf_counter() - t_a
        span = self._journal.new_span() if self._journal is not None else None
        nbytes = host_feed_nbytes(host_feed)
        t0 = time.perf_counter()
        dev = putter(host_feed)
        t1 = time.perf_counter()
        with self._lock:
            # the submit call is exposed too: the fill thread paid it
            self._stall_s += stall + (t1 - t0)
        self._q.put((dev, n, span, t0, nbytes))
        return True

    def finish(self):
        """Fill-thread end-of-stream: let in-flight transfers deliver,
        then return (immediately once the stop flag fires — deliveries
        can no longer land on a closed consumer)."""
        self._q.put(self._END)
        while self._thread.is_alive():
            self._thread.join(timeout=0.1)
            if self._stop.is_set():
                return

    def _drain(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except _queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if item is self._END:
                return
            dev, n, span, t0, nbytes = item
            try:
                self._wait_fn(dev, t0)
            except BaseException as e:  # surfaced on the consumer side
                if self._on_error is not None:
                    self._on_error(e)
                # fire the stop flag: a dead waiter releases no more
                # ring slots, so a fill thread parked in submit() (and
                # the consumer waiting on deliveries) must be unblocked
                # — the recorded error then propagates at __next__
                self._stop.set()
                self._sem.release()
                return
            seconds = time.perf_counter() - t0
            if self._metrics is not None:
                self._metrics.record_h2d(nbytes, seconds,
                                         exposed_s=self._take_stall())
            if self._journal is not None:
                self._journal.emit("feeder.fill", span=span, num_steps=n,
                                   nbytes=nbytes, put_s=round(seconds, 6))
            ok = self._deliver(dev, n, span)
            self._sem.release()
            if not ok:
                return


class DeviceFeeder:
    """Double-buffered host→device prefetch (py_reader + double_buffer
    analog). Wraps an iterator of feed dicts; ``__iter__`` yields dicts
    of on-device arrays while the next batches transfer in the
    background.

    With ``stack_k=K > 1`` the fill thread stacks K host batches into a
    super-batch, transfers it with ``put_stacked_fn`` in one put, and
    the iterator yields ``(n, feed)`` pairs — ``n == K`` for full
    chunks, ``n == 1`` (unstacked, via ``put_fn``) for remainder or
    shape-mismatched batches.

    The fill thread is CANCELLABLE: abandoning the iterator (break /
    exception / gc) or calling :meth:`close` unblocks it even when it is
    parked on a full queue holding device buffers — the old leak where a
    daemon thread pinned HBM until process exit.

    A reader/transfer exception on the fill thread PROPAGATES to the
    consumer: already-transferred batches drain first, then the original
    exception (fill-thread traceback attached) is re-raised at
    ``__next__`` — never a bare end-of-iteration that silently truncates
    the epoch. A fill thread that dies without delivering its END
    sentinel is detected by a liveness probe instead of hanging the
    consumer.

    ``encode_fn`` (e.g. ``FeedWire.encode``) runs ON THE FILL THREAD,
    per batch, BEFORE stacking — wire-format encode and per-field dtype
    conversion never touch the training-loop thread, and K-chunk
    stacking operates on the already-shrunk wire arrays. ``metrics``
    (a :class:`PipelineMetrics`) attributes per-stage time and wire
    bytes: reader wait, encode, stack, h2d put, and the
    fill-thread-blocked-on-consumer dispatch wait; pair it with a
    ``put_fn`` that does not itself record (``Trainer._put_feed``
    with ``record=False``) or the h2d stage double-counts.

    With ``overlap_depth >= 2`` (the default) and metrics attached, the
    put goes through a :class:`_StagingRing` instead of blocking the
    fill thread on ``block_until_ready``: transfers run up to
    ``overlap_depth`` deep while the fill thread keeps
    reading/encoding/stacking, completion time is recorded via a
    device-event wait on a waiter thread (the honest ``h2d_mbps``),
    and the hidden-vs-exposed split lands in
    ``PipelineMetrics.overlap_hidden_s``. ``overlap_depth=1`` restores
    the old blocking put (the bench A/B's "blocking" arm). ``wait_fn``
    overrides the completion wait — ``testing.faults.slow_h2d``
    simulates a slow link deterministically through it.

    ``journal`` (a :class:`paddle_tpu.telemetry.RunJournal`) correlates
    the pipeline with the dispatches it feeds: the fill thread mints a
    span id per chunk and emits a ``feeder.fill`` event when the
    transfer lands; after the iterator yields an item,
    :attr:`last_span` holds that item's span (exact for the serial
    single-consumer iteration contract) so the consumer can hand the
    SAME span to ``trainer.step``/``run_steps`` — fill and dispatch of
    one chunk then share one trace id end to end (``fit`` does this)."""

    def __init__(self, batches: Callable[[], Iterator[Dict[str, np.ndarray]]],
                 put_fn: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, jax.Array]]] = None,
                 capacity: int = 2, stack_k: int = 1,
                 put_stacked_fn: Optional[Callable] = None,
                 encode_fn: Optional[Callable] = None,
                 metrics: Optional[PipelineMetrics] = None,
                 logical_nbytes_fn: Optional[Callable] = None,
                 journal=None, overlap_depth: int = 2,
                 wait_fn: Optional[Callable] = None):
        self.batches = batches
        self.put_fn = put_fn or (lambda d: jax.device_put(d))
        self.put_stacked_fn = put_stacked_fn or self.put_fn
        self.capacity = capacity
        self.stack_k = max(1, int(stack_k))
        self.encode_fn = encode_fn
        self.metrics = metrics
        self.journal = journal
        self.overlap_depth = max(1, int(overlap_depth))
        self.wait_fn = wait_fn
        self.last_span: Optional[str] = None
        # spec-aware logical-byte counter (FeedWire.logical_nbytes):
        # counts already-wire-dtype reader output at its DECODED width
        # so wire_reduction reports the true link saving
        self.logical_nbytes_fn = logical_nbytes_fn or host_feed_nbytes
        self._stops: List[threading.Event] = []
        self._threads: List[threading.Thread] = []

    def pipeline_report(self) -> Optional[Dict[str, Any]]:
        """The accumulated :meth:`PipelineMetrics.report`, or None when
        the feeder was built without metrics."""
        return self.metrics.report() if self.metrics is not None else None

    def _instrumented_batches(self) -> Iterator[Dict[str, np.ndarray]]:
        """Fill-thread source: times the reader wait per batch and runs
        the wire encode (host numpy) before chunk assembly."""
        m, enc = self.metrics, self.encode_fn
        it = iter(self.batches())
        while True:
            t0 = time.perf_counter()
            try:
                b = next(it)
            except StopIteration:
                return
            if m is not None:
                m.record_batch(time.perf_counter() - t0)
            if enc is not None:
                t0 = time.perf_counter()
                logical = self.logical_nbytes_fn(b) if m is not None else 0
                b = enc(b)
                if m is not None:
                    m.record_encode(time.perf_counter() - t0, logical,
                                    host_feed_nbytes(b))
            yield b

    def _timed_put(self, fn, host_feed):
        if self.metrics is None and self.wait_fn is None:
            return fn(host_feed)
        nbytes = host_feed_nbytes(host_feed)
        t0 = time.perf_counter()
        out = fn(host_feed)
        if self.wait_fn is not None:
            # injected completion wait (testing.faults.slow_h2d): the
            # blocking arm of the overlap A/B pays the same simulated
            # link the staging ring does
            self.wait_fn(out, t0)
            if self.metrics is not None:
                self.metrics.record_h2d(nbytes,
                                        time.perf_counter() - t0)
            return out
        # the BLOCKING put (overlap_depth=1 only): wait for the
        # transfer inline so h2d_mbps measures the link, not the
        # submission. It serializes the fill thread's host work behind
        # each transfer and caps in-flight transfers at one — the
        # default path is the _StagingRing, which records the same
        # honest completion time via a device-event wait on a waiter
        # thread while transfers pipeline overlap_depth deep.
        jax.block_until_ready(out)
        self.metrics.record_h2d(nbytes, time.perf_counter() - t0)
        return out

    def close(self):
        """Cancel every live fill thread (idempotent). Threads parked on
        a full queue wake on the stop flag and exit, dropping their
        device-buffer references."""
        for ev in self._stops:
            ev.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = [t for t in self._threads if t.is_alive()]

    def __iter__(self):
        q: _queue.Queue = _queue.Queue(maxsize=self.capacity)
        END = object()
        err: List[BaseException] = []
        stop = threading.Event()
        self._stops.append(stop)

        metrics = self.metrics

        def put(item, timed: bool = True) -> bool:
            # bounded-wait put: a consumer that stopped consuming must
            # not strand this thread (and its device buffers) forever.
            # Time blocked here is the DISPATCH WAIT — the consumer's
            # device dispatches are what drains the queue.
            t0 = time.perf_counter()
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    if timed and metrics is not None:
                        metrics.add("dispatch", time.perf_counter() - t0)
                    return True
                except _queue.Full:
                    continue
            return False

        journal = self.journal

        def fill_event(n, hb, putter):
            """One chunk's transfer + its ``feeder.fill`` journal event
            (span minted HERE, on the fill thread, at chunk-creation
            time — the consumer re-uses it for the dispatch)."""
            if journal is None:
                return putter(hb), None
            span = journal.new_span()
            t0 = time.perf_counter()
            dev = putter(hb)
            journal.emit("feeder.fill", span=span, num_steps=n,
                         nbytes=host_feed_nbytes(hb),
                         put_s=round(time.perf_counter() - t0, 6))
            return dev, span

        # the staging ring replaces the blocking put when overlap is on
        # and there is something for it to do (metrics to keep honest,
        # or an injected wait_fn to obey); the legacy inline put remains
        # the overlap_depth=1 path and the metrics-less fast path
        ring = None
        if self.overlap_depth >= 2 and (metrics is not None
                                        or self.wait_fn is not None):
            def deliver(dev, n, span):
                payload = (n, dev) if self.stack_k > 1 else dev
                return put((payload, span))

            ring = _StagingRing(self.overlap_depth, deliver, stop,
                                metrics=metrics, wait_fn=self.wait_fn,
                                journal=journal, on_error=err.append)
            self._threads.append(ring._thread)

        def fill():
            try:
                chunks = (_host_chunks(self._instrumented_batches(),
                                       self.stack_k, metrics=metrics)
                          if self.stack_k > 1
                          else ((1, b) for b in
                                self._instrumented_batches()))
                for n, hb in chunks:
                    if stop.is_set():
                        return
                    putter = (lambda b, _n=n: (
                        self.put_stacked_fn if _n > 1 else self.put_fn)(b))
                    if ring is not None:
                        if not ring.submit(n, hb, putter):
                            return
                        continue
                    dev, span = fill_event(
                        n, hb, lambda b, _p=putter: self._timed_put(_p, b))
                    payload = (n, dev) if self.stack_k > 1 else dev
                    if not put((payload, span)):
                        return
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                # END must trail every in-flight staged transfer, or the
                # consumer would see end-of-epoch with chunks undelivered
                if ring is not None:
                    ring.finish()
                # END delivery is shutdown, not dispatch wait — untimed
                if not put(END, timed=False):
                    # stop was set (close() possibly from ANOTHER thread
                    # than the consumer): a consumer still parked in
                    # q.get() must not hang — if it is parked, the queue
                    # is empty and this delivery succeeds
                    try:
                        q.put_nowait(END)
                    except _queue.Full:
                        pass

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        self._threads.append(t)
        try:
            while True:
                t_wait = time.perf_counter()
                try:
                    item = q.get(timeout=0.5)
                    # starvation accounting: the training loop waited
                    # this long for input (END arrival is shutdown, not
                    # starvation — skip it below)
                    if metrics is not None and item is not END:
                        metrics.record_starved(time.perf_counter() - t_wait)
                except _queue.Empty:
                    if metrics is not None:
                        metrics.record_starved(time.perf_counter() - t_wait)
                    # liveness check: a fill thread that died without
                    # managing to enqueue END (its sentinel put lost a
                    # race with close()) must not hang the consumer —
                    # and its reader error must still surface
                    if not t.is_alive():
                        # the thread may have enqueued its final batches
                        # (and END) between our timeout and this check —
                        # drain them before concluding, or the race
                        # silently truncates the epoch
                        while True:
                            try:
                                item = q.get_nowait()
                            except _queue.Empty:
                                break
                            if item is END:
                                break
                            payload, self.last_span = item
                            yield payload
                        if err:
                            raise err[0]
                        return
                    continue
                if item is END:
                    if err:
                        # re-raise the READER's exception at __next__
                        # with its original fill-thread traceback — a
                        # reader crash must abort the epoch loudly, not
                        # truncate it to a silent StopIteration
                        raise err[0]
                    return
                payload, self.last_span = item
                yield payload
        finally:
            # break / exception / generator gc: release the fill thread
            stop.set()
