"""Native C++ runtime pieces (RecordIO, master task-queue, async
pserver, train demo) and the shared on-demand build helper."""

from __future__ import annotations

import os
import subprocess
from typing import Optional, Sequence

_DIR = os.path.dirname(__file__)


def build_native(src_name: str, bin_name: str,
                 extra_flags: Sequence[str] = ("-pthread",),
                 opt: str = "-O2", libs: Sequence[str] = ()) -> str:
    """Compile ``native/<src_name>`` to ``native/<bin_name>`` if stale.

    Concurrency-safe: compiles to a pid-unique temp path and atomically
    renames into place, so two processes racing on a stale mtime (e.g.
    parallel test workers sharing a checkout) each install a complete
    binary instead of exec'ing a half-written one.
    """
    src = os.path.join(_DIR, src_name)
    out = os.path.join(_DIR, bin_name)
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    tmp = f"{out}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", opt, "-std=c++17", *extra_flags, src, "-o", tmp, *libs],
            check=True, capture_output=True)
        os.replace(tmp, out)  # atomic on POSIX
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return out
