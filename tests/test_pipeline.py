"""Pipeline-parallel schedule tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.parallel.pipeline import pipeline_apply, stack_layer_params


def _layer_fn(x, p):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked(layers, d, seed=0):
    rng = np.random.RandomState(seed)
    per_layer = [{"w": jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.3),
                  "b": jnp.asarray(rng.randn(d).astype(np.float32) * 0.1)}
                 for _ in range(layers)]
    return stack_layer_params(per_layer)


def _ref(x, stacked):
    def one(a, lp):
        return _layer_fn(a, lp), None
    out, _ = jax.lax.scan(one, x, stacked)
    return out


def test_pipeline_matches_sequential():
    mesh = pt.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    d = 8
    stacked = _stacked(8, d)
    x = jnp.asarray(np.random.RandomState(1).randn(16, d).astype(np.float32))
    out = pipeline_apply(x, stacked, _layer_fn, mesh, microbatches=4, batch_axes=())
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, stacked)),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_with_dp():
    mesh = pt.make_mesh({"dp": 2, "pp": 4})
    d = 8
    stacked = _stacked(4, d, seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(8, d).astype(np.float32))
    out = pipeline_apply(x, stacked, _layer_fn, mesh, microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, stacked)),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_degenerate_no_pp_axis():
    mesh = pt.make_mesh({"dp": 8})
    d = 4
    stacked = _stacked(3, d, seed=4)
    x = jnp.asarray(np.random.RandomState(5).randn(6, d).astype(np.float32))
    out = pipeline_apply(x, stacked, _layer_fn, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, stacked)),
                               atol=1e-6)


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_pipeline_differentiable():
    mesh = pt.make_mesh({"pp": 2}, devices=jax.devices()[:2])
    d = 4
    stacked = _stacked(4, d, seed=6)
    x = jnp.asarray(np.random.RandomState(7).randn(8, d).astype(np.float32))

    g1 = jax.grad(lambda s: jnp.sum(
        pipeline_apply(x, s, _layer_fn, mesh, microbatches=2, batch_axes=()) ** 2))(stacked)
    g2 = jax.grad(lambda s: jnp.sum(_ref(x, s) ** 2))(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   atol=1e-4, rtol=1e-3)


def test_interleaved_matches_sequential():
    """Megatron virtual-stage schedule (interleave=2): same numerics as
    the sequential scan, bubble ticks halved per bubble_fraction."""
    mesh = pt.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    d = 8
    stacked = _stacked(8, d)  # 8 layers = pp4 × v2 × 1 layer/chunk
    x = jnp.asarray(np.random.RandomState(1).randn(16, d).astype(np.float32))
    out = pipeline_apply(x, stacked, _layer_fn, mesh, microbatches=4,
                         interleave=2, batch_axes=())
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, stacked)),
                               atol=1e-5, rtol=1e-5)


def test_interleaved_uneven_microbatch_group():
    """m not divisible by pp: the last group is partial but the schedule
    still routes every microbatch through every chunk."""
    mesh = pt.make_mesh({"pp": 2}, devices=jax.devices()[:2])
    d = 8
    stacked = _stacked(8, d, seed=11)  # pp2 × v2 × 2 layers/chunk
    x = jnp.asarray(np.random.RandomState(12).randn(12, d).astype(np.float32))
    out = pipeline_apply(x, stacked, _layer_fn, mesh, microbatches=3,
                         interleave=2, batch_axes=())
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, stacked)),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # >20s on the 1-core host (smoke budget, r5 #9)
def test_interleaved_differentiable():
    mesh = pt.make_mesh({"pp": 2}, devices=jax.devices()[:2])
    d = 4
    stacked = _stacked(8, d, seed=6)

    x = jnp.asarray(np.random.RandomState(7).randn(8, d).astype(np.float32))
    g1 = jax.grad(lambda s: jnp.sum(
        pipeline_apply(x, s, _layer_fn, mesh, microbatches=4, interleave=2,
                       batch_axes=()) ** 2))(stacked)
    g2 = jax.grad(lambda s: jnp.sum(_ref(x, s) ** 2))(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   atol=1e-4, rtol=1e-3)


def test_interleaved_with_dp_and_extras():
    mesh = pt.make_mesh({"dp": 2, "pp": 4})
    d = 8
    stacked = _stacked(8, d, seed=2)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, d).astype(np.float32))
    bias = jnp.asarray(rng.randn(8, d).astype(np.float32))

    def layer_with_extra(a, p, e):
        return jnp.tanh(a @ p["w"] + p["b"]) + 0.1 * e

    out = pipeline_apply(x, stacked, layer_with_extra, mesh, microbatches=2,
                         interleave=2, extras=bias)

    def one(a, lp):
        return layer_with_extra(a, lp, bias), None
    ref, _ = jax.lax.scan(one, x, stacked)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_interleave_perm_roundtrip_and_correctness():
    """param_layout="interleaved": rows pre-permuted by interleave_perm
    give the same result with no in-step re-layout; argsort inverts."""
    from paddle_tpu.parallel.pipeline import interleave_perm

    L, p, v = 8, 4, 2
    perm = interleave_perm(L, p, v)
    assert sorted(perm) == list(range(L))
    # row r·v + c (chunk c of rank r) holds global chunk c·p + r
    Lc = L // (p * v)
    for r in range(p):
        for c in range(v):
            assert perm[(r * v + c) * Lc] == (c * p + r) * Lc
    inv = np.argsort(perm)
    mesh = pt.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    d = 8
    stacked = _stacked(L, d)
    x = jnp.asarray(np.random.RandomState(1).randn(16, d).astype(np.float32))
    pre = jax.tree.map(lambda leaf: leaf[perm], stacked)
    out = pipeline_apply(x, pre, _layer_fn, mesh, microbatches=4,
                         interleave=v, batch_axes=(),
                         param_layout="interleaved")
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, stacked)),
                               atol=1e-5, rtol=1e-5)
    # and the inverse permutation restores logical order
    np.testing.assert_array_equal(np.asarray(pre["w"][inv]),
                                  np.asarray(stacked["w"]))


def test_interleaved_layout_step_has_no_param_relayout_collective():
    """round-4 verdict #6 Done-criterion: with the Megatron rest layout
    the compiled interleaved step contains NO all-to-all — the stacked-
    layout step pays one per leaf (re-layout fwd) plus the inverse in
    backward. Activation ppermutes remain in both."""
    from paddle_tpu.parallel.pipeline import interleave_perm
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = pt.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    L, d, v = 8, 8, 2
    stacked = _stacked(L, d)
    x = jnp.asarray(np.random.RandomState(1).randn(16, d).astype(np.float32))

    def hlo(params, layout):
        params = jax.tree.map(
            lambda leaf: jax.device_put(leaf, NamedSharding(mesh, P("pp"))),
            params)

        def loss(s, xv):
            return jnp.sum(pipeline_apply(
                xv, s, _layer_fn, mesh, microbatches=4, interleave=v,
                batch_axes=(), param_layout=layout) ** 2)
        return jax.jit(jax.grad(loss)).lower(params, x).compile().as_text()

    h_inter = hlo(jax.tree.map(
        lambda leaf: leaf[interleave_perm(L, 4, v)], stacked), "interleaved")
    h_stack = hlo(stacked, "stacked")
    assert "all-to-all" not in h_inter, "param re-layout survived"
    assert "collective-permute" in h_inter  # activation ring still there
    assert "all-to-all" in h_stack  # the cost the new layout removes


def test_bubble_fraction_interleave():
    from paddle_tpu.parallel.pipeline import bubble_fraction

    assert bubble_fraction(4, 16) == pytest.approx(3 / 19)
    assert bubble_fraction(4, 16, interleave=4) == pytest.approx(3 / 67)
    # layer-count guard
    mesh = pt.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    stacked = _stacked(4, 4)
    x = jnp.zeros((4, 4), jnp.float32)
    with pytest.raises(Exception, match="divisible by pp"):
        pipeline_apply(x, stacked, _layer_fn, mesh, microbatches=2,
                       interleave=2, batch_axes=())


def test_pipeline_3d_dp_tp_pp():
    """dp2 × tp2 × pp2 in one pipeline_apply call: Megatron MLP stage
    (w1 column-sharded, w2 row-sharded, psum over tp) pipelined over
    stacked layers, batch sharded on dp."""
    from jax.sharding import PartitionSpec as P

    mesh = pt.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    d, h = 4, 8
    rng = np.random.RandomState(7)
    per_layer = [{"w1": jnp.asarray(rng.randn(d, h).astype(np.float32) * 0.3),
                  "w2": jnp.asarray(rng.randn(h, d).astype(np.float32) * 0.3)}
                 for _ in range(4)]
    stacked = stack_layer_params(per_layer)

    def mlp_layer(x, p):
        y = jax.nn.relu(x @ p["w1"])              # tp-local columns of h
        return jax.lax.psum(y @ p["w2"], "tp") + x  # Megatron row-parallel

    def mlp_layer_ref(x, p):
        return x + jax.nn.relu(x @ p["w1"]) @ p["w2"]

    x = jnp.asarray(np.random.RandomState(8).randn(8, d).astype(np.float32))

    out = pipeline_apply(
        x, stacked, mlp_layer, mesh, microbatches=2,
        param_specs={"w1": P(None, "tp"), "w2": P("tp")})

    def one(a, lp):
        return mlp_layer_ref(a, lp), None
    ref, _ = jax.lax.scan(one, x, stacked)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)
