"""Orbax-backed async + sharded checkpointing (io.save_sharded /
save_trainer_sharded): roundtrip, async barrier, and restore across a
mesh reshape (the pserver slice/merge capability, io.py:881 analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import layers as L
from paddle_tpu import optimizer as opt


def _feed(rng):
    x = rng.randn(8, 6).astype(np.float32)
    y = rng.randint(0, 3, (8, 1)).astype(np.int64)
    return {"x": x, "label": y}


def _make_trainer(mesh=None, rules=None):
    prog = pt.build(lambda x, label: {
        "loss": L.mean(L.softmax_with_cross_entropy(L.fc(x, 3, name="head"), label))})
    return pt.Trainer(prog, opt.Adam(1e-2), loss_name="loss", mesh=mesh,
                      sharding_rules=rules)


def test_save_load_sharded_roundtrip(tmp_path):
    tree = {"a": jnp.arange(6.0).reshape(2, 3), "b": {"c": jnp.ones((4,), jnp.int32)}}
    d = str(tmp_path / "ck")
    pio.save_sharded(d, tree, async_save=False)
    back = pio.load_sharded(d)
    np.testing.assert_allclose(np.asarray(back["a"]), np.asarray(tree["a"]))
    np.testing.assert_array_equal(np.asarray(back["b"]["c"]), 1)


def test_async_save_with_barrier(tmp_path):
    tree = {"w": jnp.full((128, 128), 3.0)}
    d = str(tmp_path / "ck_async")
    pio.save_sharded(d, tree, async_save=True)
    pio.wait_for_checkpoints()
    back = pio.load_sharded(d)
    np.testing.assert_allclose(np.asarray(back["w"]), 3.0)


def test_trainer_sharded_checkpoint_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    tr = _make_trainer()
    tr.startup(sample_feed=_feed(rng))
    for _ in range(3):
        tr.step(_feed(rng))
    d = str(tmp_path / "trainer_ck")
    pio.save_trainer_sharded(d, tr, async_save=True)
    pio.wait_for_checkpoints()

    tr2 = _make_trainer()
    tr2.startup(sample_feed=_feed(rng))
    pio.load_trainer_sharded(d, tr2)
    assert tr2.global_step == tr.global_step
    for k in tr.scope.params:
        np.testing.assert_allclose(np.asarray(tr2.scope.params[k]),
                                   np.asarray(tr.scope.params[k]))
    # training continues from the restored state identically
    f = _feed(np.random.RandomState(42))
    l1 = float(tr.step(f)["loss"])
    l2 = float(tr2.step(f)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_restore_across_mesh_reshape(tmp_path):
    """Save under an 8-way dp mesh, restore into a 4-way dp×2-fsdp mesh:
    orbax re-lays shards to the new target sharding."""
    rng = np.random.RandomState(1)
    mesh8 = pt.make_mesh({"dp": 8})
    tr = _make_trainer(mesh=mesh8, rules=pt.parallel.replicated())
    tr.startup(sample_feed=_feed(rng))
    tr.step(_feed(rng))
    d = str(tmp_path / "mesh_ck")
    pio.save_trainer_sharded(d, tr, async_save=False)

    mesh42 = pt.make_mesh({"dp": 4, "fsdp": 2})
    tr2 = _make_trainer(mesh=mesh42, rules=pt.parallel.fsdp())
    tr2.startup(sample_feed=_feed(rng))
    pio.load_trainer_sharded(d, tr2)
    for k in tr.scope.params:
        np.testing.assert_allclose(np.asarray(jax.device_get(tr2.scope.params[k])),
                                   np.asarray(jax.device_get(tr.scope.params[k])),
                                   rtol=1e-6)
    out = tr2.step(_feed(rng))
    assert np.isfinite(float(out["loss"]))
