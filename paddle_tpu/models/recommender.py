"""Personalized recommendation — the book `recommender_system` config
(python/paddle/fluid/tests/book/test_recommender_system.py: movielens
user tower [id/gender/age/job embeddings → fc] and movie tower
[id embedding, mean-pooled category + title embeddings → fc], cosine
similarity scaled to the rating range, square_error_cost)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import layers as L


def make_model(num_users=944, num_movies=1683, num_genders=2, num_ages=7,
               num_jobs=21, num_categories=18, title_vocab=1000,
               emb_dim=32, fc_dim=200):
    """Inputs: user_id/gender_id/age_id/job_id [b,1] int, movie_id [b,1],
    category_ids [b, n_cat] (0-padded multi-hot), title_ids [b, n_title]
    (0-padded), score [b,1] float rating."""

    def usr_mov_net(user_id, gender_id, age_id, job_id, movie_id,
                    category_ids, title_ids, score):
        # -- user tower
        feats = [
            L.embedding(user_id, size=[num_users, emb_dim], name="usr_emb"),
            L.embedding(gender_id, size=[num_genders, emb_dim // 2], name="gender_emb"),
            L.embedding(age_id, size=[num_ages, emb_dim // 2], name="age_emb"),
            L.embedding(job_id, size=[num_jobs, emb_dim // 2], name="job_emb"),
        ]
        usr = jnp.concatenate([f.reshape(f.shape[0], -1) for f in feats], axis=-1)
        usr = L.fc(usr, fc_dim, act="tanh", name="usr_fc")

        # -- movie tower (category/title are 0-padded id lists → mean pool,
        # the sequence_pool('average') the reference applies to LoD inputs)
        mov_id = L.embedding(movie_id, size=[num_movies, emb_dim], name="mov_emb")
        mov_id = mov_id.reshape(mov_id.shape[0], -1)

        def pooled(ids, vocab, name):
            e = L.embedding(ids, size=[vocab, emb_dim // 2], name=name)
            m = (ids != 0).astype(e.dtype)[..., None]
            return (e * m).sum(1) / jnp.maximum(m.sum(1), 1.0)

        cat = pooled(category_ids, num_categories, "cat_emb")
        title = pooled(title_ids, title_vocab, "title_emb")
        mov = jnp.concatenate([mov_id, cat, title], axis=-1)
        mov = L.fc(mov, fc_dim, act="tanh", name="mov_fc")

        # -- cosine similarity scaled to [0, 5] (cos_sim + scale op chain)
        sim = L.cos_sim(usr, mov)
        pred = 5.0 * sim
        loss = L.mean(L.square_error_cost(pred, score))
        return {"loss": loss, "pred": pred}

    return usr_mov_net
