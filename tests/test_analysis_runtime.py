"""analysis.runtime — the source-level analyzer (thread:* lock
discipline + wire:* framed-verb contracts) and its CI surface
(tools/lint_gate --runtime, tools/lock_order, --wire-table).

Three layers of acceptance:

- **golden findings** — ``tests/runtime_lint_fixture.py`` plants one
  instance of every ``thread:*`` rule; the pins here are the oracle
  (rule code, ``where``, fingerprint stability under line shifts —
  the property that keeps committed baselines alive across edits);
- **historical regressions** — pre-fix reconstructions of four bug
  shapes this repo actually shipped and later fixed (AlertEngine
  snapshot race, CircuitBreaker on_trip under the lock, _spawn_worker
  register-before-start, the IMPORT combined-body read) must each be
  detected;
- **contracts** — the extracted verb table covers every verb on all
  three live wire surfaces with zero findings, and the gate/tool exit
  codes follow the shared 0/1/3 (tools: 0/2/3) convention.
"""

import json
import os
import sys

import pytest

from paddle_tpu.analysis import concurrency, runtime, wire_contracts
from paddle_tpu.analysis.report import LintReport

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from tools import lint_gate, lock_order

FIXTURE = os.path.join(os.path.dirname(__file__), "runtime_lint_fixture.py")


def _fixture_reports():
    return runtime.check_runtime(root=os.path.dirname(FIXTURE),
                                 files=[FIXTURE], wire=False)


def _findings(reports):
    return [(subj, f) for subj, rep in reports for f in rep.findings]


# --------------------------------------------------------------------------
# golden findings: one planted instance of every thread:* rule
# --------------------------------------------------------------------------


class TestGoldenFindings:
    def test_every_thread_rule_fires_once(self):
        found = _findings(_fixture_reports())
        by_code = {}
        for _, f in found:
            by_code.setdefault(f.code, []).append(f)
        assert sorted(by_code) == ["thread:callback-under-lock",
                                   "thread:join-unstarted",
                                   "thread:lock-order",
                                   "thread:unguarded-access"]
        assert all(len(v) == 1 for v in by_code.values()), by_code

    def test_unguarded_access_names_method_and_field(self):
        found = _findings(_fixture_reports())
        (f,) = [f for _, f in found if f.code == "thread:unguarded-access"]
        assert f.where == "GuardedCounter.snapshot:_count"
        assert f.data["lock"] == "_lock"

    def test_callback_under_lock_names_the_callback(self):
        found = _findings(_fixture_reports())
        (f,) = [f for _, f in found
                if f.code == "thread:callback-under-lock"]
        assert f.where == "GuardedCounter._loop"
        assert "on_full" in f.message and "_lock" in f.message

    def test_join_unstarted_names_registration_site(self):
        found = _findings(_fixture_reports())
        (f,) = [f for _, f in found if f.code == "thread:join-unstarted"]
        assert f.where == "RegisterBeforeStart.spawn"
        assert "before .start()" in f.message

    def test_lock_order_ring_is_canonical(self):
        found = _findings(_fixture_reports())
        (subj, f), = [(s, f) for s, f in found
                      if f.code == "thread:lock-order"]
        assert subj == "runtime:locks"
        assert f.where == ("InvertedLocks._a -> InvertedLocks._b "
                           "-> InvertedLocks._a")

    def test_fingerprints_stable_under_line_shift(self):
        """The property committed baselines depend on: moving code up
        or down a file must not invalidate a suppression."""
        with open(FIXTURE, encoding="utf-8") as fh:
            src = fh.read()
        base = concurrency.check_source(src, filename=FIXTURE)
        shifted = concurrency.check_source("# pad\n\n\n" + src,
                                           filename=FIXTURE)
        assert ({f.fingerprint for f in base.report.findings}
                == {f.fingerprint for f in shifted.report.findings})
        assert base.report.findings   # the set wasn't trivially empty


# --------------------------------------------------------------------------
# suppression conventions
# --------------------------------------------------------------------------


_COUNTER_TEMPLATE = '''
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0{field_allow}

    def start(self):
        self._routes = {{"peek": self.peek}}

    def bump(self):
        with self._lock:
            self._n += 1

    def peek(self):{def_allow}
        return self._n{line_allow}
'''


def _counter_src(field_allow="", def_allow="", line_allow=""):
    return _COUNTER_TEMPLATE.format(field_allow=field_allow,
                                    def_allow=def_allow,
                                    line_allow=line_allow)


class TestSuppression:
    def test_unsuppressed_baseline_fires(self):
        rep = concurrency.check_source(_counter_src()).report
        assert [f.code for f in rep.findings] == ["thread:unguarded-access"]

    def test_line_level_allow(self):
        rep = concurrency.check_source(_counter_src(
            line_allow="   # lint: allow(thread:unguarded-access)")).report
        assert not rep.findings

    def test_field_level_allow_on_init_line(self):
        rep = concurrency.check_source(_counter_src(
            field_allow="   # lint: allow(thread:unguarded-access)")).report
        assert not rep.findings

    def test_family_allow_on_def_line(self):
        rep = concurrency.check_source(_counter_src(
            def_allow="   # lint: allow(thread)")).report
        assert not rep.findings

    def test_guarded_by_annotation_declares_strict_mode(self):
        """A mutate-only container field's plain reads pass inference
        (stable-reference check-then-lock idiom) — until ``guarded-by:``
        opts the field into strict mode."""
        src = '''
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {{}}{anno}

    def start(self):
        self._routes = {{"peek": self.peek}}

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def peek(self, k):
        return self._items.get(k)
'''
        lax = concurrency.check_source(src.format(anno="")).report
        assert not lax.findings
        strict = concurrency.check_source(src.format(
            anno="   # guarded-by: _lock")).report
        assert [f.where for f in strict.findings] == ["C.peek:_items"]


# --------------------------------------------------------------------------
# historical regressions: pre-fix reconstructions must be detected
# --------------------------------------------------------------------------


class TestHistoricalRegressions:
    def test_alert_engine_snapshot_race(self):
        """The AlertEngine snapshot bug shape: evaluate/restore write
        ``_state`` under the engine lock while a route-registered
        snapshot iterates it bare — the KeyError race."""
        src = '''
import threading

class AlertEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}
        self._routes = {}

    def subscribe(self, server):
        self._routes["alerts"] = self.snapshot

    def evaluate(self, samples):
        with self._lock:
            for name in list(self._state):
                if name not in samples:
                    del self._state[name]
            self._state["last"] = samples

    def restore(self, saved):
        with self._lock:
            self._state = dict(saved)

    def snapshot(self):
        out = {}
        for name in self._state:
            out[name] = self._state[name]
        return out
'''
        rep = concurrency.check_source(src).report
        wheres = [f.where for f in rep.findings
                  if f.code == "thread:unguarded-access"]
        assert "AlertEngine.snapshot:_state" in wheres

    def test_circuit_breaker_on_trip_under_lock(self):
        """The breaker bug shape: the user's on_trip callback (a ctor
        param stored on self) fires inside the breaker lock."""
        src = '''
import threading

class CircuitBreaker:
    def __init__(self, threshold, on_trip=None):
        self._lock = threading.Lock()
        self._threshold = threshold
        self._failures = 0
        self.on_trip = on_trip

    def record_failure(self):
        with self._lock:
            self._failures += 1
            if self._failures >= self._threshold and self.on_trip:
                self.on_trip()
'''
        rep = concurrency.check_source(src).report
        cbs = [f for f in rep.findings
               if f.code == "thread:callback-under-lock"]
        assert [f.where for f in cbs] == ["CircuitBreaker.record_failure"]
        assert "on_trip" in cbs[0].message

    def test_spawn_worker_register_before_start(self):
        """The serving worker-pool bug shape: the Thread lands in the
        shared worker list before ``.start()`` — a concurrent join
        sweep sees a never-started Thread."""
        src = '''
import threading

class PredictorServer:
    def __init__(self):
        self._workers = []

    def _spawn_worker(self):
        t = threading.Thread(target=self._worker_loop, daemon=True)
        self._workers.append(t)
        t.start()

    def _worker_loop(self):
        pass
'''
        rep = concurrency.check_source(src).report
        js = [f for f in rep.findings if f.code == "thread:join-unstarted"]
        assert [f.where for f in js] == ["PredictorServer._spawn_worker"]

    def test_import_combined_body_drift(self):
        """The IMPORT migration bug shape: the client concatenates
        value+accum as TWO framed bodies while the pre-fix server read
        ONE combined body — schema drift on the body count."""
        client_src = '''
class PSClient:
    def _request(self, line, payload=b"", idempotent=True, body_len=None):
        pass

    def import_param(self, name, value, accum, dim):
        v = value.tobytes()
        a = accum.tobytes()
        self._request(
            f"IMPORT {name} {len(v)} {len(a)} {dim}", v + a)
'''
        server_src = '''
void ServeClient(PServer* ps, int fd) {
  std::string line;
  while (ReadLine(fd, &line)) {
    std::string resp, payload;
    char name[256];
    long long a = 0, b = 0, c = 0;
    if (sscanf(line.c_str(), "IMPORT %255s %lld %lld %lld",
               name, &a, &b, &c) == 4) {
      std::string body;
      if (!ReadBody(fd, (a + b) * sizeof(float), &body)) break;
      resp = ps->Import(name, a, b, c, body);
    }
  }
}
int main() { return 0; }
'''
        client = wire_contracts.scrape_python_client(client_src)
        server = wire_contracts.scrape_c_server(server_src)
        assert client["IMPORT"].bodies == 2
        assert server["IMPORT"].bodies == 1
        rep = wire_contracts.compare_tables("fixture", client, server)
        drifts = [f for f in rep.findings if f.code == "wire:schema-drift"]
        assert [f.where for f in drifts] == ["IMPORT:bodies"]
        assert drifts[0].severity == "error"
        assert drifts[0].data == {"expected": 1, "got": 2}


# --------------------------------------------------------------------------
# wire rules on planted fixtures
# --------------------------------------------------------------------------


_WIRE_CLIENT = '''
class Client:
    def _request(self, line, payload=b"", idempotent=True):
        pass

    def push(self, name, data):
        return self._request(f"PUSH {name} {len(data)}", data)

    def flush(self):
        return self._request("FLUSH")
'''

_WIRE_SERVER = '''
class Server:
    def serve(self, conn, parts, verb):
        if verb == "PUSH":
            # retry: at-most-once
            name = parts[1]
            n = int(parts[2])
            body = read_exact(conn, n)
'''


class TestWireFixtures:
    def _report(self):
        client = wire_contracts.scrape_python_client(_WIRE_CLIENT)
        server = wire_contracts.scrape_python_server(
            _WIRE_SERVER, dispatchers=("serve",))
        return wire_contracts.compare_tables("fixture", client, server)

    def test_retry_unsafe_is_an_error(self):
        unsafe = [f for f in self._report().findings
                  if f.code == "wire:retry-unsafe"]
        assert [f.where for f in unsafe] == ["PUSH"]
        assert unsafe[0].severity == "error"

    def test_unknown_verb_is_a_warning(self):
        unknown = [f for f in self._report().findings
                   if f.code == "wire:unknown-verb"]
        assert [f.where for f in unknown] == ["FLUSH"]
        assert unknown[0].severity == "warning"
        assert unknown[0].data["path"] == "client"

    def test_agreeing_schema_has_no_drift(self):
        assert not [f for f in self._report().findings
                    if f.code == "wire:schema-drift"]


# --------------------------------------------------------------------------
# the live tree: full verb coverage, zero findings
# --------------------------------------------------------------------------


EXPECTED_VERBS = {
    "ps": {"DELETE", "EXPORT", "IMPORT", "INIT", "PULL", "PUSH", "PUSHQ",
           "PUSHQB", "PUSHROWS", "QUIT", "SAVE", "STATUS"},
    "fleet": {"ARTIFACT", "FETCH", "HEALTH", "JOURNAL", "KILL", "METRICS",
              "PS", "QUIT", "RELOAD", "REPORT", "SHUTDOWN", "SPAWN", "STOP",
              "SUBMIT"},
    "telemetry": {"EVENTS", "PING", "QUIT", "SEGMENTS", "SNAPSHOT", "STATS"},
}


class TestLiveTree:
    def test_verb_table_covers_every_surface_verb_on_both_sides(self):
        rows = wire_contracts.verb_table()
        by_surface = {}
        for r in rows:
            by_surface.setdefault(r["surface"], {})[r["verb"]] = r
        assert {s: set(v) for s, v in by_surface.items()} == EXPECTED_VERBS
        for s, verbs in by_surface.items():
            for verb, r in verbs.items():
                assert r["sides"] == "both", (s, verb, r)

    def test_verb_table_pins_the_at_most_once_set(self):
        rows = wire_contracts.verb_table()
        amo = {(r["surface"], r["verb"]) for r in rows
               if r["retry"] == wire_contracts.AT_MOST_ONCE}
        assert amo == {("ps", "PUSH"), ("ps", "PUSHQ"), ("ps", "PUSHQB"),
                       ("ps", "PUSHROWS"), ("fleet", "SUBMIT"),
                       ("fleet", "RELOAD"), ("fleet", "KILL"),
                       ("fleet", "SHUTDOWN"), ("fleet", "SPAWN")}

    def test_wire_surfaces_are_clean(self):
        for subj, rep in wire_contracts.check_wire():
            assert not rep.findings, (subj, rep.findings)

    def test_runtime_sweep_is_clean_and_always_reports_aggregates(self):
        reports = runtime.check_runtime()
        subjects = [s for s, _ in reports]
        assert "runtime:locks" in subjects
        assert {"wire:ps", "wire:fleet", "wire:telemetry"} <= set(subjects)
        assert not _findings(reports)


# --------------------------------------------------------------------------
# CLI: python -m paddle_tpu.analysis --wire-table
# --------------------------------------------------------------------------


class TestWireTableCli:
    def test_markdown_output(self, capsys):
        from paddle_tpu.analysis.__main__ import main
        assert main(["--wire-table"]) == 0
        out = capsys.readouterr().out
        assert "generated by: python -m paddle_tpu.analysis" in out
        for surface in EXPECTED_VERBS:
            assert f"### `{surface}` surface" in out
        assert "| `SUBMIT` | both | 3 | 2 | 0 | yes | at-most-once |" in out

    def test_json_output_round_trips(self, capsys):
        from paddle_tpu.analysis.__main__ import main
        assert main(["--wire-table", "--format", "json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert {r["surface"] for r in rows} == set(EXPECTED_VERBS)

    def test_model_still_required_without_wire_table(self):
        from paddle_tpu.analysis.__main__ import main
        with pytest.raises(SystemExit) as exc:
            main([])
        assert exc.value.code == 2


# --------------------------------------------------------------------------
# tools/lint_gate.py --runtime: shared 0/1/3 contract
# --------------------------------------------------------------------------


def _injected_runtime_report():
    rep = LintReport("runtime:fixture")
    rep.add("thread:unguarded-access", "warning",
            "read of Fixture._n without holding self._lock",
            where="Fixture.peek:_n", lock="_lock")
    return [("runtime:fixture", rep)]


class TestLintGateRuntime:
    def test_clean_on_committed_tree(self, capsys):
        assert lint_gate.main(["--runtime"]) == 0
        assert "lint gate clean" in capsys.readouterr().out

    def test_exit1_on_new_runtime_finding(self, monkeypatch, tmp_path,
                                          capsys):
        monkeypatch.setattr(lint_gate, "run_runtime_gate",
                            _injected_runtime_report)
        rc = lint_gate.main(["--runtime",
                             "--baseline", str(tmp_path / "empty.json")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "runtime:fixture::thread:unguarded-access" in out
        assert "--write-baseline" in out

    def test_write_baseline_roundtrip(self, monkeypatch, tmp_path):
        monkeypatch.setattr(lint_gate, "run_runtime_gate",
                            _injected_runtime_report)
        path = str(tmp_path / "baseline.json")
        assert lint_gate.main(["--runtime", "--write-baseline", path]) == 0
        assert lint_gate.main(["--runtime", "--baseline", path]) == 0

    def test_exit3_on_checker_crash(self, monkeypatch, capsys):
        def boom():
            raise RuntimeError("scanner exploded")
        monkeypatch.setattr(lint_gate, "run_runtime_gate", boom)
        assert lint_gate.main(["--runtime"]) == 3
        assert "internal error" in capsys.readouterr().err


# --------------------------------------------------------------------------
# tools/lock_order.py: 0 clean / 2 cycle / 3 crash contract
# --------------------------------------------------------------------------


_CYCLE_SRC = '''
import threading

class InvertedLocks:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def transfer(self):
        with self._a:
            with self._b:
                pass

    def refund(self):
        with self._b:
            with self._a:
                pass
'''


class TestLockOrderTool:
    def test_clean_on_committed_tree(self, capsys):
        assert lock_order.main([]) == 0
        out = capsys.readouterr().out
        assert "no cycles" in out
        assert "lock-acquisition edge(s)" in out

    def test_exit2_on_cycle_with_ring_named(self, tmp_path, capsys):
        (tmp_path / "inverted.py").write_text(_CYCLE_SRC)
        assert lock_order.main(["--root", str(tmp_path)]) == 2
        out = capsys.readouterr().out
        assert ("InvertedLocks._a -> InvertedLocks._b -> InvertedLocks._a"
                in out)

    def test_dot_output_marks_cycle_edges(self, tmp_path, capsys):
        (tmp_path / "inverted.py").write_text(_CYCLE_SRC)
        assert lock_order.main(["--root", str(tmp_path), "--dot"]) == 2
        out = capsys.readouterr().out
        assert out.startswith("digraph lock_order")
        assert '"InvertedLocks._a" -> "InvertedLocks._b" [color=red' in out

    def test_exit3_on_crash(self, monkeypatch, capsys):
        import paddle_tpu.analysis.runtime as rt

        def boom(root=None, files=None):
            raise RuntimeError("walker exploded")
        monkeypatch.setattr(rt, "lock_edges", boom)
        assert lock_order.main([]) == 3
        assert "internal error" in capsys.readouterr().err
