"""Executor & Trainer — compile-and-run machinery.

Reference analog (SURVEY §3.1): ``fluid.Executor.run(program, feed,
fetch_list)`` interprets a ProgramDesc op-by-op (executor.cc:359), with
feed/fetch ops moving data in/out; ``ParallelExecutor`` schedules an SSA
graph over devices. Here the program is jit-compiled whole by XLA —
the op-loop, data transforms, and fusion passes all collapse into one
compiled executable per (program, shapes) key, cached like the
reference's program cache (executor.py:256 Executor._program_caches).

``Executor`` owns a :class:`Scope` (params/state/opt_state — the
scope.h:41 analog) so the fluid usage pattern maps 1:1:

    exe = pt.Executor()                      # place chosen like InitDevices
    exe.startup(prog, rng, sample_feed)      # startup-program analog
    out = exe.run(prog, feed={...}, fetch_list=['loss'])

``Trainer`` adds the optimizer loop: value_and_grad + optimizer.update
jitted with buffer donation (the eager-deletion/memory-reuse analog —
donation gives XLA the in-place update the reference's GC achieved).
Mesh-parallel execution plugs in through ``mesh``/``sharding_rules``
(see paddle_tpu.parallel) — the ParallelExecutor/BuildStrategy analog.
"""

from __future__ import annotations

import contextlib
import functools
import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .core import profiler
from .core.config import get_flag, make_prng_key
from .core.errors import enforce
from .core.place import Place, default_place
from .framework import Program

Feed = Dict[str, Any]


class Scope:
    """Name→value runtime store (scope.h:41 analog)."""

    def __init__(self):
        self.params: Dict[str, jax.Array] = {}
        self.state: Dict[str, jax.Array] = {}
        self.opt_state: Optional[Dict[str, Any]] = None
        self.extra: Dict[str, Any] = {}

    def var_names(self) -> List[str]:
        return sorted(self.params) + sorted(self.state)


def _trainer_log():
    import logging
    return logging.getLogger("paddle_tpu.trainer")


def _check_nan_inf(tree, where: str):
    """Host-side per-leaf scan (FLAGS_check_nan_inf analog) — still used
    on the forward/eval path (Executor.run). The TRAIN path uses the
    fused on-device guard instead (Trainer guard / GuardPolicy): one
    scalar bitmask computed inside the compiled step, no per-leaf host
    sync."""
    flat, _ = jax.tree.flatten(tree)
    for leaf in flat:
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            if bool(jnp.any(~jnp.isfinite(leaf))):
                raise FloatingPointError(f"NaN/Inf detected in {where} "
                                         "(FLAGS_check_nan_inf analog)")


def _tree_nonfinite(tree) -> jax.Array:
    """Scalar bool: ANY inexact leaf of ``tree`` holds a NaN/Inf.
    Traced inside the compiled step — the per-leaf partial reductions
    fuse into one on-device scalar, the guard's whole detection cost."""
    leaves = [x for x in jax.tree.leaves(tree)
              if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)]
    if not leaves:
        return jnp.bool_(False)
    return ~jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]).all()


# module names of the DONATING compiled step programs — the predicate
# both cache-read gates share (this one and tests/conftest.py's)
DONATING_STEP_MODULE_TAGS = ("train_step", "run_k_steps")

_cpu_cache_gate_installed = False


def _install_cpu_cache_read_gate():
    """On the CPU backend, gate persistent-compile-cache READS away from
    DONATING step executables (train_step / run_k_steps): the CPU
    runtime's disk→executable reload can lose donation alias info and a
    fetched output then reads clobbered memory — observed as sporadic
    garbage/NaN losses right after checkpoint saves (see
    tests/conftest.py, which applies the same quarantine for the test
    suite). Forward/eval/infer programs — the bulk of the cache's win —
    keep reading the cache; the step programs recompile once per
    process. TPU/GPU backends are unaffected and skip this entirely."""
    global _cpu_cache_gate_installed
    if _cpu_cache_gate_installed:
        return
    try:
        if jax.default_backend() != "cpu":
            return
        from jax._src import compiler as _jc
        orig = _jc._cache_read

        def gated(module_name, *args, **kw):
            if any(tag in (module_name or "")
                   for tag in DONATING_STEP_MODULE_TAGS):
                return None, None
            return orig(module_name, *args, **kw)

        _jc._cache_read = gated
        _cpu_cache_gate_installed = True
    except Exception as e:
        # private API drifted: the cache stays fully enabled, which on
        # this backend can silently corrupt reloaded donating steps —
        # say so instead of degrading invisibly
        _trainer_log().warning(
            "could not install the CPU cache-read gate for donating step "
            "executables (%s: %s); persistent-cache reloads of "
            "train_step/run_k_steps may corrupt fetched outputs on this "
            "backend — consider disabling compile_cache_dir on CPU",
            type(e).__name__, e)


class Executor:
    """Forward/eval executor with a held scope (executor.py:256 analog)."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or default_place()
        self.scope = Scope()
        self._jit_cache: Dict[Any, Callable] = {}

    # -- startup ------------------------------------------------------------
    def startup(self, program: Program, rng: Optional[jax.Array] = None, *example_args,
                **example_kwargs) -> Scope:
        """Run the startup-program analog: initialize params/state into
        the scope."""
        if rng is None:
            rng = make_prng_key(get_flag("seed"))
        params, state = program.init(rng, *example_args, **example_kwargs)
        dev = self.place.device()
        self.scope.params = jax.device_put(params, dev)
        self.scope.state = jax.device_put(state, dev)
        return self.scope

    # -- run ----------------------------------------------------------------
    def run(
        self,
        program: Program,
        feed: Optional[Feed] = None,
        fetch_list: Optional[Sequence[str]] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        training: bool = False,
        rng: Optional[jax.Array] = None,
        update_state: bool = False,
    ):
        """Run a program forward (Executor.run analog, executor.py:374).

        ``feed`` maps the program fn's argument names to arrays;
        ``fetch_list`` selects keys of the program's dict output (or
        returns the raw output when None).
        """
        scope = scope or self.scope
        feed = feed or {}
        # key on the Program object itself (not id(): a GC'd Program's id
        # can be reused and hit a stale compiled fn); the strong ref lives
        # until close() like the reference's per-executor program cache
        key = (program, training, tuple(sorted(feed)))
        if key not in self._jit_cache:
            def fwd(params, state, rng_, feed_):
                out, new_state = program.apply(params, state, training=training,
                                               rng=rng_, **feed_)
                return out, new_state
            self._jit_cache[key] = jax.jit(fwd)
        dev = self.place.device()
        feed_dev = {k: jax.device_put(np.asarray(v) if not isinstance(v, jax.Array) else v, dev)
                    for k, v in feed.items()}
        with profiler.record_event(f"exe.run/{program.name}"):
            out, new_state = self._jit_cache[key](scope.params, scope.state, rng, feed_dev)
        if get_flag("check_nan_inf"):
            _check_nan_inf(out, f"outputs of {program.name}")
        if update_state:
            scope.state = new_state
        if fetch_list is None:
            return jax.device_get(out) if return_numpy else out
        enforce(isinstance(out, dict),
                "fetch_list requires the program to return a dict of named outputs")
        vals = [out[name] for name in fetch_list]
        return [np.asarray(v) for v in vals] if return_numpy else vals

    def close(self):
        self._jit_cache.clear()


def _register_trainer_telemetry(trainer) -> int:
    """Register the trainer's scrape-time collector in the process
    registry: step/dispatch counters from the StepTimer, feeder stage
    counters from the PipelineMetrics, plus the trainer-level
    global-step gauge and guard-incident counter — all read from the
    structures the trainer already maintains, so the exported series
    cannot disagree with ``profile_report()``/``pipeline_report()``
    and the hot path pays nothing at publication time. The collector
    is weakly bound to the trainer (dropped when it is collected; the
    registry hands the live trainer back at scrape time)."""
    from .telemetry import get_registry
    from .telemetry.registry import counter_family, gauge_family

    def collect(tr):
        inst = tr.telemetry_inst
        labels = {"inst": inst}
        fams = [
            gauge_family("paddle_tpu_trainer_global_step",
                         "Current optimizer global step",
                         [(labels, tr.global_step)]),
            counter_family(
                "paddle_tpu_trainer_guard_incidents_total",
                "Non-finite steps discarded by the NaN/Inf guard",
                [(labels, tr.guard_incident_total)]),
        ]
        fams.extend(tr.step_timer.telemetry_families(inst))
        fams.extend(tr.pipeline_metrics.telemetry_families(inst))
        return fams

    return get_registry().add_collector(collect, owner=trainer)


class Trainer:
    """Jitted train loop: the Executor+optimizer / ParallelExecutor story.

    Single-device by default; pass ``mesh``+``sharding_rules`` (see
    paddle_tpu.parallel) for SPMD execution — params/opt-state sharded by
    rule, batch sharded over the data axes, gradients all-reduced by XLA
    over ICI (the AllReduceOpHandle analog, with zero scheduler code).
    """

    def __init__(
        self,
        program: Program,
        optimizer,
        loss_name: str = "loss",
        place: Optional[Place] = None,
        mesh=None,
        sharding_rules=None,
        strategy=None,
        donate: bool = True,
        fetch_list: Optional[Sequence[str]] = None,
        guard=None,
        feed_wire=None,
        augment=None,
    ):
        self.program = program
        self.optimizer = optimizer
        self.loss_name = loss_name
        self.place = place or default_place()
        self.mesh = mesh
        # adapt preset rule tables to the declared mesh once, up front:
        # axes the mesh doesn't have are dropped silently here (the
        # user's declared intent) instead of tripping the _validate
        # replication warning on every spec lookup. The pre-adaptation
        # table is kept for the lint's sharding audit — typo'd axes are
        # only visible on the raw table (adapted_to strips them).
        self.sharding_rules_raw = sharding_rules
        if sharding_rules is not None and mesh is not None:
            sharding_rules = sharding_rules.adapted_to(mesh)
        self.sharding_rules = sharding_rules
        enforce(not getattr(strategy, "async_mode", False),
                "DistStrategy.async_mode (DistributeTranspiler sync_mode="
                "False) selects barrier-free parameter-server training — "
                "use parallel.AsyncPSTrainer with a parallel.PServerProcess "
                "instead of the SPMD Trainer")
        self.strategy = strategy
        self.donate = donate
        # fetch_list prunes the per-step outputs INSIDE jit (executor.py
        # fetch-op analog) — unfetched outputs (e.g. full logits) are
        # dead-code-eliminated by XLA instead of materialized.
        self.fetch_list = list(fetch_list) if fetch_list is not None else None
        self.scope = Scope()
        self._step_fn = None
        self._multi_step_fn = None
        self._eval_fn = None
        # python executions of the step body == traces (the body only
        # runs at trace time inside jit/scan); tests pin no-retrace
        # guarantees on this counter staying flat
        self._trace_count = 0
        self.global_step = 0
        self.lint_report = None  # set by startup(lint=...)
        # NaN/Inf guard: guard=True -> default GuardPolicy; None ->
        # defer to the check_nan_inf flag at build time (the check is
        # compiled into the step program); False -> explicit opt-out
        # that also overrides the flag; otherwise a GuardPolicy
        from .resilience import GuardPolicy
        self.guard_policy = (GuardPolicy() if guard is True
                             else (None if not guard else guard))
        self._guard_opt_out = guard is False
        self.guard_incidents: List[Any] = []
        self._guard = None            # resolved policy (build time)
        self._guard_bit_names = ()    # bitmask bit -> checked-value name
        self._guard_pending = None    # (mask, feed, base_step, k) to examine
        # feed wire formats (data/wire.py): host-side encode in
        # _put_feed / the DeviceFeeder fill thread, device-side decode
        # traced into the step program (fused — no extra dispatch).
        # augment (data/augment.py): on-device crop/flip/normalize
        # appended to the decode inside the same traced step, per-step
        # randomness off the step rng (fused K == sequential).
        from .data.augment import FeedAugment
        from .data.feeder import PipelineMetrics
        from .data.wire import FeedWire
        from .profiling.steptime import StepTimer
        from .telemetry import get_journal, get_registry
        self.feed_wire = FeedWire.make(feed_wire)
        self.feed_augment = FeedAugment.make(augment)
        # the HBM dataset cache fit(device_cache=...) binds here, so
        # reload/reshard can invalidate it without knowing about fit
        self.device_cache = None
        self.pipeline_metrics = PipelineMetrics()
        # unified telemetry (paddle_tpu.telemetry): every trainer
        # publishes into the process registry through ONE scrape-time
        # collector (zero hot-path cost; the `inst` label keeps two
        # live trainers' series apart) and journals one correlated
        # event per dispatch through the StepTimer
        self.journal = get_journal()
        self.telemetry_inst = get_registry().next_instance("trainer")
        self.guard_incident_total = 0
        self._telemetry_server = None
        # push shipping: with PDTPU_TELEMETRY_ADDR set, this process
        # streams its journal + registry snapshots to the telemetry
        # collector — zero code beyond the env var (ship_to() is the
        # explicit door); never raises into training
        from .telemetry.shipper import maybe_auto_ship
        maybe_auto_ship()
        # per-dispatch wall-time accounting (profiling.steptime):
        # always-on — two clock reads per dispatch, <2% of step time
        # test-pinned — and merged with pipeline_metrics by
        # profile_report()
        self.step_timer = StepTimer(journal=self.journal,
                                    inst=self.telemetry_inst)
        self._fusion_report = None  # cache: fusion_report(feed) result
        # quantized-exchange state (resolved at _build_step): whether
        # the step signature carries the error-feedback residual, and
        # the static bytes-on-wire attribution of the grad exchange
        self._quant_ef = False
        self.collective_bytes = None
        # ZeRO weight-update sharding (strategy.zero_sharding): set by
        # startup to a parallel.zero.ZeroSpec when active; the step's
        # combine/partition hooks, io checkpointing, and the analysis/
        # advisor stack all key off this attribute
        self._zero = None
        self.loss_scaler = None
        if strategy is not None and (getattr(strategy, "loss_scale", None)
                                     or getattr(strategy, "dynamic_loss_scale", False)):
            from .amp import LossScaler
            self.loss_scaler = LossScaler(
                init_scale=strategy.loss_scale or 2.0 ** 15,
                dynamic=strategy.dynamic_loss_scale,
                growth_interval=strategy.loss_scale_growth_interval)
        # registered LAST: a scrape racing a half-constructed trainer
        # (or an __init__ that raises above) must never see a
        # collector whose attributes don't exist yet
        self._telemetry_cid = _register_trainer_telemetry(self)

    # ------------------------------------------------------------------
    def startup(self, rng: Optional[jax.Array] = None, sample_feed: Optional[Feed] = None,
                lint: str = "off"):
        """Initialize the scope and build the jitted step.

        ``lint`` runs the static program checker (paddle_tpu.analysis)
        over the program + built step before anything compiles:
        ``"warn"`` surfaces findings as :class:`analysis.LintWarning`
        and proceeds; ``"error"`` raises :class:`analysis.LintError` on
        any warning-or-worse finding (collective inside the microbatch
        scan, mis-sharded params, dead weights...); ``"off"`` (default)
        skips it. The report is kept at ``self.lint_report``."""
        enforce(lint in ("off", "warn", "error"),
                f"Trainer.startup(lint={lint!r}): expected off|warn|error")
        self._setup_compile_cache()
        if rng is None:
            rng = make_prng_key(get_flag("seed"))
        feed = {k: _abstractify(v) for k, v in (sample_feed or {}).items()}
        if self.feed_wire is not None:
            # a wire-typed sample feed (raw uint8 pixels) initializes
            # the model at its LOGICAL dtype — the decode runs before
            # the model ever sees the feed
            feed = self.feed_wire.logical_feed(feed)
        if self.feed_augment is not None:
            # an augmentation normalize likewise casts the feed before
            # the model sees it (shape-preserving by construction)
            feed = self.feed_augment.logical_feed(feed)
        params, state = self.program.init(rng, **feed)
        params = self._interleave_stacked_params(params)
        sd = getattr(self.strategy, "opt_state_dtype", None) if self.strategy else None
        if sd is not None:
            self.optimizer.set_state_dtype(sd)
        opt_state = self.optimizer.init(params)
        if self.mesh is not None:
            from .parallel import api as par_api
            params, state, opt_state = par_api.shard_scope(
                self.mesh, self.sharding_rules, params, state, opt_state)
        else:
            dev = self.place.device()
            params = jax.device_put(params, dev)
            state = jax.device_put(state, dev)
            opt_state = jax.device_put(opt_state, dev)
        self.scope.params, self.scope.state, self.scope.opt_state = params, state, opt_state
        if self.loss_scaler is not None:
            ls = self.loss_scaler.init_state()
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                ls = jax.device_put(ls, NamedSharding(self.mesh, PartitionSpec()))
            else:
                ls = jax.device_put(ls, self.place.device())
            self.scope.loss_scale_state = ls
        # error-feedback residual for the quantized exchange: one f32
        # slot per data-parallel rank per param — global shape
        # (dshard,) + param.shape, sharded on the leading axis so each
        # rank owns (and only ever touches) its own slot. Zeros at
        # init/restore: EF telescoping simply restarts, which costs one
        # step of correction and nothing else (deliberately NOT
        # persisted by io.save).
        self.scope.quant_resid = None
        qmode = ((getattr(self.strategy, "quantized_allreduce", "none")
                  if self.strategy else "none") or "none")
        if qmode in ("int8", "int4") and bool(
                getattr(self.strategy, "error_feedback", True)):
            axes = self._local_exchange_axes(
                f"quantized_allreduce={qmode!r}")
            dshard = 1
            for a in axes:
                dshard *= self.mesh.shape[a]
            from jax.sharding import NamedSharding, PartitionSpec
            bshard = axes if len(axes) > 1 else axes[0]
            self.scope.quant_resid = {
                name: jax.device_put(
                    jnp.zeros((dshard,) + tuple(leaf.shape), jnp.float32),
                    NamedSharding(self.mesh, PartitionSpec(
                        bshard, *([None] * len(leaf.shape)))))
                for name, leaf in self.scope.params.items()}
        # ZeRO weight-update sharding: partition params + opt_state into
        # (N, k) rows over the data axes — AFTER the EF residuals above
        # (they are built from LOGICAL shapes) and BEFORE the step
        # traces (its combine/partition hooks key off self._zero). Same
        # preconditions as the shard_map-local gradient paths.
        self._zero = None
        if self.strategy is not None and getattr(self.strategy,
                                                 "zero_sharding", False):
            from .parallel import zero as zero_mod
            zaxes = self._local_exchange_axes("zero_sharding=True")
            zspec = zero_mod.make_spec(self.mesh, zaxes, self.scope.params,
                                       self.scope.state, self.scope.opt_state)
            self.scope.params = zero_mod.partition_params(
                self.scope.params, zspec, self.mesh)
            self.scope.opt_state = zero_mod.partition_opt_state(
                self.scope.opt_state, zspec, self.mesh)
            self._zero = zspec
        self._build_step()
        self.lint_report = None
        if lint != "off":
            from . import analysis
            report = analysis.check_trainer(self, sample_feed)
            self.lint_report = report
            if lint == "error":
                report.enforce_clean("warning")
            else:
                report.emit_warnings("warning")
        return self.scope

    # ------------------------------------------------------------------
    def _pp_settings(self):
        pp_m = getattr(self.strategy, "pp_microbatches", 0) if self.strategy else 0
        pp_v = getattr(self.strategy, "pp_interleave", 1) if self.strategy else 1
        return pp_m, max(1, int(pp_v))

    def _interleave_stacked_params(self, params):
        """Megatron rest layout for the interleaved pipeline: permute
        each pp-sharded stacked leaf's layer rows into rank-major chunk
        order ONCE at startup (parallel.pipeline.interleave_perm), so
        the per-step schedule re-chunks with a free local reshape
        instead of an all-to-all over pp of (V-1)/V of the parameter
        bytes. Checkpoints stay in logical order: io.save/load_trainer*
        round-trip through stacked_to_logical/_from_logical."""
        self._pp_perm = {}
        pp_m, pp_v = self._pp_settings()
        if (pp_m <= 0 or pp_v <= 1 or self.mesh is None
                or "pp" not in self.mesh.axis_names
                or self.mesh.shape["pp"] <= 1
                or self.sharding_rules is None):
            return params
        from .parallel.pipeline import interleave_perm
        p = self.mesh.shape["pp"]
        for name, leaf in params.items():
            spec = self.sharding_rules.spec_for(name, leaf.shape, self.mesh)
            lead = spec[0] if len(spec) > 0 else None
            if not (lead == "pp" or (isinstance(lead, tuple) and "pp" in lead)):
                continue
            if leaf.ndim < 1 or leaf.shape[0] % (p * pp_v) != 0:
                continue
            perm = interleave_perm(leaf.shape[0], p, pp_v)
            params[name] = jnp.asarray(leaf)[perm]
            self._pp_perm[name] = perm
        return params

    def _apply_row_perm(self, params, opt_state, index_of):
        """Apply a per-name row permutation (``index_of(perm)`` chooses
        direction) to params and every per-param optimizer-state
        subtree.

        Optimizer-state contract (stated on the Optimizer base class):
        per-param state must live under a dict keyed by the PARAMETER
        NAME, at any depth — ``opt_state['accums'][name][slot]`` for the
        built-ins, but any other name-keyed location works. This walk
        finds every such subtree and permutes the arrays whose leading
        dim matches the permutation length, so interleaved-layout
        checkpoints stay aligned for ANY conforming optimizer (not just
        ones storing state under 'accums'). Never mutates its inputs —
        callers pass live scope trees on the save path."""
        perms = getattr(self, "_pp_perm", None) or {}
        if not perms:
            return params, opt_state
        params = dict(params)
        for name, perm in perms.items():
            if name in params:
                params[name] = jnp.asarray(params[name])[index_of(perm)]

        def permute_rows(sub, perm):
            idx = index_of(perm)
            return jax.tree.map(
                lambda a: (jnp.asarray(a)[idx]
                           if getattr(a, "ndim", 0) >= 1
                           and a.shape[0] == len(perm) else a), sub)

        def walk(tree):
            if not isinstance(tree, dict):
                return tree
            return {k: (permute_rows(v, perms[k]) if k in perms else walk(v))
                    for k, v in tree.items()}

        return params, (walk(opt_state) if opt_state is not None else None)

    def stacked_to_logical(self, params, opt_state=None):
        """Undo the interleaved rest layout (checkpoint/export order)."""
        return self._apply_row_perm(params, opt_state,
                                    lambda perm: np.argsort(perm))

    def stacked_from_logical(self, params, opt_state=None):
        """Re-apply the interleaved rest layout to logical-order arrays
        (checkpoint restore into a running interleaved trainer)."""
        return self._apply_row_perm(params, opt_state, lambda perm: perm)

    def _logical_params(self):
        """The params at their LOGICAL shapes regardless of ZeRO
        sharding — an eager all-gather of the (N, k) rows when
        ``zero_sharding`` is on, ``scope.params`` verbatim otherwise.
        For analysis traces, the advisor, and export paths; never the
        training hot path (the step's in-trace combine covers that)."""
        if getattr(self, "_zero", None) is None:
            return self.scope.params
        from .parallel import zero as zero_mod
        return zero_mod.combine_params(self.scope.params, self._zero,
                                       self.mesh)

    # ------------------------------------------------------------------
    def _ambient_mode(self, flag_desc: str, wanted: bool, axis: str, enter):
        """Strategy-knob → trace-time ambient plumbing shared by the
        parallelism modes: returns (active, context). Warns when the
        knob is set without a usable mesh axis."""
        import contextlib
        import warnings

        on = (wanted and self.mesh is not None
              and axis in self.mesh.axis_names and self.mesh.shape[axis] > 1)
        if wanted and not on:
            warnings.warn(
                f"{flag_desc} is set but the mesh "
                f"{dict(self.mesh.shape) if self.mesh is not None else None} "
                f"has no '{axis}' axis (size>1); training proceeds WITHOUT it")
        return on, (enter() if on else contextlib.nullcontext())

    @staticmethod
    def _warn_unconsumed(flag_desc: str, on: bool, cfg, hint: str):
        """Silent no-op parallelism (knob set, model never read the
        context) was a review finding — surface it."""
        import warnings

        if on and not cfg["consumed"]:
            warnings.warn(f"{flag_desc} is set but the model never consumed "
                          f"the context — {hint}")

    def _loss_and_aux(self, params, state, rng, feed):
        from .framework import pipeline_mode, remat_mode, sp_mode

        # strategy.remat (memory_optimize analog) flips the ambient
        # trace-time switch; zoo models wrap their repeated blocks in
        # maybe_remat, so jax.checkpoint lands per block
        pp_m, pp_v = self._pp_settings()
        pp_layout = ("interleaved" if getattr(self, "_pp_perm", None)
                     else "stacked")
        pp_on, pp_ctx = self._ambient_mode(
            f"DistStrategy.pp_microbatches={pp_m}", pp_m > 0, "pp",
            lambda: pipeline_mode(self.mesh, pp_m, interleave=pp_v,
                                  param_layout=pp_layout))
        sp_on, sp_ctx = self._ambient_mode(
            "DistStrategy.sequence_parallel",
            bool(getattr(self.strategy, "sequence_parallel", False)), "sp",
            lambda: sp_mode(self.mesh,
                            impl=getattr(self.strategy, "sp_impl", "ring")))
        with remat_mode(bool(getattr(self.strategy, "remat", False)),
                        policy=getattr(self.strategy, "remat_policy", None)), \
                pp_ctx as pp_cfg, sp_ctx as sp_cfg:
            out, new_state = self.program.apply(params, state, training=True,
                                                rng=rng, **feed)
        self._warn_unconsumed(
            "DistStrategy.pp_microbatches", pp_on, pp_cfg,
            "no stacked block stack routed through the pipeline; every pp "
            "rank redundantly computes the full model. Build the model with "
            "its stacked representation (e.g. TransformerConfig(stacked=True)).")
        self._warn_unconsumed(
            "DistStrategy.sequence_parallel", sp_on, sp_cfg,
            "attention is NOT ring-parallel. Use an sp-aware model "
            "(models/gpt.py).")
        if isinstance(out, dict):
            loss = out[self.loss_name]
        else:
            loss = out
            out = {self.loss_name: loss}
        if self.fetch_list is not None:
            out = {k: out[k] for k in set(self.fetch_list) | {self.loss_name}}
        return loss, (out, new_state)

    def _hoisted_accum_axes(self):
        """Validate and resolve DistStrategy.accum_exchange="hoisted":
        the shard_map-local accumulation that exchanges gradients ONCE
        per optimizer step (the wire lever SCALING.md §2 names as the
        follow-up to the measured in-loop GSPMD exchange)."""
        return self._local_exchange_axes("accum_exchange='hoisted'")

    def _local_exchange_axes(self, why: str):
        """Validate and resolve a shard_map-LOCAL gradient path (the
        hoisted exchange and the quantized collective both run the
        model per data shard and exchange explicitly). Only sound when
        the model trace is collective-free per shard, so every
        precondition is enforced loudly rather than silently computing
        something else."""
        enforce(self.mesh is not None,
                f"{why} needs a mesh (it is the cross-shard exchange "
                "policy)")
        axes = tuple(a for a in ("dp", "fsdp") if a in self.mesh.axis_names
                     and self.mesh.shape[a] > 1)
        enforce(axes, f"{why}: mesh has no data axis")
        pp_m, _ = self._pp_settings()
        enforce(pp_m == 0 and not getattr(self.strategy, "sequence_parallel",
                                          False),
                f"{why} composes only with pure data parallelism (no "
                "pp/sp: their shard_map schedules cannot nest inside "
                "the local gradient path)")
        enforce(not self.scope.state,
                f"{why} requires stateless models: per-shard mutable "
                "state (e.g. BN running stats) would silently diverge "
                "across shards")
        from jax.sharding import PartitionSpec
        for name, leaf in self.scope.params.items():
            spec = (self.sharding_rules.spec_for(name, leaf.shape, self.mesh)
                    if self.sharding_rules is not None else PartitionSpec())
            enforce(all(e is None for e in spec),
                    f"{why} requires fully replicated "
                    f"params; {name} is sharded {spec} (use fsdp/tp with "
                    "the default gspmd exchange instead)")
        return axes

    def _collective_bytes_summary(self, quant, axes):
        """Static bytes-on-wire attribution of the per-optimizer-step
        gradient exchange (the ``collective`` line of
        :meth:`profile_report` / ``collective_bytes`` in
        :meth:`fusion_report`): per-device ring-all-reduce bytes summed
        over every gradient leaf and data axis, fp32 baseline vs the
        configured wire format. ``None`` off-mesh or when the mesh has
        no data axis; with ``quantized_allreduce="none"`` the entry is
        still present (reduction 1.0) so dashboards can diff runs.
        Counts ONE exchange per step — the gspmd-accum path's
        per-microbatch exchanges cost ``accum_steps``× this."""
        if self.mesh is None:
            return None
        if axes is None:
            axes = tuple(a for a in ("dp", "fsdp")
                         if a in self.mesh.axis_names
                         and self.mesh.shape[a] > 1)
        if not axes:
            return None
        from .parallel import quantized_collectives as qc
        zero = getattr(self, "_zero", None)
        if zero is not None:
            # scope.params hold (N, k) shard rows under ZeRO; the grad
            # exchange still moves LOGICAL gradient elements
            sizes = [int(np.prod(s)) if s else 1 for s in zero.shapes.values()]
        else:
            sizes = [int(np.prod(p.shape)) if p.shape else 1
                     for p in jax.tree.leaves(self.scope.params)]
        ranks = {a: int(self.mesh.shape[a]) for a in axes}
        fp32 = sum(qc.ring_wire_bytes(n, p)
                   for n in sizes for p in ranks.values())
        wire = fp32 if quant is None else sum(
            qc.ring_wire_bytes(n, p, bits=quant["bits"],
                               block_size=quant["block_size"])
            for n in sizes for p in ranks.values())
        summary = {
            "mode": "none" if quant is None else f"int{quant['bits']}",
            "bits": None if quant is None else quant["bits"],
            "block_size": None if quant is None else quant["block_size"],
            "error_feedback": bool(quant and quant["error_feedback"]),
            "axes": axes,
            "ranks": ranks,
            "grad_elems": int(sum(sizes)),
            "fp32_bytes_per_step": int(fp32),
            "wire_bytes_per_step": int(wire),
            "reduction": (float(fp32) / wire) if wire else 1.0,
        }
        if zero is not None:
            # the ZeRO top-of-step param all-gather rides the same link
            # — attribute it on the collective line next to the grad
            # exchange it complements
            from .parallel import zero as zero_mod
            summary["zero"] = {
                "shards": zero.n,
                "axes": zero.axes,
                "allgather_bytes_per_step":
                    zero_mod.allgather_bytes_per_step(zero),
            }
        return summary

    def _quantized_exchange(self, gsum, accum_steps, axes, dshard, r,
                            res, quant, unscale):
        """The quantized replacement of the hoisted path's pmean,
        traced INSIDE the shard_map body: per gradient leaf, mean over
        microbatches, locally unscale (loss scaling — the residual
        must live in unscaled units or a dynamic-scale change between
        steps corrupts it), add the error-feedback residual, and ring-
        exchange through parallel.quantized_collectives over each data
        axis. With EF the leaf is roundtripped through the wire grid
        FIRST: the exchange then carries the already-quantized value
        (re-encoding is integer-exact — the ring chunk grid is padded
        to the block grid), so ``v - deq`` is exactly the information
        this rank failed to put on the wire, carried to the next step.
        Stochastic rounding keys derive from the shard-folded step rng
        (per-leaf, per-axis folds)."""
        from .parallel import quantized_collectives as qc

        bits, block = quant["bits"], quant["block_size"]
        sr = quant["stochastic_rounding"]
        leaves, treedef = jax.tree.flatten(gsum)
        res_leaves = (jax.tree.leaves(res) if res is not None
                      else [None] * len(leaves))
        qkey = jax.random.fold_in(r, 0x7157) if sr else None
        outg, outres = [], []
        for i, (g, rs) in enumerate(zip(leaves, res_leaves)):
            g = g / accum_steps
            if unscale is not None:
                g = unscale(g)
            key = jax.random.fold_in(qkey, i) if sr else None
            if rs is not None:
                v = g + rs
                x = qc.block_roundtrip(v, bits=bits, block_size=block,
                                       rng=key)
                outres.append(v - x)
                key = None  # the ring re-encodes x exactly; SR is spent
            else:
                x = g
            for j, a in enumerate(axes):
                x = qc.quantized_psum(
                    x, a, bits=bits, block_size=block,
                    rng=(jax.random.fold_in(key, j)
                         if key is not None else None))
            outg.append(x / dshard)
        grads = jax.tree.unflatten(treedef, outg)
        new_res = (jax.tree.unflatten(treedef, outres)
                   if res is not None else None)
        return grads, new_res

    def _hoisted_accum(self, loss_and_aux, axes, accum_steps, params,
                       state, rng, feed, resid=None, quant=None,
                       unscale=None):
        """shard_map-local gradient accumulation: each data shard scans
        its accum_steps microbatches with NO cross-shard traffic, then
        the summed gradients are pmean'd ONCE — the hoisted exchange
        GSPMD will not produce on its own (SCALING.md §2). Params enter
        replicated (enforced), the model trace is collective-free per
        shard, float outputs are pmean'd to match the GSPMD path's
        global means.

        With ``quant`` (DistStrategy.quantized_allreduce) the single
        pmean becomes the block-scaled quantized ring exchange; a
        non-None ``resid`` additionally threads the per-shard error-
        feedback residual — global shape ``(dshard,) + param.shape``,
        sharded on the leading axis so each rank owns its own slot —
        through the shard_map and back out (returned as a 4th value)."""
        import functools

        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        dshard = 1
        for a in axes:
            dshard *= mesh.shape[a]
        b = jax.tree.leaves(feed)[0].shape[0]
        enforce(b % (accum_steps * dshard) == 0,
                f"batch {b} must divide accum_steps*data shards "
                f"({accum_steps}*{dshard}) for hoisted accumulation")
        bshard = axes if len(axes) > 1 else axes[0]

        def body(p, f, r, *res_args):
            # per-shard rng: fold the shard position in so dropout
            # masks decorrelate across shards (same-in-distribution as
            # the GSPMD path's globally-sharded masks)
            for a in axes:
                r = jax.random.fold_in(r, jax.lax.axis_index(a))
            rngs = jax.random.split(r, accum_steps)
            f_m = jax.tree.map(
                lambda x: x.reshape((accum_steps,
                                     x.shape[0] // accum_steps)
                                    + x.shape[1:]), f)
            zero = jax.tree.map(lambda q: jnp.zeros(q.shape, jnp.float32), p)

            def micro(acc, mb):
                (_, (out, _)), grads = jax.value_and_grad(
                    loss_and_aux, has_aux=True)(p, {}, mb["rng"],
                                                mb["feed"])
                return jax.tree.map(jnp.add, acc, grads), out

            gsum, outs = jax.lax.scan(micro, zero,
                                      {"rng": rngs, "feed": f_m})
            pmean_all = functools.partial(
                functools.reduce, lambda v, a: jax.lax.pmean(v, a), axes)
            new_res = None
            if quant is None:
                grads = jax.tree.map(
                    lambda g: pmean_all(g / accum_steps), gsum)
            else:
                # each rank sees its (1, ...) leading slot of the
                # sharded residual
                res = (jax.tree.map(lambda x: x[0], res_args[0])
                       if res_args else None)
                grads, new_res = self._quantized_exchange(
                    gsum, accum_steps, axes, dshard, r, res, quant,
                    unscale)
            # outputs leave the shard_map replicated (out_specs=P()), so
            # only FLOAT SCALARS are sound: a pmean of per-sample arrays
            # (logits) would average across shards' DIFFERENT samples,
            # and non-float leaves have no cross-shard combine at all.
            # Models returning more must prune with Trainer(fetch_list=)
            for path, leaf in jax.tree_util.tree_flatten_with_path(outs)[0]:
                keys = jax.tree_util.keystr(path)
                enforce(jnp.issubdtype(leaf.dtype, jnp.floating)
                        and leaf.ndim == 1,  # (accum_steps,) of scalars
                        f"accum_exchange='hoisted': output {keys} is "
                        f"{leaf.dtype}{leaf.shape[1:]} per microbatch — "
                        "only float scalar outputs (loss/metrics) can be "
                        "replicated across shards; pass fetch_list=[...] "
                        "to prune per-sample or integer outputs")
            out = jax.tree.map(
                lambda x: pmean_all(jnp.mean(x, axis=0)), outs)
            if new_res is not None:
                return grads, out, jax.tree.map(lambda x: x[None], new_res)
            return grads, out

        feed_specs = jax.tree.map(
            lambda x: P(bshard, *([None] * (x.ndim - 1))), feed)
        if resid is not None:
            res_specs = jax.tree.map(
                lambda x: P(bshard, *([None] * (x.ndim - 1))), resid)
            grads, out, new_resid = jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(), feed_specs, P(), res_specs),
                out_specs=(P(), P(), res_specs), check_vma=False)(
                    params, feed, rng, resid)
            return grads, out, state, new_resid
        grads, out = jax.shard_map(
            body, mesh=mesh, in_specs=(P(), feed_specs, P()),
            out_specs=P(), check_vma=False)(params, feed, rng)
        return grads, out, state

    def _build_step(self):
        accum_steps = getattr(self.strategy, "accum_steps", 1) if self.strategy else 1
        scaler = self.loss_scaler
        # wire-format decode is resolved ONCE here, like the guard: the
        # dequant/cast is traced into the step program and fused by XLA
        # into the first consumers — the feed crosses the link in the
        # wire dtype and costs no extra device launch to decode. Use
        # set_feed_wire() to change it after startup (rebuilds).
        wire = self.feed_wire
        # on-device augmentation rides the same trace, directly after
        # the decode: crop/flip/normalize fuse into the feed's first
        # consumers, keyed off the step rng (fold_in(base, step+i)) so
        # fused K-step augmentation equals sequential exactly
        augment = self.feed_augment
        # validate the exchange mode UNCONDITIONALLY: a typo'd or
        # inapplicable knob must fail loudly, never silently no-op
        # (the _warn_unconsumed lesson)
        mode = (getattr(self.strategy, "accum_exchange", "gspmd")
                if self.strategy else "gspmd")
        enforce(mode in ("gspmd", "hoisted"),
                f"DistStrategy.accum_exchange={mode!r} (gspmd|hoisted)")
        enforce(mode == "gspmd" or accum_steps > 1,
                "accum_exchange='hoisted' without accum_steps>1 is a "
                "misconfiguration (there is no loop to hoist out of)")
        hoist_axes = (self._hoisted_accum_axes() if mode == "hoisted"
                      else None)
        # quantized gradient exchange (EQuARX lineage): resolved ONCE
        # here like the guard — bits/block/EF are compiled into the
        # step program. "none" keeps today's exchange bit-identically
        # (no quant code on the trace at all).
        qmode = ((getattr(self.strategy, "quantized_allreduce", "none")
                  if self.strategy else "none") or "none")
        enforce(qmode in ("none", "int8", "int4"),
                f"DistStrategy.quantized_allreduce={qmode!r} "
                "(none|int8|int4)")
        quant_cfg = quant_axes = None
        if qmode != "none":
            from .parallel import quantized_collectives as qc
            qbits = 8 if qmode == "int8" else 4
            qblock = int(getattr(self.strategy, "quant_block_size", 256))
            qc.wire_block_bytes(1, bits=qbits, block_size=qblock)  # validate
            quant_cfg = {
                "bits": qbits,
                "block_size": qblock,
                "error_feedback": bool(getattr(self.strategy,
                                               "error_feedback", True)),
                "stochastic_rounding": bool(getattr(
                    self.strategy, "quant_stochastic_rounding", False)),
            }
            quant_axes = self._local_exchange_axes(
                f"quantized_allreduce={qmode!r}")
        qef = bool(quant_cfg and quant_cfg["error_feedback"])
        self._quant_ef = qef
        self.collective_bytes = self._collective_bytes_summary(
            quant_cfg, quant_axes)
        # guard resolution happens ONCE here: the detection is compiled
        # into the step program, so the check_nan_inf flag is read at
        # build time (set it before startup). An explicit GuardPolicy
        # degrades gracefully; the bare flag keeps its abort semantics
        # (escalate on the first incident) minus the per-leaf host syncs.
        guard = self.guard_policy
        if guard is None and not self._guard_opt_out \
                and get_flag("check_nan_inf"):
            from .resilience import GuardPolicy
            # eager readback: the legacy flag promises an abort AT the
            # offending step, including for hand-rolled step() loops
            # that never call drain_guard()
            guard = GuardPolicy(max_incidents=0, window=1,
                                record_feed_digest=False,
                                defer_readback=False)
        self._guard = guard
        zspec = getattr(self, "_zero", None)
        if zspec is not None:
            from .parallel import zero as zero_mod

        def _step_impl(params, opt_state, state, rng, feed, ls, qresid):
            self._trace_count += 1  # trace-time only: counts compilations
            if wire is not None:
                feed = wire.decode(feed)
            if augment is not None:
                feed = augment.apply(feed, rng, training=True)
            pshards = None
            if zspec is not None:
                # top-of-step all-gather: fresh logical params from this
                # step's shard rows (GSPMD materializes the gather at
                # the replicated constraint); the rows stay bound for
                # the shard-local update below
                pshards = params
                params = zero_mod.combine_params(pshards, zspec, self.mesh)
            def loss_and_aux(p, st, r, f):
                loss, aux = self._loss_and_aux(p, st, r, f)
                if scaler is not None:
                    loss = scaler.scale_loss(loss, ls)
                return loss, aux

            new_qresid = None
            if quant_cfg is not None:
                # quantized exchange: the model runs shard_map-local
                # (same schedule as the hoisted path, at any
                # accum_steps>=1) so the ONE per-step gradient exchange
                # is the block-scaled quantized ring instead of a GSPMD
                # f32 all-reduce. Loss unscaling happens INSIDE the
                # body, before encode (the EF residual lives in
                # unscaled units).
                unscale = ((lambda g: scaler.unscale(g, ls))
                           if scaler is not None else None)
                if qef:
                    grads, out, new_state, new_qresid = self._hoisted_accum(
                        loss_and_aux, quant_axes, accum_steps, params,
                        state, rng, feed, resid=qresid, quant=quant_cfg,
                        unscale=unscale)
                else:
                    grads, out, new_state = self._hoisted_accum(
                        loss_and_aux, quant_axes, accum_steps, params,
                        state, rng, feed, quant=quant_cfg,
                        unscale=unscale)
            elif accum_steps > 1 and hoist_axes is not None:
                grads, out, new_state = self._hoisted_accum(
                    loss_and_aux, hoist_axes, accum_steps, params, state,
                    rng, feed)
            elif accum_steps > 1:
                # gradient accumulation (multi_batch_merge_pass analog):
                # microbatch over the leading feed axis with lax.scan.
                # NOTE the grad exchange rides inside this loop under
                # GSPMD (SCALING.md §2); accum_exchange="hoisted" is
                # the once-per-step alternative.
                def micro(carry, mb):
                    acc, st = carry
                    (loss, (out, new_st)), grads = jax.value_and_grad(
                        loss_and_aux, has_aux=True)(params, st, mb["rng"], mb["feed"])
                    acc = jax.tree.map(jnp.add, acc, grads)
                    return (acc, new_st), out

                feed_m = jax.tree.map(
                    lambda x: x.reshape((accum_steps, x.shape[0] // accum_steps) + x.shape[1:]),
                    feed)
                rngs = jax.random.split(rng, accum_steps)
                zero = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
                (gsum, new_state), outs = jax.lax.scan(
                    micro, (zero, state), {"rng": rngs, "feed": feed_m})
                out = jax.tree.map(lambda x: jnp.mean(x, axis=0), outs)
                grads = jax.tree.map(lambda g: g / accum_steps, gsum)
            else:
                (loss, (out, new_state)), grads = jax.value_and_grad(
                    loss_and_aux, has_aux=True)(params, state, rng, feed)

            if zspec is not None:
                # reduce-scatter: the row constraint keeps only this
                # replica's slice of the exchanged grads; rebinding the
                # shard rows makes everything below — unscale,
                # all_finite, optimizer.update, overflow/guard rollback
                # — shard-local over matching (N, k) trees (grad pads
                # are exact zeros, so norms and finiteness agree with
                # the logical grads)
                grads = zero_mod.partition_grads(grads, zspec, self.mesh)
                params = pshards

            if scaler is not None:
                if quant_cfg is None:
                    # the quant path already unscaled inside the
                    # shard_map body (pre-encode)
                    grads = scaler.unscale(grads, ls)
                finite = scaler.all_finite(grads)
                new_params, new_opt = self.optimizer.update(
                    grads, opt_state, params, self.program.param_info)
                # overflow-skip: keep old params/opt/state on non-finite grads
                new_params = scaler.select(finite, new_params, params)
                new_opt = scaler.select(finite, new_opt, opt_state)
                new_state = scaler.select(finite, new_state, state)
                if new_qresid is not None:
                    # a skipped step must not bank a NaN-poisoned (or
                    # phantom) residual: EF state rolls back with the
                    # rest of the carry
                    new_qresid = scaler.select(finite, new_qresid, qresid)
                new_ls = scaler.update(ls, finite)
                out = dict(out)
                out["loss_scale"] = new_ls["scale"]
            else:
                new_params, new_opt = self.optimizer.update(
                    grads, opt_state, params, self.program.param_info)
                new_ls = ls
            if guard is not None:
                # fused on-device NaN/Inf guard: ONE scalar bitmask over
                # the gradients and every inexact fetch output, computed
                # inside the compiled step. On a non-finite step the
                # update is discarded branchlessly — the pre-step carry
                # (params/opt_state/state) IS the last-good snapshot,
                # already on device. Loss-scale state is deliberately
                # NOT rolled back: the scaler's overflow backoff must
                # persist or the same overflow recurs forever.
                from .amp import LossScaler
                # with a loss scaler, grad overflow is the SCALER's
                # domain: it already skipped the update and backed the
                # scale off, and routine calibration overflows must not
                # count as guard incidents (much less abort the run via
                # the check_nan_inf route) — the guard then watches the
                # fetch outputs only
                names, flags = [], []
                if scaler is None:
                    names, flags = ["grads"], [_tree_nonfinite(grads)]
                for kname in sorted(out):
                    v = out[kname]
                    if hasattr(v, "dtype") and jnp.issubdtype(v.dtype,
                                                              jnp.inexact):
                        names.append(kname)
                        flags.append(_tree_nonfinite(v))
                if len(flags) > 32:
                    # uint32 mask: shifts past bit 31 are undefined and
                    # would silently drop detection — fold the tail into
                    # one combined bit (detection stays exact, only the
                    # which-output attribution coarsens)
                    rest = flags[31:]
                    flags = flags[:31] + [jnp.stack(rest).any()]
                    names = names[:31] + [
                        f"any-of-{len(rest)}-more:{'/'.join(names[31:34])}…"]
                mask = jnp.zeros((), jnp.uint32)
                for i, fl in enumerate(flags):
                    mask = mask | (fl.astype(jnp.uint32) << i)
                finite = mask == 0
                new_params = LossScaler.select(finite, new_params, params)
                new_opt = LossScaler.select(finite, new_opt, opt_state)
                new_state = LossScaler.select(finite, new_state, state)
                if new_qresid is not None:
                    new_qresid = LossScaler.select(finite, new_qresid,
                                                   qresid)
                self._guard_bit_names = tuple(names)  # trace-time capture
                out = dict(out)
                out["guard_nonfinite"] = mask
            if qef:
                return (new_params, new_opt, new_state, out, new_ls,
                        new_qresid)
            return new_params, new_opt, new_state, out, new_ls

        # the public step signature only grows the error-feedback
        # residual arg when the knob asks for it — quantized_allreduce=
        # "none" keeps today's 6-arg step (and its donation map)
        # byte-identically
        if qef:
            def train_step(params, opt_state, state, rng, feed, ls, qresid):
                return _step_impl(params, opt_state, state, rng, feed, ls,
                                  qresid)
        else:
            def train_step(params, opt_state, state, rng, feed, ls):
                return _step_impl(params, opt_state, state, rng, feed, ls,
                                  None)

        donate = ((0, 1, 2, 5, 6) if qef else (0, 1, 2, 5)) \
            if self.donate else ()
        # kept for the fused driver and the donation lint: the raw
        # python step body (check_trainer traces it to see input→output
        # passthrough aliasing that the jitted wrapper hides)
        self._train_step_core = train_step
        self._donate_argnums = donate
        if self.mesh is not None:
            from .parallel import api as par_api
            self._step_fn = par_api.jit_sharded_step(
                self.mesh, self.sharding_rules, train_step, donate_argnums=donate,
                scope=self.scope)
        else:
            self._step_fn = jax.jit(train_step, donate_argnums=donate)

        if qef:
            def run_k_steps(params, opt_state, state, base_rng, step0,
                            feed_k, ls, qresid):
                """Fused multi-step driver, error-feedback variant: the
                quantization residual rides the scan carry, so over the
                K fused steps the compression error TELESCOPES (each
                step's encode sees what the last one dropped) while the
                program stays one device launch."""
                k = jax.tree.leaves(feed_k)[0].shape[0]

                def body(carry, x):
                    p, o, s, ls_, qr = carry
                    r = jax.random.fold_in(base_rng, step0 + x["i"])
                    p, o, s, out, ls_, qr = train_step(p, o, s, r,
                                                       x["feed"], ls_, qr)
                    return (p, o, s, ls_, qr), out

                (p, o, s, new_ls, new_qr), outs = jax.lax.scan(
                    body, (params, opt_state, state, ls, qresid),
                    {"i": jnp.arange(k, dtype=jnp.int32), "feed": feed_k})
                return p, o, s, outs, new_ls, new_qr

            kdonate = (0, 1, 2, 6, 7) if self.donate else ()
        else:
            def run_k_steps(params, opt_state, state, base_rng, step0,
                            feed_k, ls):
                """Fused multi-step driver: ONE device launch runs K
                optimizer steps under lax.scan with the full training
                carry (params, opt_state, state, loss-scale state)
                resident on device between updates — per-step rng keys
                reproduce the sequential ``step()`` stream exactly
                (fold_in of the same base key at the same global
                step)."""
                k = jax.tree.leaves(feed_k)[0].shape[0]

                def body(carry, x):
                    p, o, s, ls_ = carry
                    r = jax.random.fold_in(base_rng, step0 + x["i"])
                    p, o, s, out, ls_ = train_step(p, o, s, r, x["feed"],
                                                   ls_)
                    return (p, o, s, ls_), out

                (p, o, s, new_ls), outs = jax.lax.scan(
                    body, (params, opt_state, state, ls),
                    {"i": jnp.arange(k, dtype=jnp.int32), "feed": feed_k})
                return p, o, s, outs, new_ls

            kdonate = (0, 1, 2, 6) if self.donate else ()
        if self.mesh is not None:
            from .parallel import api as par_api
            self._multi_step_fn = par_api.jit_sharded_step(
                self.mesh, self.sharding_rules, run_k_steps,
                donate_argnums=kdonate, scope=self.scope)
        else:
            self._multi_step_fn = jax.jit(run_k_steps, donate_argnums=kdonate)

        def eval_step(params, state, feed):
            if zspec is not None:
                # eval sees the same all-gathered logical params the
                # train step computes with
                params = zero_mod.combine_params(params, zspec, self.mesh)
            if wire is not None:
                feed = wire.decode(feed)
            if augment is not None:
                # deterministic ops only (normalize): eval never flips
                # or crops randomly
                feed = augment.apply(feed, None, training=False)
            # With the interleaved rest layout (pp_interleave>1) the
            # stacked rows are only meaningful through the pipeline
            # schedule, so eval must enter the same pipeline ctx as
            # training (its feeds then share the train step's
            # microbatch-divisibility requirement). Plain-pp trainers
            # keep the old scan-path eval: logical row order is intact
            # and any batch size works.
            from .framework import pipeline_mode
            pp_m, pp_v = self._pp_settings()
            if getattr(self, "_pp_perm", None):
                b = jax.tree.leaves(feed)[0].shape[0]
                enforce(
                    b % pp_m == 0,
                    f"Trainer.eval with pp_interleave={pp_v}>1 runs the "
                    f"training pipeline schedule, so the eval batch ({b}) "
                    f"must be divisible by pp_microbatches={pp_m} (and its "
                    "microbatches by the dp shard product) — pad or "
                    "re-batch the eval feed; plain-pp trainers keep the "
                    "any-batch scan path")
            ctx = (pipeline_mode(self.mesh, pp_m, interleave=pp_v,
                                 param_layout="interleaved")
                   if getattr(self, "_pp_perm", None)
                   else contextlib.nullcontext())
            with ctx:
                out, _ = self.program.apply(params, state, training=False,
                                            **feed)
            return out

        self._eval_fn = jax.jit(eval_step)

    # ------------------------------------------------------------------
    def _setup_compile_cache(self):
        """Wire the persistent XLA compilation cache (behind the
        ``compile_cache_dir`` flag / ``PDTPU_COMPILE_CACHE_DIR`` env):
        repeated bench/CI runs then skip recompiles of the (large) fused
        step program. Keyed on the HLO hash, so edited model code can
        never be served a stale executable. Hit/miss is logged on the
        first dispatch (``paddle_tpu.trainer`` logger)."""
        import os

        d = get_flag("compile_cache_dir")
        self._cache_dir = d or None
        self._cache_logged = False
        if not d:
            return
        os.makedirs(d, exist_ok=True)
        _install_cpu_cache_read_gate()
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            # the cache singleton latches the dir at first use: drop it
            # so the flag takes effect even mid-process
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
        self._cache_entries0 = len(os.listdir(d))
        _trainer_log().info(
            "persistent compilation cache at %s (%d entries)", d,
            self._cache_entries0)

    def _log_compile_cache(self, what: str):
        """After the first dispatch of a compiled fn: did the persistent
        cache serve it (entry count unchanged) or was it a miss (new
        entries written)?"""
        import os

        if self._cache_logged or not getattr(self, "_cache_dir", None):
            return
        self._cache_logged = True
        try:
            now = len(os.listdir(self._cache_dir))
        except OSError:
            return
        new = now - self._cache_entries0
        if new > 0:
            _trainer_log().info(
                "compile cache MISS for %s: %d new entr%s written to %s",
                what, new, "y" if new == 1 else "ies", self._cache_dir)
        else:
            _trainer_log().info(
                "compile cache HIT for %s (served from %s)", what,
                self._cache_dir)

    # ------------------------------------------------------------------
    def step(self, feed: Feed, rng: Optional[jax.Array] = None,
             span: Optional[str] = None) -> Dict[str, Any]:
        """One optimization step; returns the program's fetch dict.
        ``span`` correlates this dispatch's journal event with the
        feeder fill that produced the batch (``fit`` passes the
        DeviceFeeder's chunk span; minted fresh when omitted)."""
        enforce(self._step_fn is not None, "call startup() before step()")
        if rng is None:
            rng = jax.random.fold_in(make_prng_key(get_flag("seed") + 1), self.global_step)
        feed = self._put_feed(feed)
        ls = getattr(self.scope, "loss_scale_state", None) or {}
        base_step = self.global_step
        t0 = _time.perf_counter()
        with profiler.record_event("trainer.step"):
            if self._quant_ef:
                p, o, s, out, new_ls, new_qr = self._step_fn(
                    self.scope.params, self.scope.opt_state,
                    self.scope.state, rng, feed, ls,
                    self.scope.quant_resid)
                self.scope.quant_resid = new_qr
            else:
                p, o, s, out, new_ls = self._step_fn(
                    self.scope.params, self.scope.opt_state,
                    self.scope.state, rng, feed, ls)
        self.step_timer.record_dispatch(t0, _time.perf_counter(), 1, "step",
                                        span=span, base_step=base_step)
        self._log_compile_cache("train step")
        self.scope.params, self.scope.opt_state, self.scope.state = p, o, s
        if self.loss_scaler is not None:
            self.scope.loss_scale_state = new_ls
        self.global_step += 1
        if get_flag("benchmark"):
            jax.block_until_ready(out)
        if self._guard is not None:
            self._guard_enqueue(out, feed, self.global_step - 1, 1)
        else:
            self._warn_inert_nan_flag()
        return out

    def run_steps(self, stacked_feed: Feed, k: Optional[int] = None,
                  rng: Optional[jax.Array] = None,
                  span: Optional[str] = None) -> Dict[str, Any]:
        """K optimization steps in ONE device launch (fused multi-step
        dispatch): ``stacked_feed`` carries K per-step batches on a new
        leading axis (``{name: (K, batch, ...)}``), the jitted program
        scans over them with params/opt_state/state/loss-scale donated
        end-to-end, and the fetch dict comes back stacked ``(K, ...)``.

        Per-step rng keys are ``fold_in(base, global_step + i)`` — the
        SAME stream ``step()`` draws — so K fused steps are numerically
        identical to K sequential ``step()`` calls (pinned by
        tests/test_fused_steps.py). ``k`` is validated against the feed's
        leading dim; each distinct K compiles once (remainder batches
        should fall through to :meth:`step`, as ``fit`` does).
        Amortizes the Python→XLA launch overhead that dominates small
        step times (see BENCH ``dispatch_overhead``)."""
        enforce(self._multi_step_fn is not None,
                "call startup() before run_steps()")
        lead = {name: jax.tree.leaves(v)[0].shape[0]
                for name, v in stacked_feed.items()}
        enforce(len(set(lead.values())) == 1,
                f"run_steps: stacked feed leading dims disagree: {lead}")
        feed_k = next(iter(lead.values()))
        if k is None:
            k = feed_k
        enforce(k == feed_k,
                f"run_steps(k={k}): stacked feed carries {feed_k} step "
                "batches on its leading axis")
        if rng is None:
            rng = make_prng_key(get_flag("seed") + 1)
        feed = self._put_feed(stacked_feed, stacked=True)
        ls = getattr(self.scope, "loss_scale_state", None) or {}
        step0 = np.int32(self.global_step)
        t0 = _time.perf_counter()
        with profiler.record_event("trainer.run_steps"):
            if self._quant_ef:
                p, o, s, outs, new_ls, new_qr = self._multi_step_fn(
                    self.scope.params, self.scope.opt_state,
                    self.scope.state, rng, step0, feed, ls,
                    self.scope.quant_resid)
                self.scope.quant_resid = new_qr
            else:
                p, o, s, outs, new_ls = self._multi_step_fn(
                    self.scope.params, self.scope.opt_state,
                    self.scope.state, rng, step0, feed, ls)
        self.step_timer.record_dispatch(t0, _time.perf_counter(), k,
                                        "run_steps", span=span,
                                        base_step=int(step0))
        self._log_compile_cache(f"fused {k}-step program")
        self.scope.params, self.scope.opt_state, self.scope.state = p, o, s
        if self.loss_scaler is not None:
            self.scope.loss_scale_state = new_ls
        self.global_step += k
        if get_flag("benchmark"):
            jax.block_until_ready(outs)
        if self._guard is not None:
            self._guard_enqueue(outs, feed, self.global_step - k, k)
        else:
            self._warn_inert_nan_flag()
        return outs

    def _warn_inert_nan_flag(self):
        """The check_nan_inf flag is compiled into the step at
        _build_step — flipping it on AFTER startup() cannot arm the
        guard (the old host-scan read it per step). Warn once instead
        of letting the user believe detection is active."""
        if getattr(self, "_nan_flag_warned", False) or self._guard_opt_out:
            return
        if get_flag("check_nan_inf"):
            import warnings
            self._nan_flag_warned = True
            warnings.warn(
                "check_nan_inf was enabled after Trainer.startup(): the "
                "NaN guard is compiled into the step, so the flag has no "
                "effect on this trainer — set it before startup() (or "
                "pass Trainer(guard=GuardPolicy(...))). Note the guard "
                "raises FloatingPointError (the legacy host scan did "
                "too; Executor.run still uses it).")

    def _guard_enqueue(self, outs, feed, base_step: int, k: int) -> None:
        """Host half of the NaN/Inf guard, DEFERRED by one dispatch:
        the bitmask device array is parked and only examined when the
        NEXT dispatch is already in flight (or at :meth:`drain_guard`),
        so the guard adds NO host synchronization to the hot path —
        the readback overlaps the next chunk's device time. Params are
        protected regardless: the discard-select runs on device inside
        the step; the host side is bookkeeping (incident records +
        escalation, at most one chunk late). With
        ``GuardPolicy(defer_readback=False)`` the mask is examined
        immediately instead (one blocking fetch per dispatch) so
        escalation raises at the offending step."""
        if not self._guard.defer_readback:
            self._guard_examine(
                outs["guard_nonfinite"],
                feed if self._guard.record_feed_digest else None,
                base_step, k)
            return
        prev, self._guard_pending = self._guard_pending, (
            outs["guard_nonfinite"],
            feed if self._guard.record_feed_digest else None,
            base_step, k)
        if prev is not None:
            self._guard_examine(*prev)

    def drain_guard(self) -> None:
        """Examine the last parked guard bitmask (one blocking scalar
        fetch). Call when the step loop pauses — before a checkpoint
        read of ``guard_incidents``, at the end of ``fit``, on
        preemption — so no incident stays unrecorded."""
        prev, self._guard_pending = self._guard_pending, None
        if prev is not None:
            self._guard_examine(*prev)

    def _guard_examine(self, mask_dev, feed, base_step: int, k: int) -> None:
        from . import resilience

        mask = np.asarray(jax.device_get(mask_dev)).reshape(-1)
        if not mask.any():
            return
        names = self._guard_bit_names
        recorded = []
        for i, m in enumerate(mask):
            m = int(m)
            if not m:
                continue
            bad = tuple(n for b, n in enumerate(names) if (m >> b) & 1)
            digest = None
            if feed is not None:
                try:
                    # pull only THIS step's slice of a stacked super-
                    # batch across the link, not all K batches
                    sl = (jax.tree.map(lambda v: v[i], feed) if k > 1
                          else feed)
                    digest = resilience.feed_digest(jax.device_get(sl))
                except Exception:
                    digest = None  # digesting must never mask the incident
            recorded.append(resilience.record_incident(
                self.guard_incidents, base_step + i, bad or ("unknown",),
                digest))
        self.guard_incident_total += len(recorded)
        # escalation is evaluated at each INCIDENT's own step, not the
        # chunk end: with window < K a mid-chunk incident would
        # otherwise fall outside the trailing window by the time the
        # chunk finishes and never escalate (the check_nan_inf route is
        # window=1 — its abort contract must hold under fused dispatch)
        for inc in recorded:
            try:
                resilience.escalate_if_needed(self.guard_incidents,
                                              self._guard, inc.step)
            except FloatingPointError as e:
                # flight-record the escalation BEFORE it unwinds: the
                # ring still holds the incidents/dispatches leading up
                from .telemetry import flight_dump
                self.journal.emit("guard.escalation", step=inc.step,
                                  error=str(e)[:500])
                flight_dump("guard_escalation",
                            detail={"step": inc.step,
                                    "error": str(e)[:500]})
                raise

    def eval(self, feed: Feed) -> Dict[str, Any]:
        """Forward pass without dropout/updates.

        With ``pp_interleave>1`` the stacked parameter rows rest in the
        Megatron interleaved layout, so eval runs the SAME pipeline
        schedule as training and inherits its feed constraints: the
        batch must be divisible by ``DistStrategy.pp_microbatches``
        (and each microbatch by the dp shard product) — enforced at
        trace time with a message naming the knob. Plain-pp (``pp_interleave=1``) and
        non-pipeline trainers evaluate on the scan path, where any
        batch size works. See MIGRATION.md "Deep stacks"."""
        feed = self._put_feed(feed)
        return self._eval_fn(self.scope.params, self.scope.state, feed)

    def set_feed_wire(self, feed_wire) -> None:
        """Install (or change) the feed wire-format table. Before
        ``startup`` this is equivalent to the constructor arg; after,
        the step/eval programs are rebuilt so the decode is traced into
        them (one recompile on the next dispatch)."""
        from .data.wire import FeedWire
        wire = FeedWire.make(feed_wire)
        if wire == self.feed_wire:
            return
        self.feed_wire = wire
        if self._step_fn is not None:
            self._build_step()

    def set_augment(self, augment) -> None:
        """Install (or change) the on-device augmentation table
        (``{name: AugmentSpec}`` or a FeedAugment) — the
        :meth:`set_feed_wire` contract: after ``startup`` the
        step/eval programs rebuild so the augmentation is traced in
        (one recompile on the next dispatch)."""
        from .data.augment import FeedAugment
        aug = FeedAugment.make(augment)
        if aug == self.feed_augment:
            return
        self.feed_augment = aug
        if self._step_fn is not None:
            self._build_step()

    def pipeline_report(self) -> Dict[str, Any]:
        """Input-pipeline stage attribution accumulated since startup
        (or the last ``pipeline_metrics.reset()``): per-stage seconds
        (reader/encode/stack/h2d/dispatch), wire vs logical bytes, the
        effective h2d MB/s estimate, and the bottleneck stage. Fed by
        the DeviceFeeder fill thread under ``fit`` and by ``_put_feed``
        on direct ``step()``/``run_steps()`` calls."""
        return self.pipeline_metrics.report()

    def _put_feed(self, feed: Feed, stacked: bool = False,
                  record: bool = True):
        """Wire-encode (host side) and place a feed on device/mesh.
        ``stacked=True``: the feed is a K-step super-batch
        ``(K, batch, ...)`` — the steps axis stays replicated, the batch
        sharding applies from dim 1. Fields covered by ``feed_wire``
        cross the link in their wire dtype; already-encoded arrays (the
        DeviceFeeder fill thread encodes before stacking) pass through.
        ``record=False`` suppresses the pipeline-metrics accounting —
        used when a DeviceFeeder owns the timing of this call."""
        metrics = self.pipeline_metrics if record else None
        return self._put_feed_impl(feed, stacked, metrics)

    def fusion_report(self, feed: Feed, top_k: int = 8) -> Dict[str, Any]:
        """Fusion-level cost attribution of the compiled train step
        (profiling.fusion): parses the executable's optimized HLO into
        per-fusion units with bytes + analytic FLOPs + source-level op
        names and ranks the top-k by roofline cost. Re-lowers and
        re-compiles the step (same cost as
        ``debugger.collective_report``); the result is cached and rides
        along in :meth:`profile_report`."""
        from .profiling import fusion_report as _fusion_report
        self._fusion_report = _fusion_report(self, feed, top_k=top_k)
        # bytes-on-wire attribution of the grad exchange rides along so
        # one report answers "is the win link bytes or compute"
        self._fusion_report["collective_bytes"] = self.collective_bytes
        return self._fusion_report

    def profile_report(self) -> Dict[str, Any]:
        """The unified step profile (profiling.steptime): per-dispatch
        wall-time totals merged with the input-pipeline stage report
        into a compute / h2d / host-encode / starvation breakdown with
        a named bottleneck, plus the cached fusion table when
        :meth:`fusion_report` has run. Emitted as ``Event.profile`` on
        ``end_epoch``/``preempted``; see MIGRATION.md "Profiling &
        memory advisor" for the schema."""
        from .profiling import profile_report as _profile_report
        return _profile_report(self, fusion=self._fusion_report)

    def export_trace(self, path: str) -> int:
        """Write the retained dispatch spans (and any enabled-profiler
        host spans) as chrome://tracing JSON via the ``core.profiler``
        timeline machinery. Returns the number of events written."""
        from .profiling import export_chrome_trace
        return export_chrome_trace(self, path)

    def reset_profile(self) -> None:
        """Zero the step-timer and pipeline-stage accumulators (e.g.
        between warmup and a measured window)."""
        self.step_timer.reset()
        self.pipeline_metrics.reset()

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Opt-in scrape endpoint for a TRAINING worker: start the
        stdlib ``GET /metrics`` (Prometheus text of the process
        registry — this trainer's series carry its ``inst`` label) +
        ``GET /healthz`` server; port 0 picks a free port (see
        ``.port``). The same :class:`~paddle_tpu.telemetry.
        TelemetryServer` backs ``PredictorServer.serve_metrics`` —
        trainer and serving fleet look identical to the scraper.
        Idempotent — repeat calls return the same running server
        (ports/threads don't leak); caller owns ``.close()``."""
        from .telemetry import serve_metrics as _serve

        def health():
            return {
                "live": True,
                "role": "trainer",
                "inst": self.telemetry_inst,
                "run": self.journal.run_id,
                "global_step": self.global_step,
                "guard_incidents": self.guard_incident_total,
            }

        srv = self._telemetry_server
        if srv is None or not srv._thread.is_alive():
            # fresh server only when none is running (a closed one may
            # be re-opened later; never two live endpoints per trainer)
            srv = self._telemetry_server = _serve(health_fn=health,
                                                  port=port, host=host)
        return srv

    def ship_to(self, addr, origin=None, **kw):
        """Attach the PROCESS telemetry shipper to a collector at
        ``addr`` (``"host:port"`` or a tuple): journal events + registry
        snapshots stream there in the background — the push mirror of
        :meth:`serve_metrics` (``PDTPU_TELEMETRY_ADDR`` does the same
        with zero code). Returns the :class:`~paddle_tpu.telemetry.
        shipper.Shipper`."""
        from .telemetry.shipper import ship_to as _ship_to

        return _ship_to(addr, origin=origin, **kw)

    def _put_feed_impl(self, feed: Feed, stacked, metrics):
        # device-resident fast path (the cache-served epoch): a feed of
        # nothing but jax.Arrays has no host bytes to encode or move
        # (encode and the byte accounting both skip device arrays), so
        # the single-device put — a no-op device_put per field — can be
        # skipped wholesale. MESH feeds always go through put_batch:
        # its per-array same-sharding passthrough serves cached chunks
        # for free, while a user-staged array with a different layout
        # still gets re-placed to the batch sharding as before.
        if self.mesh is None \
                and all(isinstance(v, jax.Array) for v in feed.values()):
            return feed
        if self.feed_wire is not None:
            t0 = _time.perf_counter()
            encoded = self.feed_wire.encode(feed)
            if metrics is not None:
                host = {k: v for k, v in feed.items()
                        if not isinstance(v, jax.Array)}
                if host:
                    # logical bytes are spec-aware: a reader that
                    # already produces wire-dtype data (raw uint8
                    # pixels) still counts at the decode dtype's width,
                    # so wire_reduction states the true link saving
                    logical = self.feed_wire.logical_nbytes(host)
                    wire_b = sum(np.asarray(encoded[k]).nbytes
                                 for k in host)
                    metrics.record_encode(_time.perf_counter() - t0,
                                          logical, wire_b)
            feed = encoded
        if self.mesh is not None:
            from .parallel import api as par_api
            return par_api.put_batch(self.mesh, self.sharding_rules, feed,
                                     stacked=stacked, metrics=metrics)
        dev = self.place.device()
        host_bytes = 0
        if metrics is not None:
            from .data.feeder import host_feed_nbytes
            host_bytes = host_feed_nbytes(feed)
            t0 = _time.perf_counter()
        out = {k: jax.device_put(np.asarray(v) if not isinstance(v, jax.Array) else v, dev)
               for k, v in feed.items()}
        if metrics is not None and host_bytes:
            metrics.record_h2d(host_bytes, _time.perf_counter() - t0)
        return out


class CheckpointConfig:
    """contrib.trainer CheckpointConfig analog (contrib/trainer.py:100)."""

    def __init__(self, checkpoint_dir: str, epoch_interval: int = 1,
                 step_interval: int = 0, max_num_checkpoints: int = 3):
        self.checkpoint_dir = checkpoint_dir
        self.epoch_interval = epoch_interval
        self.step_interval = step_interval
        self.max_num_checkpoints = max_num_checkpoints


class Event:
    """Training events (contrib.trainer BeginEpochEvent/EndStepEvent…).

    ``num_steps`` > 1 marks a fused-dispatch chunk (``fit(...,
    steps_per_dispatch=K)``): one begin_step/end_step pair covers
    ``num_steps`` optimizer steps and the end_step ``metrics`` arrays
    carry a leading ``(num_steps, ...)`` axis — see MIGRATION.md
    "Fused stepping". A ``"preempted"`` event fires once after the
    boundary checkpoint when fit exits on SIGTERM/SIGINT.

    ``pipeline`` carries the input-pipeline stage report
    (``Trainer.pipeline_report()``) on ``end_epoch``/``preempted``
    events — per-stage time, wire bytes, h2d MB/s, bottleneck stage.
    ``profile`` carries the unified step profile
    (``Trainer.profile_report()``) on the same events — per-dispatch
    wall time, the compute/h2d/host-encode/starvation breakdown with
    its named bottleneck, and the cached fusion table when one was
    computed.

    A ``"profile"`` event fires every time ``global_step`` crosses a
    multiple of ``fit(profile_interval_steps=N)`` (chunk-boundary
    rounded like interval checkpoints), carrying the same
    ``pipeline``/``profile`` payloads — so a long epoch reports
    between boundaries through the same path, with no extra host
    sync."""

    def __init__(self, kind: str, epoch: int, step: int, metrics=None,
                 num_steps: int = 1, pipeline=None, profile=None):
        # begin_epoch | end_epoch | begin_step | end_step | profile
        # | preempted
        self.kind = kind
        self.epoch = epoch
        self.step = step
        self.metrics = metrics or {}
        self.num_steps = num_steps
        self.pipeline = pipeline
        self.profile = profile


def fit(trainer: "Trainer", reader, num_epochs: int, feed_names: Sequence[str],
        dtypes: Optional[Sequence[Any]] = None, event_handler=None,
        checkpoint_config: Optional[CheckpointConfig] = None,
        prefetch: bool = True, steps_per_dispatch: int = 1,
        resume: bool = False, elastic: bool = False,
        preemption: Optional[bool] = None, resize=None,
        feed_wire=None, profile_interval_steps: int = 0,
        device_cache=None, augment=None):
    """High-level train loop (contrib.trainer.Trainer.train analog):
    reader → DataFeeder → (optional double-buffered prefetch) →
    trainer.step, with event callbacks and periodic checkpoints.

    ``profile_interval_steps=N`` fires a ``"profile"`` event (carrying
    ``Event.profile``/``Event.pipeline`` exactly like ``end_epoch``
    does) every time ``global_step`` crosses a multiple of N, so a
    long epoch is not blind between boundaries — same report path,
    host-side accumulators only, no extra device↔host sync.

    **Telemetry** (MIGRATION.md "Telemetry"): checkpoint saves/
    restores, preemption, and guard incidents are journaled; with a
    ``checkpoint_config`` the process flight recorder re-roots to
    ``<checkpoint_dir>/flight`` and dumps the recent-event ring on
    SIGTERM preemption, guard escalation, ``ReshardError``, and any
    unhandled exception that aborts the loop.

    ``steps_per_dispatch=K`` fuses the hot path: the prefetch thread
    stacks K host batches into one super-batch, transfers it in one
    sharded put, and ``trainer.run_steps`` runs the K optimizer steps in
    a single device launch. Events fire once per CHUNK (``Event.num_steps``,
    stacked metrics), ``global_step`` advances by the true step count
    (remainder batches run singly through ``trainer.step``), and
    ``step_interval`` checkpoints round forward to the chunk boundary
    that crossed the interval. See MIGRATION.md "Fused stepping".

    ``feed_wire={name: WireSpec}`` (or a FeedWire) installs feed wire
    formats (MIGRATION.md "Feed wire formats"): the fill thread encodes
    each batch to its wire dtype (uint8/int8 quantized, bf16/f16
    truncated) BEFORE stacking, the transfer carries the shrunk bytes,
    and the compiled step decodes on device with no extra launch.
    Per-stage pipeline metrics (reader/encode/stack/h2d/dispatch wait,
    wire bytes, effective link MB/s) accumulate either way and ride the
    ``end_epoch``/``preempted`` events as ``Event.pipeline``
    (``trainer.pipeline_report()`` at any time).

    **Device-resident data path** (MIGRATION.md section of that name):

    - ``device_cache=True|"auto"|<bytes>|DeviceCache`` arms the HBM
      dataset cache (``data/device_cache.py``): epoch 1 streams
      normally but retains each encoded chunk on device (admission
      budgeted against the advisor's residual-HBM estimate; the
      explicit int budget is for CPU/tests); epoch 2+ feeds the step
      device-to-device — ZERO h2d wire bytes, bit-identical losses.
      Degrades to partial (cache a prefix, stream the rest) or off
      (no budget / dataset too big). Invalidated on resume-restore and
      elastic reshard; assumes an epoch-stable reader (a per-epoch
      shuffle would replay epoch-1 order — don't cache one).
    - ``augment={name: AugmentSpec}`` traces on-device
      crop/flip/normalize into the step right after the wire decode
      (``trainer.set_augment``); per-step randomness follows the
      ``fold_in(base, global_step+i)`` discipline, so fused K-step
      equals sequential and resume reproduces the stream.
    - transfers run through the DeviceFeeder's 2-deep staging ring:
      chunk N+1's h2d overlaps chunk N's K-step scan, with the
      hidden-vs-exposed split reported as ``overlap_hidden_s``.

    **Fault tolerance** (MIGRATION.md "Fault tolerance & resume"):

    - ``resume=True`` restores the newest *valid* checkpoint under
      ``checkpoint_config.checkpoint_dir`` (corrupt ones are skipped
      with a warning, falling back to older), fast-forwards the
      epoch/in-epoch position recorded in the checkpoint meta, and
      continues with exact step/loss continuity — restart reproduces
      the uninterrupted run bit-for-bit for a deterministic reader.
    - ``elastic=True`` (with ``resume=True``) lets the resume ride
      through a WORKER-COUNT change: a checkpoint saved at different
      mesh axes than this trainer's is reshard-restored
      (``resilience.reshard_restore`` — bit-exact re-placement per the
      trainer's target rules) instead of raising. Step accounting needs
      no special casing across the N→M boundary: the reader batch is
      GLOBAL (dp only splits it across devices), so the epoch/
      epoch_step fast-forward and ``steps_per_dispatch`` re-stacking
      (including a different K than the run that saved) hold unchanged
      — one reader batch is one optimizer step at any mesh. Without
      ``elastic``, the mesh mismatch surfaces as a structured
      ``resilience.ReshardError`` at startup, naming saved vs. target
      axes, instead of a ``device_put`` stack trace mid-run.
    - The checkpoint ROTATION list is rebuilt from the directory at
      startup, so ``max_num_checkpoints`` holds across restarts.
    - SIGTERM/SIGINT (``preemption``; default on whenever a
      ``checkpoint_config`` is given, main thread only) requests a
      checkpoint at the next chunk boundary: fit saves
      ``step_<global_step>``, drains async orbax saves, fires a
      ``"preempted"`` event, and returns cleanly.
    - ``resize=`` (a path or a ``resilience.ResizeRequest``) is the
      SCHEDULED elastic grow/shrink — the autoscaler's trainer-side
      analog. When the resize-request file appears (or its optional
      signal arrives), fit exits at the same chunk boundary with the
      same boundary checkpoint, but journals ``fit.resized`` (with the
      request's advisory target) and fires a ``"resized"`` event
      instead: the launcher reads the event, ``consume()``s the
      request, and relaunches at the new worker count with
      ``fit(elastic=True, resume=True)`` — the mesh change rides the
      reshard-restore path above. A concurrent SIGTERM wins: a real
      preemption must never be reported as a planned resize.
    """
    import os

    from . import resilience
    from .telemetry import flight_dump, get_recorder

    if checkpoint_config is not None:
        # crash artifacts live next to the checkpoints they explain
        get_recorder().set_root(
            os.path.join(checkpoint_config.checkpoint_dir, "flight"))
    try:
        return _fit_impl(trainer, reader, num_epochs, feed_names, dtypes,
                         event_handler, checkpoint_config, prefetch,
                         steps_per_dispatch, resume, elastic, preemption,
                         feed_wire, profile_interval_steps, device_cache,
                         augment, resize)
    except resilience.InjectedCrash:
        raise  # models abrupt process death: a real kill -9 dumps nothing
    except FloatingPointError:
        raise  # guard escalation already flight-dumped at the escalate site
    except resilience.ReshardError:
        raise  # already flight-dumped at the raise site (resilience)
    except Exception as e:
        # unhandled abort: capture what the run was doing when it died
        err = f"{type(e).__name__}: {e}"[:500]
        trainer.journal.emit("fit.error", error=err,
                             global_step=trainer.global_step)
        flight_dump("fit_exception",
                    detail={"error": err,
                            "global_step": trainer.global_step})
        raise


def _fit_impl(trainer, reader, num_epochs, feed_names, dtypes,
              event_handler, checkpoint_config, prefetch,
              steps_per_dispatch, resume, elastic, preemption,
              feed_wire, profile_interval_steps, device_cache=None,
              augment=None, resize=None):
    import contextlib as _contextlib
    import os
    import shutil

    from .core.errors import enforce as _enforce
    from . import io as _io
    from . import resilience
    from .data.device_cache import DeviceCache
    from .data.feeder import DataFeeder, DeviceFeeder, iter_chunked
    from .telemetry import flight_dump, get_registry

    ckpt_counter = get_registry().counter(
        "paddle_tpu_trainer_checkpoints_total",
        "Checkpoints committed by fit", ("kind",))

    _enforce(steps_per_dispatch >= 1,
             f"fit(steps_per_dispatch={steps_per_dispatch}): need >= 1")
    _enforce(profile_interval_steps >= 0,
             f"fit(profile_interval_steps={profile_interval_steps}): "
             "need >= 0 (0 disables interval profile events)")
    if feed_wire is not None:
        trainer.set_feed_wire(feed_wire)
    if augment is not None:
        trainer.set_augment(augment)
    # the HBM dataset cache: bound to the trainer so reload/reshard
    # paths can invalidate it without knowing about this loop
    cache = DeviceCache.make(device_cache, trainer=trainer)
    trainer.device_cache = cache
    feeder = DataFeeder(feed_names, dtypes)

    _enforce(resume or not elastic,
             "fit(elastic=True) without resume=True does nothing: elastic "
             "names the resume-across-a-mesh-change behavior")
    start_epoch, skip_steps = 0, 0
    if resume:
        _enforce(checkpoint_config is not None,
                 "fit(resume=True) needs a checkpoint_config to scan")
        sample_feed = None
        if elastic:
            # peek one reader batch so the reshard feasibility check can
            # prove the per-step batch divides the target shards — the
            # infeasible case must be a structured ReshardError HERE,
            # not a raw put_batch ValueError mid-run (readers are
            # re-iterable callables; each epoch calls reader() fresh,
            # so the peek consumes nothing)
            first = next(iter(reader()), None)
            if first is not None:
                sample_feed = feeder.feed(first)
        meta = resilience.restore_latest(checkpoint_config.checkpoint_dir,
                                         trainer, elastic=elastic,
                                         sample_feed=sample_feed)
        if meta is not None:
            start_epoch = int(meta.get("epoch", 0))
            skip_steps = int(meta.get("epoch_step", 0))
            trainer.journal.emit("ckpt.restore",
                                 global_step=trainer.global_step,
                                 epoch=start_epoch, epoch_step=skip_steps)
            if cache is not None:
                # a restore lands mid-epoch / possibly on a new mesh:
                # any cached prefix no longer aligns with what the
                # epoch will consume (reshard_restore invalidates on
                # its own for direct callers)
                cache.invalidate("checkpoint restore")

    # rebuild the rotation list from disk (oldest first) so pre-existing
    # checkpoints rotate out across restarts instead of accumulating,
    # and sweep torn-save tmp leftovers from crashed predecessors
    def _fit_tag(tag: str) -> bool:
        # only fit-OWNED tags enter rotation: a user's hand-saved
        # checkpoint living in the same dir (e.g. "best") must never be
        # rotation-deleted by us
        head, _, num = tag.partition("_")
        return head in ("step", "epoch") and num.isdigit()

    kept: List[str] = []
    if checkpoint_config is not None:
        resilience.sweep_tmp_dirs(checkpoint_config.checkpoint_dir)
        kept = [c.path for c in resilience.list_checkpoints(
            checkpoint_config.checkpoint_dir) if _fit_tag(c.tag)]
        # over-quota pre-existing checkpoints are trimmed by the FIRST
        # save, not here: a startup trim could delete the oldest-but-
        # only-VALID checkpoint that resume just restored from (newer
        # ones corrupt) before this run has committed anything new

    last_saved_step = [None]  # step of the last save THIS run performed

    def save(tag: str, epoch: int, epoch_step: int):
        if checkpoint_config is None:
            return
        d = os.path.join(checkpoint_config.checkpoint_dir, tag)
        t0 = _time.perf_counter()
        _io.save_trainer(d, trainer, extra_meta={"epoch": epoch,
                                                 "epoch_step": epoch_step})
        trainer.journal.emit("ckpt.save", tag=tag, path=d,
                             global_step=trainer.global_step,
                             seconds=round(_time.perf_counter() - t0, 6))
        ckpt_counter.inc(kind=tag.partition("_")[0] or "other")
        last_saved_step[0] = trainer.global_step
        if d in kept:      # re-saved tag (e.g. preempt at an interval
            kept.remove(d)  # boundary): refresh its rotation position
        kept.append(d)
        while len(kept) > checkpoint_config.max_num_checkpoints:
            shutil.rmtree(kept.pop(0), ignore_errors=True)

    use_preempt = (preemption if preemption is not None
                   else checkpoint_config is not None)
    preempt_ctx = (resilience.PreemptionHandler() if use_preempt
                   else _contextlib.nullcontext())
    # scheduled elastic resize: a path becomes a ResizeRequest; an
    # existing handler (caller already holds the signal) is used as-is
    resize_ctx = (resilience.ResizeRequest(resize)
                  if isinstance(resize, (str, os.PathLike)) else resize)
    si = checkpoint_config.step_interval if checkpoint_config else 0
    with preempt_ctx as ph, (resize_ctx if resize_ctx is not None
                             else _contextlib.nullcontext()) as rz:
        for epoch in range(start_epoch, num_epochs):
            # resume lands mid-epoch: fast-forward past the batches the
            # restored checkpoint already consumed (1 batch == 1 step)
            skip = skip_steps if epoch == start_epoch else 0
            steps_in_epoch = skip
            trainer.journal.emit("fit.begin_epoch", epoch=epoch,
                                 global_step=trainer.global_step)
            if event_handler:
                event_handler(Event("begin_epoch", epoch, trainer.global_step))

            # device-cache disposition for THIS epoch. Serving and
            # admission both require the epoch to start at batch 0 (a
            # resume lands mid-epoch — the cached prefix would not
            # align); an invalidated cache re-arms on the next clean
            # epoch start.
            serve_cache = False
            admitting = False
            cached_steps = 0
            if cache is not None and skip == 0:
                if cache.state == "invalid":
                    cache.reset()
                serve_cache = cache.ready
                admitting = (not serve_cache
                             and cache.state in ("cold", "admitting"))
                cached_steps = cache.cached_steps if serve_cache else 0

            def batches(_skip=skip + cached_steps):
                for i, samples in enumerate(reader()):
                    if i < _skip:
                        continue
                    yield feeder.feed(samples)

            device_feeder = None
            if serve_cache and cache.complete:
                # the whole epoch is resident: no reader, no fill
                # thread, zero h2d wire bytes
                iterator = iter(())
            elif prefetch:
                # the feeder owns the stage timing (put_fn record=False
                # so h2d isn't double-counted) and runs the wire encode
                # on the fill thread, per batch, before stacking
                device_feeder = DeviceFeeder(
                    batches,
                    put_fn=functools.partial(trainer._put_feed,
                                             record=False),
                    stack_k=steps_per_dispatch,
                    put_stacked_fn=functools.partial(trainer._put_feed,
                                                     stacked=True,
                                                     record=False),
                    encode_fn=(trainer.feed_wire.encode
                               if trainer.feed_wire is not None else None),
                    metrics=trainer.pipeline_metrics,
                    logical_nbytes_fn=(trainer.feed_wire.logical_nbytes
                                       if trainer.feed_wire is not None
                                       else None),
                    journal=trainer.journal)
                iterator = iter(device_feeder)
            elif steps_per_dispatch > 1:
                iterator = iter_chunked(
                    batches(), steps_per_dispatch, put_fn=trainer._put_feed,
                    put_stacked_fn=functools.partial(trainer._put_feed,
                                                     stacked=True))
            else:
                iterator = map(trainer._put_feed, batches())
            def epoch_items():
                """(n, feed, span, streamed): cache-served chunks first
                (device-to-device, span-less, hit bytes attributed),
                then the streamed remainder."""
                if serve_cache:
                    for n, feed in cache.chunks(
                            metrics=trainer.pipeline_metrics):
                        yield n, feed, None, False
                for item in iterator:
                    n, feed = (item if steps_per_dispatch > 1
                               else (1, item))
                    # the chunk's trace id, minted by the fill thread:
                    # its dispatch event correlates with the
                    # feeder.fill event that produced this batch
                    span = (device_feeder.last_span
                            if device_feeder is not None else None)
                    yield n, feed, span, True

            preempted = False
            resized = False
            try:
                for n, feed, span, streamed in epoch_items():
                    if admitting and streamed:
                        # epoch-1 tee: retain the encoded device chunk
                        # (feeds are never donated, so the buffers
                        # survive the dispatch untouched)
                        cache.offer(n, feed)
                    gs_before = trainer.global_step
                    if event_handler:
                        event_handler(Event("begin_step", epoch, gs_before,
                                            num_steps=n))
                    out = trainer.run_steps(feed, k=n, span=span) if n > 1 \
                        else trainer.step(feed, span=span)
                    steps_in_epoch += n
                    if event_handler:
                        event_handler(Event("end_step", epoch,
                                            trainer.global_step, out,
                                            num_steps=n))
                    # interval profile events: same chunk-boundary
                    # rounding as checkpoints, same report path as
                    # end_epoch (host accumulators only, no host sync)
                    pi = profile_interval_steps
                    if pi and event_handler and \
                            trainer.global_step // pi > gs_before // pi:
                        profile = trainer.profile_report()
                        event_handler(Event("profile", epoch,
                                            trainer.global_step,
                                            num_steps=n,
                                            pipeline=profile["pipeline"],
                                            profile=profile))
                    # chunk-boundary rounding: save whenever this dispatch
                    # crossed a step_interval multiple (== the exact-multiple
                    # check when n == 1)
                    if si and trainer.global_step // si > gs_before // si:
                        save(f"step_{trainer.global_step}", epoch,
                             steps_in_epoch)
                    if ph is not None and ph.requested:
                        preempted = True
                        break
                    if rz is not None and rz.requested:
                        preempted = True
                        resized = True
                        break
            finally:
                # consumer abandoned mid-epoch (exception/early exit): the
                # fill thread must not stay blocked holding device buffers
                if device_feeder is not None:
                    device_feeder.close()
            if admitting:
                if preempted:
                    # a half-observed epoch must not seal: the next fit
                    # resumes mid-epoch and appending its chunks after
                    # this prefix would interleave two epochs
                    cache.invalidate("preempted mid-admission")
                else:
                    cache.seal(steps_in_epoch)
            if preempted:
                # preemption flow: boundary checkpoint, drain the parked
                # guard bitmask and async orbax writes, clean exit (the
                # TPU maintenance-event analog). Skip the save when the
                # interval save that just ran already committed this
                # exact step — a duplicate full gather+write would burn
                # the preemption grace period for bit-identical state.
                # A pending guard ESCALATION must not forfeit the
                # boundary checkpoint (device state is clean — the bad
                # updates were discarded on device): save first, then
                # re-raise.
                guard_err = None
                try:
                    trainer.drain_guard()
                except FloatingPointError as e:
                    guard_err = e
                # "already saved" must mean saved by THIS run — a stale
                # same-tag dir from a previous run (rebuilt into `kept`)
                # holds old params and must not suppress the save
                if last_saved_step[0] != trainer.global_step:
                    save(f"step_{trainer.global_step}", epoch,
                         steps_in_epoch)
                _io.wait_for_checkpoints()
                # journal + flight-record the preemption AFTER the
                # boundary save so the dump's ring contains the
                # ckpt.save event (and any guard incidents drained
                # above) — the black box explains the exit
                if ph is not None and ph.requested:
                    # a SIGTERM that landed after the resize poll wins:
                    # a real preemption is never reported as planned
                    resized = False
                signum = getattr(ph, "signum", None)
                if resized:
                    target = rz.target if rz is not None else {}
                    trainer.journal.emit("fit.resized", epoch=epoch,
                                         global_step=trainer.global_step,
                                         target=target)
                    get_registry().counter(
                        "paddle_tpu_trainer_resizes_total",
                        "Scheduled elastic resizes handled by fit").inc()
                    flight_dump("resized",
                                detail={"global_step": trainer.global_step,
                                        "epoch": epoch, "target": target})
                else:
                    trainer.journal.emit("fit.preempted", epoch=epoch,
                                         global_step=trainer.global_step,
                                         signum=signum)
                    get_registry().counter(
                        "paddle_tpu_trainer_preemptions_total",
                        "SIGTERM/SIGINT preemptions handled by fit").inc()
                    flight_dump("preempted",
                                detail={"global_step": trainer.global_step,
                                        "epoch": epoch, "signum": signum})
                if event_handler:
                    # ONE profile snapshot: Event.pipeline aliases its
                    # pipeline section, so handlers comparing the two
                    # never see the fill thread advance between them
                    profile = trainer.profile_report()
                    event_handler(Event("resized" if resized
                                        else "preempted", epoch,
                                        trainer.global_step,
                                        pipeline=profile["pipeline"],
                                        profile=profile))
                if guard_err is not None:
                    raise guard_err
                return trainer
            if event_handler:
                profile = trainer.profile_report()
                event_handler(Event("end_epoch", epoch, trainer.global_step,
                                    pipeline=profile["pipeline"],
                                    profile=profile))
            if checkpoint_config and checkpoint_config.epoch_interval and \
                    (epoch + 1) % checkpoint_config.epoch_interval == 0:
                save(f"epoch_{epoch}", epoch + 1, 0)
    trainer.drain_guard()
    return trainer


def _abstractify(v):
    if isinstance(v, jax.ShapeDtypeStruct):
        return v
    arr = np.asarray(v)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


_global_scope = Scope()


def global_scope() -> Scope:
    """executor.py global_scope analog: the process-wide name→array
    scope used when no explicit scope is passed."""
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    """executor.py scope_guard analog: swap the global scope within a
    with-block."""
    global _global_scope
    old, _global_scope = _global_scope, scope
    try:
        yield scope
    finally:
        _global_scope = old


def _switch_scope(scope: Scope) -> Scope:
    """executor.py _switch_scope analog (reference exports it)."""
    global _global_scope
    old, _global_scope = _global_scope, scope
    return old


class Inferencer:
    """High-level inference wrapper (contrib/inferencer.py:31): build the
    inference program fn, load a checkpoint, run batches.

        inf = Inferencer(infer_fn, param_path="ckpt_dir")
        out = inf.infer({"image": batch})

    ``param_path`` may hold either a persistables checkpoint
    (io.save_persistables / save_trainer) or explicit (params, state)."""

    def __init__(self, infer_func: Callable, param_path: Optional[str] = None,
                 params=None, state=None, place: Optional[Place] = None):
        from .framework import build

        self.program = infer_func if isinstance(infer_func, Program) else build(infer_func)
        self.place = place or default_place()
        if param_path is not None:
            from . import io as _io
            params, state, _, _ = _io.load_persistables(param_path)
            enforce(bool(params),
                    f"Inferencer: no parameters found in {param_path!r}")
        enforce(params is not None, "Inferencer: need param_path or params")
        dev = self.place.device()
        self._params = jax.device_put(params, dev)
        self._state = jax.device_put(state or {}, dev)
        self._jit = jax.jit(functools.partial(self.program.apply, training=False))

    def infer(self, inputs: Feed, return_numpy: bool = True):
        out, _ = self._jit(self._params, self._state, **inputs)
        return jax.device_get(out) if return_numpy else out
