"""Op-test harness: numeric-reference and finite-difference grad checks.

Analog of python/paddle/fluid/tests/unittests/op_test.py (OpTest:131):
``check_output`` compares a layer's outputs against a numpy reference
(op_test.py:293), ``check_grad`` compares jax.grad against central
finite differences (get_numeric_gradient, op_test.py:43).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def check_output(fn: Callable, np_ref: Callable, inputs: Sequence[np.ndarray],
                 atol: float = 1e-5, rtol: float = 1e-5):
    """Run fn (jax) and np_ref (numpy) on the same inputs; compare."""
    got = fn(*[jnp.asarray(x) for x in inputs])
    want = np_ref(*inputs)
    got_flat = jax.tree.leaves(got)
    want_flat = jax.tree.leaves(want)
    assert len(got_flat) == len(want_flat), (
        f"output arity mismatch: {len(got_flat)} vs {len(want_flat)}")
    for g, w in zip(got_flat, want_flat):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=atol, rtol=rtol)


def numeric_grad(fn: Callable, inputs: Sequence[np.ndarray], wrt: int = 0,
                 eps: float = 1e-3) -> np.ndarray:
    """Central finite-difference gradient of sum(fn(...)) wrt inputs[wrt]
    (get_numeric_gradient analog, op_test.py:43)."""
    inputs = [np.asarray(x, dtype=np.float64 if np.issubdtype(np.asarray(x).dtype, np.floating)
              else np.asarray(x).dtype) for x in inputs]
    x = inputs[wrt]
    grad = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gflat = grad.reshape(-1)

    def f(v):
        args = list(inputs)
        args[wrt] = v.reshape(x.shape).astype(np.float32)
        out = fn(*[jnp.asarray(a) for a in args])
        return float(jnp.sum(jnp.asarray(out, dtype=jnp.float32)))

    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        fp = f(flat)
        flat[i] = orig - eps
        fm = f(flat)
        flat[i] = orig
        gflat[i] = (fp - fm) / (2 * eps)
    return grad


def check_grad(fn: Callable, inputs: Sequence[np.ndarray], wrt: int = 0,
               eps: float = 1e-3, atol: float = 1e-2, rtol: float = 1e-2):
    """Compare jax.grad of sum(fn) against finite differences
    (check_grad_with_place analog, op_test.py:400)."""
    jinputs = [jnp.asarray(np.asarray(x, dtype=np.float32)
                           if np.issubdtype(np.asarray(x).dtype, np.floating)
                           else np.asarray(x)) for x in inputs]

    def loss(v):
        args = list(jinputs)
        args[wrt] = v
        return jnp.sum(fn(*args).astype(jnp.float32))

    analytic = np.asarray(jax.grad(loss)(jinputs[wrt]))
    numeric = numeric_grad(fn, inputs, wrt, eps)
    np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=rtol)


def check_grad_built(layer_fn, feed, wrt, eps: float = 1e-3,
                     atol: float = 1e-2, rtol: float = 1e-2):
    """FD gradcheck for PARAMETERIZED layers (conv/fc/norms — anything
    that creates weights through LayerHelper): builds the single-op
    program, inits params once, then checks jax.grad of sum(outputs)
    against central differences w.r.t. one feed input OR one parameter
    (``wrt="param:<name>"``). The parameterized analog of check_grad —
    op_test.py:400 gradchecks ops with weights the same way."""
    import paddle_tpu as pt

    names = sorted(feed)
    prog = pt.build(lambda **kw: {"out": layer_fn(**kw)})
    params, state = prog.init(jax.random.PRNGKey(0), **feed)

    if wrt.startswith("param:"):
        pname = wrt[len("param:"):]
        if pname not in params:  # unique-suffix match ("w", "scale", ...)
            cand = [k for k in params if k.endswith(pname)]
            assert len(cand) == 1, (pname, sorted(params))
            pname = cand[0]

        def fn(v):
            p2 = dict(params, **{pname: v})
            out, _ = prog.apply(p2, state, training=True, **feed)
            return out["out"]

        x0 = np.asarray(params[pname], np.float64)
    else:
        assert wrt in feed, (wrt, names)

        def fn(v):
            f2 = dict(feed, **{wrt: v})
            out, _ = prog.apply(params, state, training=True, **f2)
            return out["out"]

        x0 = np.asarray(feed[wrt], np.float64)

    def loss(v):
        return jnp.sum(fn(v).astype(jnp.float32))

    analytic = np.asarray(jax.grad(loss)(jnp.asarray(x0, jnp.float32)))
    numeric = numeric_grad(lambda v: fn(v), [x0], wrt=0, eps=eps)
    np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=rtol)


# Shared StableHLO scraper for the lowering-level dtype pins
# (test_mxu_dtypes, test_int8_serving, test_flash_attention): one copy,
# so an MLIR printer format change is fixed in one place. Returns
# (op_kind, lhs_type, rhs_type, out_type) tuples.
import re as _re

STABLEHLO_DOT_RE = _re.compile(
    r'(dot_general|convolution)[^\n]*:\s*\(tensor<([^>]+)>,\s*'
    r'tensor<([^>]+)>\)\s*->\s*tensor<([^>]+)>')


def find_dots(stablehlo_text: str):
    return [m.groups() for m in STABLEHLO_DOT_RE.finditer(stablehlo_text)]
