"""Gradient clipping.

Analog of python/paddle/fluid/clip.py (GradientClipByValue:~,
GradientClipByNorm, GradientClipByGlobalNorm). Each is a pure transform
over the gradient pytree applied inside the jitted optimizer update.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp


class GradientClipBase:
    def __call__(self, grads: Dict[str, jax.Array], params: Dict[str, jax.Array]):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, grads, params):
        return {k: jnp.clip(g, self.min, self.max) for k, g in grads.items()}


class GradientClipByNorm(GradientClipBase):
    """Per-tensor L2-norm clip (clip_by_norm_op analog)."""

    def __init__(self, clip_norm: float):
        self.clip_norm = float(clip_norm)

    def __call__(self, grads, params):
        out = {}
        for k, g in grads.items():
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(norm, 1e-12))
            out[k] = (g.astype(jnp.float32) * scale).astype(g.dtype)
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    """Global-norm clip across all grads (clip.py GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm: float):
        self.clip_norm = float(clip_norm)

    def __call__(self, grads, params):
        gnorm = global_norm(list(grads.values()))
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return {k: (g.astype(jnp.float32) * scale).astype(g.dtype) for k, g in grads.items()}


def global_norm(tensors):
    return jnp.sqrt(sum(jnp.sum(jnp.square(t.astype(jnp.float32))) for t in tensors))


class ErrorClipByValue:
    """API-parity stub: the reference clips *activation gradients* flowing
    through named vars (clip.py ErrorClipByValue). With jax autodiff, use
    ``paddle_tpu.layers.clip``/custom_vjp at the point of interest."""

    def __init__(self, max, min=None):
        self.max, self.min = max, min
