"""Flash attention kernel vs XLA reference (interpret mode on CPU)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import flash_attention as fa


def _ref(q, k, v, causal=False, key_bias=None):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if key_bias is not None:
        s = s + key_bias[:, None, None, :]
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        s = jnp.where(cm, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _rand(b=1, h=2, s=128, d=32, sk=None, seed=0):
    rng = np.random.RandomState(seed)
    sk = sk or s
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, h, sk, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, h, sk, d).astype(np.float32))
    return q, k, v


def test_forward_matches_reference():
    q, k, v = _rand(s=128)
    out = fa.flash_attention(q, k, v, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_forward_causal():
    q, k, v = _rand(s=128)
    out = fa.flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, causal=True)),
                               atol=2e-5, rtol=2e-5)


def test_forward_with_key_bias_padding():
    q, k, v = _rand(s=128)
    bias = jnp.where(jnp.arange(128)[None, :] < 100, 0.0, -1e9)  # [1, sk]
    out = fa.flash_attention(q, k, v, key_bias=bias, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_ref(q, k, v, key_bias=bias)),
                               atol=2e-5, rtol=2e-5)


def test_forward_uneven_blocks():
    # seq not a multiple of block: exercised via block > seq fallback
    q, k, v = _rand(s=96)
    out = fa.flash_attention(q, k, v, block_q=96, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_cross_attention_different_kv_len():
    q, k, v = _rand(s=64, sk=128)
    out = fa.flash_attention(q, k, v, block_q=32, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_gradients_match_reference():
    q, k, v = _rand(s=64, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, causal=True,
                                          block_q=32, block_k=32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_gradients_with_bias():
    q, k, v = _rand(s=64, d=16)
    bias = jnp.where(jnp.arange(64)[None, :] < 48, 0.0, -1e9)

    gf = jax.grad(lambda a, b, c: jnp.sum(
        fa.flash_attention(a, b, c, key_bias=bias, block_q=32, block_k=32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(_ref(a, b, c, key_bias=bias) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_attention_layer_uses_flash():
    """layers.attention with use_flash must agree with the XLA path."""
    import paddle_tpu as pt
    from paddle_tpu.layers import attention as A
    q, k, v = _rand(b=2, h=4, s=64, d=16)
    out_x = A.scaled_dot_product_attention(q, k, v, causal=True, use_flash=False)
    out_f = A.scaled_dot_product_attention(q, k, v, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(out_x), np.asarray(out_f), atol=2e-5, rtol=2e-5)
