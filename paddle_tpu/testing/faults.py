"""Deterministic fault-injection harness for the resilience + serving
layers.

Four fault families, all exactly reproducible (no subprocess roulette,
no timing races):

- **Bad batches**: :func:`nan_batch_reader` poisons one batch of a
  reader with NaN/Inf at an exact batch index — drives the Trainer's
  on-device guard.
- **Scripted crashes**: :func:`crash_at_step` (an event handler that
  dies after step k) and :func:`crashing` (arms a named
  :func:`~paddle_tpu.resilience.crash_point` inside the save path, so a
  "kill -9 mid-save" happens at an exact phase: files written but no
  manifest, manifest written but not committed, ...).
- **Checkpoint corruption**: :func:`truncate_file` / :func:`flip_byte`
  tear a committed checkpoint (or inference artifact) the way a torn
  disk write would.
- **Serving faults**: :class:`FaultyPredictor` wraps a Predictor with a
  scripted ``run`` behavior — :func:`hanging_predictor` (wedged
  executable, drives the dispatch watchdog), :func:`failing_predictor`
  (crash-looping executable, drives the circuit breaker) — with call
  counts shared across ``clone()`` so a worker pool sees one fault
  script, not one per worker; :func:`kill_server` is the replica-death
  drill for a serving fleet (abrupt ``PredictorServer.kill``:
  never-dispatched requests fail retryable, dispatched ones fail
  at-most-once — drives ``FleetRouter``'s reroute contract and
  ``tools/fleet_drill.py``).
- **Wire faults** (the cross-process fleet): :class:`LinkProxy` — a
  deterministic localhost TCP proxy a ``RemoteReplica`` routes
  through — with :func:`partition` (drop both ways, half-open
  sockets), :func:`heal`, and :func:`slow_link` (per-chunk delay, the
  slow-but-alive replica behind probe-latency demotion); plus
  :func:`kill_process` (real SIGKILL of a replica process, no
  cleanup) — re-proving the in-process kill contracts against real
  process death and real TCP partitions.
- **Membership changes**: :func:`visible_devices` /
  :func:`membership_meshes` build deterministic shrunk/grown device
  meshes (the preempted-worker / rejoined-worker analog on the CPU
  test fixture) so elastic reshard drills replay exactly;
  :func:`acting` runs a side effect — e.g. ``srv.stop()`` killing a
  pserver — at a named crash point WITHOUT dying there, so "a server
  died mid-shard-split" happens at an exact phase.

Known crash-point tags in the save/reshard paths:

- ``save_trainer:files-written`` — npz/meta files on disk, no manifest
- ``save_trainer:manifest-written`` — manifest on disk, dir not renamed
- ``save_inference_model:files-written`` / ``:manifest-written`` — the
  same two phases of the inference-artifact export
- ``ps_resize:exported`` — one param's state left its old pserver,
  import not yet sent (fires per moved param during a shard
  split/merge)
- ``ps_resize:imported`` — every move imported, routing not switched
"""

from __future__ import annotations

import contextlib
import os
import socket
import threading
import time
from typing import Callable, Dict, Iterator, Optional

import numpy as np

from .. import resilience

InjectedCrash = resilience.InjectedCrash


# -- bad batches -------------------------------------------------------------


def nan_batch_reader(reader: Callable[[], Iterator], at_batch: int,
                     column: int = 0, value: float = float("nan")):
    """Wrap a paddle-style reader (``reader() -> iterator of sample
    lists``): batch ``at_batch`` (0-based) has ``value`` splatted over
    sample column ``column``. Deterministic: same batch every epoch."""

    def poisoned():
        for i, samples in enumerate(reader()):
            if i == at_batch:
                samples = [
                    tuple(np.full_like(np.asarray(v, dtype=np.float64)
                                       if np.asarray(v).dtype.kind in "iu"
                                       else np.asarray(v), value)
                          if j == column else v
                          for j, v in enumerate(s))
                    for s in samples]
            yield samples
    return poisoned


def nan_feed(feed: Dict[str, np.ndarray], name: str,
             value: float = float("nan")) -> Dict[str, np.ndarray]:
    """Return a copy of a feed dict with ``name`` fully non-finite."""
    out = dict(feed)
    out[name] = np.full_like(np.asarray(feed[name], dtype=np.float32), value)
    return out


# -- scripted crashes --------------------------------------------------------


def crash_at_step(step: int, kind: str = "end_step"):
    """Event handler for ``fit``: raises :class:`InjectedCrash` once
    ``global_step`` reaches ``step`` at the given event kind — the
    in-process stand-in for ``kill -9`` between chunks (checkpoints
    already on disk stay exactly as a real crash would leave them)."""

    def handler(event):
        if event.kind == kind and event.step >= step:
            raise InjectedCrash(f"scripted crash at step {event.step}")
    return handler


@contextlib.contextmanager
def crashing(tag: str):
    """Arm crash point ``tag`` for the duration of the block: the next
    time the save path reaches it, :class:`InjectedCrash` is raised —
    phase-exact kill-mid-save."""
    resilience.crash_points.add(tag)
    try:
        yield
    finally:
        resilience.crash_points.discard(tag)


@contextlib.contextmanager
def acting(tag: str, callback: Callable[[], None], once: bool = True):
    """Run ``callback()`` when crash point ``tag`` fires, WITHOUT
    raising there — the process under test keeps running while
    something else dies at an exact phase (e.g. ``srv.stop()`` killing
    a pserver mid-shard-split, so the migration's own fault handling is
    what gets exercised). ``once`` (default) disarms after the first
    firing — a per-item tag like ``ps_resize:exported`` fires per move,
    and the drill usually wants exactly one deterministic kill. Yields
    a one-element list holding the firing count."""
    fired = [0]

    def _cb():
        if once and fired[0]:
            return
        fired[0] += 1
        callback()

    resilience.crash_callbacks[tag] = _cb
    try:
        yield fired
    finally:
        resilience.crash_callbacks.pop(tag, None)


# -- membership changes ------------------------------------------------------


def visible_devices(n: int):
    """The first ``n`` of the process's devices, deterministically — the
    stand-in for "the job restarted with a different worker count" on
    the fixed-size CPU test fixture (the 8-device
    ``xla_force_host_platform_device_count`` mesh): meshes built over
    ``visible_devices(4)`` and ``visible_devices(2)`` are exactly what
    a dp 4→2 preemption drill restores between."""
    import jax

    devs = list(jax.devices())
    if not 1 <= int(n) <= len(devs):
        raise ValueError(f"visible_devices({n}): process has {len(devs)} "
                         "devices")
    return devs[:int(n)]


def membership_meshes(counts, axis: str = "dp"):
    """Deterministic membership-change schedule: one ``{axis: n}`` mesh
    per entry of ``counts``, each over :func:`visible_devices` — e.g.
    ``membership_meshes([4, 2])`` scripts a kill-at-dp-4 →
    rejoin-at-dp-2 elastic drill. Same counts, same meshes, every
    run."""
    from ..parallel.mesh import make_mesh

    return [make_mesh({axis: int(n)}, devices=visible_devices(int(n)))
            for n in counts]


# -- checkpoint corruption ---------------------------------------------------


def truncate_file(ckpt_dir: str, name: Optional[str] = None,
                  keep_bytes: Optional[int] = None) -> str:
    """Truncate a file inside a committed checkpoint (default: the
    largest npz, to half its size) — the torn-tail failure mode."""
    name = name or _largest_npz(ckpt_dir)
    p = os.path.join(ckpt_dir, name)
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size // 2 if keep_bytes is None else keep_bytes)
    return name


def flip_byte(ckpt_dir: str, name: Optional[str] = None,
              offset: Optional[int] = None) -> str:
    """XOR one byte of a checkpoint file (default: the largest npz,
    middle byte) — the silent-bitrot failure mode that only a checksum
    catches."""
    name = name or _largest_npz(ckpt_dir)
    p = os.path.join(ckpt_dir, name)
    off = os.path.getsize(p) // 2 if offset is None else offset
    with open(p, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))
    return name


def _largest_npz(ckpt_dir: str) -> str:
    npz = [n for n in os.listdir(ckpt_dir) if n.endswith(".npz")]
    if not npz:
        raise FileNotFoundError(f"no npz files in {ckpt_dir}")
    return max(npz, key=lambda n: os.path.getsize(os.path.join(ckpt_dir, n)))


# -- serving faults ----------------------------------------------------------


class FaultyPredictor:
    """Duck-typed :class:`paddle_tpu.io.Predictor` wrapper for serving
    fault injection: validation/bucketing surfaces delegate to the real
    predictor, ``run`` routes through ``behavior(base, feed, call_index)``
    — which may hang, raise, or serve normally. The call counter and the
    behavior are SHARED across :meth:`clone`, so a
    ``serving.PredictorServer`` worker pool executes one deterministic
    fault script regardless of which worker dequeues which request."""

    def __init__(self, base, behavior: Callable, _counter=None, _lock=None):
        self._base = base
        self._behavior = behavior
        self._counter = _counter if _counter is not None else [0]
        self._lock = _lock if _lock is not None else threading.Lock()

    # validation/bucketing surface: delegate
    @property
    def feed_names(self):
        return self._base.feed_names

    @property
    def batch_buckets(self):
        return self._base.batch_buckets

    @property
    def batched_feeds(self):
        return self._base.batched_feeds

    @property
    def batch_size(self):
        return self._base.batch_size

    def feed_spec(self, batch=None):
        return self._base.feed_spec(batch)

    def validate_feed(self, feed, allow_padding=False):
        return self._base.validate_feed(feed, allow_padding=allow_padding)

    def run(self, feed):
        with self._lock:
            i = self._counter[0]
            self._counter[0] += 1
        return self._behavior(self._base, feed, i)

    def clone(self) -> "FaultyPredictor":
        return FaultyPredictor(self._base.clone(), self._behavior,
                               _counter=self._counter, _lock=self._lock)


def hanging_predictor(base, release: "threading.Event",
                      hang_calls: int = 1,
                      skip_calls: int = 0) -> FaultyPredictor:
    """``run`` blocks on ``release`` for calls ``[skip_calls,
    skip_calls + hang_calls)`` (then serves normally) — the
    wedged-executable fault that drives the serving watchdog. Always
    ``release.set()`` in test teardown or the abandoned worker thread
    outlives the test."""

    def behavior(b, feed, i):
        if skip_calls <= i < skip_calls + hang_calls:
            release.wait()
        return b.run(feed)

    return FaultyPredictor(base, behavior)


def kill_server(server, reason: str = "injected replica kill"):
    """Abrupt replica death for fleet drills: delegates to
    :meth:`paddle_tpu.serving.PredictorServer.kill` — the in-process
    stand-in for the serving process being ``kill -9``'d. Queued
    (never-dispatched) requests fail with ``ServerClosed`` (a
    ``FleetRouter`` reroutes them), dispatched in-flight requests fail
    with ``ReplicaDied`` exactly once (at-most-once, never retried),
    and the flight recorder captures the kill with the in-flight
    request's span. Deterministic: no subprocess, no signal timing —
    the kill happens exactly where the drill calls it."""
    server.kill(reason=reason)
    return server


def failing_predictor(base, fail_calls: int = 1_000_000,
                      skip_calls: int = 0,
                      exc: Optional[Callable[[], BaseException]] = None
                      ) -> FaultyPredictor:
    """``run`` raises on calls ``[skip_calls, skip_calls + fail_calls)``
    (then serves normally) — the crash-looping executable that trips the
    circuit breaker; a finite ``fail_calls`` lets the half-open probe
    find a recovered executable."""

    def behavior(b, feed, i):
        if skip_calls <= i < skip_calls + fail_calls:
            raise (exc() if exc is not None
                   else RuntimeError(f"injected executable failure #{i}"))
        return b.run(feed)

    return FaultyPredictor(base, behavior)


# -- wire faults (the cross-process fleet) ------------------------------------


class LinkProxy:
    """Deterministic TCP link fault injector for the cross-process
    fleet: a localhost forwarding proxy a :class:`~paddle_tpu.fleet.
    remote.RemoteReplica` is pointed THROUGH (``RemoteReplica(
    proxy.addr, proc=proc)``), whose forwarding can be scripted:

    - :meth:`partition` — stop forwarding BOTH ways without closing
      either side's socket: a real half-open connection. The
      endpoints' ``send()`` keeps succeeding into kernel buffers and
      no reply ever arrives — exactly the observable behavior of a
      network partition, with none of the iptables/root — until
      :meth:`heal` resumes delivery (buffered bytes then arrive, like
      a healed route).
    - :meth:`slow` — delay every forwarded chunk by ``delay_ms``: the
      slow-but-alive replica that drives the router's probe-latency
      demotion.

    All state changes are instant and exact (a flag the pump threads
    read per chunk) — no packet-loss roulette, reproducible from
    tier-1 tests."""

    def __init__(self, target: "tuple", host: str = "127.0.0.1"):
        self.target = (str(target[0]), int(target[1]))
        self._mode = "pass"
        self._delay_ms = 0.0
        self._lock = threading.Lock()
        self._conns: list = []
        self._ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._ls.bind((host, 0))
        self._ls.listen(64)
        self.addr = (host, self._ls.getsockname()[1])
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="pdtpu-linkproxy-accept").start()

    # -- fault script --------------------------------------------------------
    def partition(self) -> "LinkProxy":
        """Blackhole the link both ways (half-open: sockets stay
        open, nothing is delivered)."""
        with self._lock:
            self._mode = "partition"
        return self

    def heal(self) -> "LinkProxy":
        with self._lock:
            self._mode = "pass"
        return self

    def slow(self, delay_ms: float) -> "LinkProxy":
        """Delay each forwarded chunk by ``delay_ms`` (0 restores)."""
        with self._lock:
            self._delay_ms = float(delay_ms)
        return self

    # -- plumbing ------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._ls.accept()
            except OSError:
                return
            try:
                up = socket.create_connection(self.target, timeout=5.0)
            except OSError:
                conn.close()
                continue
            with self._lock:
                self._conns += [conn, up]
            for a, b in ((conn, up), (up, conn)):
                threading.Thread(target=self._pump, args=(a, b),
                                 daemon=True,
                                 name="pdtpu-linkproxy-pump").start()

    def _pump(self, src: socket.socket, dst: socket.socket) -> None:
        while True:
            with self._lock:
                mode, delay = self._mode, self._delay_ms
            if mode == "partition":
                # do not even read: bytes pile up in kernel buffers on
                # the sender's side of the blackhole, delivered only
                # if/when the link heals
                time.sleep(0.01)
                continue
            try:
                src.settimeout(0.05)
                data = src.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if not data:
                break
            if delay > 0:
                time.sleep(delay / 1e3)
            # a read that raced the partition flip HOLDS its chunk
            # until heal — dropping it would desync the framed byte
            # stream for the healed link (a partition delays bytes,
            # it never corrupts the stream)
            while not self._closed:
                with self._lock:
                    if self._mode != "partition":
                        break
                time.sleep(0.01)
            try:
                dst.sendall(data)
            except OSError:
                break
        for s in (src, dst):
            try:
                s.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._ls.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            try:
                s.close()
            except OSError:
                pass

    def __enter__(self) -> "LinkProxy":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def partition(link: LinkProxy) -> LinkProxy:
    """Drop everything both ways on a :class:`LinkProxy` link — a real
    half-open TCP partition (sockets stay open, sends succeed, replies
    never come). Pair with :func:`heal`."""
    return link.partition()


def heal(link: LinkProxy) -> LinkProxy:
    """Resume delivery on a partitioned/slowed link."""
    return link.heal().slow(0.0)


def slow_link(link: LinkProxy, delay_ms: float) -> LinkProxy:
    """Delay every chunk on the link by ``delay_ms`` — the
    slow-but-alive failure mode behind probe-latency demotion."""
    return link.slow(delay_ms)


def slow_h2d(delay_ms: float):
    """The :func:`slow_link` analog for the HOST→DEVICE link: a
    ``DeviceFeeder(wait_fn=...)`` completion wait under which each
    chunk's transfer completes ``delay_ms`` after its submission,
    independently of other chunks — a latency-dominated link, the
    regime the 2-deep staging ring pipelines (two in-flight transfers
    → two completions per delay window), and the regime the BLOCKING
    put serializes (one transfer at a time, host work stalled behind
    each). Deterministic: no bandwidth model, no jitter — the same
    feed script produces the same timeline, so the
    ``overlap_vs_blocking`` A/B (bench ``device_cache`` row,
    tests/test_device_cache.py) measures the ring, not the scheduler."""
    import jax

    delay_s = float(delay_ms) / 1e3

    def wait(dev, t_submit):
        remaining = t_submit + delay_s - time.perf_counter()
        if remaining > 0:
            time.sleep(remaining)
        jax.block_until_ready(dev)

    return wait


def kill_process(replica) -> None:
    """SIGKILL a fleet replica PROCESS, no cleanup, no warning — the
    real thing, unlike :func:`kill_server`'s in-process stand-in.
    Accepts a :class:`~paddle_tpu.fleet.remote.RemoteReplica`, a
    :class:`~paddle_tpu.fleet.remote.ReplicaProcess`, or a bare pid.
    Deterministic: the kill lands exactly where the drill calls it
    (the kernel delivers bytes the victim already wrote — which is
    what makes the never-dispatched/dispatched classification on the
    surviving side exact)."""
    proc = getattr(replica, "proc", replica)
    if isinstance(proc, int):
        os.kill(proc, 9)
        return
    kill = getattr(proc, "kill", None)
    if kill is None:
        raise TypeError(f"kill_process: cannot kill {replica!r}")
    kill()
