"""Fault-tolerance END-TO-END composition (VERDICT r2 #5): two trainer
processes drain the C++ master queue while checkpointing; one is killed
mid-task; its lease times out and the task requeues; the worker restarts
from its sharded checkpoint with step/loss continuity; every task is
processed exactly once. This is the composition the Go master exists for
(go/master/service.go:313 processFailedTask, :341 checkTimeoutFunc,
go/pserver/service.go:346 checkpoint)."""

import os
import re
import subprocess
import sys
import time

import pytest

from paddle_tpu.data.master import MasterClient, MasterServer

HERE = os.path.dirname(__file__)
WORKER = os.path.join(HERE, "ft_worker.py")
N_SHARDS = 6
KILL_AFTER = 2  # victim crashes while holding its 3rd task's lease


def _spawn(port, ckpt_dir, kill_after, worker_id):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, WORKER, str(port), ckpt_dir, str(kill_after), worker_id],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)


@pytest.mark.slow
def test_kill_requeue_resume_composition(tmp_path):
    snap = str(tmp_path / "master.snap")
    ck_a = str(tmp_path / "ck_victim")
    ck_b = str(tmp_path / "ck_survivor")

    with MasterServer(snapshot_path=snap, failure_max=3,
                      lease_timeout_ms=5000) as srv:
        admin = MasterClient(srv.addr)
        shards = [f"shard-{i}" for i in range(N_SHARDS)]
        admin.set_tasks(shards)

        # phase 1: the victim drains ALONE so its scripted crash (mid
        # 3rd task, lease held) cannot be raced away by a faster peer
        # finishing the queue first (observed under CPU contention)
        victim = _spawn(srv.port, ck_a, KILL_AFTER, "victim")
        v_out, v_err = victim.communicate(timeout=300)
        assert victim.returncode == 137, f"victim didn't crash as scripted:\n{v_err[-2000:]}"
        v_ckpts = re.findall(r"CKPT step=(\d+) loss=([\d.]+)", v_out)
        assert len(v_ckpts) == KILL_AFTER  # checkpointed each finished task
        last_step, last_loss = int(v_ckpts[-1][0]), float(v_ckpts[-1][1])

        # phase 2: a fresh peer and the restarted victim drain the rest,
        # including the crashed task once its lease times out
        survivor = _spawn(srv.port, ck_b, -1, "survivor")
        restarted = _spawn(srv.port, ck_a, -1, "victim2")
        r_out, r_err = restarted.communicate(timeout=300)
        s_out, s_err = survivor.communicate(timeout=300)
        assert restarted.returncode == 0, r_err[-2000:]
        assert survivor.returncode == 0, s_err[-2000:]

        # --- step/loss continuity from the sharded checkpoint ---------
        m = re.search(r"RESUMED step=(\d+) loss=([\d.]+)", r_out)
        assert m, r_out
        assert int(m.group(1)) == last_step, \
            "restart must resume at the last checkpointed step (in-flight " \
            "steps of the crashed task are lost, not the checkpointed ones)"
        assert abs(float(m.group(2)) - last_loss) < 1e-5, \
            "restored params must reproduce the checkpointed probe loss"

        # --- exactly-once-or-requeued: every shard finished once ------
        done = re.findall(r"DONE (shard-\d+)", v_out + r_out + s_out)
        assert sorted(done) == sorted(shards), (
            f"each task must be finished exactly once across all workers "
            f"(crashed lease requeued, no loss, no dup): {sorted(done)}")
        st = admin.status()
        assert st["done"] == N_SHARDS and st["todo"] == 0 \
            and st["leased"] == 0 and st["discarded"] == 0, st

        # the crashed task's shard was finished by a phase-2 worker, not
        # the victim — the requeue actually happened
        v_done = set(re.findall(r"DONE (shard-\d+)", v_out))
        assert len(v_done) == KILL_AFTER
        assert set(shards) - v_done <= set(
            re.findall(r"DONE (shard-\d+)", r_out + s_out))
        admin.close()
