"""Recurrent layers.

Analog of the reference's RNN stack: dynamic_lstm/dynamic_gru ops
(operators/lstm_op.cc, gru_op.cc with fused gate kernels in
operators/math/lstm_compute.h), StaticRNN/DynamicRNN sugar
(layers/control_flow.py:429/:1542) compiled to while_op. TPU-native
design: time recursion is ``lax.scan`` (compiler-friendly, static
shapes); ragged batches use a length mask (the segment-ids/LoD
equivalent — SURVEY §7 hard-part 1) instead of lod_rank_table
reordering; gates are computed as ONE [d, 4d] matmul so the MXU sees a
big GEMM per step (what the reference's xbyak JIT fusion chased on CPU).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..framework import LayerHelper, cast_compute
from .. import initializer as init


def lstm_cell_step(x_proj, h, c, w_h, forget_bias: float = 0.0):
    """One LSTM step from a precomputed input projection x_proj
    [b, 4d]. Gate order (i, f, c, o) matches lstm_op.cc."""
    gates = x_proj + jnp.matmul(h, w_h)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def dynamic_lstm(
    input,
    size: int,
    sequence_length: Optional[jax.Array] = None,
    is_reverse: bool = False,
    forget_bias: float = 0.0,
    param_attr=None,
    bias_attr=None,
    name: Optional[str] = None,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """LSTM over a padded batch [b, t, d] (dynamic_lstm op analog).

    Returns (outputs [b, t, size], (h_last, c_last)). ``sequence_length``
    [b] masks state updates past each sequence's end — the LoD analog —
    so h_last/c_last equal the states at each sequence's true end.

    The input projection for ALL timesteps is one [b*t, d]×[d, 4size]
    GEMM (MXU-friendly); the scan carries only the [size,4size] recurrent
    matmul.
    """
    helper = LayerHelper("lstm", name=name)
    b, t, d = input.shape
    w_x = helper.create_parameter("w_x", (d, 4 * size), jnp.float32, attr=param_attr,
                                  initializer=init.Xavier())
    w_h = helper.create_parameter("w_h", (size, 4 * size), jnp.float32,
                                  initializer=init.Xavier())
    bias = helper.create_parameter("b", (4 * size,), jnp.float32, attr=bias_attr,
                                   initializer=init.Constant(0.0))
    input, w_x, w_h = cast_compute(input, w_x, w_h)
    dtype = input.dtype
    x_proj = jnp.matmul(input.reshape(b * t, d), w_x).reshape(b, t, 4 * size) \
        + bias.astype(dtype)
    x_proj_t = jnp.swapaxes(x_proj, 0, 1)  # [t, b, 4d]
    if is_reverse:
        x_proj_t = x_proj_t[::-1]

    steps = jnp.arange(t)
    if is_reverse:
        steps = steps[::-1]

    def step(carry, inp):
        h, c = carry
        xp, idx = inp
        h_new, c_new = lstm_cell_step(xp, h, c, w_h, forget_bias)
        if sequence_length is not None:
            valid = (idx < sequence_length)[:, None]
            h_new = jnp.where(valid, h_new, h)
            c_new = jnp.where(valid, c_new, c)
        return (h_new, c_new), h_new

    h0 = jnp.zeros((b, size), dtype)
    c0 = jnp.zeros((b, size), dtype)
    (h_last, c_last), outs = jax.lax.scan(step, (h0, c0), (x_proj_t, steps))
    outs = jnp.swapaxes(outs, 0, 1)
    if is_reverse:
        outs = outs[:, ::-1]
    return outs, (h_last, c_last)


def gru_cell_step(x_proj, h, w_h):
    """One GRU step; gate order (update z, reset r, candidate) matches
    gru_op.cc."""
    size = h.shape[-1]
    zr_x, c_x = x_proj[..., :2 * size], x_proj[..., 2 * size:]
    zr_h = jnp.matmul(h, w_h[:, :2 * size])
    z, r = jnp.split(jax.nn.sigmoid(zr_x + zr_h), 2, axis=-1)
    c = jnp.tanh(c_x + jnp.matmul(r * h, w_h[:, 2 * size:]))
    return (1 - z) * h + z * c


def dynamic_gru(
    input,
    size: int,
    sequence_length: Optional[jax.Array] = None,
    is_reverse: bool = False,
    param_attr=None,
    bias_attr=None,
    name: Optional[str] = None,
):
    """GRU over a padded batch [b, t, d] (dynamic_gru op analog).
    Returns outputs [b, t, size]."""
    helper = LayerHelper("gru", name=name)
    b, t, d = input.shape
    w_x = helper.create_parameter("w_x", (d, 3 * size), jnp.float32, attr=param_attr,
                                  initializer=init.Xavier())
    w_h = helper.create_parameter("w_h", (size, 3 * size), jnp.float32,
                                  initializer=init.Xavier())
    bias = helper.create_parameter("b", (3 * size,), jnp.float32, attr=bias_attr,
                                   initializer=init.Constant(0.0))
    input, w_x, w_h = cast_compute(input, w_x, w_h)
    dtype = input.dtype
    x_proj = jnp.matmul(input.reshape(b * t, d), w_x).reshape(b, t, 3 * size) \
        + bias.astype(dtype)
    x_proj_t = jnp.swapaxes(x_proj, 0, 1)
    if is_reverse:
        x_proj_t = x_proj_t[::-1]
    steps = jnp.arange(t)
    if is_reverse:
        steps = steps[::-1]

    def step(h, inp):
        xp, idx = inp
        h_new = gru_cell_step(xp, h, w_h)
        if sequence_length is not None:
            valid = (idx < sequence_length)[:, None]
            h_new = jnp.where(valid, h_new, h)
        return h_new, h_new

    h0 = jnp.zeros((b, size), dtype)
    h_last, outs = jax.lax.scan(step, h0, (x_proj_t, steps))
    outs = jnp.swapaxes(outs, 0, 1)
    if is_reverse:
        outs = outs[:, ::-1]
    return outs


def rnn(cell_fn, inputs, initial_state, sequence_length: Optional[jax.Array] = None):
    """Generic scan-based RNN (StaticRNN/DynamicRNN analog,
    control_flow.py:429/:1542): ``cell_fn(state, x_t) -> (new_state,
    out_t)`` applied over axis 1 of ``inputs`` [b, t, ...]."""
    xs = jnp.swapaxes(inputs, 0, 1)
    steps = jnp.arange(xs.shape[0])

    def step(state, inp):
        x_t, idx = inp
        new_state, out = cell_fn(state, x_t)
        if sequence_length is not None:
            valid = (idx < sequence_length)
            new_state = jax.tree.map(
                lambda n, o: jnp.where(valid.reshape((-1,) + (1,) * (n.ndim - 1)), n, o),
                new_state, state)
        return new_state, out

    last_state, outs = jax.lax.scan(step, initial_state, (xs, steps))
    return jnp.swapaxes(outs, 0, 1), last_state


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias: float = 0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step with its own weights (lstm_unit_op.cc +
    layers/nn.py lstm_unit): concat(x, h) × [d+h, 4h] GEMM. Returns
    (hidden_t, cell_t)."""
    helper = LayerHelper("lstm_unit", name=name)
    d = x_t.shape[-1]
    size = hidden_t_prev.shape[-1]
    w = helper.create_parameter("w", (d + size, 4 * size), jnp.float32,
                                attr=param_attr, initializer=init.Xavier())
    b = helper.create_parameter("b", (4 * size,), jnp.float32, attr=bias_attr,
                                initializer=init.Constant(0.0))
    x_t, hidden_t_prev, cell_t_prev, w = cast_compute(x_t, hidden_t_prev, cell_t_prev, w)
    x_proj = jnp.matmul(x_t, w[:d]) + b.astype(x_t.dtype)
    return lstm_cell_step(x_proj, hidden_t_prev, cell_t_prev, w[d:], forget_bias)


def gru_unit(input, hidden, size: int, param_attr=None, bias_attr=None,
             activation: str = "tanh", gate_activation: str = "sigmoid", name=None):
    """Single GRU step (gru_unit_op.cc; fluid passes size = 3×dim).
    Returns (new_hidden, reset_hidden_pre, gate) like the reference."""
    from ..core.errors import enforce
    enforce(activation == "tanh" and gate_activation == "sigmoid",
            "gru_unit: only tanh/sigmoid activations (reference defaults) supported")
    dim = size // 3
    helper = LayerHelper("gru_unit", name=name)
    w_h = helper.create_parameter("w_h", (dim, 3 * dim), jnp.float32,
                                  attr=param_attr, initializer=init.Xavier())
    b = helper.create_parameter("b", (3 * dim,), jnp.float32, attr=bias_attr,
                                initializer=init.Constant(0.0))
    input, hidden, w_h = cast_compute(input, hidden, w_h)
    xp = input + b.astype(input.dtype)
    zr_x, c_x = xp[..., :2 * dim], xp[..., 2 * dim:]
    zr = jax.nn.sigmoid(zr_x + jnp.matmul(hidden, w_h[:, :2 * dim]))
    z, r = jnp.split(zr, 2, axis=-1)
    reset_hidden_pre = r * hidden
    c = jnp.tanh(c_x + jnp.matmul(reset_hidden_pre, w_h[:, 2 * dim:]))
    new_hidden = (1 - z) * hidden + z * c
    gate = jnp.concatenate([z, r, c], axis=-1)
    return new_hidden, reset_hidden_pre, gate


def dynamic_lstmp(input, size: int, proj_size: int,
                  sequence_length: Optional[jax.Array] = None,
                  is_reverse: bool = False, forget_bias: float = 0.0,
                  proj_clip: Optional[float] = None, cell_clip: Optional[float] = None,
                  param_attr=None, bias_attr=None, name=None):
    """LSTM with recurrent projection (lstmp_op.cc): the recurrent state
    fed back into the gates is r = proj(h) [proj_size], shrinking the
    recurrent GEMM — the LSTMP of Sak et al. that the reference ships for
    large-vocab acoustic models. Returns (projected outputs
    [b, t, proj_size], (r_last, c_last))."""
    helper = LayerHelper("lstmp", name=name)
    b, t, d = input.shape
    w_x = helper.create_parameter("w_x", (d, 4 * size), jnp.float32, attr=param_attr,
                                  initializer=init.Xavier())
    w_r = helper.create_parameter("w_r", (proj_size, 4 * size), jnp.float32,
                                  attr=param_attr, initializer=init.Xavier())
    w_p = helper.create_parameter("w_p", (size, proj_size), jnp.float32,
                                  attr=param_attr, initializer=init.Xavier())
    bias = helper.create_parameter("b", (4 * size,), jnp.float32, attr=bias_attr,
                                   initializer=init.Constant(0.0))
    input, w_x, w_r, w_p = cast_compute(input, w_x, w_r, w_p)
    dtype = input.dtype
    x_proj = jnp.matmul(input.reshape(b * t, d), w_x).reshape(b, t, 4 * size) \
        + bias.astype(dtype)
    x_proj_t = jnp.swapaxes(x_proj, 0, 1)
    steps = jnp.arange(t)
    if is_reverse:
        x_proj_t = x_proj_t[::-1]
        steps = steps[::-1]

    def step(carry, inp):
        r, c = carry
        xp, idx = inp
        gates = xp + jnp.matmul(r, w_r)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f + forget_bias)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        if cell_clip is not None:
            c_new = jnp.clip(c_new, -cell_clip, cell_clip)
        h_new = o * jnp.tanh(c_new)
        r_new = jnp.matmul(h_new, w_p)
        if proj_clip is not None:
            r_new = jnp.clip(r_new, -proj_clip, proj_clip)
        if sequence_length is not None:
            valid = (idx < sequence_length)[:, None]
            r_new = jnp.where(valid, r_new, r)
            c_new = jnp.where(valid, c_new, c)
        return (r_new, c_new), r_new

    r0 = jnp.zeros((b, proj_size), dtype)
    c0 = jnp.zeros((b, size), dtype)
    (r_last, c_last), outs = jax.lax.scan(step, (r0, c0), (x_proj_t, steps))
    outs = jnp.swapaxes(outs, 0, 1)
    if is_reverse:
        outs = outs[:, ::-1]
    return outs, (r_last, c_last)
