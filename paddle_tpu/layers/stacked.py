"""Stacked transformer blocks — the pipeline-parallel layer representation.

Gap-fill component (SURVEY §2.2: PP absent in the reference; the closest
machinery is the multi-device SSA replication of
framework/details/multi_devices_graph_pass.cc, which replicates ops per
device — here we *partition layers* per device instead).

TPU-native design: per-layer parameters live STACKED on a leading
``[num_layers, ...]`` axis, created once through the normal LayerHelper
scope (so save/load, sharding rules, and optimizers see ordinary named
params). The stack is applied either

- sequentially with ``lax.scan`` (single chip, or dp/fsdp/tp meshes where
  GSPMD partitions the scanned matmuls), or
- pipelined with ``parallel.pipeline.pipeline_apply`` when the Trainer
  has entered :func:`framework.pipeline_mode` (``DistStrategy.pp_microbatches``),
  each pp rank owning a contiguous span of layers.

Blocks are pure functions of ``(activation, layer_params, extra)`` — no
LayerHelper calls inside, so they trace safely under scan and shard_map.
Dropout IS supported on the scan path: the naive scan-traced rng would
reuse one key across every layer (the per-call counter is a Python int
fixed at trace time), so ``apply_stacked`` folds the traced layer index
into the ambient rng stream per iteration (:func:`framework.rng_fold`),
giving each layer independent masks at the same four sites as the
unrolled transformer layer (attention softmax, two residuals, ffn
inner). The pipeline path supports dropout too: a per-step key is
threaded into the schedule and folded per (layer, microbatch,
data-shard) inside the shard_map body (parallel/pipeline.py module doc
covers the tp-axis caveat).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.errors import enforce
from ..framework import (LayerHelper, cast_compute, in_training as _in_training,
                         maybe_remat, pipeline_config, rng_fold, sp_config)
from .. import initializer as init

NEG_INF = -1e9


class StackedInit:
    """Apply a base initializer per layer over the leading stack axis, so
    a ``[L, d, k]`` leaf gets L independent ``[d, k]`` inits (fan-in/out
    computed per layer, matching the unstacked model exactly)."""

    def __init__(self, base):
        self.base = base

    def __call__(self, key, shape, dtype):
        keys = jax.random.split(key, shape[0])
        return jnp.stack([self.base(k, shape[1:], dtype) for k in keys])


def _ln(x, scale, bias, eps: float = 1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    return out * scale + bias


def _drop(x, rate: float):
    """Residual/inner dropout (upscale_in_train, matching the unrolled
    transformer layer); no-op at rate 0 or outside training."""
    if rate == 0.0:
        return x
    from .nn import dropout
    return dropout(x, rate, dropout_implementation="upscale_in_train")


def _sdpa(q, k, v, key_bias, causal: bool, use_flash: bool, sp_cfg=None,
          dropout_rate: float = 0.0):
    """[b,h,s,hd] attention with an additive [b,s_k] key bias. With an
    active sequence-parallel context, self-attention runs as ring
    attention over the mesh's sp axis. The layout comes from the sp
    context ("natural" unless the MODEL set "zigzag" after permuting its
    own activations, as models/gpt.py does) — natural-order callers get
    the numerically-safe per-call gathers, never a silent mismatch."""
    if sp_cfg is not None:
        enforce(key_bias is None,
                "sequence-parallel attention does not take a padding bias "
                "(pack full sequences; pad-free is the long-context contract)")
        enforce(dropout_rate == 0.0 or not _in_training(),
                "sequence-parallel attention has no softmax-dropout site "
                "(ring/ulysses kernels); train sp stacks with dropout 0")
        if sp_cfg.get("impl", "ring") == "ulysses":
            from ..parallel.ulysses import ulysses_attention

            def inner(qh, kh, vh, caus):
                if use_flash:
                    from ..ops.flash_attention import flash_attention
                    return flash_attention(qh, kh, vh, causal=caus)
                return _sdpa(qh, kh, vh, None, caus, False)

            return ulysses_attention(q, k, v, sp_cfg["mesh"],
                                     axis_name=sp_cfg["axis"], causal=causal,
                                     attn_fn=inner)
        from ..parallel.ring_attention import ring_attention
        layout = sp_cfg.get("layout", "natural")
        return ring_attention(q, k, v, sp_cfg["mesh"], axis_name=sp_cfg["axis"],
                              causal=causal,
                              schedule="zigzag" if (causal and layout == "zigzag")
                              else "auto",
                              layout=layout)
    if use_flash and (dropout_rate == 0.0 or not _in_training()):
        # same gate as layers/attention.py: the flash kernel has no
        # dropout; rate > 0 falls to the dense path with softmax dropout
        # during training, while eval/serving traces (dropout no-op)
        # keep the kernel
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, key_bias=key_bias)
    from ..ops.attention_scores import scores_mxu
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = scores_mxu(q, k, scale)
    if key_bias is not None:
        logits = logits + key_bias[:, None, None, :]
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        logits = jnp.where(cm, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    probs = _drop(probs, dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _split_heads(x, head_dim):
    # split by head_dim, not head count: under tensor parallelism the
    # projection output is a tp-local slice holding num_heads/tp whole
    # heads, so the local head count falls out of the shape
    b, s, d = x.shape
    return x.reshape(b, s, d // head_dim, head_dim).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, s, hd = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * hd)


# -- parameter stacks --------------------------------------------------------


def encoder_stack_params(num_layers: int, d_model: int, d_inner: int,
                         name: str = "encoder_stack") -> Dict[str, jax.Array]:
    """Create the stacked params of ``num_layers`` pre-LN self-attention
    blocks. The fused qkv weight is [L, d, 3, d_model] (q/k/v on their own
    axis, so a tensor-parallel shard of the LAST dim keeps whole heads —
    the Megatron fused-qkv layout)."""
    helper = LayerHelper(name, name=name)
    xavier = StackedInit(init.Xavier())
    zeros = init.Constant(0.0)
    ones = init.Constant(1.0)
    L, d, di = num_layers, d_model, d_inner
    p = {
        "ln1/scale": helper.create_parameter("ln1/scale", (L, d), jnp.float32, initializer=ones),
        "ln1/bias": helper.create_parameter("ln1/bias", (L, d), jnp.float32, initializer=zeros),
        "qkv/w": helper.create_parameter("qkv/w", (L, d, 3, d), jnp.float32, initializer=xavier),
        "qkv/b": helper.create_parameter("qkv/b", (L, 3, d), jnp.float32, initializer=zeros),
        "out/w": helper.create_parameter("out/w", (L, d, d), jnp.float32, initializer=xavier),
        "out/b": helper.create_parameter("out/b", (L, d), jnp.float32, initializer=zeros),
        "ln2/scale": helper.create_parameter("ln2/scale", (L, d), jnp.float32, initializer=ones),
        "ln2/bias": helper.create_parameter("ln2/bias", (L, d), jnp.float32, initializer=zeros),
        "ffn_in/w": helper.create_parameter("ffn_in/w", (L, d, di), jnp.float32, initializer=xavier),
        "ffn_in/b": helper.create_parameter("ffn_in/b", (L, di), jnp.float32, initializer=zeros),
        "ffn_out/w": helper.create_parameter("ffn_out/w", (L, di, d), jnp.float32, initializer=xavier),
        "ffn_out/b": helper.create_parameter("ffn_out/b", (L, d), jnp.float32, initializer=zeros),
    }
    return p


def decoder_stack_params(num_layers: int, d_model: int, d_inner: int,
                         name: str = "decoder_stack") -> Dict[str, jax.Array]:
    """Stacked pre-LN decoder blocks: causal self-attention + cross
    attention (encoder-decoder capability of the reference's transformer
    benchmark) + FFN."""
    p = encoder_stack_params(num_layers, d_model, d_inner, name=name)
    helper = LayerHelper(name, name=name)
    xavier = StackedInit(init.Xavier())
    zeros = init.Constant(0.0)
    ones = init.Constant(1.0)
    L, d = num_layers, d_model
    p.update({
        "lnx/scale": helper.create_parameter("lnx/scale", (L, d), jnp.float32, initializer=ones),
        "lnx/bias": helper.create_parameter("lnx/bias", (L, d), jnp.float32, initializer=zeros),
        "xq/w": helper.create_parameter("xq/w", (L, d, d), jnp.float32, initializer=xavier),
        "xq/b": helper.create_parameter("xq/b", (L, d), jnp.float32, initializer=zeros),
        "xkv/w": helper.create_parameter("xkv/w", (L, d, 2, d), jnp.float32, initializer=xavier),
        "xkv/b": helper.create_parameter("xkv/b", (L, 2, d), jnp.float32, initializer=zeros),
        "xout/w": helper.create_parameter("xout/w", (L, d, d), jnp.float32, initializer=xavier),
        "xout/b": helper.create_parameter("xout/b", (L, d), jnp.float32, initializer=zeros),
    })
    return p


# -- block functions ---------------------------------------------------------


def _self_attention(x, p, num_heads, causal, use_flash, key_bias, tp_axis,
                    sp_cfg=None, dropout_rate: float = 0.0):
    q, k, v = _attn_qkv(x, p, num_heads)
    return _attn_out(x, p, _sdpa(q, k, v, key_bias, causal, use_flash, sp_cfg,
                                 dropout_rate=dropout_rate),
                     tp_axis, dropout_rate=dropout_rate)


def _ffn(x, p, tp_axis, dropout_rate: float = 0.0):
    h = _ln(x, p["ln2/scale"], p["ln2/bias"])
    h, w1, w2 = cast_compute(h, p["ffn_in/w"], p["ffn_out/w"])
    h = jax.nn.relu(jnp.matmul(h, w1) + p["ffn_in/b"].astype(h.dtype))
    h = _drop(h, dropout_rate)
    h = jnp.matmul(h, w2)
    if tp_axis:
        h = jax.lax.psum(h, tp_axis)
    return x + _drop(h + p["ffn_out/b"].astype(h.dtype), dropout_rate)


def make_encoder_block(num_heads: int, use_flash: bool = False,
                       causal: bool = False,
                       tp_axis: Optional[str] = None,
                       sp_cfg: Optional[dict] = None,
                       dropout_rate: float = 0.0) -> Callable:
    """layer_fn(x, layer_params, key_bias) for pipeline_apply/scan. When
    ``tp_axis`` is set, attention/ffn heads are tp-local and the output
    projections psum partial sums (Megatron pattern inside a stage).
    ``sp_cfg`` routes self-attention through zigzag ring attention.
    ``dropout_rate`` mirrors the unrolled layer's four dropout sites;
    the scan path decorrelates layers via rng_fold (see module doc)."""

    def block(x, p, key_bias=None):
        x = _self_attention(x, p, num_heads, causal, use_flash,
                            key_bias, tp_axis, sp_cfg,
                            dropout_rate=dropout_rate)
        return _ffn(x, p, tp_axis, dropout_rate=dropout_rate)

    return block


def make_decoder_block(num_heads: int, use_flash: bool = False,
                       causal: bool = True,
                       tp_axis: Optional[str] = None,
                       sp_cfg: Optional[dict] = None,
                       dropout_rate: float = 0.0) -> Callable:
    """layer_fn(x, layer_params, extra) with extra = {"enc": encoder
    output [b,s,d], "enc_bias": additive [b,s] padding bias}. Causal
    self-attention + cross attention + FFN."""
    enforce(sp_cfg is None,
            "sequence parallelism is wired for the self-attention-only "
            "stack (models/gpt.py); the encoder-decoder cross-attention "
            "path does not support it")

    def block(x, p, extra):
        head_dim = x.shape[-1] // num_heads
        x = _self_attention(x, p, num_heads, causal, use_flash, None, tp_axis,
                            dropout_rate=dropout_rate)
        h = _ln(x, p["lnx/scale"], p["lnx/bias"])
        h, wq, wkv, enc = cast_compute(h, p["xq/w"], p["xkv/w"], extra["enc"])
        q = jnp.matmul(h, wq) + p["xq/b"].astype(h.dtype)
        kv = jnp.einsum("bsd,dke->bske", enc, wkv) + p["xkv/b"].astype(h.dtype)
        q = _split_heads(q, head_dim)
        k, v = (_split_heads(kv[:, :, i], head_dim) for i in range(2))
        o = _merge_heads(_sdpa(q, k, v, extra.get("enc_bias"), False, use_flash,
                               dropout_rate=dropout_rate))
        o, ow = cast_compute(o, p["xout/w"])
        o = jnp.matmul(o, ow)
        if tp_axis:
            o = jax.lax.psum(o, tp_axis)
        x = x + _drop(o + p["xout/b"].astype(o.dtype), dropout_rate)
        return _ffn(x, p, tp_axis, dropout_rate=dropout_rate)

    return block


# -- incremental decoding (KV cache over stacked params) ---------------------


def _attn_qkv(x, p, num_heads):
    head_dim = x.shape[-1] // num_heads
    h = _ln(x, p["ln1/scale"], p["ln1/bias"])
    h, w = cast_compute(h, p["qkv/w"])
    qkv = jnp.einsum("bsd,dke->bske", h, w) + p["qkv/b"].astype(h.dtype)
    return tuple(_split_heads(qkv[:, :, i], head_dim) for i in range(3))


def _attn_out(x, p, o, tp_axis=None, dropout_rate: float = 0.0):
    o, ow = cast_compute(_merge_heads(o), p["out/w"])
    o = jnp.matmul(o, ow)
    if tp_axis:
        o = jax.lax.psum(o, tp_axis)
    return x + _drop(o + p["out/b"].astype(o.dtype), dropout_rate)


def prefill_block(x, p, num_heads: int, use_flash: bool = False):
    """Causal block that also returns its (k, v) for cache seeding —
    the stacked-layer analog of the transformer decoder's cache path
    (models/transformer.py make_decoder)."""
    q, k, v = _attn_qkv(x, p, num_heads)
    x = _attn_out(x, p, _sdpa(q, k, v, None, True, use_flash))
    return _ffn(x, p, None), (k, v)


def quantize_kv(x):
    """Symmetric per-vector int8 quantization of a cache entry over
    the head_dim axis. One quantizer for the whole repo: delegates to
    quantize._quant_dynamic and converts its absmax scale convention
    (dequant = q/qmax·scale) to the multiply-direct one the decode
    matmuls factor out (dequant = q·scale), so the two can never
    drift. Scale shape [..., 1] float32; zero vectors dequantize to
    exact 0."""
    from ..quantize import _quant_dynamic

    q, scale = _quant_dynamic(x, axes=(-1,))
    return q, scale / 127.0


def decode_block_q8(x, p, k_q, k_s, v_q, v_s, index, num_heads: int):
    """decode_block with an int8 KV cache: k_q/v_q int8 [rows, h, T,
    hd] plus per-position scales k_s/v_s [rows, h, T, 1]. Decode is
    HBM-bound — the cache read dominates — so halving (vs bf16) or
    quartering (vs f32) the cache bytes is direct serving throughput.
    The scales FACTOR OUT of both attention matmuls (score[t] ∝ k_s[t],
    out ∝ probs∘v_s), so no dequantized cache array is ever
    materialized: the int8→compute-dtype convert feeds the dot
    operands directly. Returns (x, k_q, k_s, v_q, v_s)."""
    q, k1, v1 = _attn_qkv(x, p, num_heads)
    k1q, k1s = quantize_kv(k1)
    v1q, v1s = quantize_kv(v1)
    k_q = jax.lax.dynamic_update_slice(k_q, k1q, (0, 0, index, 0))
    k_s = jax.lax.dynamic_update_slice(k_s, k1s.astype(k_s.dtype),
                                       (0, 0, index, 0))
    v_q = jax.lax.dynamic_update_slice(v_q, v1q, (0, 0, index, 0))
    v_s = jax.lax.dynamic_update_slice(v_s, v1s.astype(v_s.dtype),
                                       (0, 0, index, 0))
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_q.astype(q.dtype),
                        preferred_element_type=jnp.float32)
    logits = logits * k_s[..., 0][:, :, None, :] * scale
    pos = jnp.arange(k_q.shape[2])
    logits = jnp.where(pos[None, None, None, :] <= index, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    pv = (probs * v_s[..., 0][:, :, None, :]).astype(q.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", pv, v_q.astype(q.dtype))
    x = _attn_out(x, p, o)
    return _ffn(x, p, None), k_q, k_s, v_q, v_s


def decode_block(x, p, k_cache, v_cache, index, num_heads: int):
    """One-token step: x [rows, 1, d]; caches [rows, h, T, hd]; attends
    to cache positions <= index. Returns (x, new_k, new_v)."""
    q, k1, v1 = _attn_qkv(x, p, num_heads)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k1.astype(k_cache.dtype),
                                           (0, 0, index, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v1.astype(v_cache.dtype),
                                           (0, 0, index, 0))
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    pos = jnp.arange(k_cache.shape[2])
    logits = jnp.where(pos[None, None, None, :] <= index, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v_cache.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", probs, v_cache)
    x = _attn_out(x, p, o)
    return _ffn(x, p, None), k_cache, v_cache


# -- tensor-parallel specs (non-layer dims, pipeline_apply param_specs) ------

_ENCODER_TP_SPECS = {
    "ln1/scale": P(), "ln1/bias": P(),
    "qkv/w": P(None, None, "tp"), "qkv/b": P(None, "tp"),
    "out/w": P("tp"), "out/b": P(),
    "ln2/scale": P(), "ln2/bias": P(),
    "ffn_in/w": P(None, "tp"), "ffn_in/b": P("tp"),
    "ffn_out/w": P("tp"), "ffn_out/b": P(),
}

_DECODER_TP_SPECS = dict(_ENCODER_TP_SPECS, **{
    "lnx/scale": P(), "lnx/bias": P(),
    "xq/w": P(None, "tp"), "xq/b": P("tp"),
    "xkv/w": P(None, None, "tp"), "xkv/b": P(None, "tp"),
    "xout/w": P("tp"), "xout/b": P(),
})


def stack_tp_specs(stacked: Dict[str, Any]) -> Dict[str, Any]:
    table = _DECODER_TP_SPECS if "xq/w" in stacked else _ENCODER_TP_SPECS
    return {k: table[k] for k in stacked}


# -- apply -------------------------------------------------------------------


def apply_stacked(x, stacked: Dict[str, jax.Array], make_block: Callable,
                  extras=None, num_heads: int = 8, use_flash: bool = False,
                  causal: bool = False, remat: bool = False,
                  dropout_rate: float = 0.0):
    """Run a parameter stack over ``x``: pipelined across the ``pp`` mesh
    axis when the Trainer has entered :func:`framework.pipeline_mode`
    (DistStrategy.pp_microbatches — the BuildStrategy-knob analog),
    sequential ``lax.scan`` otherwise (where GSPMD still tp/fsdp-shards
    the scanned matmuls from the rule-table shardings).

    ``make_block(num_heads=…, use_flash=…, causal=…, tp_axis=…)`` builds
    the layer fn — tp_axis is set when the pipeline mesh also has a
    ``tp`` axis, making dp×tp×pp one call.
    """
    cfg = pipeline_config()
    sp = sp_config()
    enforce(not (cfg is not None and sp is not None),
            "pipeline and sequence parallelism cannot wrap the same stack "
            "(ring attention's shard_map cannot nest inside the pipeline's)")
    if cfg is None:
        block = make_block(num_heads=num_heads, use_flash=use_flash,
                           causal=causal, tp_axis=None, sp_cfg=sp,
                           dropout_rate=dropout_rate)
        num_layers = next(iter(stacked.values())).shape[0]

        def scan_body(a, xs):
            lp, idx = xs

            def fn(a_, lp_):
                # per-layer rng: the traced layer index folds into the
                # ambient stream so dropout masks decorrelate across
                # scan iterations (the body is traced ONCE)
                with rng_fold(idx):
                    return block(a_, lp_, extras) if extras is not None \
                        else block(a_, lp_)
            # remat=True forces per-layer checkpointing (cfg.remat);
            # False defers to the ambient strategy.remat switch
            return maybe_remat(fn, enabled=remat or None)(a, lp), None
        out, _ = jax.lax.scan(scan_body, x,
                              (stacked, jnp.arange(num_layers)))
        return out

    from ..framework import next_rng_key
    from ..parallel.pipeline import pipeline_apply
    mesh = cfg["mesh"]
    tp = "tp" if ("tp" in mesh.axis_names and mesh.shape["tp"] > 1) else None
    if tp:
        enforce(num_heads % mesh.shape["tp"] == 0,
                f"stacked blocks with tp={mesh.shape['tp']} need num_heads "
                f"({num_heads}) divisible by tp")
    block = make_block(num_heads=num_heads, use_flash=use_flash,
                       causal=causal, tp_axis=tp, sp_cfg=None,
                       dropout_rate=dropout_rate)
    layer_fn = block if extras is not None else (lambda a, lp: block(a, lp))
    # dropout in the pipeline: thread one per-step key into the schedule
    # (the body runs under shard_map, where the ambient stream is not
    # addressable); pipeline_apply folds it per (layer, microbatch,
    # data-shard). Eval traces pass None — dropout is a no-op there.
    rng_key = (next_rng_key()
               if dropout_rate > 0.0 and _in_training() else None)
    return pipeline_apply(
        x, stacked, layer_fn, mesh, axis_name=cfg["axis"],
        microbatches=cfg["microbatches"],
        interleave=cfg.get("interleave", 1),
        param_specs=stack_tp_specs(stacked) if tp else None,
        extras=extras,
        param_layout=cfg.get("param_layout", "stacked"),
        rng_key=rng_key)
