"""Framework-core tests: scope, naming, state, ParamAttr — the
scope_test.cc / operator_test.cc / test_program.py family analog."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import initializer as init
from paddle_tpu import layers as L
from paddle_tpu.core.errors import EnforceError, NotFoundError


def test_unique_names_stable_across_init_apply():
    def net(x):
        a = L.fc(x, 4)
        b = L.fc(x, 4)
        return a + b

    prog = pt.build(net)
    x = np.random.randn(2, 3).astype(np.float32)
    params, state = prog.init(jax.random.PRNGKey(0), x)
    assert set(params) == {"fc_0/w", "fc_0/b", "fc_1/w", "fc_1/b"}
    out, _ = prog.apply(params, state, x)  # must not raise NotFound
    assert out.shape == (2, 4)


def test_param_attr_custom_name_and_initializer():
    def net(x):
        return L.fc(x, 3, param_attr=pt.ParamAttr(name="my_w", initializer=init.Constant(2.0)),
                    bias_attr=False)

    prog = pt.build(net)
    x = np.ones((1, 2), np.float32)
    params, _ = prog.init(jax.random.PRNGKey(0), x)
    assert "my_w" in params
    np.testing.assert_allclose(np.asarray(params["my_w"]), 2.0)


def test_layer_outside_context_raises():
    with pytest.raises(EnforceError):
        L.fc(jnp.ones((1, 2)), 3)


def test_missing_param_raises_not_found():
    prog = pt.build(lambda x: L.fc(x, 3))
    x = np.ones((1, 2), np.float32)
    prog.init(jax.random.PRNGKey(0), x)
    with pytest.raises(NotFoundError):
        prog.apply({}, {}, x)


def test_init_deterministic_under_same_seed():
    prog = pt.build(lambda x: L.fc(x, 8))
    x = np.ones((1, 4), np.float32)
    p1, _ = prog.init(jax.random.PRNGKey(7), x)
    p2, _ = prog.init(jax.random.PRNGKey(7), x)
    np.testing.assert_allclose(np.asarray(p1["fc_0/w"]), np.asarray(p2["fc_0/w"]))
    p3, _ = prog.init(jax.random.PRNGKey(8), x)
    assert not np.allclose(np.asarray(p1["fc_0/w"]), np.asarray(p3["fc_0/w"]))


def test_shape_dtype_struct_init():
    prog = pt.build(lambda x: L.fc(x, 5))
    spec = jax.ShapeDtypeStruct((2, 3), jnp.float32)
    params, _ = prog.init(jax.random.PRNGKey(0), spec)
    assert params["fc_0/w"].shape == (3, 5)


def test_state_threading_batch_norm():
    prog = pt.build(lambda x: L.batch_norm(x))
    x = np.random.randn(4, 2).astype(np.float32)
    params, state = prog.init(jax.random.PRNGKey(0), x)
    assert "batch_norm_0/moving_mean" in state
    _, s1 = prog.apply(params, state, x, training=True)
    _, s2 = prog.apply(params, s1, x, training=True)
    # moving mean moves monotonically toward batch mean over steps
    assert not np.allclose(np.asarray(s1["batch_norm_0/moving_mean"]),
                           np.asarray(s2["batch_norm_0/moving_mean"]))


def test_name_scope_nesting():
    def net(x):
        with pt.name_scope("encoder"):
            h = L.fc(x, 4)
        return h

    prog = pt.build(net)
    params, _ = prog.init(jax.random.PRNGKey(0), np.ones((1, 2), np.float32))
    assert any(k.startswith("encoder/fc_0/") for k in params)


def test_program_desc_jaxpr():
    prog = pt.build(lambda x: L.fc(x, 3))
    x = np.ones((1, 2), np.float32)
    params, state = prog.init(jax.random.PRNGKey(0), x)
    jaxpr = prog.desc(params, state, x)
    assert "dot_general" in str(jaxpr)


def test_initializers():
    key = jax.random.PRNGKey(0)
    assert float(init.Constant(3.0)(key, (2,), jnp.float32)[0]) == 3.0
    u = init.Uniform(-0.5, 0.5)(key, (1000,), jnp.float32)
    assert -0.5 <= float(u.min()) and float(u.max()) <= 0.5
    n = np.asarray(init.Normal(0, 1)(key, (5000,), jnp.float32))
    assert abs(n.mean()) < 0.1 and abs(n.std() - 1) < 0.1
    x = np.asarray(init.Xavier()(key, (100, 100), jnp.float32))
    limit = np.sqrt(6.0 / 200)
    assert x.min() >= -limit and x.max() <= limit
    m = np.asarray(init.MSRA(uniform=False)(key, (64, 32, 3, 3), jnp.float32))
    assert abs(m.std() - np.sqrt(2.0 / (32 * 9))) < 0.01
    b = init.Bilinear()(key, (1, 1, 4, 4), jnp.float32)
    assert b.shape == (1, 1, 4, 4)
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_allclose(np.asarray(init.NumpyArrayInitializer(arr)(key, (2, 3), jnp.float32)), arr)


def test_enforce_helpers():
    from paddle_tpu.core.errors import enforce, enforce_eq
    enforce(True)
    with pytest.raises(EnforceError):
        enforce(False, "boom %d", 42)
    with pytest.raises(EnforceError):
        enforce_eq(1, 2)


def test_flags_env(monkeypatch):
    from paddle_tpu.core import config
    config.set_flag("check_nan_inf", True)
    assert config.get_flag("check_nan_inf") is True
    config.set_flag("check_nan_inf", False)
